"""KV-cache inference for the Llama decoder: prefill + decode.

The serving counterpart of ``models.llama`` — the reference serves
Llama through JetStream (examples/tpu/v6e/serve-llama2-7b.yaml,
README.md:95-120: 11.42 req/s, ~2500 input tok/s on v6e); this module
is the TPU-native engine that plays that role here.

Design (TPU-first, not a torch translation):

- **Prefill / decode split.** ``prefill`` runs the full-sequence
  forward once (MXU-bound, flash attention) and writes K/V for every
  prompt position into a preallocated cache; ``decode_step`` then
  advances one token per call (HBM-bandwidth-bound: one pass over the
  cache per layer). Both are single traced programs — the layer loop
  is ``lax.scan`` over stacked per-layer params *and* the stacked
  cache, so cache updates are part of the scan's carry-free ys and XLA
  aliases the buffers in place under ``donate_argnums``.
- **GQA-native cache.** K/V are stored at ``n_kv_heads`` — never
  repeated to ``n_heads`` (an 8:1-GQA Llama-8B cache stays 4x smaller
  in HBM and on ICI than the repeat-then-attend layout). Query heads
  are folded as ``[B, n_kv, rep, hd]`` and contracted against the
  shared K/V with einsums XLA maps onto the MXU.
- **Ragged batches.** Each sequence carries its own length; cache
  writes use per-row scatter and attention masks positions ``>=
  length``, so one batch mixes prompt lengths freely (continuous
  batching shape, as JetStream does).
- **Sharding.** The cache is a pytree with PartitionSpecs: kv-heads on
  'tp', batch on ('dp','fsdp') — decode scales over a mesh with the
  same ``param_specs`` used for training.

Static shapes throughout (cache is [L, B, max_seq, n_kv, hd]); the
token index is data, not shape, so decode never recompiles.
"""
from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from skypilot_tpu.models.llama import (LlamaConfig, _attention,
                                       _rmsnorm, _rope, forward_hidden)
from skypilot_tpu.models import quantization
from skypilot_tpu.models.quantization import qdot, qdot_a8, qembed
from skypilot_tpu.ops import decode_attention as decode_attn

# Cache layout: [n_layers, B, max_seq, n_kv_heads, head_dim].
CACHE_SPEC = P(None, ('dp', 'fsdp'), None, 'tp', None)
# Per-vector quantization scales: [n_layers, B, max_seq, n_kv_heads].
SCALE_SPEC = P(None, ('dp', 'fsdp'), None, 'tp')


def cache_specs(kv_quant: bool = False) -> Dict:
    specs = {'k': CACHE_SPEC, 'v': CACHE_SPEC,
             'length': P(('dp', 'fsdp')),
             'dmask': P(('dp', 'fsdp'), None),
             'base': P(), 'steps': P()}
    if kv_quant:
        specs['k_scale'] = SCALE_SPEC
        specs['v_scale'] = SCALE_SPEC
    return specs


# KV-cache int8 quantization lives with the other quantization
# machinery; aliased here for the cache write sites below.
_quantize_kv = quantization.quantize_kv
_dequantize_kv = quantization.dequantize_kv


def _mlp_delta(h: jax.Array, lp: Dict, cfg: LlamaConfig,
               dot=qdot) -> jax.Array:
    """The residual-branch MLP output for one layer, by model family:
    dense SwiGLU for LlamaConfig; for MoEConfig, DROPLESS exact top-k
    expert mixing (moe.moe_block_dropless) — training's capacity
    dispatch drops tokens batch-dependently, which would make served
    tokens depend on their batchmates. Static shapes either way, so
    decode never recompiles. The router aux loss is a training
    signal; inference has none."""
    from skypilot_tpu.models import moe
    cdt = cfg.compute_dtype
    if isinstance(cfg, moe.MoEConfig):
        h3 = h if h.ndim == 3 else h[:, None]
        if cfg.infer_dispatch == 'capacity':
            # Capacity-gather dispatch (moe.moe_block_capacity):
            # expert compute scales with the capacity factor, not E —
            # the form that scales past E=8. At the default auto cf
            # it is provably dropless (and flop-equal to dropless);
            # cf < E/k buys the compute saving at an accepted
            # batch-dependent drop risk. See the block's docstring.
            y = moe.moe_block_capacity(h3, lp, cfg)
        else:
            # DROPLESS all-experts routing (moe.moe_block_dropless):
            # exact top-k mixing, right for small E.
            y = moe.moe_block_dropless(h3, lp, cfg)
        return y if h.ndim == 3 else y[:, 0]
    gate = jax.nn.silu(dot(h, lp['w_gate'], cdt))
    up = dot(h, lp['w_up'], cdt)
    return dot(gate * up, lp['w_down'], cdt)


# Cache slot layout (the key to fast TPU decode): prompts occupy
# slots 0..base-1 (base = padded prompt length; rows shorter than
# base leave garbage in their tail slots, masked at read), and decode
# step i writes slot base+i for EVERY row. The write index is
# therefore a traced *scalar*, so the cache update is a
# dynamic_update_slice XLA performs in place on the loop carry — no
# scatter, no full-cache rewrite. Which slots are READABLE per row is
# an explicit bool mask ``dmask`` [B, S]: prompt slots < length at
# prefill, and each decode write flips its column on for the rows
# that were active that step. The mask (B*S bits — negligible HBM)
# is what makes *continuous batching* exact: when ServingEngine
# recycles a batch slot for a new request, the first prefill chunk
# (prefill_chunk, start == 0) clearing the row's mask makes every
# stale decode slot of the previous occupant unreadable, with no
# cache rewrite. Per-row raggedness lives in the mask and the RoPE
# positions.


def _constrain(x, spec, mesh):
    if mesh is None:
        return x
    return lax.with_sharding_constraint(
        x, jax.sharding.NamedSharding(mesh, spec))


def _gqa_decode_attention(q, kc, vc, valid, k_self=None, v_self=None,
                          k_scale=None, v_scale=None):
    """One-position GQA attention against the cache (+ self).

    q: [B, n_heads, hd]; kc/vc: [B, S, n_kv, hd] (bf16, or int8 with
    k_scale/v_scale [B, S, n_kv]); valid: [B, S] bool; k_self/v_self:
    [B, n_kv, hd] — the incoming token's own K/V, attended without
    being read back from the cache. Returns [B, n_heads * hd]. K/V
    stay at n_kv_heads — query heads fold into [B, n_kv, rep, hd]
    instead (GQA-native, no repeat).

    int8 handling: the convert-to-bf16 happens *inside* the einsum
    operand (a fusible unary op — the dot reads int8 from HBM) and the
    per-vector scales are applied OUTSIDE the contraction: on the
    [.., s]-indexed scores for K, and folded into probs for V (the
    contraction is over s, so a per-s scale factors through linearly).
    Pre-multiplying the page (dequantize-then-attend) materializes a
    full bf16 copy and measured *slower* than bf16 caches on v5e.
    """
    b, s, n_kv, hd = kc.shape
    rep = q.shape[1] // n_kv
    # bf16 operands, f32 accumulation: the cache is never upcast in
    # HBM (decode is cache-bandwidth-bound; a materialized f32 copy
    # would double the traffic).
    qf = q.reshape(b, n_kv, rep, hd)
    scores = jnp.einsum(
        'bkrh,bskh->bkrs', qf, kc.astype(qf.dtype),
        preferred_element_type=jnp.float32) * hd**-0.5
    if k_scale is not None:
        # [B, S, n_kv] -> [B, n_kv, 1, S]
        scores = scores * jnp.transpose(
            k_scale, (0, 2, 1))[:, :, None, :].astype(jnp.float32)
    scores = jnp.where(valid[:, None, None, :], scores, -jnp.inf)
    if k_self is not None:
        s_self = jnp.einsum('bkrh,bkh->bkr', qf, k_self,
                            preferred_element_type=jnp.float32
                            )[..., None] * hd**-0.5
        scores = jnp.concatenate([scores, s_self], axis=-1)
    # Stable softmax across cache + self scores.
    m = jnp.max(scores, axis=-1, keepdims=True)
    e = jnp.exp(scores - m)
    denom = jnp.sum(e, axis=-1, keepdims=True)
    probs = e / denom
    if k_self is not None:
        probs, p_self = probs[..., :-1], probs[..., -1]
    pv = probs
    if v_scale is not None:
        pv = probs * jnp.transpose(
            v_scale, (0, 2, 1))[:, :, None, :].astype(probs.dtype)
    out = jnp.einsum('bkrs,bskh->bkrh', pv.astype(q.dtype),
                     vc.astype(q.dtype),
                     preferred_element_type=jnp.float32)
    if v_self is not None:
        out = out + (p_self[..., None] *
                     v_self[:, :, None].astype(jnp.float32))
    return out.reshape(b, n_kv * rep * hd).astype(q.dtype)


def prefill(params: Dict,
            tokens: jax.Array,
            lengths: jax.Array,
            cfg: LlamaConfig,
            mesh=None,
            max_seq: Optional[int] = None,
            kv_quant: bool = False) -> Tuple[jax.Array, Dict]:
    """Process prompts and build the cache.

    tokens: [B, S] right-padded prompts; lengths: [B] true lengths.
    Returns (next-token logits [B, vocab] f32 at each prompt's last
    position, cache). Padded positions write garbage K/V but the
    dmask marks everything >= length unreadable. ``kv_quant`` stores
    K/V as int8 with per-vector scales (half the decode bandwidth).
    """
    cdt = cfg.compute_dtype
    b, s = tokens.shape
    s_max = max_seq or cfg.max_seq
    positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))

    x = qembed(params['tok_emb'], tokens, cdt)
    x = _constrain(x, P(('dp', 'fsdp'), None, None), mesh)
    # Prefill is MXU-bound: with int8 weights, cfg.prefill_a8 also
    # quantizes activations per token so the matmuls run on the int8
    # MXU path (quantization.qdot_a8). Decode never does this.
    dot = qdot_a8 if cfg.prefill_a8 else qdot

    def layer(x, lp):
        h = _rmsnorm(x, lp['attn_norm'], cfg.norm_eps)
        q = dot(h, lp['wq'], cdt).reshape(b, s, cfg.n_heads,
                                          cfg.head_dim)
        k = dot(h, lp['wk'], cdt).reshape(b, s, cfg.n_kv_heads,
                                          cfg.head_dim)
        v = dot(h, lp['wv'], cdt).reshape(b, s, cfg.n_kv_heads,
                                          cfg.head_dim)
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
        # Same attention dispatch as training (Pallas flash kernel on
        # TPU, XLA fallback elsewhere) — prefill never materializes
        # the [S, S] score matrix.
        o = _attention(q, k, v, cfg, mesh)
        o = o.reshape(b, s, cfg.n_heads * cfg.head_dim).astype(cdt)
        x = x + dot(o, lp['wo'], cdt)

        h = _rmsnorm(x, lp['mlp_norm'], cfg.norm_eps)
        x = x + _mlp_delta(h, lp, cfg, dot=dot)
        # Pad this layer's K/V out to the cache length.
        pad = [(0, 0), (0, s_max - s), (0, 0), (0, 0)]
        if kv_quant:
            qk, sk = _quantize_kv(k)
            qv, sv = _quantize_kv(v)
            return x, (jnp.pad(qk, pad), jnp.pad(qv, pad),
                       jnp.pad(sk, pad[:3]), jnp.pad(sv, pad[:3]))
        return x, (jnp.pad(k, pad), jnp.pad(v, pad))

    x, ys = lax.scan(layer, x, params['layers'])
    x = _rmsnorm(x, params['final_norm'], cfg.norm_eps)

    # Hidden state at each prompt's final position -> logits.
    last = jnp.take_along_axis(
        x, (lengths - 1)[:, None, None].astype(jnp.int32), axis=1)[:, 0]
    logits = qdot(last, params['lm_head'], cdt,
                  preferred=jnp.float32)

    lengths = lengths.astype(jnp.int32)
    dmask = jnp.arange(s_max)[None, :] < lengths[:, None]
    cache = {'length': lengths,
             'dmask': _constrain(dmask, P(('dp', 'fsdp'), None), mesh),
             'base': jnp.asarray(s, jnp.int32),
             'steps': jnp.zeros((), jnp.int32)}
    if kv_quant:
        ks, vs, sks, svs = ys
        cache['k_scale'] = _constrain(sks, SCALE_SPEC, mesh)
        cache['v_scale'] = _constrain(svs, SCALE_SPEC, mesh)
    else:
        ks, vs = ys
    cache['k'] = _constrain(ks, CACHE_SPEC, mesh)
    cache['v'] = _constrain(vs, CACHE_SPEC, mesh)
    return logits, cache


def decode_step(params: Dict,
                cache: Dict,
                tokens: jax.Array,
                cfg: LlamaConfig,
                mesh=None,
                active: Optional[jax.Array] = None,
                *,
                attn_impl: Optional[str] = None,
                num_pages: Optional[int] = None,
                page: Optional[int] = None
                ) -> Tuple[jax.Array, Dict]:
    """Advance every sequence by one token.

    tokens: [B] int32 (the tokens being fed in, whose K/V are appended
    at slot ``base + steps``). ``active``: optional [B] bool — rows
    marked inactive still compute (the batch is one traced program)
    but their write column stays masked, so an empty ServingEngine
    slot never contaminates a later occupant. Returns (logits
    [B, vocab] f32 for the *next* token, updated cache).

    Attention dispatch (all static, resolved at trace time):
    ``attn_impl`` 'paged' runs the Pallas paged ragged kernel
    (ops.decode_attention — reads only live cache pages, int8 dequant
    fused), 'lax' the einsum reference, None/'auto' picks paged on
    TPU. ``num_pages`` (with ``page``) bounds the cache region that
    is READ to the first num_pages*page slots — length-aware
    dispatch: callers that know the live region (ServingEngine,
    bench) pass it so per-step HBM traffic scales with occupancy,
    not ``max_seq``. Every dmask-true slot must lie below the bound;
    cache WRITES are unaffected (they target the full buffer).

    Structure (why this is fast on TPU): the layer loop is a
    ``lax.scan`` whose *carry* holds the full stacked cache; each
    layer (a) dynamic-slices its [B, S, kv, hd] page for attention
    reads and (b) dynamic-update-slices the new K/V at scalar indices
    (layer, slot) — an in-place write of a [B, 1, kv, hd] sliver on
    the loop-carried buffer. The incoming token attends to the cached
    slots plus itself, so the updated page never needs materializing.
    Per-step HBM traffic = params + one cache read + O(B*kv*hd)
    writes. Alternatives measured on v5e (1B model, batch 32, ctx
    1024): per-row scatter ~52 ms/step, select-rewrite ~37 ms/step,
    this layout is bandwidth-bound. int8 caches (see _quantize_kv)
    halve the read traffic; dequantization happens in-register after
    the sliced page is loaded.
    """
    cdt = cfg.compute_dtype
    b = tokens.shape[0]
    quant = 'k_scale' in cache
    pos = cache['length']                       # [B] logical position
    base, steps = cache['base'], cache['steps']
    slot = base + steps                         # scalar write slot
    # Readable slots: exactly the dmask. The incoming token is handled
    # by the explicit self term, so ``slot`` itself is not read back.
    valid = cache['dmask']
    if active is None:
        active = jnp.ones((b,), bool)

    s_max = cache['k'].shape[2]
    page = page or decode_attn.default_page()
    impl = decode_attn.resolve_impl(attn_impl)
    if s_max % page != 0:
        # The paged kernel needs page-aligned caches; the lax path
        # still honors the length-aware slice below. (Meshes no
        # longer downgrade: the sharded cache goes through the
        # shard_map wrapper below.)
        impl = 'lax'
    n_slots = None
    if num_pages is not None:
        n_slots = min(num_pages * page, s_max)
        if n_slots >= s_max:
            n_slots = None                   # full cache; no slicing
    # Per-row live upper bound for page skipping: before any decode
    # write the live slots are exactly the (ragged) prompt lengths;
    # once decode slots exist every row's region extends to the
    # shared write frontier base + steps (prompt lengths are <= base).
    row_bound = jnp.where(steps > 0, base + steps, pos)

    x = qembed(params['tok_emb'], tokens, cdt)  # [B, D]
    x = _constrain(x, P(('dp', 'fsdp'), None), mesh)

    def layer(carry, inp):
        if quant:                           # kc/vc [L, B, S, kv, hd]
            x, kc, vc, ksc, vsc = carry
        else:
            x, kc, vc = carry
            ksc = vsc = None
        lp, li = inp
        h = _rmsnorm(x, lp['attn_norm'], cfg.norm_eps)
        q = qdot(h, lp['wq'], cdt).reshape(b, cfg.n_heads,
                                           cfg.head_dim)
        k = qdot(h, lp['wk'], cdt).reshape(b, cfg.n_kv_heads,
                                           cfg.head_dim)
        v = qdot(h, lp['wv'], cdt).reshape(b, cfg.n_kv_heads,
                                           cfg.head_dim)
        q = _rope(q[:, None], pos[:, None], cfg.rope_theta)[:, 0]
        k = _rope(k[:, None], pos[:, None], cfg.rope_theta)[:, 0]
        page_k = lax.dynamic_index_in_dim(kc, li, 0, keepdims=False)
        page_v = lax.dynamic_index_in_dim(vc, li, 0, keepdims=False)
        page_ks = page_vs = None
        if quant:
            page_ks = lax.dynamic_index_in_dim(ksc, li, 0,
                                               keepdims=False)
            page_vs = lax.dynamic_index_in_dim(vsc, li, 0,
                                               keepdims=False)
        if impl == 'paged':
            # Grid-limited to num_pages; per-row early exit inside.
            if mesh is not None:
                # Mesh-sharded cache: each shard runs the unchanged
                # kernel on its local kv-head slice (batch stays on
                # the data axes, row bounds replicated over 'tp').
                o = decode_attn.sharded_paged_gqa_decode_attention(
                    q, page_k, page_v, valid, row_bound,
                    k_self=k, v_self=v,
                    k_scale=page_ks, v_scale=page_vs,
                    mesh=mesh, page=page, num_pages=num_pages)
            else:
                o = decode_attn.paged_gqa_decode_attention(
                    q, page_k, page_v, valid, row_bound,
                    k_self=k, v_self=v,
                    k_scale=page_ks, v_scale=page_vs,
                    page=page, num_pages=num_pages)
        else:
            pk, pv, vd = page_k, page_v, valid
            pks, pvs = page_ks, page_vs
            if n_slots is not None:
                # Length-aware slice: XLA fuses the slice into the
                # einsum's operand read, so the contraction only
                # pulls the live region from HBM.
                pk, pv = pk[:, :n_slots], pv[:, :n_slots]
                vd = valid[:, :n_slots]
                if quant:
                    pks = pks[:, :n_slots]
                    pvs = pvs[:, :n_slots]
            o = _gqa_decode_attention(q, pk, pv, vd,
                                      k_self=k, v_self=v,
                                      k_scale=pks, v_scale=pvs)
        x = x + qdot(o, lp['wo'], cdt)

        h = _rmsnorm(x, lp['mlp_norm'], cfg.norm_eps)
        x = x + _mlp_delta(h, lp, cfg)

        # In-place sliver write at scalar (layer, slot).
        if quant:
            k, sk = _quantize_kv(k)
            v, sv = _quantize_kv(v)
            ksc = lax.dynamic_update_slice(
                ksc, sk[None, :, None], (li, 0, slot, 0))
            vsc = lax.dynamic_update_slice(
                vsc, sv[None, :, None], (li, 0, slot, 0))
        kc = lax.dynamic_update_slice(
            kc, k[None, :, None].astype(kc.dtype), (li, 0, slot, 0, 0))
        vc = lax.dynamic_update_slice(
            vc, v[None, :, None].astype(vc.dtype), (li, 0, slot, 0, 0))
        if quant:
            return (x, kc, vc, ksc, vsc), None
        return (x, kc, vc), None

    if quant:
        carry0 = (x, cache['k'], cache['v'], cache['k_scale'],
                  cache['v_scale'])
    else:
        carry0 = (x, cache['k'], cache['v'])
    out_carry, _ = lax.scan(
        layer, carry0, (params['layers'], jnp.arange(cfg.n_layers)))
    if quant:
        x, ks, vs, sks, svs = out_carry
    else:
        (x, ks, vs), sks, svs = out_carry, None, None
    x = _rmsnorm(x, params['final_norm'], cfg.norm_eps)
    logits = qdot(x, params['lm_head'], cdt, preferred=jnp.float32)
    dmask = lax.dynamic_update_slice(cache['dmask'], active[:, None],
                                     (0, slot))
    new_cache = {'k': _constrain(ks, CACHE_SPEC, mesh),
                 'v': _constrain(vs, CACHE_SPEC, mesh),
                 'length': jnp.where(active, pos + 1, pos),
                 'dmask': dmask,
                 'base': base, 'steps': steps + 1}
    if quant:
        new_cache['k_scale'] = _constrain(sks, SCALE_SPEC, mesh)
        new_cache['v_scale'] = _constrain(svs, SCALE_SPEC, mesh)
    return logits, new_cache


def prefill_chunk(params: Dict,
                  cache: Dict,
                  tokens: jax.Array,
                  starts: jax.Array,
                  lens: jax.Array,
                  live: jax.Array,
                  slots: jax.Array,
                  cfg: LlamaConfig,
                  *,
                  prompt_base: int,
                  mesh=None) -> Tuple[jax.Array, Dict]:
    """Process one prompt *chunk* per row directly into the batch
    cache — the chunked-prefill primitive (Sarathi-style): instead of
    a monolithic whole-prompt prefill + ``insert_prefill`` copy, the
    serving engine streams each prompt through here ``C`` tokens at a
    time, so prefill work coalesces with decode ticks under a token
    budget and never stalls in-flight decodes.

    tokens: [G, C] — row j holds prompt positions
    [starts[j], starts[j] + lens[j]) of slot ``slots[j]``'s prompt,
    right-padded to C. ``live``: [G] bool — padding rows (False) are
    fully inert: their cache rows, dmask and length are bit-preserved
    (rows may then safely repeat slot indices). ``prompt_base``
    (static) is the cache's prompt region size (== engine
    max_prompt); all chunk writes land below it.

    Per layer the slot rows' prompt regions are gathered, the chunk's
    K/V written at ``starts`` (quantized in place for int8 caches),
    attention taken over [0, start + C) under the query-offset causal
    rule (``ops.flash_attention.chunk_prefill_attention`` — Pallas
    q-tiled kernel on TPU, exact einsum elsewhere/int8), and the
    region scattered back. Positions past a partial chunk's ``len``
    hold garbage K/V but stay dmask-false, and causality keeps them
    out of every valid query's window — exactly the ragged-tail
    discipline of monolithic ``prefill``.

    Returns (logits [G, vocab] f32 at each row's last valid chunk
    position — the next-token logits when the chunk completes its
    prompt — and the updated cache). Like ``prefill``, activations
    take the int8 path when ``cfg.prefill_a8``.
    """
    # Direct-from-module import: the ops package re-exports a
    # ``flash_attention`` *function* under the module's name, so a
    # ``from skypilot_tpu.ops import flash_attention`` would bind the
    # function, not the module.
    from skypilot_tpu.ops.flash_attention import chunk_prefill_attention
    cdt = cfg.compute_dtype
    g, c = tokens.shape
    quant = 'k_scale' in cache
    s_max = cache['k'].shape[2]
    base = prompt_base
    assert 0 < base <= s_max, (base, s_max)
    n_kv, hd = cfg.n_kv_heads, cfg.head_dim
    positions = (starts[:, None] +
                 jnp.arange(c, dtype=jnp.int32)[None, :])
    starts = starts.astype(jnp.int32)
    dot = qdot_a8 if cfg.prefill_a8 else qdot

    x = qembed(params['tok_emb'], tokens, cdt)       # [G, C, D]

    def _gather_rows(layer_cache):
        """[B, S, ...] -> [G, base+C, ...] slot rows padded with
        ``c`` slots of chunk headroom so a C-wide write at start <=
        base-1 never clamps (clamping would silently overwrite
        earlier prompt positions)."""
        rows = jnp.take(layer_cache[:, :base], slots, axis=0)
        pad = [(0, 0), (0, c)] + [(0, 0)] * (rows.ndim - 2)
        return jnp.pad(rows, pad)

    def _scatter_rows(layer_cache, rows):
        """Write rows' [0:base] regions back at their slots. Static
        unroll with a fresh read per row: dead (live=False) rows keep
        the cache's CURRENT content even when they duplicate a live
        row's slot index (a vector scatter with duplicate indices has
        unspecified order and could revert a live write)."""
        region = (1, base) + layer_cache.shape[2:]
        for j in range(g):
            start = (slots[j],) + (0,) * (layer_cache.ndim - 1)
            cur = lax.dynamic_slice(layer_cache, start, region)
            new = jnp.where(live[j], rows[j:j + 1, :base], cur)
            layer_cache = lax.dynamic_update_slice(
                layer_cache, new.astype(layer_cache.dtype), start)
        return layer_cache

    def layer(carry, inp):
        if quant:
            x, kc, vc, ksc, vsc = carry
        else:
            x, kc, vc = carry
            ksc = vsc = None
        lp, li = inp
        h = _rmsnorm(x, lp['attn_norm'], cfg.norm_eps)
        q = dot(h, lp['wq'], cdt).reshape(g, c, cfg.n_heads, hd)
        k = dot(h, lp['wk'], cdt).reshape(g, c, n_kv, hd)
        v = dot(h, lp['wv'], cdt).reshape(g, c, n_kv, hd)
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)

        kc_l = lax.dynamic_index_in_dim(kc, li, 0, keepdims=False)
        vc_l = lax.dynamic_index_in_dim(vc, li, 0, keepdims=False)
        rows_k = _gather_rows(kc_l)                  # [G, base+C, ...]
        rows_v = _gather_rows(vc_l)
        if quant:
            ksc_l = lax.dynamic_index_in_dim(ksc, li, 0,
                                             keepdims=False)
            vsc_l = lax.dynamic_index_in_dim(vsc, li, 0,
                                             keepdims=False)
            rows_ks = _gather_rows(ksc_l)
            rows_vs = _gather_rows(vsc_l)
            wk, sk = _quantize_kv(k)
            wv, sv = _quantize_kv(v)
        else:
            rows_ks = rows_vs = None
            wk, wv, sk, sv = k, v, None, None
        # Write this chunk's K/V at each row's start (scales too);
        # the write is C wide, so a partial chunk leaves garbage in
        # its tail — causally invisible, dmask-false.
        wrt = jax.vmap(lambda row, blk, st: lax.dynamic_update_slice(
            row, blk.astype(row.dtype), (st,) + (0,) * (row.ndim - 1)))
        rows_k = wrt(rows_k, wk, starts)
        rows_v = wrt(rows_v, wv, starts)
        if quant:
            rows_ks = wrt(rows_ks, sk, starts)
            rows_vs = wrt(rows_vs, sv, starts)
        # Meshes no longer force the einsum reference: the Pallas
        # path shard_maps over 'tp' (kv heads), the xla path is
        # GSPMD-partitioned either way.
        o = chunk_prefill_attention(
            q, rows_k, rows_v, starts, rows_ks, rows_vs, mesh=mesh)
        o = o.reshape(g, c, cfg.n_heads * hd).astype(cdt)
        x = x + dot(o, lp['wo'], cdt)

        h = _rmsnorm(x, lp['mlp_norm'], cfg.norm_eps)
        x = x + _mlp_delta(h, lp, cfg, dot=dot)

        kc_l = _scatter_rows(kc_l, rows_k)
        vc_l = _scatter_rows(vc_l, rows_v)
        kc = lax.dynamic_update_slice(
            kc, kc_l[None], (li,) + (0,) * (kc.ndim - 1))
        vc = lax.dynamic_update_slice(
            vc, vc_l[None], (li,) + (0,) * (vc.ndim - 1))
        if quant:
            ksc_l = _scatter_rows(ksc_l, rows_ks)
            vsc_l = _scatter_rows(vsc_l, rows_vs)
            ksc = lax.dynamic_update_slice(
                ksc, ksc_l[None], (li,) + (0,) * (ksc.ndim - 1))
            vsc = lax.dynamic_update_slice(
                vsc, vsc_l[None], (li,) + (0,) * (vsc.ndim - 1))
            return (x, kc, vc, ksc, vsc), None
        return (x, kc, vc), None

    if quant:
        carry0 = (x, cache['k'], cache['v'], cache['k_scale'],
                  cache['v_scale'])
    else:
        carry0 = (x, cache['k'], cache['v'])
    out_carry, _ = lax.scan(
        layer, carry0, (params['layers'], jnp.arange(cfg.n_layers)))
    if quant:
        x, ks, vs, sks, svs = out_carry
    else:
        (x, ks, vs), sks, svs = out_carry, None, None

    x = _rmsnorm(x, params['final_norm'], cfg.norm_eps)
    last = jnp.take_along_axis(
        x, jnp.maximum(lens - 1, 0)[:, None, None].astype(jnp.int32),
        axis=1)[:, 0]
    logits = qdot(last, params['lm_head'], cdt, preferred=jnp.float32)

    # dmask/length updates: unrolled with fresh reads per row for the
    # same duplicate-slot safety as _scatter_rows. A dead row's
    # ``newly`` mask is all-False and its length keeps the current
    # value, so padding rows are exact no-ops. A prompt's FIRST chunk
    # (start == 0) clears the whole row before setting its own
    # positions — the slot-recycling guarantee ``insert_prefill``
    # gave: every decode slot and prompt-tail position of the
    # previous occupant becomes unreadable, with no cache rewrite.
    dmask, lengths = cache['dmask'], cache['length']
    pos_idx = jnp.arange(s_max, dtype=jnp.int32)
    for j in range(g):
        newly = (live[j] & (pos_idx >= starts[j]) &
                 (pos_idx < starts[j] + lens[j]))
        cur = lax.dynamic_slice(dmask, (slots[j], 0), (1, s_max))
        cur = jnp.where(live[j] & (starts[j] == 0),
                        jnp.zeros_like(cur), cur)
        dmask = lax.dynamic_update_slice(dmask, cur | newly[None],
                                         (slots[j], 0))
        cur_len = lax.dynamic_slice(lengths, (slots[j],), (1,))
        new_len = jnp.where(live[j],
                            (starts[j] + lens[j]).astype(lengths.dtype),
                            cur_len[0])
        lengths = lax.dynamic_update_slice(lengths, new_len[None],
                                           (slots[j],))

    new_cache = {'k': _constrain(ks, CACHE_SPEC, mesh),
                 'v': _constrain(vs, CACHE_SPEC, mesh),
                 'length': lengths,
                 'dmask': _constrain(dmask, P(('dp', 'fsdp'), None),
                                     mesh),
                 'base': cache['base'], 'steps': cache['steps']}
    if quant:
        new_cache['k_scale'] = _constrain(sks, SCALE_SPEC, mesh)
        new_cache['v_scale'] = _constrain(svs, SCALE_SPEC, mesh)
    return logits, new_cache


def verify_step(params: Dict,
                cache: Dict,
                tokens: jax.Array,
                drafts: jax.Array,
                spec_len: jax.Array,
                cfg: LlamaConfig,
                key: jax.Array,
                temperature: jax.Array,
                top_k,
                mesh=None,
                active: Optional[jax.Array] = None,
                *,
                num_pages: Optional[int] = None,
                page: Optional[int] = None
                ) -> Tuple[jax.Array, jax.Array, jax.Array, Dict]:
    """One draft-and-verify speculative step (Leviathan et al. 2023):
    score every drafted candidate in a single forward and accept the
    longest prefix the model itself would have produced.

    tokens: [B] — each row's current (sampled, not-yet-fed) token;
    drafts: [B, K] — up to K proposed continuation tokens per row
    (host-side n-gram/prompt-lookup proposer); spec_len: [B] int32 in
    [0, K] — how many of the K are real (0 = the row runs a plain
    one-token step inside the same program). The row's verify segment
    f_0..f_V-1 = [token, d_1..d_K] (V = K+1) is fed at positions
    length..length+V-1, its K/V written at the shared write-frontier
    columns [base+steps, base+steps+V), and every position's
    next-token distribution computed in ONE forward — attention runs
    ``ops.flash_attention.verify_attention`` (dmask-valid +
    segment-causal into the paged cache; int8 scales via the
    reference path, same discipline as decode).

    Acceptance is exact greedy/sampling equivalence per position:
    sample m_i from position i's logits (per-row temperature, traced
    top_k); accept the longest prefix with d_{i+1} == m_i (i <
    spec_len); the first rejected position falls back to m_a — the
    model's own sample for that position, which is bitwise what the
    sequential path would have emitted. Rows therefore always advance
    >= 1 token and greedy outputs are bitwise identical to
    speculation-off. K/V written for rejected candidates are rolled
    back through the existing dmask/length machinery: only columns
    base+steps+i with i <= accepted become readable, lengths advance
    by accepted+1, and the dead columns stay dmask-false forever
    (the shared frontier still advances V — capacity accounting is
    the engine's spec guard).

    Returns (emit [B, V] — tokens to surface, valid up to counts;
    counts [B] — accepted+1 for active rows, 0 otherwise;
    next_tok [B] — the new current token (frozen for inactive rows);
    updated cache). ``num_pages`` bounds the attention read region
    exactly as in decode_step and must cover base+steps+V.
    """
    # Direct-from-module import (see prefill_chunk): the ops package
    # re-exports a ``flash_attention`` function under the module name.
    from skypilot_tpu.ops.flash_attention import verify_attention
    cdt = cfg.compute_dtype
    b, k_max = drafts.shape
    v = k_max + 1
    quant = 'k_scale' in cache
    pos = cache['length']                       # [B] logical position
    base, steps = cache['base'], cache['steps']
    slot = base + steps                         # scalar segment start
    valid = cache['dmask']
    if active is None:
        active = jnp.ones((b,), bool)

    s_max = cache['k'].shape[2]
    page = page or decode_attn.default_page()
    n_slots = None
    if num_pages is not None:
        n_slots = min(num_pages * page, s_max)
        if n_slots >= s_max:
            n_slots = None                   # full cache; no slicing
    # int8 caches verify through the exact einsum reference (same
    # rule as chunk prefill); bf16 TPU runs the Pallas verify kernel
    # — shard_map'd over the mesh when one is set.
    impl = 'xla' if quant else None

    fed = jnp.concatenate(
        [tokens[:, None], drafts.astype(jnp.int32)], axis=1)  # [B, V]
    positions = pos[:, None] + jnp.arange(v, dtype=jnp.int32)[None, :]

    x = qembed(params['tok_emb'], fed, cdt)     # [B, V, D]
    x = _constrain(x, P(('dp', 'fsdp'), None, None), mesh)

    def layer(carry, inp):
        if quant:
            x, kc, vc, ksc, vsc = carry
        else:
            x, kc, vc = carry
            ksc = vsc = None
        lp, li = inp
        h = _rmsnorm(x, lp['attn_norm'], cfg.norm_eps)
        q = qdot(h, lp['wq'], cdt).reshape(b, v, cfg.n_heads,
                                           cfg.head_dim)
        k = qdot(h, lp['wk'], cdt).reshape(b, v, cfg.n_kv_heads,
                                           cfg.head_dim)
        vv = qdot(h, lp['wv'], cdt).reshape(b, v, cfg.n_kv_heads,
                                            cfg.head_dim)
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)

        kc_l = lax.dynamic_index_in_dim(kc, li, 0, keepdims=False)
        vc_l = lax.dynamic_index_in_dim(vc, li, 0, keepdims=False)
        if quant:
            ksc_l = lax.dynamic_index_in_dim(ksc, li, 0,
                                             keepdims=False)
            vsc_l = lax.dynamic_index_in_dim(vsc, li, 0,
                                             keepdims=False)
            wk, sk = _quantize_kv(k)
            wv, sv = _quantize_kv(vv)
        else:
            ksc_l = vsc_l = None
            wk, wv, sk, sv = k, vv, None, None
        # Write the whole V-token segment BEFORE attending (like
        # prefill_chunk): query i then reads f_0..f_i through the
        # segment-causal term; rejected candidates' columns are
        # rolled back below via dmask, never via a cache rewrite.
        kc_l = lax.dynamic_update_slice(
            kc_l, wk.astype(kc_l.dtype), (0, slot, 0, 0))
        vc_l = lax.dynamic_update_slice(
            vc_l, wv.astype(vc_l.dtype), (0, slot, 0, 0))
        if quant:
            ksc_l = lax.dynamic_update_slice(
                ksc_l, sk.astype(ksc_l.dtype), (0, slot, 0))
            vsc_l = lax.dynamic_update_slice(
                vsc_l, sv.astype(vsc_l.dtype), (0, slot, 0))
        pk, pv, vd = kc_l, vc_l, valid
        pks, pvs = ksc_l, vsc_l
        if n_slots is not None:
            # Length-aware slice: only the live region is read.
            pk, pv = pk[:, :n_slots], pv[:, :n_slots]
            vd = valid[:, :n_slots]
            if quant:
                pks = pks[:, :n_slots]
                pvs = pvs[:, :n_slots]
        o = verify_attention(q, pk, pv, vd, slot,
                             k_scale=pks, v_scale=pvs, impl=impl,
                             mesh=mesh)
        o = o.reshape(b, v, cfg.n_heads * cfg.head_dim).astype(cdt)
        x = x + qdot(o, lp['wo'], cdt)

        h = _rmsnorm(x, lp['mlp_norm'], cfg.norm_eps)
        x = x + _mlp_delta(h, lp, cfg)

        kc = lax.dynamic_update_slice(
            kc, kc_l[None], (li,) + (0,) * (kc.ndim - 1))
        vc = lax.dynamic_update_slice(
            vc, vc_l[None], (li,) + (0,) * (vc.ndim - 1))
        if quant:
            ksc = lax.dynamic_update_slice(
                ksc, ksc_l[None], (li,) + (0,) * (ksc.ndim - 1))
            vsc = lax.dynamic_update_slice(
                vsc, vsc_l[None], (li,) + (0,) * (vsc.ndim - 1))
            return (x, kc, vc, ksc, vsc), None
        return (x, kc, vc), None

    if quant:
        carry0 = (x, cache['k'], cache['v'], cache['k_scale'],
                  cache['v_scale'])
    else:
        carry0 = (x, cache['k'], cache['v'])
    out_carry, _ = lax.scan(
        layer, carry0, (params['layers'], jnp.arange(cfg.n_layers)))
    if quant:
        x, ks, vs, sks, svs = out_carry
    else:
        (x, ks, vs), sks, svs = out_carry, None, None
    x = _rmsnorm(x, params['final_norm'], cfg.norm_eps)
    logits = qdot(x, params['lm_head'], cdt,
                  preferred=jnp.float32)        # [B, V, vocab]

    # Per-position sampling (greedy rows: argmax; the RNG split only
    # matters for temperature > 0 rows, whose spec_len is 0 — they
    # just draw their one sample from position 0's logits).
    keys = jax.random.split(key, v)
    m = jnp.stack([
        _sample(logits[:, i], keys[i], temperature, top_k)
        for i in range(v)], axis=1)             # [B, V]

    # Longest accepted prefix: d_{i+1} == m_i, i < spec_len.
    cmp = (drafts.astype(jnp.int32) == m[:, :-1])          # [B, K]
    within = (jnp.arange(k_max, dtype=jnp.int32)[None, :] <
              spec_len[:, None])
    acc = jnp.cumprod((cmp & within).astype(jnp.int32), axis=1)
    a = jnp.sum(acc, axis=1)                    # [B] accepted drafts

    # Emission: d_1..d_a then m_a (the model's own token for the
    # first rejected position — or the bonus token when all accept).
    jidx = jnp.arange(v, dtype=jnp.int32)[None, :]
    drafts_pad = jnp.concatenate(
        [drafts.astype(jnp.int32), jnp.zeros((b, 1), jnp.int32)],
        axis=1)
    emit = jnp.where(jidx < a[:, None], drafts_pad, m)
    counts = jnp.where(active, a + 1, 0)
    next_tok = jnp.take_along_axis(m, a[:, None], axis=1)[:, 0]
    # Inactive rows freeze their token chain (same rule as the decode
    # scan): a just-prefilled slot's first token must survive.
    next_tok = jnp.where(active, next_tok, tokens)

    # dmask rollback: within the segment columns, exactly f_0..f_a
    # become readable for active rows; rejected candidates' K/V stay
    # dark forever. Columns outside the segment keep their mask.
    cols = jnp.arange(s_max, dtype=jnp.int32)[None, :]
    seg = (cols >= slot) & (cols < slot + v)
    keep = active[:, None] & ((cols - slot) <= a[:, None])
    dmask = jnp.where(seg, keep, cache['dmask'])
    new_cache = {'k': _constrain(ks, CACHE_SPEC, mesh),
                 'v': _constrain(vs, CACHE_SPEC, mesh),
                 'length': jnp.where(active, pos + a + 1, pos),
                 'dmask': _constrain(dmask, P(('dp', 'fsdp'), None),
                                     mesh),
                 'base': base, 'steps': steps + v}
    if quant:
        new_cache['k_scale'] = _constrain(sks, SCALE_SPEC, mesh)
        new_cache['v_scale'] = _constrain(svs, SCALE_SPEC, mesh)
    return emit, counts, next_tok, new_cache


def _sample(logits, key, temperature, top_k):
    """temperature is a *traced* value (<= 0 means greedy) — a scalar,
    or a [B] vector for per-request temperatures in one batch — so a
    server can vary it per request without recompiling. top_k is
    traced too (<= 0 or >= vocab disables the filter): varying it per
    call reuses the compiled program. The filter branch lives under
    ``lax.cond`` so the unfiltered/greedy path never pays the vocab
    sort it used to skip statically."""
    vocab = logits.shape[-1]
    tk = jnp.asarray(top_k, jnp.int32)

    def _filtered(lg):
        # Threshold at the top_k-th largest logit: ascending sort,
        # element vocab - top_k (the old static ``[:, -top_k]``),
        # fetched at a traced index.
        srt = jnp.sort(lg, axis=-1)
        idx = jnp.clip(vocab - tk, 0, vocab - 1)
        thresh = lax.dynamic_slice_in_dim(srt, idx, 1, axis=-1)
        return jnp.where(lg < thresh, -jnp.inf, lg)

    logits = lax.cond((tk > 0) & (tk < vocab), _filtered,
                      lambda lg: lg, logits)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    temp = jnp.asarray(temperature, jnp.float32)
    t = jnp.maximum(temp, 1e-6)
    if t.ndim == 1:
        t = t[:, None]
    sampled = jax.random.categorical(
        key, logits / t, axis=-1).astype(jnp.int32)
    return jnp.where(temp <= 0.0, greedy, sampled)


def generate(params: Dict,
             tokens: jax.Array,
             lengths: jax.Array,
             cfg: LlamaConfig,
             max_new: int,
             temperature: float = 0.0,
             top_k: int = 0,
             key: Optional[jax.Array] = None,
             max_seq: Optional[int] = None,
             kv_quant: bool = False,
             attn_impl: Optional[str] = None,
             page: Optional[int] = None) -> jax.Array:
    """Prefill + autoregressive decode, one traced program.

    tokens: [B, S] right-padded prompts; lengths: [B]. Returns
    generated tokens [B, max_new] (greedy when temperature <= 0;
    temperature is traced, so varying it does not recompile).
    """
    # Resolve the attention dispatch BEFORE jit so the compiled-
    # program cache key carries the concrete choice — resolving the
    # SKYTPU_DECODE_ATTN/_PAGE env inside the trace would silently
    # reuse a stale program after the env changes.
    return _generate_jit(params, tokens, lengths, cfg, max_new,
                         temperature, top_k, key, max_seq, kv_quant,
                         decode_attn.resolve_impl(attn_impl),
                         page or decode_attn.default_page())


# top_k is deliberately NOT in the static set: _sample traces it, so
# a server varying top_k per request (or a bench sweeping it) reuses
# the compiled program exactly like temperature always has.
@functools.partial(jax.jit, static_argnames=(
    'cfg', 'max_new', 'max_seq', 'kv_quant', 'attn_impl', 'page'))
def _generate_jit(params: Dict,
                  tokens: jax.Array,
                  lengths: jax.Array,
                  cfg: LlamaConfig,
                  max_new: int,
                  temperature: float,
                  top_k: int,
                  key: Optional[jax.Array],
                  max_seq: Optional[int],
                  kv_quant: bool,
                  attn_impl: Optional[str],
                  page: Optional[int]) -> jax.Array:
    if key is None:
        key = jax.random.PRNGKey(0)
    s_max = max_seq or cfg.max_seq
    if tokens.shape[1] + max_new > s_max:
        # Decode slots are prompt_pad + step; past the cache end the
        # write would silently clamp and corrupt the newest tokens.
        raise ValueError(
            f'prompt ({tokens.shape[1]}) + max_new ({max_new}) '
            f'exceeds the cache ({s_max} slots); raise max_seq or '
            'trim the prompt.')
    logits, cache = prefill(params, tokens, lengths, cfg,
                            max_seq=max_seq, kv_quant=kv_quant)
    first = _sample(logits, key, temperature, top_k)

    def step(carry, _):
        cache, tok, key = carry
        key, sub = jax.random.split(key)
        logits, cache = decode_step(params, cache, tok, cfg,
                                    attn_impl=attn_impl, page=page)
        nxt = _sample(logits, sub, temperature, top_k)
        return (cache, nxt, key), tok

    (_, last, _), toks = lax.scan(
        step, (cache, first, key), None, length=max_new - 1)
    toks = jnp.moveaxis(toks, 0, 1)             # [B, max_new-1]
    return jnp.concatenate([toks, last[:, None]], axis=1)


# The wrapper keeps the jitted function's compile-cache introspection
# (tests assert traced-not-static argument behavior through it).
generate._cache_size = _generate_jit._cache_size


def reference_generate(params: Dict, tokens: jax.Array,
                       lengths: jax.Array, cfg: LlamaConfig,
                       max_new: int) -> jax.Array:
    """Cache-free greedy generation (full forward per token) — the
    correctness oracle for the KV-cache path in tests."""
    from skypilot_tpu.models import moe
    b, s = tokens.shape
    buf = jnp.concatenate(
        [tokens, jnp.zeros((b, max_new), jnp.int32)], axis=1)
    cur = lengths.astype(jnp.int32)
    if isinstance(cfg, moe.MoEConfig):
        # Dropless, matching the cache path's inference routing.
        full = jax.jit(lambda p, t: moe.forward(p, t, cfg,
                                                dropless=True))
    else:
        full = jax.jit(lambda p, t: forward_hidden(p, t, cfg) @
                       p['lm_head'].astype(cfg.compute_dtype))
    out = []
    for _ in range(max_new):
        logits = full(params, buf)
        last = jnp.take_along_axis(
            logits, (cur - 1)[:, None, None], axis=1)[:, 0]
        nxt = jnp.argmax(last, axis=-1).astype(jnp.int32)
        buf = buf.at[jnp.arange(b), cur].set(nxt)
        cur = cur + 1
        out.append(nxt)
    return jnp.stack(out, axis=1)
