"""Post-training int8 weight quantization for serving.

The reference serves its 7B-class models through JetStream with
``quantize_weights=True`` (examples/tpu/v6e/serve-llama2-7b.yaml,
README.md:95-120) — weight-only int8 is what fits a 7-8B model on a
single 16 GB chip and is the standard serving quantization on TPU.
This module is the TPU-native equivalent for our engine:

- **Per-output-channel symmetric int8.** Each weight matrix ``w``
  [.., in, out] stores ``q = round(w / s)`` as int8 with a scale
  ``s = max|w| / 127`` per *output* channel ([.., out]). Because the
  scale is constant along the contraction (``in``) axis it factors
  out of the matmul: ``x @ w  ==  (x @ q) * s`` — the dot reads int8
  straight from HBM (the convert is a fusible unary on the operand)
  and the dequantize is one cheap per-column multiply on the output.
  Decode is weight-bandwidth-bound, so halving the bytes per step
  (~2x vs bf16) is, to first order, 2x decode throughput — the same
  lever the int8 KV cache pulls for the cache reads.
- **Embedding rows quantize per-row** (the lookup gathers rows, so
  the scale must be constant along ``dim``, not ``vocab``).
- **Norm weights and the MoE router stay unquantized**: together they
  are <0.1% of bytes, and the router's top-k is the one place a
  quantization flip changes *which* weights run, not just their
  values.

A quantized leaf is the pytree dict ``{'q': int8, 's': f32}`` — the
params tree keeps its keys, so ``lax.scan`` over stacked layers, the
engine's donation, and checkpoint save/restore all work unchanged.

``init_quantized_params`` builds a random *already-quantized* tree
directly (int8 allocation only): an 8B bf16 tree (16 GB) cannot be
materialized then quantized on a 16 GB chip, but its int8 form
(~8 GB) fits with room for the KV cache — which is exactly the
configuration the serving benchmark runs.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

# Tree keys never quantized: norms are vectors (negligible bytes) and
# the MoE router decides top-k expert identity (precision-critical).
_SKIP_KEYS = frozenset({'attn_norm', 'mlp_norm', 'final_norm',
                        'router'})
# Keys quantized per-ROW (scale over the last axis) because they are
# consumed by gather, not matmul.
_ROW_KEYS = frozenset({'tok_emb'})

# Uniform int8 in [-127, 127] has std sqrt((255^2 - 1) / 12) — used by
# init_quantized_params to pick scales that reproduce the bf16 init's
# fan-in-normalized weight std.
_INT8_UNIFORM_STD = 73.6116


def quantize_kv(x: jax.Array) -> tuple:
    """Symmetric int8 per-vector quantization over head_dim for the
    KV cache. Decode is cache-bandwidth-bound: int8 halves the bytes
    per step vs bf16, which at equal HBM budget doubles the batch —
    the same lever JetStream pulls with quantize_kvcache. Scale is
    per (position, kv-head) vector: accurate enough that greedy
    decode matches bf16 on short horizons (tested), 1/16 the overhead
    bytes. The paged decode kernel (ops.decode_attention) applies
    these scales in-register, fused into the attention contraction.
    """
    scale = jnp.max(jnp.abs(x), axis=-1) / 127.0
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.round(x / scale[..., None]).astype(jnp.int8)
    return q, scale.astype(jnp.bfloat16)


def dequantize_kv(q: jax.Array, scale: jax.Array, dtype) -> jax.Array:
    return q.astype(dtype) * scale[..., None].astype(dtype)


def is_quantized(params: Dict) -> bool:
    """True if the tree contains any {'q', 's'} quantized leaf."""
    if isinstance(params, dict):
        if set(params.keys()) == {'q', 's'}:
            return True
        return any(is_quantized(v) for v in params.values())
    return False


def _quantize_leaf(w: jax.Array, axis: int) -> Dict[str, jax.Array]:
    wf = w.astype(jnp.float32)
    s = jnp.max(jnp.abs(wf), axis=axis) / 127.0
    s = jnp.maximum(s, 1e-12)
    q = jnp.round(wf / jnp.expand_dims(s, axis))
    return {'q': q.astype(jnp.int8), 's': s}


def dequantize_leaf(w: Dict[str, jax.Array], axis: int,
                    dtype=jnp.float32) -> jax.Array:
    return (w['q'].astype(dtype) *
            jnp.expand_dims(w['s'], axis).astype(dtype))


def quantize_params(params: Dict) -> Dict:
    """Quantize a dense param tree (llama or moe family) to int8.

    Matmul weights quantize over their contraction axis (-2: scale
    per output channel); embedding tables per-row (-1). Stacked layer
    and expert leading axes are untouched — a [L, E, in, out] MoE
    expert bank gets scales [L, E, out].
    """
    out: Dict[str, Any] = {}
    for k, v in params.items():
        if isinstance(v, dict):
            out[k] = quantize_params(v)
        elif k in _SKIP_KEYS:
            out[k] = v
        elif k in _ROW_KEYS:
            out[k] = _quantize_leaf(v, -1)
        else:
            out[k] = _quantize_leaf(v, -2)
    return out


def quantize_specs(specs: Dict, params: Dict) -> Dict:
    """PartitionSpec tree matching ``quantize_params(params)``.

    The int8 payload keeps the dense leaf's spec; the scale drops the
    spec entry of the reduced axis (contraction axis for matmuls, the
    trailing dim for embeddings), so e.g. wq P(None, 'fsdp', 'tp')
    -> {'q': P(None, 'fsdp', 'tp'), 's': P(None, 'tp')}.
    """
    from jax.sharding import PartitionSpec as P
    out: Dict[str, Any] = {}
    for k, spec in specs.items():
        if isinstance(spec, dict):
            out[k] = quantize_specs(spec, params[k])
            continue
        leaf = params[k]
        if not (isinstance(leaf, dict) and set(leaf) == {'q', 's'}):
            out[k] = spec
            continue
        axis = -1 if k in _ROW_KEYS else -2
        entries = list(spec) + [None] * (leaf['q'].ndim - len(spec))
        del entries[axis]
        out[k] = {'q': spec, 's': P(*entries)}
    return out


def qdot(x: jax.Array, w, cdt,
         preferred: Optional[Any] = None) -> jax.Array:
    """``x @ w`` where ``w`` is a dense array OR a quantized leaf.

    For quantized weights the int8 payload is the matmul operand (XLA
    fuses the int8->cdt convert into the dot's HBM read — never
    materialize a dequantized copy; decode is weight-bandwidth-bound)
    and the per-output-channel scale multiplies the result.
    """
    if isinstance(w, dict):
        y = jnp.matmul(x, w['q'].astype(cdt),
                       preferred_element_type=preferred)
        return y * w['s'].astype(y.dtype)
    return jnp.matmul(x, w.astype(cdt),
                      preferred_element_type=preferred)


def qdot_a8(x: jax.Array, w, cdt,
            preferred: Optional[Any] = None) -> jax.Array:
    """W8A8 matmul: dynamic per-token int8 activations against an
    int8 weight leaf, accumulating in int32 on the MXU's int8 path
    (measured 1.35x bf16 matmul throughput on v5e through XLA's
    lowering; the chip's nominal int8 peak is 2x). Used for PREFILL
    only — decode is weight-bandwidth-bound, where weight-only
    quantization is already optimal and activation rounding would be
    pure accuracy loss. Per-token scales (max|x| along the feature
    axis) factor out of the contraction exactly like the weight's
    per-output-channel scales, so dequantization is one outer-product
    multiply on the int32 result. Dense weights fall back to qdot.
    """
    if not isinstance(w, dict):
        return qdot(x, w, cdt, preferred)
    from jax import lax
    sx = jnp.maximum(
        jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1,
                keepdims=True) / 127.0, 1e-8)
    xq = jnp.round(x.astype(jnp.float32) / sx).astype(jnp.int8)
    y = lax.dot_general(xq, w['q'],
                        (((x.ndim - 1,), (0,)), ((), ())),
                        preferred_element_type=jnp.int32)
    out = y.astype(jnp.float32) * sx * w['s'].astype(jnp.float32)
    return out.astype(preferred or cdt)


def qembed(emb, tokens: jax.Array, cdt) -> jax.Array:
    """Embedding lookup for a dense or per-row-quantized table."""
    if isinstance(emb, dict):
        return (emb['q'][tokens].astype(cdt) *
                emb['s'][tokens][..., None].astype(cdt))
    return emb.astype(cdt)[tokens]


def qindex(w, e) -> Any:
    """Index an expert bank along its leading expert axis, preserving
    quantization ({'q': q[e], 's': s[e]})."""
    if isinstance(w, dict):
        return {'q': w['q'][e], 's': w['s'][e]}
    return w[e]


def init_quantized_params(cfg, key: jax.Array) -> Dict:
    """Random params born int8 — the structure ``quantize_params``
    would produce, without ever materializing the bf16 tree (an 8B
    bf16 tree is 16 GB; its int8 form fits the serving chip).

    Weight values are uniform int8 with per-channel scales chosen so
    the dequantized std matches the dense init's fan_in**-0.5 —
    magnitudes (hence activation/logit ranges and step timings) match
    a real quantized checkpoint; values are random.
    """
    from skypilot_tpu import models
    fam = models.family(cfg)
    shapes = jax.eval_shape(lambda k: fam.init_params(cfg, k),
                            jax.ShapeDtypeStruct((2,), jnp.uint32))

    def build(tree, key):
        out: Dict[str, Any] = {}
        for k, v in tree.items():
            key, sub = jax.random.split(key)
            if isinstance(v, dict):
                out[k] = build(v, sub)
            elif k in _SKIP_KEYS:
                # Same skip set as quantize_params, so the two trees
                # always share one structure. Norms init to ones; the
                # router (the one skipped matmul) gets the fan-in init.
                if k == 'router':
                    out[k] = (jax.random.normal(sub, v.shape,
                                                jnp.float32)
                              * v.shape[-2]**-0.5).astype(
                                  cfg.param_dtype)
                else:
                    out[k] = jnp.ones(v.shape, cfg.param_dtype)
            else:
                axis = -1 if k in _ROW_KEYS else -2
                fan_in = v.shape[axis]
                s_shape = list(v.shape)
                del s_shape[axis]
                q = jax.random.randint(sub, v.shape, -127, 128,
                                       jnp.int8)
                s = jnp.full(tuple(s_shape),
                             fan_in**-0.5 / _INT8_UNIFORM_STD,
                             jnp.float32)
                out[k] = {'q': q, 's': s}
        return out

    return build(shapes, key)


def quantized_bytes(params: Dict) -> int:
    """Total on-device bytes of a (possibly quantized) param tree."""
    return sum(x.size * x.dtype.itemsize
               for x in jax.tree.leaves(params))


def quantize_checkpoint(in_path: str, out_path: str, cfg) -> Dict:
    """Quantize a dense orbax checkpoint to int8 ON THE HOST and save
    it back — the offline step that makes a real 8B checkpoint
    servable on a 16 GB chip (its bf16 tree could never materialize
    in HBM to quantize there; host RAM holds it once, here).

    The saved tree is exactly ``quantize_params``'s structure, so
    ``serving_http --checkpoint <out> --checkpoint-quantized
    --weight-quant`` restores it shard-by-shard straight to device.
    """
    import os

    import orbax.checkpoint as ocp

    from skypilot_tpu import models
    from skypilot_tpu.models import gpt2 as gpt2_mod
    if isinstance(cfg, gpt2_mod.GPT2Config):
        # Same family gate as ServingEngine: the quantization scheme
        # is structured around the Llama/MoE param tree (2-D+ matmul
        # leaves with a contraction axis). GPT-2's tree carries 1-D
        # leaves (e.g. biases) whose axis=-2 scale reduction crashes
        # _quantize_leaf MID-RUN — after minutes of host restore work.
        from skypilot_tpu import exceptions
        raise exceptions.NotSupportedError(
            'int8 quantization supports the Llama and MoE families; '
            'GPT-2 is a training family here (its 1-D param leaves '
            'have no per-output-channel scale axis).')
    fam = models.family(cfg)
    cpu = jax.devices('cpu')[0]
    host = jax.sharding.SingleDeviceSharding(cpu)
    # EXPLICIT host sharding on every target leaf: an unsharded
    # target makes orbax re-use the checkpoint's saved sharding file,
    # so a TPU-saved training checkpoint would restore back into HBM
    # — the exact OOM this tool exists to avoid.
    target = jax.tree.map(
        lambda sd: jax.ShapeDtypeStruct(sd.shape, sd.dtype,
                                        sharding=host),
        jax.eval_shape(
            lambda: fam.init_params(cfg, jax.random.PRNGKey(0))))
    ckptr = ocp.StandardCheckpointer()
    with jax.default_device(cpu):
        params = ckptr.restore(
            os.path.abspath(os.path.expanduser(in_path)), target)
        qparams = jax.jit(quantize_params)(params)
        qparams = jax.block_until_ready(qparams)
    ckptr.save(os.path.abspath(os.path.expanduser(out_path)), qparams)
    ckptr.wait_until_finished()
    return qparams


def _main() -> None:
    import argparse

    from skypilot_tpu import models
    parser = argparse.ArgumentParser(
        description='Quantize a dense checkpoint to int8 weights '
        '(host-side; serve with serving_http --checkpoint-quantized).')
    parser.add_argument('in_path')
    parser.add_argument('out_path')
    parser.add_argument('--model', required=True,
                        help="Config preset name, e.g. 'llama3_8b'.")
    args = parser.parse_args()
    # bf16 restore target: presets default to f32 param_dtype (a
    # training choice), which would make orbax upcast the checkpoint
    # on restore and DOUBLE host peak RAM (an 8B tree: 32 GB instead
    # of 16). Checkpoints worth quantizing are bf16.
    import jax.numpy as _jnp

    from skypilot_tpu import exceptions
    cfg = models.config_preset(args.model)(param_dtype=_jnp.bfloat16)
    try:
        quantize_checkpoint(args.in_path, args.out_path, cfg)
    except exceptions.NotSupportedError as e:
        # Family gate (GPT-2 etc.): a clean one-line CLI error, not a
        # traceback out of _quantize_leaf.
        raise SystemExit(f'error: {e}') from None
    print(f'Quantized {args.in_path} -> {args.out_path}')


if __name__ == '__main__':
    _main()
