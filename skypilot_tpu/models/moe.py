"""Mixture-of-Experts decoder (Mixtral-style) with expert parallelism.

The reference ships MoE models only as serve recipes (llm/mixtral/,
llm/dbrx/ — YAML invoking vLLM; SURVEY.md §2.11 lists expert
parallelism as recipe-level). Here MoE is a first-class model family:
the Llama block's dense SwiGLU is replaced by a top-k routed expert
layer, built the TPU way —

- **Dense dispatch, static shapes** (Switch-Transformer style): a
  [tokens, experts, capacity] combine tensor turns routing into three
  einsums XLA maps straight onto the MXU. No ragged gather/scatter,
  no recompilation; over-capacity tokens drop (standard capacity-
  factor semantics).
- **Expert parallelism over the 'tp' mesh axis**: expert weights are
  sharded one-expert-group-per-device (P on the E dim), so the
  dispatch einsum becomes XLA's all-to-all — the EP layout — while
  attention stays Megatron-sharded exactly as in the dense model.
- **Load-balancing aux loss** (router z-loss omitted for brevity):
  mean(expert fraction * router probability) * n_experts, added to
  the LM loss with ``router_aux_coef``.

API mirrors models.llama (init_params / param_specs / forward /
loss_fn), so the same train step and checkpointing drive both
families. KV-cache serving (models/inference, ServingEngine) serves
MoE too, with DROPLESS routing (``moe_block_dropless``): capacity
drops are a training device whose pattern depends on batch
composition, which served tokens must not.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from skypilot_tpu.models.llama import (ACT_SPEC, LlamaConfig,
                                       _attention, _rmsnorm, _rope,
                                       remat_layer_fn)


@dataclasses.dataclass(frozen=True)
class MoEConfig(LlamaConfig):
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    router_aux_coef: float = 0.01
    # Training dispatch: 'sorted' (gather/scatter into [E, C] slots —
    # no [T, E, C] combine einsums, the single-chip MFU win),
    # 'dense' (combine-tensor einsums — the form XLA maps onto
    # all-to-all when experts shard over 'ep'), or 'auto' (sorted
    # when ep == 1, dense otherwise). Both produce the IDENTICAL
    # capacity-drop pattern (slot-major fill), so a checkpoint
    # trains the same mixture either way.
    dispatch: str = 'auto'
    # Serving-side expert dispatch: 'dropless' runs all E experts per
    # token (exact, batch-independent — right for small E);
    # 'capacity' gathers tokens into [E, C] slots (C from
    # infer_capacity_factor) — E/k-fold less expert compute, the form
    # that scales to E=64. With infer_capacity_factor >= n_experts /
    # top_k the capacity path is provably dropless too (C >= T).
    infer_dispatch: str = 'dropless'
    infer_capacity_factor: float = 0.0  # 0 = auto: n_experts / top_k

    # ---- presets -------------------------------------------------
    @classmethod
    def tiny_moe(cls, **kw) -> 'MoEConfig':
        d = dict(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                 n_kv_heads=2, ffn_dim=128, max_seq=128,
                 n_experts=4, top_k=2,
                 param_dtype=jnp.float32, compute_dtype=jnp.float32)
        d.update(kw)
        return cls(**d)

    @classmethod
    def tpu_moe_1b(cls, **kw) -> 'MoEConfig':
        """~1.9B-param (8 experts, ~0.7B active) single-chip MoE:
        tpu_1b's attention stack with the dense ffn split into 8
        experts of ffn_dim 2048, top-2 routed — fits a 16 GB v5e for
        serving benchmarks of the MoE family."""
        d = dict(vocab_size=128256, dim=2048, n_layers=16, n_heads=16,
                 n_kv_heads=8, ffn_dim=2048, max_seq=8192,
                 n_experts=8, top_k=2)
        d.update(kw)
        return cls(**d)

    @classmethod
    def mixtral_8x7b(cls, **kw) -> 'MoEConfig':
        """Mixtral-8x7B shape (public): the MoE flagship."""
        d = dict(vocab_size=32000, dim=4096, n_layers=32, n_heads=32,
                 n_kv_heads=8, ffn_dim=14336, max_seq=8192,
                 n_experts=8, top_k=2, rope_theta=1e6)
        d.update(kw)
        return cls(**d)


def init_params(cfg: MoEConfig, key: jax.Array) -> Dict:
    """Stacked-layer param pytree; experts carry a leading E dim."""
    k_emb, k_layers, k_head = jax.random.split(key, 3)
    hd, nl, ne = cfg.head_dim, cfg.n_layers, cfg.n_experts
    dt = cfg.param_dtype

    def dense_init(key, *shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) *
                fan_in**-0.5).astype(dt)

    ks = jax.random.split(k_layers, 8)
    return {
        'tok_emb': dense_init(k_emb, cfg.vocab_size, cfg.dim,
                              fan_in=cfg.dim),
        'layers': {
            'attn_norm': jnp.ones((nl, cfg.dim), dt),
            'wq': dense_init(ks[0], nl, cfg.dim, cfg.n_heads * hd,
                             fan_in=cfg.dim),
            'wk': dense_init(ks[1], nl, cfg.dim, cfg.n_kv_heads * hd,
                             fan_in=cfg.dim),
            'wv': dense_init(ks[2], nl, cfg.dim, cfg.n_kv_heads * hd,
                             fan_in=cfg.dim),
            'wo': dense_init(ks[3], nl, cfg.n_heads * hd, cfg.dim,
                             fan_in=cfg.n_heads * hd),
            'mlp_norm': jnp.ones((nl, cfg.dim), dt),
            'router': dense_init(ks[4], nl, cfg.dim, ne,
                                 fan_in=cfg.dim),
            'w_gate': dense_init(ks[5], nl, ne, cfg.dim, cfg.ffn_dim,
                                 fan_in=cfg.dim),
            'w_up': dense_init(ks[6], nl, ne, cfg.dim, cfg.ffn_dim,
                               fan_in=cfg.dim),
            'w_down': dense_init(ks[7], nl, ne, cfg.ffn_dim, cfg.dim,
                                 fan_in=cfg.ffn_dim),
        },
        'final_norm': jnp.ones((cfg.dim,), dt),
        'lm_head': dense_init(k_head, cfg.dim, cfg.vocab_size,
                              fan_in=cfg.dim),
    }


def param_specs(cfg: MoEConfig, pp: bool = False) -> Dict:
    """Expert parallelism over the 'ep' mesh axis: expert banks shard
    their E dim over 'ep' (token dispatch to expert shards becomes an
    XLA all-to-all across it — the EP layout, SURVEY §2.11), while
    each expert's ffn dim shards Megatron-style over 'tp' and
    attention stays Megatron-sharded exactly as in the dense model.
    On a mesh without an 'ep' axis (or ep=1) the specs degrade
    gracefully: experts replicate, tp still splits the expert ffn."""
    del cfg
    if pp:
        raise NotImplementedError(
            "MoE with a pp>1 flagship mesh is not wired up; use "
            "parallel.pipeline.pipeline_apply (the MoE GPipe path) "
            "or pp=1.")
    return {
        'tok_emb': P('tp', 'fsdp'),
        'layers': {
            'attn_norm': P(None, None),
            'wq': P(None, 'fsdp', 'tp'),
            'wk': P(None, 'fsdp', 'tp'),
            'wv': P(None, 'fsdp', 'tp'),
            'wo': P(None, 'tp', 'fsdp'),
            'mlp_norm': P(None, None),
            'router': P(None, 'fsdp', None),
            'w_gate': P(None, 'ep', 'fsdp', 'tp'),
            'w_up': P(None, 'ep', 'fsdp', 'tp'),
            'w_down': P(None, 'ep', 'tp', 'fsdp'),
        },
        'final_norm': P(None),
        'lm_head': P('fsdp', 'tp'),
    }


def _route(xf: jax.Array, router: jax.Array,
           cfg: MoEConfig) -> Tuple[jax.Array, jax.Array]:
    """Top-k routing -> (combine [T, E, C], aux loss scalar)."""
    t = xf.shape[0]
    e, k = cfg.n_experts, cfg.top_k
    capacity = _capacity(cfg, t)
    weights, idx, probs = _topk_weights(xf, router, cfg)

    combine = jnp.zeros((t, e, capacity), jnp.float32)
    # Expert fill is tracked ACROSS the k slots: slot 1 continues
    # where slot 0 left off, so two tokens never share a capacity row.
    fill = jnp.zeros((e,), jnp.int32)
    for slot in range(k):
        onehot = jax.nn.one_hot(idx[:, slot], e, dtype=jnp.int32)
        pos = fill[None, :] + jnp.cumsum(onehot, axis=0) - onehot
        pos = jnp.sum(pos * onehot, axis=-1)          # [T]
        keep = pos < capacity
        cap_onehot = jax.nn.one_hot(pos, capacity,
                                    dtype=jnp.float32)  # [T, C]
        combine += (weights[:, slot, None, None] *
                    keep[:, None, None] *
                    onehot[:, :, None].astype(jnp.float32) *
                    cap_onehot[:, None, :])
        fill = fill + jnp.sum(onehot, axis=0)

    return combine, _aux_loss(idx, probs, e)


def _aux_loss(idx: jax.Array, probs: jax.Array,
              n_experts: int) -> jax.Array:
    """Load-balancing aux (Switch eq. 4): fraction of tokens routed
    to each expert (top-1 assignment) x mean router prob, scaled by
    E. ONE definition shared by both dispatches — sorted and dense
    training must optimize the identical objective or a checkpoint
    would train a different mixture depending on dispatch."""
    top1 = jax.nn.one_hot(idx[:, 0], n_experts, dtype=jnp.float32)
    return n_experts * jnp.sum(
        jnp.mean(top1, axis=0) * jnp.mean(probs, axis=0))


def _topk_weights(xf: jax.Array, router: jax.Array,
                  cfg: MoEConfig) -> Tuple[jax.Array, jax.Array,
                                           jax.Array]:
    """Shared router prologue: (weights [T,k], idx [T,k], probs
    [T,E]). ONE definition for training and inference — the f32 cast
    placement and renorm floor define the expert mixture a checkpoint
    was trained with; a serving-side copy that drifted would silently
    change which experts serve each token."""
    logits = (xf @ router.astype(xf.dtype)).astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    weights, idx = lax.top_k(probs, cfg.top_k)
    weights = weights / jnp.maximum(
        weights.sum(-1, keepdims=True), 1e-9)
    return weights, idx, probs


def moe_block_dropless(x: jax.Array, lp: Dict,
                       cfg: MoEConfig) -> jax.Array:
    """Exact top-k expert mixing with NO capacity drops — the
    INFERENCE routing. Capacity dropping is a training-throughput
    device (static dispatch shapes, load-balance pressure) whose drop
    pattern depends on which other tokens share the batch; under
    incremental decode that would make generated tokens depend on
    batch composition. Serving engines therefore route dropless (as
    vLLM/JetStream-class MoE serving does): every token reaches its
    exact top-k experts. Cost: all E experts run for every token
    (E/k-fold ffn flops) — the simple dense form; capacity dispatch
    with an ample factor is the optimization when E is large."""
    cdt = cfg.compute_dtype
    b, s, d = x.shape
    xf = x.reshape(b * s, d)
    weights, idx, probs = _topk_weights(xf, lp['router'], cfg)
    wfull = jnp.zeros_like(probs)
    for slot in range(cfg.top_k):
        wfull += (weights[:, slot, None] *
                  jax.nn.one_hot(idx[:, slot], cfg.n_experts,
                                 dtype=jnp.float32))
    wfull = wfull.astype(cdt)
    # Loop over experts (static unroll, E is small): the all-experts
    # einsum form materializes [T, E, F] activations — at Mixtral
    # scale (S 8192, E 8, F 14336) that is gigabytes per layer and
    # OOMs prefill. Per-expert matmuls keep the working set at
    # [T, F] while computing the identical dropless result.
    y = jnp.zeros_like(xf)
    for e in range(cfg.n_experts):
        y = y + wfull[:, e, None] * _expert_swiglu(xf, lp, e, cdt)
    return y.reshape(b, s, d)


def _capacity(cfg: MoEConfig, t: int) -> int:
    return max(4, int(cfg.capacity_factor * t * cfg.top_k /
                      cfg.n_experts))


def _expert_swiglu(x: jax.Array, lp: Dict, e, cdt) -> jax.Array:
    """ONE expert's SwiGLU on [T, D] tokens — the single definition
    both the dropless all-experts loop and the quantized capacity
    path run, so the two serving dispatches can never diverge.
    Handles dense and int8 expert banks (qdot/qindex)."""
    from skypilot_tpu.models.quantization import qdot, qindex
    gate = jax.nn.silu(qdot(x, qindex(lp['w_gate'], e), cdt))
    up = qdot(x, qindex(lp['w_up'], e), cdt)
    return qdot(gate * up, qindex(lp['w_down'], e), cdt)


def _expert_matmul(expert_in: jax.Array, w, cdt,
                   eq: str) -> jax.Array:
    """Batched per-expert matmul ([E, C, .] x [E, ., .]) for dense or
    int8-quantized expert banks (scale is per (expert, out-channel):
    broadcast over the capacity dim)."""
    if isinstance(w, dict):
        y = jnp.einsum(eq, expert_in, w['q'].astype(cdt))
        return y * w['s'][:, None].astype(y.dtype)
    return jnp.einsum(eq, expert_in, w.astype(cdt))


def _expert_ffn(expert_in: jax.Array, lp: Dict,
                cfg: MoEConfig) -> jax.Array:
    """SwiGLU over every expert's [C, D] slot block: [E, C, D] ->
    [E, C, D]. The three einsums are the MoE layer's MXU work."""
    cdt = cfg.compute_dtype
    if isinstance(lp['w_gate'], dict):
        # int8 expert banks run as per-expert 2-D dots (static E
        # unroll): the batched 3-D einsum with an int8 operand
        # kernel-faults the v5e TPU runtime (worker crash, observed
        # round 5 and reproducible), while 2-D int8 dots are the
        # dropless loop's proven path. Same math, same flops.
        return jnp.stack([
            _expert_swiglu(expert_in[e], lp, e, cdt)
            for e in range(cfg.n_experts)
        ])
    gate = jax.nn.silu(
        _expert_matmul(expert_in, lp['w_gate'], cdt, 'ecd,edf->ecf'))
    up = _expert_matmul(expert_in, lp['w_up'], cdt, 'ecd,edf->ecf')
    return _expert_matmul(gate * up, lp['w_down'], cdt,
                          'ecf,efd->ecd')


def _sorted_assignment(idx: jax.Array, n_experts: int, capacity: int
                       ) -> Tuple[jax.Array, jax.Array, jax.Array,
                                  jax.Array]:
    """Sorted routing plan: (tok [A], dest [A], keep [A]), A = T*k.

    Assignments flatten SLOT-MAJOR (all slot-0 picks in token order,
    then slot-1, ...) and stable-sort by expert, so each expert's
    capacity rows fill in exactly the order the dense combine-tensor
    path fills them (_route tracks fill across slots the same way) —
    the two dispatches drop the SAME tokens and a checkpoint trains
    the same mixture under either. ``dest`` is the flat
    expert*capacity+rank slot; over-capacity assignments point at a
    scratch row (n_experts*capacity) that is computed and discarded.
    """
    t, k = idx.shape
    eflat = jnp.transpose(idx).reshape(-1)            # [A] slot-major
    order = jnp.argsort(eflat, stable=True)
    sorted_e = eflat[order]
    counts = jnp.bincount(eflat, length=n_experts)
    starts = jnp.cumsum(counts) - counts
    rank = jnp.arange(t * k) - starts[sorted_e]
    keep = rank < capacity
    dest = jnp.where(keep, sorted_e * capacity + rank,
                     n_experts * capacity)
    return order % t, dest, keep, order


def _moe_sorted(xf: jax.Array, lp: Dict, cfg: MoEConfig,
                capacity: int) -> Tuple[jax.Array, jax.Array]:
    """Sorted/gather dispatch: route T tokens into [E, C] expert slot
    blocks by GATHER (argsort + take), run the batched expert SwiGLU,
    and scatter-add weighted outputs back.

    Vs the dense combine-tensor form (``_moe_dense``): identical drop
    semantics, but the two [T, E, C] dispatch/combine einsums —
    2*T*E*C*D flops each, comparable to an expert matmul once E*C is
    a few multiples of T — become index ops at O(T*k*D) bytes. This
    is what lifts single-chip MoE train MFU (VERDICT r4 item 4).
    """
    cdt = cfg.compute_dtype
    t, d = xf.shape
    e = cfg.n_experts
    weights, idx, probs = _topk_weights(xf, lp['router'], cfg)
    tok, dest, keep, order = _sorted_assignment(idx, e, capacity)
    buf = jnp.zeros((e * capacity + 1, d), cdt)
    expert_in = buf.at[dest].set(xf[tok])[:-1].reshape(e, capacity, d)
    out_e = _expert_ffn(expert_in, lp, cfg).reshape(e * capacity, d)
    out_e = jnp.concatenate(
        [out_e, jnp.zeros((1, d), out_e.dtype)])      # scratch row
    order_w = jnp.transpose(weights).reshape(-1)[order]
    contrib = out_e[dest] * (order_w * keep)[:, None].astype(cdt)
    y = jnp.zeros((t, d), cdt).at[tok].add(contrib)
    return y, _aux_loss(idx, probs, e)


def _moe_dense(xf: jax.Array, lp: Dict, cfg: MoEConfig,
               mesh=None) -> Tuple[jax.Array, jax.Array]:
    """Dense combine-tensor dispatch: three einsums XLA maps straight
    onto the MXU — and, with experts sharded over 'ep', onto an
    all-to-all: the dispatch einsum's output is constrained to
    P('ep', ...), so the partitioner moves each token's row to its
    expert's shard (the EP exchange), runs the expert ffn locally,
    and the combine einsum routes results back."""
    cdt = cfg.compute_dtype

    def ec(v, spec):
        if mesh is None or mesh.shape.get('ep', 1) == 1:
            return v
        return lax.with_sharding_constraint(
            v, jax.sharding.NamedSharding(mesh, spec))

    combine, aux = _route(xf, lp['router'], cfg)
    dispatch = (combine > 0).astype(cdt)              # [T, E, C]
    expert_in = ec(jnp.einsum('tec,td->ecd', dispatch, xf),
                   P('ep', None, None))
    out_e = ec(_expert_ffn(expert_in, lp, cfg), P('ep', None, None))
    y = jnp.einsum('tec,ecd->td', combine.astype(cdt), out_e)
    return y, aux


def _moe_block(x: jax.Array, lp: Dict, cfg: MoEConfig,
               mesh=None) -> Tuple[jax.Array, jax.Array]:
    """x [B, S, D] -> (y [B, S, D], aux loss). Dispatch choice per
    cfg.dispatch: 'auto' = sorted on a single chip / ep=1 mesh (MFU),
    dense when experts are ep-sharded (all-to-all form)."""
    b, s, d = x.shape
    xf = x.reshape(b * s, d)
    use_sorted = cfg.dispatch == 'sorted' or (
        cfg.dispatch == 'auto' and
        (mesh is None or mesh.shape.get('ep', 1) == 1))
    if use_sorted:
        y, aux = _moe_sorted(xf, lp, cfg, _capacity(cfg, b * s))
    else:
        y, aux = _moe_dense(xf, lp, cfg, mesh)
    return y.reshape(b, s, d), aux


def moe_block_capacity(x: jax.Array, lp: Dict,
                       cfg: MoEConfig) -> jax.Array:
    """Capacity-gather expert dispatch for SERVING — the E=64-scale
    FORM: expert compute is C*E slot rows, set by the capacity
    factor, independent of E (moe_block_dropless's all-experts loop
    computes T*E rows, linear in E).

    Capacity C = ceil(cf * T * k / E) with cf =
    infer_capacity_factor (0 = auto E/k), clamped to T. The cf knob
    trades compute for drop risk: at the auto cf (C = T) NO
    assignment can drop (an expert can receive at most T) — exactly
    dropless, same flops as the dropless loop (correctness mode, the
    parity tests' setting); at cf < E/k expert compute shrinks
    proportionally (cf=1 computes k/E of the dropless flops — the
    E=64 win) but over-capacity assignments drop batch-dependently,
    which the operator must accept knowingly for served traffic."""
    import math
    b, s, d = x.shape
    t = b * s
    cf = cfg.infer_capacity_factor or (cfg.n_experts / cfg.top_k)
    capacity = min(t, max(1, math.ceil(cf * t * cfg.top_k /
                                       cfg.n_experts)))
    y, _ = _moe_sorted(x.reshape(t, d), lp, cfg, capacity)
    return y.reshape(b, s, d)


def forward_hidden(params: Dict, tokens: jax.Array, cfg: MoEConfig,
                   mesh=None,
                   dropless: bool = False) -> Tuple[jax.Array, jax.Array]:
    """tokens [B, S] -> (hidden [B, S, D], total aux loss).

    ``dropless=True`` routes with exact top-k mixing (no capacity
    drops) — inference semantics, used by the KV-cache oracle."""
    cdt = cfg.compute_dtype
    b, s = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32),
                                 (b, s))

    def constrain(x, spec):
        if mesh is None:
            return x
        return lax.with_sharding_constraint(
            x, jax.sharding.NamedSharding(mesh, spec))

    emb = constrain(params['tok_emb'], P(None, None))
    x = emb.astype(cdt)[tokens]
    x = constrain(x, ACT_SPEC)

    def layer(carry, lp):
        x, aux = carry
        # checkpoint_name tags match llama.forward_hidden's, so the
        # selective remat policies ('kvo'/'qkvo' in remat_layer_fn)
        # save the same tensors for the MoE family — without them
        # save_only_these_names finds nothing and silently degrades
        # to full remat (r4 advisor finding).
        from jax.ad_checkpoint import checkpoint_name as name
        h = _rmsnorm(x, lp['attn_norm'], cfg.norm_eps)
        q = (h @ lp['wq'].astype(cdt)).reshape(b, s, cfg.n_heads,
                                               cfg.head_dim)
        k = (h @ lp['wk'].astype(cdt)).reshape(b, s, cfg.n_kv_heads,
                                               cfg.head_dim)
        v = (h @ lp['wv'].astype(cdt)).reshape(b, s, cfg.n_kv_heads,
                                               cfg.head_dim)
        q = name(_rope(q, positions, cfg.rope_theta), 'attn_q')
        k = name(_rope(k, positions, cfg.rope_theta), 'attn_k')
        v = name(v, 'attn_v')
        o = _attention(q, k, v, cfg, mesh)
        o = name(o.reshape(b, s, cfg.n_heads * cfg.head_dim), 'attn_o')
        x = x + constrain(o @ lp['wo'].astype(cdt), ACT_SPEC)

        h = _rmsnorm(x, lp['mlp_norm'], cfg.norm_eps)
        if dropless:
            y, layer_aux = (moe_block_dropless(h, lp, cfg),
                            jnp.zeros((), jnp.float32))
        else:
            y, layer_aux = _moe_block(h, lp, cfg, mesh)
        x = x + constrain(y, ACT_SPEC)
        return (x, aux + layer_aux), None

    (x, aux), _ = lax.scan(remat_layer_fn(layer, cfg.remat),
                           (x, jnp.zeros((), jnp.float32)),
                           params['layers'])
    return _rmsnorm(x, params['final_norm'], cfg.norm_eps), aux


def forward(params: Dict, tokens: jax.Array, cfg: MoEConfig,
            mesh=None, dropless: bool = False) -> jax.Array:
    x, _ = forward_hidden(params, tokens, cfg, mesh,
                          dropless=dropless)
    return jnp.einsum('bsd,dv->bsv', x,
                      params['lm_head'].astype(cfg.compute_dtype),
                      preferred_element_type=jnp.float32)


def loss_fn(params: Dict, batch: Dict[str, jax.Array], cfg: MoEConfig,
            mesh=None) -> jax.Array:
    """Next-token CE (shared chunked_lm_loss — the [B, S, vocab]
    logits never materialize) + router load-balancing aux."""
    from skypilot_tpu.models.llama import (chunked_lm_loss,
                                           split_lm_batch)
    inputs, targets = split_lm_batch(batch)
    x, aux = forward_hidden(params, inputs, cfg, mesh)
    ce = chunked_lm_loss(
        x, params['lm_head'].astype(cfg.compute_dtype), targets, cfg)
    return ce + cfg.router_aux_coef * aux
