"""Mixture-of-Experts decoder (Mixtral-style) with expert parallelism.

The reference ships MoE models only as serve recipes (llm/mixtral/,
llm/dbrx/ — YAML invoking vLLM; SURVEY.md §2.11 lists expert
parallelism as recipe-level). Here MoE is a first-class model family:
the Llama block's dense SwiGLU is replaced by a top-k routed expert
layer, built the TPU way —

- **Dense dispatch, static shapes** (Switch-Transformer style): a
  [tokens, experts, capacity] combine tensor turns routing into three
  einsums XLA maps straight onto the MXU. No ragged gather/scatter,
  no recompilation; over-capacity tokens drop (standard capacity-
  factor semantics).
- **Expert parallelism over the 'tp' mesh axis**: expert weights are
  sharded one-expert-group-per-device (P on the E dim), so the
  dispatch einsum becomes XLA's all-to-all — the EP layout — while
  attention stays Megatron-sharded exactly as in the dense model.
- **Load-balancing aux loss** (router z-loss omitted for brevity):
  mean(expert fraction * router probability) * n_experts, added to
  the LM loss with ``router_aux_coef``.

API mirrors models.llama (init_params / param_specs / forward /
loss_fn), so the same train step and checkpointing drive both
families. KV-cache serving (models/inference, ServingEngine) serves
MoE too, with DROPLESS routing (``moe_block_dropless``): capacity
drops are a training device whose pattern depends on batch
composition, which served tokens must not.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from skypilot_tpu.models.llama import (ACT_SPEC, LlamaConfig,
                                       _attention, _rmsnorm, _rope,
                                       remat_layer_fn)


@dataclasses.dataclass(frozen=True)
class MoEConfig(LlamaConfig):
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    router_aux_coef: float = 0.01

    # ---- presets -------------------------------------------------
    @classmethod
    def tiny_moe(cls, **kw) -> 'MoEConfig':
        d = dict(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                 n_kv_heads=2, ffn_dim=128, max_seq=128,
                 n_experts=4, top_k=2,
                 param_dtype=jnp.float32, compute_dtype=jnp.float32)
        d.update(kw)
        return cls(**d)

    @classmethod
    def tpu_moe_1b(cls, **kw) -> 'MoEConfig':
        """~1.9B-param (8 experts, ~0.7B active) single-chip MoE:
        tpu_1b's attention stack with the dense ffn split into 8
        experts of ffn_dim 2048, top-2 routed — fits a 16 GB v5e for
        serving benchmarks of the MoE family."""
        d = dict(vocab_size=128256, dim=2048, n_layers=16, n_heads=16,
                 n_kv_heads=8, ffn_dim=2048, max_seq=8192,
                 n_experts=8, top_k=2)
        d.update(kw)
        return cls(**d)

    @classmethod
    def mixtral_8x7b(cls, **kw) -> 'MoEConfig':
        """Mixtral-8x7B shape (public): the MoE flagship."""
        d = dict(vocab_size=32000, dim=4096, n_layers=32, n_heads=32,
                 n_kv_heads=8, ffn_dim=14336, max_seq=8192,
                 n_experts=8, top_k=2, rope_theta=1e6)
        d.update(kw)
        return cls(**d)


def init_params(cfg: MoEConfig, key: jax.Array) -> Dict:
    """Stacked-layer param pytree; experts carry a leading E dim."""
    k_emb, k_layers, k_head = jax.random.split(key, 3)
    hd, nl, ne = cfg.head_dim, cfg.n_layers, cfg.n_experts
    dt = cfg.param_dtype

    def dense_init(key, *shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) *
                fan_in**-0.5).astype(dt)

    ks = jax.random.split(k_layers, 8)
    return {
        'tok_emb': dense_init(k_emb, cfg.vocab_size, cfg.dim,
                              fan_in=cfg.dim),
        'layers': {
            'attn_norm': jnp.ones((nl, cfg.dim), dt),
            'wq': dense_init(ks[0], nl, cfg.dim, cfg.n_heads * hd,
                             fan_in=cfg.dim),
            'wk': dense_init(ks[1], nl, cfg.dim, cfg.n_kv_heads * hd,
                             fan_in=cfg.dim),
            'wv': dense_init(ks[2], nl, cfg.dim, cfg.n_kv_heads * hd,
                             fan_in=cfg.dim),
            'wo': dense_init(ks[3], nl, cfg.n_heads * hd, cfg.dim,
                             fan_in=cfg.n_heads * hd),
            'mlp_norm': jnp.ones((nl, cfg.dim), dt),
            'router': dense_init(ks[4], nl, cfg.dim, ne,
                                 fan_in=cfg.dim),
            'w_gate': dense_init(ks[5], nl, ne, cfg.dim, cfg.ffn_dim,
                                 fan_in=cfg.dim),
            'w_up': dense_init(ks[6], nl, ne, cfg.dim, cfg.ffn_dim,
                               fan_in=cfg.dim),
            'w_down': dense_init(ks[7], nl, ne, cfg.ffn_dim, cfg.dim,
                                 fan_in=cfg.ffn_dim),
        },
        'final_norm': jnp.ones((cfg.dim,), dt),
        'lm_head': dense_init(k_head, cfg.dim, cfg.vocab_size,
                              fan_in=cfg.dim),
    }


def param_specs(cfg: MoEConfig, pp: bool = False) -> Dict:
    """Expert parallelism: the E dim shards over 'tp' (experts replace
    the tp-sharded dense FFN); attention stays Megatron-sharded."""
    del cfg
    if pp:
        raise NotImplementedError(
            "MoE with a pp>1 flagship mesh is not wired up; use "
            "parallel.pipeline.pipeline_apply (the MoE GPipe path) "
            "or pp=1.")
    return {
        'tok_emb': P('tp', 'fsdp'),
        'layers': {
            'attn_norm': P(None, None),
            'wq': P(None, 'fsdp', 'tp'),
            'wk': P(None, 'fsdp', 'tp'),
            'wv': P(None, 'fsdp', 'tp'),
            'wo': P(None, 'tp', 'fsdp'),
            'mlp_norm': P(None, None),
            'router': P(None, 'fsdp', None),
            'w_gate': P(None, 'tp', 'fsdp', None),
            'w_up': P(None, 'tp', 'fsdp', None),
            'w_down': P(None, 'tp', None, 'fsdp'),
        },
        'final_norm': P(None),
        'lm_head': P('fsdp', 'tp'),
    }


def _route(xf: jax.Array, router: jax.Array,
           cfg: MoEConfig) -> Tuple[jax.Array, jax.Array]:
    """Top-k routing -> (combine [T, E, C], aux loss scalar)."""
    t = xf.shape[0]
    e, k = cfg.n_experts, cfg.top_k
    capacity = max(4, int(cfg.capacity_factor * t * k / e))
    weights, idx, probs = _topk_weights(xf, router, cfg)

    combine = jnp.zeros((t, e, capacity), jnp.float32)
    # Expert fill is tracked ACROSS the k slots: slot 1 continues
    # where slot 0 left off, so two tokens never share a capacity row.
    fill = jnp.zeros((e,), jnp.int32)
    for slot in range(k):
        onehot = jax.nn.one_hot(idx[:, slot], e, dtype=jnp.int32)
        pos = fill[None, :] + jnp.cumsum(onehot, axis=0) - onehot
        pos = jnp.sum(pos * onehot, axis=-1)          # [T]
        keep = pos < capacity
        cap_onehot = jax.nn.one_hot(pos, capacity,
                                    dtype=jnp.float32)  # [T, C]
        combine += (weights[:, slot, None, None] *
                    keep[:, None, None] *
                    onehot[:, :, None].astype(jnp.float32) *
                    cap_onehot[:, None, :])
        fill = fill + jnp.sum(onehot, axis=0)

    # Load-balancing aux (Switch eq. 4): fraction of tokens routed to
    # each expert (top-1 assignment) x mean router prob, scaled by E.
    top1 = jax.nn.one_hot(idx[:, 0], e, dtype=jnp.float32)
    aux = cfg.n_experts * jnp.sum(
        jnp.mean(top1, axis=0) * jnp.mean(probs, axis=0))
    return combine, aux


def _topk_weights(xf: jax.Array, router: jax.Array,
                  cfg: MoEConfig) -> Tuple[jax.Array, jax.Array,
                                           jax.Array]:
    """Shared router prologue: (weights [T,k], idx [T,k], probs
    [T,E]). ONE definition for training and inference — the f32 cast
    placement and renorm floor define the expert mixture a checkpoint
    was trained with; a serving-side copy that drifted would silently
    change which experts serve each token."""
    logits = (xf @ router.astype(xf.dtype)).astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    weights, idx = lax.top_k(probs, cfg.top_k)
    weights = weights / jnp.maximum(
        weights.sum(-1, keepdims=True), 1e-9)
    return weights, idx, probs


def moe_block_dropless(x: jax.Array, lp: Dict,
                       cfg: MoEConfig) -> jax.Array:
    """Exact top-k expert mixing with NO capacity drops — the
    INFERENCE routing. Capacity dropping is a training-throughput
    device (static dispatch shapes, load-balance pressure) whose drop
    pattern depends on which other tokens share the batch; under
    incremental decode that would make generated tokens depend on
    batch composition. Serving engines therefore route dropless (as
    vLLM/JetStream-class MoE serving does): every token reaches its
    exact top-k experts. Cost: all E experts run for every token
    (E/k-fold ffn flops) — the simple dense form; capacity dispatch
    with an ample factor is the optimization when E is large."""
    cdt = cfg.compute_dtype
    b, s, d = x.shape
    xf = x.reshape(b * s, d)
    weights, idx, probs = _topk_weights(xf, lp['router'], cfg)
    wfull = jnp.zeros_like(probs)
    for slot in range(cfg.top_k):
        wfull += (weights[:, slot, None] *
                  jax.nn.one_hot(idx[:, slot], cfg.n_experts,
                                 dtype=jnp.float32))
    wfull = wfull.astype(cdt)
    # Loop over experts (static unroll, E is small): the all-experts
    # einsum form materializes [T, E, F] activations — at Mixtral
    # scale (S 8192, E 8, F 14336) that is gigabytes per layer and
    # OOMs prefill. Per-expert matmuls keep the working set at
    # [T, F] while computing the identical dropless result.
    from skypilot_tpu.models.quantization import qdot, qindex
    y = jnp.zeros_like(xf)
    for e in range(cfg.n_experts):
        gate = jax.nn.silu(qdot(xf, qindex(lp['w_gate'], e), cdt))
        up = qdot(xf, qindex(lp['w_up'], e), cdt)
        out_e = qdot(gate * up, qindex(lp['w_down'], e), cdt)
        y = y + wfull[:, e, None] * out_e
    return y.reshape(b, s, d)


def _moe_block(x: jax.Array, lp: Dict, cfg: MoEConfig
               ) -> Tuple[jax.Array, jax.Array]:
    """x [B, S, D] -> (y [B, S, D], aux loss)."""
    cdt = cfg.compute_dtype
    b, s, d = x.shape
    xf = x.reshape(b * s, d)
    combine, aux = _route(xf, lp['router'], cfg)
    dispatch = (combine > 0).astype(cdt)              # [T, E, C]
    expert_in = jnp.einsum('tec,td->ecd', dispatch, xf)
    gate = jax.nn.silu(
        jnp.einsum('ecd,edf->ecf', expert_in,
                   lp['w_gate'].astype(cdt)))
    up = jnp.einsum('ecd,edf->ecf', expert_in, lp['w_up'].astype(cdt))
    out_e = jnp.einsum('ecf,efd->ecd', gate * up,
                       lp['w_down'].astype(cdt))
    y = jnp.einsum('tec,ecd->td', combine.astype(cdt), out_e)
    return y.reshape(b, s, d), aux


def forward_hidden(params: Dict, tokens: jax.Array, cfg: MoEConfig,
                   mesh=None,
                   dropless: bool = False) -> Tuple[jax.Array, jax.Array]:
    """tokens [B, S] -> (hidden [B, S, D], total aux loss).

    ``dropless=True`` routes with exact top-k mixing (no capacity
    drops) — inference semantics, used by the KV-cache oracle."""
    cdt = cfg.compute_dtype
    b, s = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32),
                                 (b, s))

    def constrain(x, spec):
        if mesh is None:
            return x
        return lax.with_sharding_constraint(
            x, jax.sharding.NamedSharding(mesh, spec))

    emb = constrain(params['tok_emb'], P(None, None))
    x = emb.astype(cdt)[tokens]
    x = constrain(x, ACT_SPEC)

    def layer(carry, lp):
        x, aux = carry
        h = _rmsnorm(x, lp['attn_norm'], cfg.norm_eps)
        q = (h @ lp['wq'].astype(cdt)).reshape(b, s, cfg.n_heads,
                                               cfg.head_dim)
        k = (h @ lp['wk'].astype(cdt)).reshape(b, s, cfg.n_kv_heads,
                                               cfg.head_dim)
        v = (h @ lp['wv'].astype(cdt)).reshape(b, s, cfg.n_kv_heads,
                                               cfg.head_dim)
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
        o = _attention(q, k, v, cfg, mesh)
        o = o.reshape(b, s, cfg.n_heads * cfg.head_dim)
        x = x + constrain(o @ lp['wo'].astype(cdt), ACT_SPEC)

        h = _rmsnorm(x, lp['mlp_norm'], cfg.norm_eps)
        if dropless:
            y, layer_aux = (moe_block_dropless(h, lp, cfg),
                            jnp.zeros((), jnp.float32))
        else:
            y, layer_aux = _moe_block(h, lp, cfg)
        x = x + constrain(y, ACT_SPEC)
        return (x, aux + layer_aux), None

    (x, aux), _ = lax.scan(remat_layer_fn(layer, cfg.remat),
                           (x, jnp.zeros((), jnp.float32)),
                           params['layers'])
    return _rmsnorm(x, params['final_norm'], cfg.norm_eps), aux


def forward(params: Dict, tokens: jax.Array, cfg: MoEConfig,
            mesh=None, dropless: bool = False) -> jax.Array:
    x, _ = forward_hidden(params, tokens, cfg, mesh,
                          dropless=dropless)
    return jnp.einsum('bsd,dv->bsv', x,
                      params['lm_head'].astype(cfg.compute_dtype),
                      preferred_element_type=jnp.float32)


def loss_fn(params: Dict, batch: Dict[str, jax.Array], cfg: MoEConfig,
            mesh=None) -> jax.Array:
    """Next-token CE + router load-balancing aux."""
    if 'inputs' in batch:
        inputs, targets = batch['inputs'], batch['targets']
    else:
        inputs, targets = batch['tokens'][:, :-1], batch['tokens'][:, 1:]
    x, aux = forward_hidden(params, inputs, cfg, mesh)
    logits = jnp.einsum('bsd,dv->bsv', x,
                        params['lm_head'].astype(cfg.compute_dtype),
                        preferred_element_type=jnp.float32)
    mask = (targets >= 0).astype(jnp.float32)
    targets = jnp.maximum(targets, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None],
                               axis=-1)[..., 0]
    ce = jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return ce + cfg.router_aux_coef * aux
