"""Llama-family decoder in functional JAX, sharding-native.

Architecture parity with the reference's finetune recipes
(llm/llama-3_1-finetuning/lora.yaml drives torchtune's Llama-3.1):
RMSNorm, rotary embeddings, grouped-query attention, SwiGLU MLP,
untied LM head. Implementation is TPU-idiomatic rather than a torch
translation: params are a pytree of stacked per-layer arrays consumed
by ``lax.scan`` (one trace for all layers), compute in bf16 with f32
accumulation, rematerialized layer body, and every weight/activation
carries a (dp, fsdp, sp, tp) PartitionSpec so the same code runs
single-chip or pjit-sharded over a pod slice.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from skypilot_tpu.ops import flash_attention, reference_attention
from skypilot_tpu.parallel.ring_attention import ring_attention_sharded


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 2048
    n_layers: int = 16
    n_heads: int = 16
    n_kv_heads: int = 8
    ffn_dim: int = 5632
    max_seq: int = 2048
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    attn_impl: str = 'auto'   # auto | flash | ring | xla
    # GPipe microbatches when the mesh has pp > 1 (0 = auto: 4 *
    # n_stages, bubble fraction (n-1)/(M+n-1) ≈ 19% at pp=2).
    pp_microbatches: int = 0
    # True = full remat; 'dots' = selective (save matmul outputs,
    # recompute elementwise); False = none.
    remat: Any = True
    loss_chunk: int = 512     # seq positions per cross-entropy chunk
    # Serving-only, DENSE family only: int8 ACTIVATIONS for prefill
    # matmuls against int8-quantized weights (quantization.qdot_a8)
    # — engages the MXU's int8 path. Decode stays weight-only
    # (bandwidth-bound); MoE expert blocks ignore this flag (their
    # dispatch paths are weight-only regardless).
    prefill_a8: bool = False

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    # ---- presets -------------------------------------------------
    @classmethod
    def tiny(cls, **kw) -> 'LlamaConfig':
        """CPU-test scale."""
        d = dict(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                 n_kv_heads=2, ffn_dim=128, max_seq=128,
                 param_dtype=jnp.float32, compute_dtype=jnp.float32)
        d.update(kw)
        return cls(**d)

    @classmethod
    def llama3_1b(cls, **kw) -> 'LlamaConfig':
        """Llama-3.2-1B shape (public): single-chip bench model."""
        d = dict(vocab_size=128256, dim=2048, n_layers=16, n_heads=32,
                 n_kv_heads=8, ffn_dim=8192, max_seq=2048)
        d.update(kw)
        return cls(**d)

    @classmethod
    def tpu_1b(cls, **kw) -> 'LlamaConfig':
        """1B-class config tuned for the TPU MXU: head_dim 128 (no
        tile padding), 2:1 GQA. Same param count class as llama3_1b."""
        d = dict(vocab_size=128256, dim=2048, n_layers=16, n_heads=16,
                 n_kv_heads=8, ffn_dim=8192, max_seq=8192)
        d.update(kw)
        return cls(**d)

    @classmethod
    def llama3_8b(cls, **kw) -> 'LlamaConfig':
        """Llama-3.1-8B shape (public): pod-slice flagship."""
        d = dict(vocab_size=128256, dim=4096, n_layers=32, n_heads=32,
                 n_kv_heads=8, ffn_dim=14336, max_seq=8192)
        d.update(kw)
        return cls(**d)


# ----------------------------------------------------------------- params


def init_params(cfg: LlamaConfig, key: jax.Array) -> Dict:
    """Stacked-layer param pytree (layer dim first, for lax.scan)."""
    k_emb, k_layers, k_head = jax.random.split(key, 3)
    hd, nl = cfg.head_dim, cfg.n_layers
    dt = cfg.param_dtype

    def norm_init(*shape):
        return jnp.ones(shape, dt)

    def dense_init(key, *shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) *
                fan_in**-0.5).astype(dt)

    ks = jax.random.split(k_layers, 7)
    return {
        'tok_emb': dense_init(k_emb, cfg.vocab_size, cfg.dim,
                              fan_in=cfg.dim),
        'layers': {
            'attn_norm': norm_init(nl, cfg.dim),
            'wq': dense_init(ks[0], nl, cfg.dim, cfg.n_heads * hd,
                             fan_in=cfg.dim),
            'wk': dense_init(ks[1], nl, cfg.dim, cfg.n_kv_heads * hd,
                             fan_in=cfg.dim),
            'wv': dense_init(ks[2], nl, cfg.dim, cfg.n_kv_heads * hd,
                             fan_in=cfg.dim),
            'wo': dense_init(ks[3], nl, cfg.n_heads * hd, cfg.dim,
                             fan_in=cfg.n_heads * hd),
            'mlp_norm': norm_init(nl, cfg.dim),
            'w_gate': dense_init(ks[4], nl, cfg.dim, cfg.ffn_dim,
                                 fan_in=cfg.dim),
            'w_up': dense_init(ks[5], nl, cfg.dim, cfg.ffn_dim,
                               fan_in=cfg.dim),
            'w_down': dense_init(ks[6], nl, cfg.ffn_dim, cfg.dim,
                                 fan_in=cfg.ffn_dim),
        },
        'final_norm': norm_init(cfg.dim),
        'lm_head': dense_init(k_head, cfg.dim, cfg.vocab_size,
                              fan_in=cfg.dim),
    }


def param_specs(cfg: LlamaConfig, pp: bool = False) -> Dict:
    """PartitionSpec pytree matching init_params: Megatron ('tp' on
    heads/ffn/vocab) + ZeRO-3 ('fsdp' on the other matrix dim). With
    ``pp``, the stacked layer dim is sharded over the pipeline axis
    (stage s holds its contiguous block of layers)."""
    del cfg
    layer_axis = 'pp' if pp else None
    return {
        'tok_emb': P('tp', 'fsdp'),
        'layers': {
            'attn_norm': P(layer_axis, None),
            'wq': P(layer_axis, 'fsdp', 'tp'),
            'wk': P(layer_axis, 'fsdp', 'tp'),
            'wv': P(layer_axis, 'fsdp', 'tp'),
            'wo': P(layer_axis, 'tp', 'fsdp'),
            'mlp_norm': P(layer_axis, None),
            'w_gate': P(layer_axis, 'fsdp', 'tp'),
            'w_up': P(layer_axis, 'fsdp', 'tp'),
            'w_down': P(layer_axis, 'tp', 'fsdp'),
        },
        'final_norm': P(None),
        'lm_head': P('fsdp', 'tp'),
    }


def remat_layer_fn(layer, remat):
    """Apply the config's rematerialization policy to a scan body.

    True = full remat (checkpoint everything); 'dots' = selective
    (keep matmul outputs — the expensive MXU work — and recompute
    only elementwise/norm ops in the backward: cheaper recompute than
    full remat at a fraction of no-remat's activation memory);
    'qkvo' = save only the attention projections (q/k/v/o, named in
    decoder_layer) — the middle ground for memory-tight single-chip
    training: the ffn activations (the bulk of 'dots' memory) still
    remat, but the backward skips recomputing the qkv/o projections
    and feeds the flash-attention backward from saved q/k/v;
    False = no remat.
    """
    if remat == 'dots':
        return jax.checkpoint(
            layer,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
    if remat == 'qkvo':
        return jax.checkpoint(
            layer,
            policy=jax.checkpoint_policies.save_only_these_names(
                'attn_q', 'attn_k', 'attn_v', 'attn_o'))
    if remat == 'kvo':
        # Like 'qkvo' minus the q projection (the largest saved
        # tensor, n_heads x head_dim per token): q recomputes from
        # the saved layer input at one matmul+rope, buying ~2 GB at
        # seq 8192 batch 4 — the difference between fitting and
        # OOMing on a 16 GB chip.
        return jax.checkpoint(
            layer,
            policy=jax.checkpoint_policies.save_only_these_names(
                'attn_k', 'attn_v', 'attn_o'))
    if remat:
        return jax.checkpoint(layer)
    return layer


ACT_SPEC = P(('dp', 'fsdp'), 'sp', None)          # [B, S, D]
HEAD_SPEC = P(('dp', 'fsdp'), 'sp', 'tp', None)   # [B, S, H, hd]


# ---------------------------------------------------------------- forward


def _rmsnorm(x, w, eps):
    xf = x.astype(jnp.float32)
    rms = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * rms).astype(x.dtype) * w.astype(x.dtype)


def _rope(x, positions, theta):
    """Rotary embedding; x: [B, S, H, D], positions: [B, S]."""
    d = x.shape[-1]
    freqs = theta**(-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B,S,D/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                          axis=-1)
    return out.astype(x.dtype)


def _attention(q, k, v, cfg: LlamaConfig, mesh, impl_override=None):
    impl = impl_override or cfg.attn_impl
    if impl == 'auto':
        if mesh is not None and mesh.shape.get('sp', 1) > 1:
            impl = 'ring'
        elif jax.default_backend() == 'tpu':
            impl = 'flash'
        else:
            impl = 'xla'
    if impl == 'ring':
        # GQA-native: K/V stay at n_kv_heads through the ring (a
        # pre-repeat would multiply K/V HBM and per-hop ICI traffic
        # by n_heads/n_kv_heads — 4x for Llama-8B's 8:1 GQA).
        assert mesh is not None, 'ring attention needs a mesh'
        return ring_attention_sharded(q, k, v, mesh, causal=True)
    if impl == 'flash':
        return flash_attention(q, k, v, causal=True)
    return reference_attention(q, k, v, causal=True)


def forward_hidden(params: Dict,
                   tokens: jax.Array,
                   cfg: LlamaConfig,
                   mesh=None,
                   positions: Optional[jax.Array] = None) -> jax.Array:
    """tokens [B, S] int32 -> final hidden states [B, S, dim]."""
    cdt = cfg.compute_dtype
    b, s = tokens.shape
    if positions is None:
        # With sequence parallelism the global position is implicit in
        # the (sharded) sequence index — iota over the global length.
        positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32),
                                     (b, s))

    def constrain(x, spec):
        if mesh is None:
            return x
        # get_abstract_mesh is absent on older jax (no set_mesh there
        # either, so there is never an ambient mesh to honor).
        ambient = getattr(jax.sharding, 'get_abstract_mesh',
                          lambda: None)()
        if ambient is not None and len(ambient.shape) > 0:
            # Ambient-mesh form (bare spec): required inside the
            # partial-manual pipeline region, equivalent outside it.
            return lax.with_sharding_constraint(x, spec)
        return lax.with_sharding_constraint(
            x, jax.sharding.NamedSharding(mesh, spec))

    # Embedding lookup. The table lives sharded P('tp','fsdp') (ZeRO-3
    # style); gathering rows straight out of a 2-axis-sharded table
    # hits XLA SPMD's "involuntary full rematerialization" path (it
    # replicates the table implicitly, with a warning). Make the
    # FSDP-style all-gather-at-use explicit instead: same bytes on the
    # wire, but planned — and the backward becomes a clean
    # reduce-scatter of the table gradient.
    emb = constrain(params['tok_emb'], P(None, None))
    x = emb.astype(cdt)[tokens]                      # [B, S, D]
    x = constrain(x, ACT_SPEC)

    def decoder_layer(x, lp, pos, attn_override=None):
        """One decoder block; shapes derived from x so the same body
        runs on full batches (scan path) and microbatches (pp path)."""
        bx, sx = x.shape[0], x.shape[1]
        from jax.ad_checkpoint import checkpoint_name as name
        h = _rmsnorm(x, lp['attn_norm'], cfg.norm_eps)
        q = (h @ lp['wq'].astype(cdt)).reshape(bx, sx, cfg.n_heads,
                                               cfg.head_dim)
        k = (h @ lp['wk'].astype(cdt)).reshape(bx, sx, cfg.n_kv_heads,
                                               cfg.head_dim)
        v = (h @ lp['wv'].astype(cdt)).reshape(bx, sx, cfg.n_kv_heads,
                                               cfg.head_dim)
        q = name(constrain(_rope(q, pos, cfg.rope_theta), HEAD_SPEC),
                 'attn_q')
        k = name(_rope(k, pos, cfg.rope_theta), 'attn_k')
        v = name(v, 'attn_v')
        o = _attention(q, k, v, cfg, mesh, impl_override=attn_override)
        o = name(o.reshape(bx, sx, cfg.n_heads * cfg.head_dim),
                 'attn_o')
        x = x + constrain(o @ lp['wo'].astype(cdt), ACT_SPEC)

        h = _rmsnorm(x, lp['mlp_norm'], cfg.norm_eps)
        gate = jax.nn.silu(h @ lp['w_gate'].astype(cdt))
        up = h @ lp['w_up'].astype(cdt)
        x = x + constrain((gate * up) @ lp['w_down'].astype(cdt),
                          ACT_SPEC)
        return x

    pp = mesh.shape.get('pp', 1) if mesh is not None else 1
    if pp > 1:
        # GPipe over the 'pp' mesh axis (parallel/pipeline.py
        # pipeline_layers): manual only over 'pp', so the Megatron/
        # ZeRO-3/sp sharding of the layer math above keeps working
        # inside each stage unchanged. Sharding constraints inside the
        # partial-manual region must use bare PartitionSpecs under the
        # ambient mesh (jax.set_mesh) — a NamedSharding over the
        # concrete mesh would type 'pp' as Auto and be rejected.
        from skypilot_tpu.parallel.pipeline import pipeline_layers

        def pipe_layer(lp, h, pos):
            # Ring attention's own shard_map cannot nest inside the
            # pp-manual region today (jax 0.9 rejects the backward's
            # residual capture across nested partial-manual regions);
            # inside pipeline stages, sequence parallelism runs as
            # XLA auto-sp instead (seq stays sharded over 'sp'; the
            # partitioner all-gathers K/V for the attention — more
            # bytes than the ring but on the same ICI links).
            override = 'xla' if (
                mesh.shape.get('sp', 1) > 1 or
                cfg.attn_impl == 'ring') else None
            return decoder_layer(h, lp, pos, attn_override=override)

        m = cfg.pp_microbatches or min(b, 4 * pp)
        while b % m:
            m -= 1
        with jax.sharding.use_abstract_mesh(mesh.abstract_mesh):
            # Caller-supplied positions are split per microbatch
            # alongside x, so custom RoPE offsets survive pipelining.
            x = pipeline_layers(remat_layer_fn(pipe_layer, cfg.remat),
                                params['layers'], x, mesh=mesh,
                                num_microbatches=m,
                                positions=positions)
    else:

        def layer(x, lp):
            return decoder_layer(x, lp, positions), None

        x, _ = lax.scan(remat_layer_fn(layer, cfg.remat),
                        x, params['layers'])

    return _rmsnorm(x, params['final_norm'], cfg.norm_eps)


def forward(params: Dict,
            tokens: jax.Array,
            cfg: LlamaConfig,
            mesh=None,
            positions: Optional[jax.Array] = None) -> jax.Array:
    """tokens [B, S] int32 -> logits [B, S, vocab] f32."""
    x = forward_hidden(params, tokens, cfg, mesh, positions)
    return jnp.einsum('bsd,dv->bsv', x,
                      params['lm_head'].astype(cfg.compute_dtype),
                      preferred_element_type=jnp.float32)


def _chunked_ce(x, lm_head, targets, mask, n_chunks):
    """Cross entropy without materializing [B, S, vocab] logits.

    Scans over sequence chunks; each chunk's logits ([B, S/n, V]) are
    rematerialized in the backward, so peak memory is one chunk.
    """
    b, s, d = x.shape
    c = s // n_chunks
    xc = x.reshape(b, n_chunks, c, d).transpose(1, 0, 2, 3)
    tc = targets.reshape(b, n_chunks, c).transpose(1, 0, 2)
    mc = mask.reshape(b, n_chunks, c).transpose(1, 0, 2)

    @jax.checkpoint
    def chunk_nll(carry, args):
        xi, ti, mi = args
        logits = jnp.einsum('bcd,dv->bcv', xi, lm_head,
                            preferred_element_type=jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, ti[..., None],
                                   axis=-1)[..., 0]
        return carry + jnp.sum(nll * mi), None

    total, _ = lax.scan(chunk_nll, jnp.zeros((), jnp.float32),
                        (xc, tc, mc))
    return total


def split_lm_batch(batch: Dict[str, jax.Array]):
    """(inputs, targets) from {'tokens': [B, S+1]} or
    {'inputs'/'targets': [B, S]} (targets may use -100 = ignore) —
    ONE definition for every model family."""
    if 'inputs' in batch:
        return batch['inputs'], batch['targets']
    return batch['tokens'][:, :-1], batch['tokens'][:, 1:]


def chunked_lm_loss(x: jax.Array, head: jax.Array,
                    targets: jax.Array, cfg) -> jax.Array:
    """Masked-mean next-token CE over hidden states ``x`` with the
    unembedding ``head`` [D, V], sequence-chunked so [B, S, vocab]
    logits never materialize (at 128k vocab and 8k seq that tensor
    alone would be ~16 GB). Shared by every family — the ignore-index
    convention and chunk-divisor walk must never diverge between
    them."""
    mask = (targets >= 0).astype(jnp.float32)
    targets = jnp.maximum(targets, 0)
    s = x.shape[1]
    n_chunks = max(1, s // max(1, cfg.loss_chunk))
    while s % n_chunks:
        n_chunks -= 1
    total = _chunked_ce(x, head, targets, mask, n_chunks)
    return total / jnp.maximum(jnp.sum(mask), 1.0)


def loss_fn(params: Dict,
            batch: Dict[str, jax.Array],
            cfg: LlamaConfig,
            mesh=None) -> jax.Array:
    """Next-token cross entropy (see split_lm_batch for batch forms)."""
    inputs, targets = split_lm_batch(batch)
    x = forward_hidden(params, inputs, cfg, mesh)
    return chunked_lm_loss(
        x, params['lm_head'].astype(cfg.compute_dtype), targets, cfg)


def num_params(cfg: LlamaConfig) -> int:
    shapes = jax.eval_shape(
        functools.partial(init_params, cfg),
        jax.ShapeDtypeStruct((2,), jnp.uint32))
    return sum(int(jnp.prod(jnp.array(x.shape)))
               for x in jax.tree.leaves(shapes))
