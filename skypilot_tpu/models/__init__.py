"""Flagship JAX model family (Llama-style decoder) + sharded training.

The reference ships models only as *recipes* (llm/llama-3_1-finetuning,
llm/gpt-2 — user YAML invoking torchtune/llm.c; SURVEY.md §2.11). Our
TPU-first build promotes the model layer to a library: a functional
Llama implementation whose forward/train step is pjit-shardable over a
(dp, fsdp, sp, tp) mesh, using the Pallas flash-attention kernel on TPU
and ring attention for long-context sequence parallelism.
"""
from skypilot_tpu.models.inference import (cache_specs, decode_step,
                                           generate, prefill)
from skypilot_tpu.models.llama import (LlamaConfig, forward, init_params,
                                       loss_fn, param_specs)
from skypilot_tpu.models.moe import MoEConfig
from skypilot_tpu.models.train import (TrainState, init_train_state,
                                       make_eval_step, make_optimizer,
                                       make_train_step, shard_batch)


def family(cfg):
    """Model-family module for a config (llama, moe or gpt2) — each
    exposes init_params / param_specs / forward / loss_fn with the
    same signatures. The ONE family-dispatch point: training, serving
    and checkpoint-restore all route through it."""
    from skypilot_tpu.models import gpt2 as gpt2_mod
    from skypilot_tpu.models import llama as llama_mod
    from skypilot_tpu.models import moe as moe_mod
    if isinstance(cfg, moe_mod.MoEConfig):
        return moe_mod
    if isinstance(cfg, gpt2_mod.GPT2Config):
        return gpt2_mod
    return llama_mod


def config_preset(name: str):
    """Resolve a preset name ('tpu_1b', 'mixtral_8x7b', 'gpt2', ...)
    across families (used by serving_http --model and the bench)."""
    from skypilot_tpu.models.gpt2 import GPT2Config
    for cls in (LlamaConfig, MoEConfig, GPT2Config):
        fn = getattr(cls, name, None)
        if fn is not None:
            return fn
    raise ValueError(f'No model preset named {name!r} on LlamaConfig, '
                     'MoEConfig or GPT2Config.')


__all__ = [
    'LlamaConfig', 'MoEConfig', 'forward', 'init_params', 'loss_fn',
    'param_specs', 'family', 'config_preset',
    'TrainState', 'init_train_state', 'make_eval_step', 'make_optimizer',
    'make_train_step', 'shard_batch',
    'cache_specs', 'decode_step', 'generate', 'prefill',
]
