"""GPT-2 family in functional JAX — the native counterpart of the
reference's ``llm/gpt-2`` recipe (YAML driving karpathy's llm.c:
"reproduce GPT-2 (124M) for ~$20"; README.md:1-5). Here the model is
a library the train step runs directly on TPU, not a shell-out.

Architecture (GPT-2 proper, distinct from the Llama family):
LayerNorm with bias (not RMSNorm), LEARNED positional embeddings (not
RoPE), GELU MLP at 4x (not SwiGLU), biased projections, and a TIED
lm_head (logits = x @ wte^T). TPU-first deviations from the original
checkpoint format:

- the vocab pads 50257 -> 50304 (128-multiple) so the lm_head matmul
  tiles the MXU without a ragged edge — llm.c does the same padding
  for its GPUs;
- params are stacked per-layer arrays consumed by ``lax.scan`` (one
  trace for all layers), bf16 compute with f32 accumulation,
  rematerialized layer body;
- every weight carries a (dp, fsdp, tp) PartitionSpec so the same
  code runs single-chip or pjit-sharded (Megatron heads/ffn over
  'tp', ZeRO-3 over 'fsdp').

API mirrors models.llama (init_params / param_specs / forward /
loss_fn), so models.family dispatches training, checkpointing and the
bench to it unchanged.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from skypilot_tpu.models.llama import (chunked_lm_loss,
                                       remat_layer_fn, split_lm_batch)
from skypilot_tpu.ops import flash_attention, reference_attention

ACT_SPEC = P(('dp', 'fsdp'), 'sp', None)


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50304        # 50257 padded to a 128 multiple
    # Learned-positional-embedding table length (GPT-2's context
    # limit); named max_seq for uniformity with the other families so
    # the train step and bench knobs apply unchanged.
    max_seq: int = 1024
    dim: int = 768
    n_layers: int = 12
    n_heads: int = 12
    norm_eps: float = 1e-5
    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    remat: Any = True
    loss_chunk: int = 512
    # auto = Pallas flash on TPU when head_dim is a 128 multiple
    # (the kernel's validated tile shape — GPT-2's head_dim 64
    # compiles pathologically there), XLA attention otherwise.
    attn_impl: str = 'auto'

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    # ---- presets -------------------------------------------------
    @classmethod
    def tiny_gpt2(cls, **kw) -> 'GPT2Config':
        d = dict(vocab_size=256, max_seq=128, dim=64, n_layers=2,
                 n_heads=4, param_dtype=jnp.float32,
                 compute_dtype=jnp.float32)
        d.update(kw)
        return cls(**d)

    @classmethod
    def gpt2(cls, **kw) -> 'GPT2Config':
        """GPT-2 124M — the reference recipe's model."""
        return cls(**kw)

    @classmethod
    def gpt2_medium(cls, **kw) -> 'GPT2Config':
        d = dict(dim=1024, n_layers=24, n_heads=16)
        d.update(kw)
        return cls(**d)

    @classmethod
    def gpt2_xl(cls, **kw) -> 'GPT2Config':
        d = dict(dim=1600, n_layers=48, n_heads=25)
        d.update(kw)
        return cls(**d)


def init_params(cfg: GPT2Config, key: jax.Array) -> Dict:
    """Stacked-layer param pytree (layer dim first, for lax.scan).
    lm_head is TIED to wte (GPT-2's defining weight share) — there is
    deliberately no separate head matrix."""
    k_wte, k_wpe, k_layers = jax.random.split(key, 3)
    nl, d = cfg.n_layers, cfg.dim
    dt = cfg.param_dtype

    def dense(key, *shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) *
                fan_in**-0.5).astype(dt)

    ks = jax.random.split(k_layers, 4)
    return {
        'wte': dense(k_wte, cfg.vocab_size, d, fan_in=d),
        'wpe': (jax.random.normal(k_wpe, (cfg.max_seq, d),
                                  jnp.float32) * 0.01).astype(dt),
        'layers': {
            'ln1_g': jnp.ones((nl, d), dt),
            'ln1_b': jnp.zeros((nl, d), dt),
            'w_qkv': dense(ks[0], nl, d, 3 * d, fan_in=d),
            'b_qkv': jnp.zeros((nl, 3 * d), dt),
            'w_proj': dense(ks[1], nl, d, d, fan_in=d),
            'b_proj': jnp.zeros((nl, d), dt),
            'ln2_g': jnp.ones((nl, d), dt),
            'ln2_b': jnp.zeros((nl, d), dt),
            'w_fc': dense(ks[2], nl, d, 4 * d, fan_in=d),
            'b_fc': jnp.zeros((nl, 4 * d), dt),
            'w_out': dense(ks[3], nl, 4 * d, d, fan_in=4 * d),
            'b_out': jnp.zeros((nl, d), dt),
        },
        'lnf_g': jnp.ones((d,), dt),
        'lnf_b': jnp.zeros((d,), dt),
    }


def param_specs(cfg: GPT2Config, pp: bool = False) -> Dict:
    """Megatron ('tp' on the fused qkv/ffn out-dims) + ZeRO-3
    ('fsdp' on the other matrix dim); biases shard with their
    matmul's output dim."""
    del cfg
    if pp:
        raise NotImplementedError('GPT-2 pp sharding is not wired; '
                                  'use the Llama family for pp.')
    return {
        'wte': P('tp', 'fsdp'),
        'wpe': P(None, 'fsdp'),
        'layers': {
            'ln1_g': P(None, None),
            'ln1_b': P(None, None),
            'w_qkv': P(None, 'fsdp', 'tp'),
            'b_qkv': P(None, 'tp'),
            'w_proj': P(None, 'tp', 'fsdp'),
            'b_proj': P(None, None),
            'ln2_g': P(None, None),
            'ln2_b': P(None, None),
            'w_fc': P(None, 'fsdp', 'tp'),
            'b_fc': P(None, 'tp'),
            'w_out': P(None, 'tp', 'fsdp'),
            'b_out': P(None, None),
        },
        'lnf_g': P(None),
        'lnf_b': P(None),
    }


def _layernorm(x, g, b, eps):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean((xf - mu) ** 2, axis=-1, keepdims=True)
    out = (xf - mu) * lax.rsqrt(var + eps)
    return out.astype(x.dtype) * g.astype(x.dtype) + b.astype(x.dtype)


def forward_hidden(params: Dict, tokens: jax.Array, cfg: GPT2Config,
                   mesh=None,
                   positions: Optional[jax.Array] = None) -> jax.Array:
    """tokens [B, S] int32 -> final hidden states [B, S, dim]."""
    cdt = cfg.compute_dtype
    b, s = tokens.shape
    if positions is None:
        positions = jnp.arange(s, dtype=jnp.int32)

    if cfg.remat in ('qkvo', 'kvo'):
        # Those policies save tensors by checkpoint_name tags that
        # only the Llama-family decoder attaches; here they would
        # silently degrade to full remat while claiming otherwise
        # (the r4-advisor failure mode). Fail loudly instead.
        raise ValueError(
            "remat='qkvo'/'kvo' are Llama-family policies "
            "(checkpoint_name tags); use True, False or 'dots' for "
            'GPT-2.')

    def constrain(x, spec):
        if mesh is None:
            return x
        return lax.with_sharding_constraint(
            x, jax.sharding.NamedSharding(mesh, spec))

    wte = constrain(params['wte'], P(None, None))
    x = wte.astype(cdt)[tokens] + params['wpe'].astype(cdt)[positions]
    x = constrain(x, ACT_SPEC)

    def layer(x, lp):
        h = _layernorm(x, lp['ln1_g'], lp['ln1_b'], cfg.norm_eps)
        qkv = h @ lp['w_qkv'].astype(cdt) + lp['b_qkv'].astype(cdt)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, s, cfg.n_heads, cfg.head_dim)
        k = k.reshape(b, s, cfg.n_heads, cfg.head_dim)
        v = v.reshape(b, s, cfg.n_heads, cfg.head_dim)
        impl = cfg.attn_impl
        if impl == 'auto':
            impl = ('flash' if jax.default_backend() == 'tpu' and
                    cfg.head_dim % 128 == 0 else 'xla')
        if impl == 'flash':
            o = flash_attention(q, k, v, causal=True)
        else:
            o = reference_attention(q, k, v, causal=True)
        o = o.reshape(b, s, cfg.dim).astype(cdt)
        x = x + constrain(
            o @ lp['w_proj'].astype(cdt) + lp['b_proj'].astype(cdt),
            ACT_SPEC)

        h = _layernorm(x, lp['ln2_g'], lp['ln2_b'], cfg.norm_eps)
        h = jax.nn.gelu(h @ lp['w_fc'].astype(cdt) +
                        lp['b_fc'].astype(cdt))
        x = x + constrain(
            h @ lp['w_out'].astype(cdt) + lp['b_out'].astype(cdt),
            ACT_SPEC)
        return x, None

    x, _ = lax.scan(remat_layer_fn(layer, cfg.remat), x,
                    params['layers'])
    return _layernorm(x, params['lnf_g'], params['lnf_b'],
                      cfg.norm_eps)


def forward(params: Dict, tokens: jax.Array, cfg: GPT2Config,
            mesh=None) -> jax.Array:
    """tokens [B, S] -> logits [B, S, vocab] f32 (tied head)."""
    x = forward_hidden(params, tokens, cfg, mesh)
    return jnp.einsum('bsd,vd->bsv', x,
                      params['wte'].astype(cfg.compute_dtype),
                      preferred_element_type=jnp.float32)


def loss_fn(params: Dict, batch: Dict[str, jax.Array],
            cfg: GPT2Config, mesh=None) -> jax.Array:
    """Next-token cross entropy with the TIED head (shared
    chunked_lm_loss)."""
    inputs, targets = split_lm_batch(batch)
    x = forward_hidden(params, inputs, cfg, mesh)
    head = jnp.transpose(params['wte'].astype(cfg.compute_dtype))
    return chunked_lm_loss(x, head, targets, cfg)
