"""Automatic prefix caching for the serving engine: block-hash page
pool with copy-on-write sharing (vLLM's automatic prefix caching /
SGLang's RadixAttention, on our paged KV substrate).

Real chat/agent traffic is dominated by shared system prompts and
multi-turn history, yet without this every request re-prefills from
token 0 and admission charges the full ``ceil(prompt/chunk)`` ticks.
This module keeps a device-resident pool of **KV pages** keyed by a
**chain hash** of the prompt token blocks they were computed from: a
page's key commits to its WHOLE prefix (hash(page_i) folds in
hash(page_{i-1})), so a hash hit means the page's K/V are exactly
what this request's own prefill would compute for those positions.

Sharing model (the copy-on-write discipline):

- Pool pages are **immutable once published**. An admission hit
  copies the matched pages into the slot's private prompt-region KV
  (fixed-shape jitted copy — never a new traced shape); the slot's
  chunked prefill then resumes at the cached boundary. The writer
  only ever touches its own row, so a sharer's pages can never be
  corrupted — the "copy" IS the write barrier, taken eagerly at the
  first divergent token (the page where the chain hash stops
  matching).
- Pages a slot copied in stay **pinned** (refcounted) until the
  request reaches a terminal state, so eviction can never recycle a
  page an in-flight request may still need republished.
- A completed (or cancelled/expired) slot **publishes** its now-final
  full prompt pages back to the pool and releases its pins; pages
  already present are deduplicated by hash.
- Eviction is LRU over **unpinned** pages only; when every page is
  pinned, publishing degrades gracefully (the pool just misses).

Bitwise-parity discipline: the reuse boundary is rounded DOWN to a
multiple of the engine's ``prefill_chunk``, so the uncached suffix
prefills with exactly the chunk starts a cache-off run would use.
Published pages were themselves computed at those canonical chunk
starts (inductively: a publisher's own reuse boundary was aligned
too), so greedy decode over a cache-hit prompt is bit-identical to
the cache-off path. The last token of a prompt is never served from
the pool — at least one suffix token always prefills, producing the
first-token logits through the already-warmed chunk program.

Threading: all mutation (acquire/publish/evict) happens on the
engine's driver thread; ``reusable_tokens`` is a pure read safe to
call from HTTP threads (the deadline-shed estimate).

Sharded engines: with a device mesh the pool carries the same
kv-head 'tp' sharding as the live cache (``POOL_SPEC`` mirrors
``inference.CACHE_SPEC``), and the three copy programs are
sharding-constrained so a page copy-in/out moves each shard's local
head slice device-to-device — nothing ever gathers to one chip. The
copies only ever slice the layer/page/position axes, so GSPMD keeps
them collective-free.

Knobs: ``SKYTPU_PREFIX_CACHE`` (set to 1 to enable; off means the
engine is bit-identical to a build without this module),
``SKYTPU_PREFIX_POOL_PAGES`` (pool size; at the engine's page size)
and ``SKYTPU_PREFIX_POOL_SHARD`` (default 1; 0 keeps the pool
replicated on mesh engines — a debugging escape hatch).
"""
from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from skypilot_tpu import metrics as metrics_lib
from skypilot_tpu.models import inference
from skypilot_tpu.utils import chain_hash
from skypilot_tpu.utils import env_registry
from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)

# Pool layout [n_layers, pool_pages, page, n_kv, head_dim]: kv heads
# shard on 'tp' exactly like the live cache (inference.CACHE_SPEC);
# everything else is replicated (the pool is shared by all rows).
POOL_SPEC = P(None, None, None, 'tp', None)
POOL_SCALE_SPEC = P(None, None, None, 'tp')

# Default pool size in pages (SKYTPU_PREFIX_POOL_PAGES overrides): at
# the default 128-token page and an 8B int8 KV shape this is ~100 MB
# of HBM — roughly 4 slots' worth of prompt region buying unbounded
# cross-request reuse.
DEFAULT_POOL_PAGES = 512

_M_HITS = metrics_lib.counter(
    'skytpu_engine_prefix_hits_total',
    'Admissions that reused at least one cached prompt page from the '
    'prefix pool (docs/metrics.md; PERFORMANCE.md "Prefix-reuse KV '
    'cache").')
_M_SAVED = metrics_lib.counter(
    'skytpu_engine_prefix_tokens_saved_total',
    'Prompt tokens served from the prefix pool instead of being '
    'prefilled (chunk-aligned reuse boundary; rate() of this is the '
    'prefill compute the cache is saving).')
_M_POOL = metrics_lib.gauge(
    'skytpu_engine_prefix_pool_pages',
    'Occupied pages in the shared prefix pool (capacity is '
    'SKYTPU_PREFIX_POOL_PAGES).')
_M_EVICTIONS = metrics_lib.counter(
    'skytpu_engine_prefix_evictions_total',
    'Cold (unpinned) prefix pages evicted LRU to make room for a '
    'newly published page.')
_M_IMPORTED = metrics_lib.counter(
    'skytpu_engine_prefix_pages_imported_total',
    'Remote KV pages landed into the local prefix pool via the '
    'transfer import path (serve/kv_transfer.py; '
    'docs/disaggregation.md).')


# Chain hashing is shared with the serve LB's PrefixAffinityPolicy —
# the one definition lives in utils/chain_hash.py so the two sides
# can never diverge. Re-exported here under its historical name.
page_hashes = chain_hash.page_hashes

# Schema version of the /health prefix digest (prefix_summary);
# shared with the LB via chain_hash so both sides compare one value.
SUMMARY_SCHEMA_VERSION = chain_hash.SUMMARY_SCHEMA_VERSION


def summary_pages() -> int:
    """Bound on the hash list a /health digest advertises
    (SKYTPU_AFFINITY_SUMMARY_PAGES). 32 hex chars per page: the
    default 128 is ~4 KB of probe-cadence JSON for full directory
    visibility on every test/bench pool size used here."""
    return max(0, int(env_registry.get(
        env_registry.SKYTPU_AFFINITY_SUMMARY_PAGES, '128')))


class PrefixCache:
    """Device-resident shared page pool + host-side hash directory.

    The pool holds ``pool_pages`` pages of ``page`` token positions
    each, laid out ``[n_layers, pool_pages, page, n_kv, head_dim]``
    (+ per-vector scale planes for int8 KV caches, so
    ``quantization.quantize_kv`` composes — pages are copied in the
    cache's native dtype, never dequantized). All device work is
    three fixed-shape jitted programs (page copy-in, page copy-out,
    dmask/length fix) whose indices are traced scalars: warmed once,
    they serve every slot/page combination with zero recompiles.
    """

    def __init__(self, cfg, *, page: int, pool_pages: int,
                 kv_quant: bool = False, mesh=None) -> None:
        if page < 1:
            raise ValueError(f'page ({page}) must be positive')
        if pool_pages < 1:
            raise ValueError(
                f'pool_pages ({pool_pages}) must be positive')
        self.page = int(page)
        self.pool_pages = int(pool_pages)
        if mesh is not None and env_registry.get(
                env_registry.SKYTPU_PREFIX_POOL_SHARD, '1') != '1':
            mesh = None
        self.mesh = mesh
        kv_dtype = jnp.int8 if kv_quant else cfg.compute_dtype
        shape = (cfg.n_layers, self.pool_pages, self.page,
                 cfg.n_kv_heads, cfg.head_dim)
        self._fields: Tuple[str, ...] = ('k', 'v')
        pool = {'k': jnp.zeros(shape, kv_dtype),
                'v': jnp.zeros(shape, kv_dtype)}
        pool_specs = {'k': POOL_SPEC, 'v': POOL_SPEC}
        if kv_quant:
            self._fields += ('k_scale', 'v_scale')
            pool['k_scale'] = jnp.ones(shape[:4], jnp.bfloat16)
            pool['v_scale'] = jnp.ones(shape[:4], jnp.bfloat16)
            pool_specs['k_scale'] = POOL_SCALE_SPEC
            pool_specs['v_scale'] = POOL_SCALE_SPEC
        # The live cache's per-field specs (inference.cache_specs
        # family): constraint targets for the copy programs.
        cache_specs = {'k': inference.CACHE_SPEC,
                       'v': inference.CACHE_SPEC,
                       'k_scale': inference.SCALE_SPEC,
                       'v_scale': inference.SCALE_SPEC}

        def _c(x, spec):
            """Pin ``x`` to ``spec`` on the mesh (no-op unsharded)."""
            if mesh is None:
                return x
            return lax.with_sharding_constraint(
                x, jax.sharding.NamedSharding(mesh, spec))

        if mesh is not None:
            # Pool lives kv-head-sharded from birth: copy-in/out then
            # move each shard's local slice, never a gathered page.
            pool = {f: jax.device_put(
                a, jax.sharding.NamedSharding(mesh, pool_specs[f]))
                for f, a in pool.items()}
        self.pool = pool

        # Host directory: hash -> pool page index, plus per-page
        # refcounts (pins), LRU stamps and the free list. Mutated only
        # on the engine driver thread; read-only lookups
        # (reusable_tokens) are safe from other threads.
        self._by_hash: Dict[bytes, int] = {}
        self._hash_of: List[Optional[bytes]] = [None] * self.pool_pages
        self._refs: List[int] = [0] * self.pool_pages
        self._stamp: List[int] = [0] * self.pool_pages
        self._tick = 0
        # pop() hands out low indices first (cosmetic determinism).
        self._free: List[int] = list(range(self.pool_pages - 1, -1, -1))
        self._pins: Dict[Any, List[int]] = {}
        # Host-side stats for bench detail (the metric counters carry
        # the same numbers to scrapes).
        self.hits = 0
        self.lookups = 0
        self.tokens_saved = 0
        self.evictions = 0
        # Directory version: bumped whenever the hash->page mapping
        # changes (publish insertions, evictions). Lookup results are
        # a pure function of (tokens, version), which is what lets
        # the engine memoize its per-tick _fits lookup.
        self.version = 0
        # prefix_summary memo: (version, bound, dict). Invalidated by
        # comparison, never cleared — safe to read from HTTP threads.
        self._summary_cache: Optional[Tuple[int, int, Dict]] = None
        _M_POOL.touch()

        n_layers = cfg.n_layers

        @functools.partial(jax.jit, donate_argnums=(0,))
        def _copy_in(kv, pool, slot, dst_off, src):
            """Pool page ``src`` -> cache row ``slot`` at position
            ``dst_off``. All indices traced: one compiled program
            serves every (slot, page) pair. Sharding-constrained: the
            slice never touches the kv-head axis, so each shard moves
            its local head slice in place."""
            out = dict(kv)
            for f in self._fields:
                sizes = (n_layers, 1) + pool[f].shape[2:]
                blk = lax.dynamic_slice(
                    pool[f], (0, src) + (0,) * (pool[f].ndim - 2),
                    sizes)
                out[f] = _c(lax.dynamic_update_slice(
                    kv[f], blk,
                    (0, slot, dst_off) + (0,) * (kv[f].ndim - 3)),
                    cache_specs[f])
            return out

        @functools.partial(jax.jit, donate_argnums=(1,))
        def _copy_out(kv, pool, slot, src_off, dst):
            """Cache row ``slot`` page at ``src_off`` -> pool page
            ``dst`` (publish); sharding-constrained like _copy_in."""
            out = dict(pool)
            for f in self._fields:
                sizes = (n_layers, 1) + pool[f].shape[2:]
                blk = lax.dynamic_slice(
                    kv[f],
                    (0, slot, src_off) + (0,) * (kv[f].ndim - 3),
                    sizes)
                out[f] = _c(lax.dynamic_update_slice(
                    pool[f], blk,
                    (0, dst) + (0,) * (pool[f].ndim - 2)),
                    pool_specs[f])
            return out

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def _mask_fix(dmask, length, slot, cached):
            """After a copy-in: row ``slot`` reads exactly [0, cached)
            — everything else (the previous occupant's prompt tail and
            decode slots) becomes unreadable, the same recycling
            guarantee a first prefill chunk (start == 0) gives, taken
            over here because a cache-hit prompt's first chunk starts
            at the cached boundary instead."""
            s_max = dmask.shape[1]
            row = (jnp.arange(s_max, dtype=jnp.int32) <
                   jnp.asarray(cached, jnp.int32))[None]
            # No output constraints: dmask/length are tiny replicated
            # arrays, and constraining them here would stamp sharding
            # specs that differ TEXTUALLY from the tick programs'
            # GSPMD-normalized forms — every downstream tick would
            # then retrace on the new jit key. Propagating the input
            # shardings keeps one canonical form in circulation.
            dmask = lax.dynamic_update_slice(dmask, row, (slot, 0))
            length = length.at[slot].set(
                jnp.asarray(cached, length.dtype))
            return dmask, length

        @jax.jit
        def _export_page(pool, src):
            """Pool page ``src`` -> one per-field block ready for a
            host copy (the KV-transfer export path). Traced index —
            one compiled program serves every page, so exports never
            add compiles after warmup. No donation: the pool must
            survive an export (HTTP threads read while the driver
            publishes)."""
            out = {}
            for f in self._fields:
                sizes = (n_layers, 1) + pool[f].shape[2:]
                out[f] = _c(lax.dynamic_slice(
                    pool[f], (0, src) + (0,) * (pool[f].ndim - 2),
                    sizes), pool_specs[f])
            return out

        @functools.partial(jax.jit, donate_argnums=(0,))
        def _import_page(pool, blk, dst):
            """One remote page block -> pool page ``dst`` (the
            KV-transfer import path); sharding-constrained like
            _copy_out, traced index, pool donated in place."""
            out = dict(pool)
            for f in self._fields:
                out[f] = _c(lax.dynamic_update_slice(
                    pool[f], blk[f],
                    (0, dst) + (0,) * (pool[f].ndim - 2)),
                    pool_specs[f])
            return out

        self._copy_in = _copy_in
        self._copy_out = _copy_out
        self._mask_fix = _mask_fix
        self._export_page = _export_page
        self._import_page = _import_page

    # --------------------------------------------------------- lookup
    def _hashes_of(self, tokens: Sequence[int],
                   holder: Optional[Any] = None) -> List[bytes]:
        """Chain hashes of ``tokens``, cached on ``holder`` (the
        engine's Request object) when one is given — estimates and
        per-tick _fits re-checks then never re-hash a prompt. The
        cache key is the token list's identity (prompts are immutable
        after submit); a benign cross-thread race at worst recomputes
        once."""
        if holder is not None:
            cached = getattr(holder, '_prefix_hashes', None)
            if cached is not None and cached[0] is tokens:
                return cached[1]
        out = page_hashes(tokens, self.page)
        if holder is not None:
            try:
                holder._prefix_hashes = (tokens, out)
            except (AttributeError, TypeError):
                pass               # slotted/frozen holder: no cache
        return out

    def match_pages(self, tokens: Sequence[int],
                    holder: Optional[Any] = None) -> List[int]:
        """Pool page indices of the longest cached prefix (pure read,
        cross-thread safe)."""
        ids: List[int] = []
        for h in self._hashes_of(tokens, holder):
            idx = self._by_hash.get(h)
            if idx is None:
                break
            ids.append(idx)
        return ids

    def _reuse_len(self, n_pages: int, prompt_len: int,
                   chunk: int) -> int:
        """Reusable prompt tokens given ``n_pages`` matched pages:
        capped at prompt_len - 1 (the last token always prefills, so
        first-token logits come from the warmed chunk program) and
        rounded DOWN to a ``chunk`` multiple (suffix chunk starts land
        exactly where a cache-off prefill would put them — the bitwise
        parity discipline; see the module docstring)."""
        cap = min(n_pages * self.page, prompt_len - 1)
        return max(0, (cap // max(1, chunk)) * chunk)

    def reusable_tokens(self, tokens: Sequence[int], chunk: int,
                        holder: Optional[Any] = None) -> int:
        """How many prompt tokens a lookup NOW would serve from the
        pool. Pure read: the admission estimate (estimate_wait_s, the
        deadline shed) calls this from HTTP threads."""
        return self._reuse_len(len(self.match_pages(tokens, holder)),
                               len(tokens), chunk)

    def would_reuse(self, tokens: Sequence[int], chunk: int,
                    extra_hashes: Sequence[bytes] = ()) -> int:
        """Reuse length IF the pages named by ``extra_hashes`` were
        also in the pool. Pure read: the decode-side import path
        reports expected re-prefill savings (the X-KV-Reused-Tokens
        header) before the driver thread has landed the queued
        pages."""
        extra = set(extra_hashes)
        n = 0
        for h in page_hashes(tokens, self.page):
            if h in self._by_hash or h in extra:
                n += 1
            else:
                break
        return self._reuse_len(n, len(tokens), chunk)

    # ----------------------------------------------------- admission
    def acquire(self, request_id: Any, tokens: Sequence[int],
                chunk: int, holder: Optional[Any] = None
                ) -> Tuple[int, List[int], List[bytes]]:
        """Look up the longest cached prefix for an admission and PIN
        the pages to copy. Returns (reuse_tokens, page_ids,
        prompt_hashes); reuse of 0 means a miss (no pins held). The
        hash list covers every full page of the prompt — callers keep
        it so the terminal ``publish`` never re-hashes. Pins release
        at the request's terminal state (``release``)."""
        self.lookups += 1
        # _hashes_of memoizes on the holder, so the match walk below
        # reuses the same digests it returns — ONE matching
        # implementation (match_pages) for _fits, estimates and the
        # admission itself.
        hashes = self._hashes_of(tokens, holder)
        ids = self.match_pages(tokens, holder)
        reuse = self._reuse_len(len(ids), len(tokens), chunk)
        if reuse == 0:
            return 0, [], hashes
        ids = ids[:-(-reuse // self.page)]
        for i in ids:
            self._refs[i] += 1
            self._touch(i)
        self._pins[request_id] = list(ids)
        self.hits += 1
        self.tokens_saved += reuse
        _M_HITS.inc()
        _M_SAVED.inc(reuse)
        return reuse, ids, hashes

    def release(self, request_id: Any) -> None:
        """Drop a terminal request's pins (idempotent; misses and
        queued-only requests hold none)."""
        for i in self._pins.pop(request_id, ()):
            self._refs[i] -= 1

    def pinned_pages(self) -> int:
        return sum(1 for r in self._refs if r > 0)

    def copy_into(self, cache: Dict, slot: int, page_ids: List[int],
                  cached_len: int) -> Dict:
        """Copy the acquired pages into ``slot``'s prompt-region KV
        and mark exactly [0, cached_len) readable. One fixed-shape
        dispatch per page + the mask fix — all programs warmed by
        ``warm()``, so a hit never compiles."""
        sub = {f: cache[f] for f in self._fields}
        for j, src in enumerate(page_ids):
            sub = self._copy_in(sub, self.pool, slot, j * self.page,
                                src)
        dmask, length = self._mask_fix(cache['dmask'], cache['length'],
                                       slot, cached_len)
        out = dict(cache)
        out.update(sub)
        out['dmask'] = dmask
        out['length'] = length
        return out

    # ------------------------------------------------------- publish
    def publish(self, tokens: Sequence[int], final_len: int,
                cache: Dict, slot: int,
                hashes: Optional[List[bytes]] = None) -> None:
        """Copy a terminal slot's finalized full prompt pages into the
        pool (dedup by hash). ``final_len`` is the slot's prefill
        cursor at the end — a cancel mid-prefill publishes only the
        pages it actually finished. ``hashes`` (the admission
        lookup's chain hashes, when the caller kept them) skips
        re-hashing the prompt on the driver's tick loop. Publishing
        stops at the first allocation failure (every page in the pool
        pinned): a chain with a missing link is unreachable anyway."""
        n_full = min(final_len, len(tokens)) // self.page
        if n_full == 0:
            return
        if hashes is None or len(hashes) < n_full:
            hashes = page_hashes(tokens[:n_full * self.page],
                                 self.page)
        sub = {f: cache[f] for f in self._fields}
        for i, h in enumerate(hashes[:n_full]):
            cur = self._by_hash.get(h)
            if cur is not None:
                self._touch(cur)
                continue
            dst = self._alloc()
            if dst is None:
                logger.debug(
                    'Prefix pool exhausted (all %d pages pinned): '
                    'skipping publish of %d page(s).', self.pool_pages,
                    n_full - i)
                break
            self.pool = self._copy_out(sub, self.pool, slot,
                                       i * self.page, dst)
            self._by_hash[h] = dst
            self._hash_of[dst] = h
            self._refs[dst] = 0
            self._touch(dst)
            self.version += 1
        _M_POOL.set(len(self._by_hash))

    def _alloc(self) -> Optional[int]:
        if self._free:
            return self._free.pop()
        victim, best = None, None
        for i, h in enumerate(self._hash_of):
            if h is None or self._refs[i] > 0:
                continue
            if best is None or self._stamp[i] < best:
                victim, best = i, self._stamp[i]
        if victim is None:
            return None            # every occupied page is pinned
        del self._by_hash[self._hash_of[victim]]
        self._hash_of[victim] = None
        self.evictions += 1
        self.version += 1
        _M_EVICTIONS.inc()
        return victim

    def _touch(self, idx: int) -> None:
        self._tick += 1
        self._stamp[idx] = self._tick

    # ------------------------------------------------------ transfer
    def page_signature(self) -> Dict[str, Any]:
        """Wire-compat signature of this pool's page blocks (serve/
        kv_transfer.py): page size plus per-field dtype and block
        shape ``[n_layers, 1, page, ...]``. Two replicas exchange
        pages iff their signatures are equal — the cheap structural
        check in front of the content-address guarantee."""
        return {
            'page': self.page,
            'fields': {
                f: {'dtype': str(np.dtype(self.pool[f].dtype)),
                    'shape': [int(self.pool[f].shape[0]), 1] +
                             [int(d) for d in self.pool[f].shape[2:]]}
                for f in self._fields},
        }

    def export_page(self, h: bytes
                    ) -> Optional[Dict[str, np.ndarray]]:
        """Host copy of the pool page for chain hash ``h`` (None on a
        miss). Safe from HTTP threads against a concurrently
        publishing driver: the copy programs DONATE the pool, so a
        publish can invalidate the buffers this read is walking —
        the directory is checked before AND after the device->host
        copy and the copy retried (bounded) when the page moved or
        the buffer died underneath it. A page that cannot be read
        consistently is reported as a miss; the requester re-prefills
        those positions."""
        for _ in range(3):
            idx = self._by_hash.get(h)
            if idx is None:
                return None
            pool = self.pool
            try:
                blk = jax.device_get(self._export_page(pool, idx))
            except RuntimeError:
                # Donated-away buffer (publish/import raced us):
                # re-read the directory and try again.
                continue
            if self._by_hash.get(h) == idx and pool is self.pool:
                return {f: np.asarray(blk[f]) for f in self._fields}
        return None

    def import_pages(
            self,
            items: Sequence[Tuple[bytes, Dict[str, np.ndarray]]]
    ) -> int:
        """Land fetched remote pages into the pool (dedup by hash;
        DRIVER THREAD ONLY — this mutates the pool and directory
        exactly like publish). Shape/dtype are trusted here: the
        kv_transfer decoder already validated every block against
        the local signature. Stops at the first allocation failure
        (every page pinned) — the remaining pages simply miss and
        re-prefill. Returns the number of pages imported."""
        imported = 0
        for h, blk in items:
            if h in self._by_hash:
                self._touch(self._by_hash[h])
                continue
            dst = self._alloc()
            if dst is None:
                logger.debug(
                    'Prefix pool exhausted (all %d pages pinned): '
                    'dropping remaining KV import(s).',
                    self.pool_pages)
                break
            dev = {f: jnp.asarray(np.asarray(blk[f]),
                                  dtype=self.pool[f].dtype)
                   for f in self._fields}
            self.pool = self._import_page(self.pool, dev, dst)
            self._by_hash[h] = dst
            self._hash_of[dst] = h
            self._refs[dst] = 0
            self._touch(dst)
            self.version += 1
            imported += 1
        if imported:
            _M_IMPORTED.inc(imported)
            _M_POOL.set(len(self._by_hash))
        return imported

    def prefix_summary(self,
                       sample: Optional[int] = None) -> Dict[str, Any]:
        """Versioned directory digest for /health (docs/
        affinity_routing.md): occupied-page count, page size, the
        directory ``version``, and a recency-ordered bounded hash
        list with an explicit ``truncated`` flag — so the LB can
        tell "no match" (hash absent, not truncated) from "sample
        too small" (truncated: absence proves nothing). Memoized on
        the directory version: probes between pool mutations reuse
        the same dict with zero re-serialization. Pure host read; no
        device work."""
        if sample is None:
            sample = summary_pages()
        sample = max(0, int(sample))
        version = self.version
        cached = self._summary_cache
        if (cached is not None and cached[0] == version
                and cached[1] == sample):
            return cached[2]
        occupied = [(self._stamp[i], h)
                    for i, h in enumerate(self._hash_of)
                    if h is not None]
        occupied.sort(reverse=True)
        summary = {
            'v': SUMMARY_SCHEMA_VERSION,
            'version': version,
            'pages': len(self._by_hash),
            'page': self.page,
            'hashes': [h.hex() for _, h in occupied[:sample]],
            'truncated': len(occupied) > sample,
        }
        self._summary_cache = (version, sample, summary)
        return summary

    # ------------------------------------------------------ plumbing
    def warm(self, cache: Dict) -> Dict:
        """Compile all three programs with dummy indices (engine
        warmup calls this before its cache reset, so no XLA compile
        ever lands inside a live admission). Directory state is
        untouched — page 0 receives garbage the first real publish
        overwrites before it is ever mapped."""
        sub = {f: cache[f] for f in self._fields}
        # Two rounds, threading each program's outputs back in: the
        # first compiles against the freshly device_put pool (verbose
        # sharding specs), the second against the program-emitted
        # (GSPMD-normalized) specs every later call circulates — jit
        # keys on input shardings, so under a mesh both variants must
        # be compiled here or the first real publish retraces.
        dmask, length = cache['dmask'], cache['length']
        # The import warm block mirrors what a real fetch stages:
        # uncommitted host-built arrays (jnp.asarray of numpy in
        # import_pages), so the warmed jit key matches live imports.
        zero_blk = {
            f: jnp.zeros((self.pool[f].shape[0], 1) +
                         self.pool[f].shape[2:], self.pool[f].dtype)
            for f in self._fields}
        for _ in range(2 if self.mesh is not None else 1):
            sub = self._copy_in(sub, self.pool, 0, 0, 0)
            self.pool = self._copy_out(sub, self.pool, 0, 0, 0)
            dmask, length = self._mask_fix(dmask, length, 0, 0)
            jax.device_get(self._export_page(self.pool, 0))
            self.pool = self._import_page(self.pool, zero_blk, 0)
        out = dict(cache)
        out.update(sub)
        out['dmask'] = dmask
        out['length'] = length
        return out

    def compile_cache_sizes(self) -> Tuple[int, int, int]:
        """Compiled-program counts of the three jitted ops (the
        no-recompile-after-warmup assertion reads these)."""
        return (self._copy_in._cache_size(),
                self._copy_out._cache_size(),
                self._mask_fix._cache_size())

    def import_compile_cache_size(self) -> Tuple[int, int]:
        """Compiled-program counts of the transfer ops (export,
        import) — the disagg no-recompile assertion's counterpart to
        compile_cache_sizes (kept separate so that 3-tuple's star-
        unpacking consumers never move)."""
        return (self._export_page._cache_size(),
                self._import_page._cache_size())

    def stats(self) -> Dict[str, Any]:
        """Flat summary for bench detail (same numbers the metric
        counters expose to scrapes)."""
        return {
            'page': self.page,
            'pool_pages': self.pool_pages,
            'occupied': len(self._by_hash),
            'pinned': self.pinned_pages(),
            'lookups': self.lookups,
            'hits': self.hits,
            'hit_rate': (round(self.hits / self.lookups, 4)
                         if self.lookups else None),
            'tokens_saved': self.tokens_saved,
            'evictions': self.evictions,
        }
