"""Continuous-batching serving engine over the KV-cache decoder.

The role JetStream plays for the reference
(examples/tpu/v6e/README.md:95-120: an orchestrator that keeps a
fixed-size decode batch full by inserting freshly-prefilled requests
into slots as running ones finish). The static-batch ``generate`` in
``models.inference`` drains a whole batch before admitting new work —
a finished sequence's slot idles, capping served throughput well below
what the decode step sustains. This engine recycles slots:

- a fixed decode batch of ``batch_size`` slots, one traced
  ``decode_step`` program regardless of which slots are live
  (``active`` mask — no recompiles as load varies);
- per-request prefill at bucketed prompt lengths (powers of two up to
  ``max_prompt``), inserted into a free slot with
  ``inference.insert_prefill`` — dynamic_update_slice at the batch
  index, in place under donation;
- slot validity via the cache's dmask, so a recycled slot never reads
  its previous occupant's K/V;
- optional int8 KV cache (``kv_quant=True``): half the decode
  bandwidth, which at fixed HBM doubles ``batch_size``;
- double-buffered dispatch: the next-token vector lives on device, so
  ``step()`` dispatches decode chunk N+1 before syncing chunk N —
  host-side work (result attribution, admission grouping, HTTP
  serving, streaming callbacks) overlaps device decode instead of
  stalling it. Prefill-sampled first tokens flow into the decode
  chain on device; their host values sync lazily for emission.

Decode capacity: every engine decode step consumes one shared cache
slot (the scalar-write-slot design that keeps the step
bandwidth-bound — see inference.decode_step). A request admitted when
``remaining_slots() >= max_new`` is guaranteed to finish; when the
region is exhausted and all slots are idle the engine resets the
cache (steps=0) and keeps admitting. Size ``max_seq`` several times
the typical ``max_new`` so resets are rare.
"""
from __future__ import annotations

import collections
import dataclasses
import functools
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from skypilot_tpu import metrics as metrics_lib
from skypilot_tpu import trace as trace_lib
from skypilot_tpu.models import inference
from skypilot_tpu.models.llama import LlamaConfig

# Serving metrics (docs/metrics.md): host-side only — nothing here
# touches the jitted programs, and each update is one dict op under a
# lock, negligible against a decode chunk's device time.
_M_QUEUE_DEPTH = metrics_lib.gauge(
    'skytpu_engine_queue_depth',
    'Requests queued for admission (not yet in a decode slot).')
_M_ACTIVE_SLOTS = metrics_lib.gauge(
    'skytpu_engine_active_slots',
    'Decode slots currently occupied by a live request.')
_M_REQUESTS = metrics_lib.counter(
    'skytpu_engine_requests_total',
    'Requests accepted by submit().')
_M_TOKENS = metrics_lib.counter(
    'skytpu_engine_tokens_total',
    'Output tokens emitted to requests (rate() of this is tokens/s).')
_M_RESETS = metrics_lib.counter(
    'skytpu_engine_cache_resets_total',
    'KV-cache rebuilds after decode-region exhaustion.')
_M_TTFT = metrics_lib.histogram(
    'skytpu_engine_ttft_seconds',
    'Submit-to-first-token latency (queue wait + prefill + sync).',
    buckets=metrics_lib.LATENCY_BUCKETS)
_M_TOKEN_LATENCY = metrics_lib.histogram(
    'skytpu_engine_per_token_seconds',
    'Decode latency per emitted token: engine tick interval over '
    'tokens emitted that tick (chunk-granular; in steady state the '
    'tick interval IS the device chunk time, thanks to the '
    'double-buffered dispatch).',
    buckets=metrics_lib.FAST_LATENCY_BUCKETS)


@dataclasses.dataclass
class Request:
    request_id: Any
    tokens: Sequence[int]          # prompt token ids
    max_new: int
    # None -> the engine's default temperature. Per-request values are
    # traced (a [B] vector), so mixing them never recompiles.
    temperature: Optional[float] = None


@dataclasses.dataclass
class _SlotState:
    request_id: Any
    max_new: int
    generated: List[int]
    # Device ref (array, row) to the prefill-sampled first token;
    # synced lazily when the slot's first decode chunk is processed,
    # so admission never blocks the pipeline on a host round-trip.
    first_ref: Optional[tuple]
    prompt_len: int = 0
    # Occupancy generation: a decode chunk snapshot only credits its
    # tokens to a slot whose epoch still matches — a slot freed and
    # re-admitted while the chunk was in flight discards them.
    epoch: int = 0


@dataclasses.dataclass
class Result:
    request_id: Any
    tokens: List[int]
    prompt_len: int
    submitted_at: float
    finished_at: float


def _buckets(max_prompt: int) -> List[int]:
    out, b = [], 32
    while b < max_prompt:
        out.append(b)
        b *= 2
    out.append(max_prompt)
    return out


class ServingEngine:
    """Host-side slot orchestrator; all device work is jitted."""

    def __init__(self,
                 params: Dict,
                 cfg: LlamaConfig,
                 batch_size: int = 8,
                 max_prompt: int = 512,
                 max_seq: Optional[int] = None,
                 kv_quant: bool = False,
                 weight_quant: bool = False,
                 eos_id: Optional[int] = None,
                 temperature: float = 0.0,
                 top_k: int = 0,
                 decode_chunk: int = 8,
                 mesh=None,
                 page: Optional[int] = None,
                 decode_attn: Optional[str] = None,
                 paged_dispatch: bool = True) -> None:
        # ``mesh``: serve a model larger than one chip — params shard
        # Megatron-style (tp on heads/ffn/vocab) and the KV cache's
        # kv-head axis shards over 'tp' (inference.CACHE_SPEC), the
        # slice-serving shape of the reference's JetStream demo. The
        # host-side slot orchestration is mesh-oblivious; only the
        # jitted programs carry shardings.
        self.mesh = mesh
        from skypilot_tpu.models import gpt2 as gpt2_mod
        from skypilot_tpu.models import quantization
        if isinstance(cfg, gpt2_mod.GPT2Config):
            # The KV-cache engine (models/inference.py) is structured
            # around the Llama/MoE param tree; without this gate a
            # GPT-2 config dies deep in prefill with KeyError
            # 'tok_emb'.
            from skypilot_tpu import exceptions
            raise exceptions.NotSupportedError(
                'The serving engine supports the Llama and MoE '
                'families; GPT-2 is a training family here.')
        if weight_quant and not quantization.is_quantized(params):
            # int8 weight-only quantization (per-output-channel
            # scales): ~2x less HBM per decode step — what lets an 8B
            # model serve on one 16 GB chip. NOT donated: norm leaves
            # pass through quantize_params unchanged, so donation
            # would delete buffers the caller's tree (and any other
            # tree built from it) still aliases. The transient
            # dense+int8 residency only affects models that fit in
            # HBM dense anyway — larger models arrive pre-quantized
            # (init_quantized_params / int8 checkpoints) and skip
            # this branch.
            params = jax.jit(quantization.quantize_params)(params)
        if mesh is not None:
            # Family-dispatched specs: MoE params carry 'router' +
            # 3-D expert weights that llama's dense tree lacks.
            from skypilot_tpu import models
            specs = models.family(cfg).param_specs(cfg)
            if quantization.is_quantized(params):
                specs = quantization.quantize_specs(specs, params)
            params = jax.device_put(
                params,
                jax.tree.map(
                    lambda spec: jax.sharding.NamedSharding(mesh, spec),
                    specs))
        self.params = params
        self.cfg = cfg
        self.batch_size = batch_size
        self.max_prompt = max_prompt
        self.max_seq = max_seq or cfg.max_seq
        if self.max_seq <= max_prompt:
            raise ValueError(
                f'max_seq ({self.max_seq}) must exceed max_prompt '
                f'({max_prompt}) to leave decode slots.')
        self.kv_quant = kv_quant
        self.eos_id = eos_id
        self.temperature = temperature
        self.top_k = top_k
        # Length-aware decode dispatch: decode cost should scale with
        # cache OCCUPANCY, not max_seq. The engine tracks the live
        # region (always [0, base + steps + chunk) — the prompt
        # region up to ``base`` is pinned live the moment any decode
        # slot exists, per-row raggedness below that is the kernel's
        # per-row early exit), rounds it up to page granularity and
        # passes the page count to the jitted decode as a static arg.
        # Page counts beyond the prompt region grow in powers of two
        # (ops.decode_attention.num_pages_for), so at most
        # log2(headroom/page) decode programs exist per chunk size —
        # the same compile discipline as the power-of-two chunks.
        from skypilot_tpu.ops import decode_attention as decode_attn_mod
        self._decode_attn_mod = decode_attn_mod
        self._page = page or decode_attn_mod.default_page()
        # Resolved NOW (not at trace time inside the jitted decode):
        # the engine's dispatch is bound at construction, and the jit
        # closures never depend on a later env change.
        self._attn_impl = decode_attn_mod.resolve_impl(decode_attn)
        self.paged_dispatch = paged_dispatch
        self._total_pages = -(-self.max_seq // self._page)
        self._base_pages = -(-max_prompt // self._page)
        # Decode steps per host round-trip. Each tick scans `chunk`
        # steps on device and syncs token values once — slots that
        # finish mid-chunk idle until the tick ends (≈chunk/2 wasted
        # steps per request), but host dispatch/transfer amortizes
        # chunk-fold. 8 balances the two for max_new ~100s.
        self.decode_chunk = max(1, decode_chunk)
        self.buckets = _buckets(max_prompt)
        # Admissions go to the device in fixed-size groups (padded by
        # repetition) so each prompt bucket compiles exactly one
        # prefill+insert program.
        self.admit_group = min(8, batch_size)

        self.queue: collections.deque = collections.deque()
        self.slots: List[Optional[_SlotState]] = [None] * batch_size
        self.results: Dict[Any, Result] = {}
        self._submitted_at: Dict[Any, float] = {}
        # Per-request span state (docs/tracing.md), populated only
        # when tracing is enabled at submit() and the engine is not
        # warming: {'request', 'queue', 'prefill', 'first_chunk'}
        # spans keyed by request_id. These decompose TTFT —
        # queue-wait, prefill dispatch, first-chunk decode — and the
        # request span's start is the single timing source the TTFT
        # histogram observes (with the trace id as exemplar).
        self._req_spans: Dict[Any, Dict[str, Any]] = {}
        self._key = jax.random.PRNGKey(0)
        self._steps_done = 0
        self._epoch = 0
        # The in-flight decode chunk (double buffering): step()
        # dispatches chunk N+1 to the device BEFORE syncing chunk N's
        # tokens, so host work — result sync, admission grouping, HTTP
        # handling between ticks — overlaps device decode instead of
        # serializing with it.
        self._pending: Optional[Dict[str, Any]] = None
        # Optional streaming hook: called on the driving thread as
        # on_token(request_id, [new tokens]) every time a live
        # request's tokens reach the host (per decode chunk).
        self.on_token: Optional[Callable[[Any, List[int]], None]] = None

        cdt = cfg.compute_dtype
        kv_dtype = jnp.int8 if kv_quant else cdt
        kv_shape = (cfg.n_layers, batch_size, self.max_seq,
                    cfg.n_kv_heads, cfg.head_dim)

        def _make_empty():
            """Build a fresh zero cache ON DEMAND. No persistent
            empty template: a resident template plus the live cache
            would hold 2x the cache HBM for the engine's lifetime —
            at 8B serving shapes (3+ GB of int8 KV) exactly the
            difference between fitting a 16 GB chip and OOMing."""
            empty = {
                'k': jnp.zeros(kv_shape, kv_dtype),
                'v': jnp.zeros(kv_shape, kv_dtype),
                'length': jnp.zeros((batch_size,), jnp.int32),
                'dmask': jnp.zeros((batch_size, self.max_seq), bool),
                'base': jnp.asarray(max_prompt, jnp.int32),
                'steps': jnp.zeros((), jnp.int32),
            }
            if kv_quant:
                empty['k_scale'] = jnp.ones(kv_shape[:4], jnp.bfloat16)
                empty['v_scale'] = jnp.ones(kv_shape[:4], jnp.bfloat16)
            if mesh is not None:
                specs = inference.cache_specs(kv_quant)
                empty = {
                    f: jax.device_put(
                        v, jax.sharding.NamedSharding(mesh, specs[f]))
                    for f, v in empty.items()
                }
            return empty

        self._make_empty = _make_empty
        self.cache = _make_empty()

        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def _prefill_insert(params, cache, cur_tokens, tokens, lengths,
                            slots, key, temperature):
            """Prefill a group of same-bucket prompts and insert each
            into its batch slot — ONE device call per admission group
            (per-request calls would pay a host round-trip each, which
            dominates serving latency on high-dispatch-cost links).
            tokens: [m, bucket]; slots: [m]; cur_tokens: the
            device-resident [B] next-token vector, updated in place so
            the following decode chunk can consume the prefill-sampled
            first tokens WITHOUT a host sync. Returns (cache,
            cur_tokens, firsts).
            """
            logits, group = inference.prefill(
                params, tokens, lengths, self.cfg, mesh=self.mesh,
                max_seq=tokens.shape[1], kv_quant=self.kv_quant)
            firsts = inference._sample(logits, key, temperature,
                                       self.top_k)
            m = tokens.shape[0]
            for j in range(m):  # static unroll: m <= batch_size
                # Batch axis is second for k/v/scales ([L, B, S, ...]),
                # first for length/dmask.
                one = {
                    f: (group[f][:, j:j + 1]
                        if f in ('k', 'v', 'k_scale', 'v_scale')
                        else group[f][j:j + 1])
                    for f in group if f not in ('base', 'steps')
                }
                one['base'] = group['base']
                cache = inference.insert_prefill(cache, one, slots[j])
            cur_tokens = cur_tokens.at[slots].set(firsts)
            return cache, cur_tokens, firsts

        self._prefill_insert = _prefill_insert

        @functools.partial(jax.jit, donate_argnums=(1,),
                           static_argnames=('n', 'num_pages'))
        def _decode(params, cache, tokens, active, key, temperature,
                    *, n, num_pages=None):
            """Scan ``n`` decode steps on device, feeding each sampled
            token forward; one host sync per call, not per token.
            ``num_pages`` (static) bounds the cache region attention
            reads — the length-aware dispatch knob."""

            def body(carry, _):
                cache, tok, key = carry
                key, sub = jax.random.split(key)
                logits, cache = inference.decode_step(
                    params, cache, tok, self.cfg, mesh=self.mesh,
                    active=active, attn_impl=self._attn_impl,
                    num_pages=num_pages, page=self._page)
                nxt = inference._sample(logits, sub, temperature,
                                        self.top_k)
                return (cache, nxt, key), nxt

            (cache, last, _), toks = jax.lax.scan(
                body, (cache, tokens, key), None, length=n)
            return cache, toks, last    # toks: [n, B]; last: [B]

        self._decode = _decode
        # Per-slot current token fed into the next decode step —
        # DEVICE-resident: the token chain between chunks (and from
        # prefill into the first chunk) resolves on device, which is
        # what lets chunk N+1 dispatch before chunk N's host sync.
        self._tokens_dev = jnp.zeros((batch_size,), jnp.int32)
        # Per-slot sampling temperature (requests may override the
        # engine default; temperature is traced, so this never
        # recompiles).
        self._temps = np.full((batch_size,), temperature, np.float32)
        # Gauges exist (as 0) from boot, so a scrape of an idle
        # replica still sees the full metric surface.
        _M_QUEUE_DEPTH.touch()
        _M_ACTIVE_SLOTS.touch()
        # Warmup's synthetic requests must not count: their "TTFT"
        # is multi-second XLA compiles, which would sit in the
        # cumulative histogram forever and poison every later p99.
        self._warming = False
        # Previous step() timestamp, the per-token latency anchor.
        self._last_tick_at: Optional[float] = None

    # ------------------------------------------------------------------
    def warmup(self) -> None:
        """Compile every program a serving run can hit (one per prompt
        bucket, plus the decode chunks), then reset. Without this the
        first request of each shape pays multi-second XLA compiles
        inside its serving latency."""
        import numpy as _np
        rng = _np.random.default_rng(0)
        # Every admission call is padded to (admit_group, bucket), so
        # one request per bucket compiles its whole program.
        reqs = [
            Request(('warmup', b),
                    list(rng.integers(0, self.cfg.vocab_size, b)),
                    max_new=2) for b in self.buckets
        ]
        self._warming = True
        try:
            self.run(reqs)
        finally:
            self._warming = False
        # Also compile every (chunk size, page count) static-arg pair
        # a run can dispatch, so no XLA compile ever lands inside a
        # live request's latency. Chunk sizes fold to powers of two
        # exactly as step() does. The main chunk runs at any
        # occupancy (page-stride enumeration — the page count only
        # changes at page boundaries, and num_pages_for's pow2
        # headroom rounding keeps the set log2-bounded); tail chunks
        # fold only near region exhaustion, where remaining slots are
        # in [n, 2n) — the count is monotone in occupancy, so that
        # window's endpoints cover it.
        n = self.decode_chunk
        while n & (n - 1):
            n &= n - 1
        chunk = n

        def count_for(steps_done: int, n_: int) -> Optional[int]:
            if not self.paged_dispatch:
                return None
            return self._decode_attn_mod.num_pages_for(
                self.max_prompt + steps_done + n_, self._page,
                self._total_pages, base_pages=self._base_pages)

        cap = self.decode_capacity()
        pairs = set()
        for s in range(0, max(cap - chunk, 0) + 1,
                       max(1, self._page)):
            pairs.add((chunk, count_for(s, chunk)))
        pairs.add((chunk, count_for(max(cap - chunk, 0), chunk)))
        while n > 1:
            n //= 2
            pairs.add((n, count_for(max(0, cap - 2 * n + 1), n)))
            pairs.add((n, count_for(max(0, cap - n), n)))
        for n_, np_ in sorted(pairs, key=lambda t: (t[0], t[1] or 0)):
            self._key, sub = jax.random.split(self._key)
            self.cache, _, self._tokens_dev = self._decode(
                self.params, self.cache, self._tokens_dev,
                jnp.zeros((self.batch_size,), bool), sub,
                jnp.asarray(self._temps), n=n_, num_pages=np_)
        self.reset()

    def reset(self) -> None:
        """Drop all cache state (keeps compiled programs). Only valid
        when no requests are in flight."""
        if self.num_active() or self.queue or self._pending is not None:
            raise RuntimeError('reset() with requests in flight')
        # Drop the old cache BEFORE building the new one so the two
        # never coexist on device.
        self.cache = None
        self.cache = self._make_empty()
        self._steps_done = 0
        self.results = {}

    def submit(self, request: Request) -> None:
        if len(request.tokens) > self.max_prompt:
            raise ValueError(
                f'prompt ({len(request.tokens)}) exceeds max_prompt '
                f'({self.max_prompt}).')
        if request.max_new > self.decode_capacity():
            raise ValueError(
                f'max_new ({request.max_new}) exceeds the decode '
                f'capacity ({self.decode_capacity()}); raise max_seq.')
        self._submitted_at[request.request_id] = time.time()
        if not self._warming and trace_lib.enabled():
            # Parent = the ambient span of the submitting thread (the
            # HTTP handler's http.generate span) or the inherited
            # process context; spans then live across driver-loop
            # ticks keyed by request_id, since no call stack connects
            # submit to the first decoded token.
            req_span = trace_lib.start_span(
                'engine.request', request_id=str(request.request_id),
                prompt_len=len(request.tokens),
                max_new=request.max_new)
            self._req_spans[request.request_id] = {
                'request': req_span,
                'queue': trace_lib.start_span('engine.queue_wait',
                                              parent=req_span),
            }
        self.queue.append(request)
        if not self._warming:
            _M_REQUESTS.inc()
            _M_QUEUE_DEPTH.set(len(self.queue))

    def decode_capacity(self) -> int:
        return self.max_seq - self.max_prompt

    def _num_pages(self, n: int) -> Optional[int]:
        """Page count for the next ``n``-step decode chunk: covers the
        live region [0, base + steps_done + n) rounded up per
        ``num_pages_for`` (page-granular, pow2 headroom). None when
        length-aware dispatch is off (full cache)."""
        if not self.paged_dispatch:
            return None
        live = self.max_prompt + self._steps_done + n
        return self._decode_attn_mod.num_pages_for(
            live, self._page, self._total_pages,
            base_pages=self._base_pages)

    def remaining_slots(self) -> int:
        return self.decode_capacity() - self._steps_done

    def num_active(self) -> int:
        return sum(s is not None for s in self.slots)

    # ------------------------------------------------------------------
    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        raise AssertionError(n)

    def _admit(self) -> None:
        """Fill free slots from the queue, grouped by prompt bucket so
        each group costs one fused prefill+insert device call."""
        admits = []
        for slot_idx, state in enumerate(self.slots):
            if state is not None or not self.queue:
                continue
            if self.queue[0].max_new > self.remaining_slots():
                if (self.num_active() == 0 and not admits and
                        self._pending is None):
                    # Region exhausted, nothing running (and no chunk
                    # still in flight): fresh cache (old one dropped
                    # first — see reset()).
                    self.cache = None
                    self.cache = self._make_empty()
                    self._steps_done = 0
                    _M_RESETS.inc()
                else:
                    break  # wait for running requests to drain
            admits.append((slot_idx, self.queue.popleft()))
        if not admits:
            return

        groups: Dict[int, list] = collections.defaultdict(list)
        for slot_idx, req in admits:
            groups[self._bucket_for(len(req.tokens))].append(
                (slot_idx, req))
        chunks = []
        for bucket, items in groups.items():
            for i in range(0, len(items), self.admit_group):
                chunks.append((bucket, items[i:i + self.admit_group]))
        for bucket, items in chunks:
            m = len(items)
            # Pad every group to the fixed admit_group size by
            # repeating the first entry (a duplicate insert rewrites
            # the same slot with the same content): exactly ONE
            # compiled program per bucket, all covered by warmup().
            m_pad = self.admit_group
            padded = items + [items[0]] * (m_pad - m)
            tokens = np.zeros((m_pad, bucket), np.int32)
            lengths = np.zeros((m_pad,), np.int32)
            slot_arr = np.zeros((m_pad,), np.int32)
            for j, (slot_idx, req) in enumerate(padded):
                tokens[j, :len(req.tokens)] = req.tokens
                lengths[j] = len(req.tokens)
                slot_arr[j] = slot_idx
            temps = np.asarray([
                (req.temperature if req.temperature is not None
                 else self.temperature) for _, req in padded
            ], np.float32)
            self._key, sub = jax.random.split(self._key)
            # TTFT decomposition: queue-wait ends exactly where the
            # prefill dispatch begins (no gap between the spans).
            for _, req in items:
                ts = self._req_spans.get(req.request_id)
                if ts is not None:
                    qs = ts.pop('queue', None)
                    if qs is not None:
                        qs.finish()
                    ts['prefill'] = trace_lib.start_span(
                        'engine.prefill', parent=ts['request'],
                        bucket=bucket)
            # Fully async: the prefill-sampled first tokens land in
            # the device-resident token vector for the next decode
            # chunk; the host-side values (for emission) sync lazily
            # when that chunk's results are processed.
            self.cache, self._tokens_dev, firsts = self._prefill_insert(
                self.params, self.cache, self._tokens_dev,
                jnp.asarray(tokens), jnp.asarray(lengths),
                jnp.asarray(slot_arr), sub, jnp.asarray(temps))
            for j, (slot_idx, req) in enumerate(items):
                self._epoch += 1
                self.slots[slot_idx] = _SlotState(
                    request_id=req.request_id, max_new=req.max_new,
                    generated=[], first_ref=(firsts, j),
                    prompt_len=len(req.tokens), epoch=self._epoch)
                self._temps[slot_idx] = temps[j]
                ts = self._req_spans.get(req.request_id)
                if ts is not None:
                    ps = ts.pop('prefill', None)
                    if ps is not None:
                        # Host-side dispatch window: the device-side
                        # prefill completion is folded into the
                        # first-chunk span that starts here.
                        ps.finish(slot=slot_idx)
                    ts['first_chunk'] = trace_lib.start_span(
                        'engine.decode.first_chunk',
                        parent=ts['request'], slot=slot_idx)

    def _finish(self, slot_idx: int) -> None:
        state = self.slots[slot_idx]
        finished_at = time.time()
        self.results[state.request_id] = Result(
            request_id=state.request_id,
            tokens=state.generated,
            prompt_len=state.prompt_len,
            submitted_at=self._submitted_at.pop(state.request_id, 0.0),
            finished_at=finished_at)
        ts = self._req_spans.pop(state.request_id, None)
        if ts is not None:
            # A request can finish without ever surfacing a first
            # token through the normal path (e.g. max_new reached in
            # the same chunk): close any stragglers before the root.
            for name in ('queue', 'prefill', 'first_chunk'):
                sp = ts.pop(name, None)
                if sp is not None:
                    sp.finish()
            ts['request'].finish(tokens=len(state.generated))
        self.slots[slot_idx] = None

    def _is_done(self, state: _SlotState) -> bool:
        return (len(state.generated) >= state.max_new or
                (self.eos_id is not None and state.generated and
                 state.generated[-1] == self.eos_id))

    def step(self) -> int:
        """One pipelined engine tick.

        Admit queued requests, DISPATCH decode chunk N+1 (device),
        then sync and process chunk N. The device is already decoding
        the next chunk while the host attributes tokens, finishes
        requests, runs streaming callbacks and serves HTTP — decode
        never waits on host work (double buffering).

        Results therefore surface one tick after their final decode
        chunk. Returns the number of tokens emitted this tick.
        """
        self._admit()
        new_entry = self._dispatch_chunk()
        prev, self._pending = self._pending, new_entry
        emitted = self._process_chunk(prev)
        # Per-token latency at tick granularity: the interval between
        # consecutive ticks over the tokens this tick surfaced. Host
        # timestamps within one tick would be sync artifacts (a
        # request finishing inside a single chunk shows ~0s/token);
        # the tick interval is the real pipeline rate.
        tick_at = time.perf_counter()
        if (emitted and not self._warming and
                self._last_tick_at is not None):
            _M_TOKEN_LATENCY.observe(
                (tick_at - self._last_tick_at) / emitted)
        self._last_tick_at = tick_at
        _M_QUEUE_DEPTH.set(len(self.queue))
        _M_ACTIVE_SLOTS.set(self.num_active())
        return emitted

    def flush(self) -> int:
        """Sync and process the in-flight chunk without dispatching a
        new one (pipeline drain at shutdown / idle)."""
        prev, self._pending = self._pending, None
        return self._process_chunk(prev)

    @property
    def has_pending(self) -> bool:
        return self._pending is not None

    def _dispatch_chunk(self) -> Optional[Dict[str, Any]]:
        active_list = [s is not None for s in self.slots]
        if not any(active_list):
            return None
        # Chunk size: bounded by global capacity (admission guarantees
        # every active request fits in the remaining region) and kept
        # to power-of-two tails so at most log2(chunk) programs exist.
        n = min(self.decode_chunk, self.remaining_slots())
        if n < 1:
            # Region exhausted while slots are still occupied. Because
            # slots free one tick AFTER their final chunk (pipelining),
            # this is the normal end state of a request whose max_new
            # consumed the region exactly: every active slot has
            # already decoded its full max_new in flight — admission
            # guarantees capacity ≥ the largest outstanding need, and
            # all slots advance together. Dispatch nothing; processing
            # the pending chunk frees them.
            if self._pending is None:
                raise RuntimeError(
                    'capacity accounting violated: region exhausted '
                    'with active slots and no chunk in flight')
            return None
        while n & (n - 1):
            n &= n - 1
        self._key, sub = jax.random.split(self._key)
        self.cache, toks, self._tokens_dev = self._decode(
            self.params, self.cache, self._tokens_dev,
            jnp.asarray(active_list), sub, jnp.asarray(self._temps),
            n=n, num_pages=self._num_pages(n))
        self._steps_done += n
        # Snapshot which occupant each decoded column belongs to: by
        # the time this chunk is synced the slot may have finished and
        # been recycled (its column decoded garbage — discarded by the
        # epoch check).
        snapshot = [(i, s.epoch) for i, s in enumerate(self.slots)
                    if s is not None]
        return {'toks': toks, 'n': n, 'snapshot': snapshot}

    def _process_chunk(self, entry: Optional[Dict[str, Any]]) -> int:
        if entry is None:
            return 0
        toks_host = np.asarray(entry['toks'])   # [n, B] — THE sync
        emitted = 0
        now = time.time()
        firsts_cache: Dict[int, np.ndarray] = {}
        for slot_idx, epoch in entry['snapshot']:
            state = self.slots[slot_idx]
            if state is None or state.epoch != epoch:
                continue          # freed/recycled mid-flight
            fresh: List[int] = []
            if state.first_ref is not None:
                # Prefill-sampled first token: computed strictly
                # before this chunk on device, so this sync is free.
                arr, j = state.first_ref
                host = firsts_cache.get(id(arr))
                if host is None:
                    host = np.asarray(arr)
                    firsts_cache[id(arr)] = host
                state.first_ref = None
                state.generated.append(int(host[j]))
                fresh.append(int(host[j]))
                emitted += 1
                if not self._warming:
                    # Single timing source: with tracing on, TTFT is
                    # the request span's age at first token — exactly
                    # what the span tree decomposes — and the trace
                    # id rides on the histogram as an exemplar.
                    ts = self._req_spans.get(state.request_id)
                    if ts is not None:
                        fc = ts.pop('first_chunk', None)
                        if fc is not None:
                            fc.finish()
                        _M_TTFT.observe(
                            now - ts['request'].start_time,
                            exemplar=ts['request'].exemplar)
                    else:
                        _M_TTFT.observe(now - self._submitted_at.get(
                            state.request_id, now))
            if not self._is_done(state):
                for t in range(entry['n']):
                    tok = int(toks_host[t, slot_idx])
                    state.generated.append(tok)
                    fresh.append(tok)
                    emitted += 1
                    if self._is_done(state):
                        # Tokens past max_new/EOS within the chunk
                        # are discarded.
                        break
            if fresh and self.on_token is not None:
                self.on_token(state.request_id, fresh)
            if self._is_done(state):
                self._finish(slot_idx)
        if emitted and not self._warming:
            _M_TOKENS.inc(emitted)
        return emitted

    def drain_results(self) -> Dict[Any, Result]:
        """Pop and return all finished results. Long-running servers
        MUST drain (rather than read ``results``) or every request's
        tokens are archived forever."""
        out = self.results
        self.results = {}
        return out

    def _inflight_ids(self) -> set:
        ids = {r.request_id for r in self.queue}
        ids.update(s.request_id for s in self.slots if s is not None)
        return ids

    def run(self,
            requests: Sequence[Request],
            on_result: Optional[Callable[[Result], None]] = None
            ) -> Dict[Any, Result]:
        """Serve ``requests`` to completion (continuous batching).

        Returns (and fires ``on_result`` for) only THIS call's
        requests; finished results are drained, not archived.
        """
        wanted = set()
        inflight = self._inflight_ids()
        for r in requests:
            if r.request_id in wanted or r.request_id in inflight:
                raise ValueError(
                    f'duplicate request_id {r.request_id!r}')
            wanted.add(r.request_id)
        for r in requests:
            self.submit(r)
        collected: Dict[Any, Result] = {}
        while self.queue or self.num_active() or self.has_pending:
            self.step()
            for rid, res in self.drain_results().items():
                collected[rid] = res
                if on_result and rid in wanted:
                    on_result(res)
        return {rid: collected[rid] for rid in wanted}
