"""Continuous-batching serving engine over the KV-cache decoder.

The role JetStream plays for the reference
(examples/tpu/v6e/README.md:95-120: an orchestrator that keeps a
fixed-size decode batch full by inserting freshly-prefilled requests
into slots as running ones finish). The static-batch ``generate`` in
``models.inference`` drains a whole batch before admitting new work —
a finished sequence's slot idles, capping served throughput well below
what the decode step sustains. This engine recycles slots:

- a fixed decode batch of ``batch_size`` slots, one traced
  ``decode_step`` program regardless of which slots are live
  (``active`` mask — no recompiles as load varies);
- **chunked prefill with a token-budgeted mixed scheduler**
  (Sarathi-Serve, Agrawal et al., OSDI '24): an admitted prompt is
  not prefilled monolithically — it streams into its slot's
  prompt-region KV ``prefill_chunk`` tokens per tick
  (``inference.prefill_chunk``), coalesced INTO the same fused device
  program as the decode chunk for active slots. Per tick at most
  ``prefill_budget`` prompt tokens are processed across prefilling
  slots, so inter-token latency of in-flight decodes is bounded by
  the tick budget, never by a co-admitted prompt's length. This also
  kills the old power-of-two prefill buckets: ONE chunk shape serves
  any prompt length <= max_prompt with zero padding waste, instead
  of log2(max_prompt) bucket programs padded up to 2x.
- slot validity via the cache's dmask, so a recycled slot never reads
  its previous occupant's K/V;
- **automatic prefix caching** (``SKYTPU_PREFIX_CACHE=1``;
  models/prefix_cache.py): prompt token blocks are chain-hashed at
  page granularity against a device-resident shared page pool. An
  admission hit copies the longest cached prefix into the slot's
  prompt-region KV (fixed-shape warmed copy programs — no new traced
  shapes), starts the prefill cursor at the cached boundary, and
  charges admission only for the uncached suffix — hits raise
  effective capacity, not just TTFT. Terminal slots publish their
  final prompt pages back and release their pins. Off (default) the
  engine is bit-identical to a build without the cache.
- **speculative multi-token decoding** (``SKYTPU_SPEC_DECODE=1``;
  Leviathan et al. 2023, proposer in the spirit of prompt-lookup /
  n-gram decoding, Saxena 2023): decode MFU is pinned by one token
  per model step — the MXU idles while HBM streams the same weights
  every step. A host-side prompt-lookup proposer drafts up to
  ``SKYTPU_SPEC_K`` candidate tokens per greedy decode slot from the
  slot's own token chain; the tick's batched verify pass
  (``inference.verify_step`` over
  ``ops.flash_attention.verify_attention``) scores all of them in ONE
  forward and accepts the longest prefix matching the model's own
  samples, falling back to the model's token at the first rejection —
  greedy outputs stay bitwise identical to speculation-off. Rejected
  candidates' K/V roll back through the existing dmask/length
  machinery; sampling (temperature>0) slots transparently bypass
  speculation; a capacity guard falls back to the plain decode chunk
  near region exhaustion so the finish guarantee is untouched. Spec
  tick shapes are keyed on ``(spec_k,)`` and compiled in
  ``warmup()`` — no recompiles after warmup, speculation on or off.
- optional int8 KV cache (``kv_quant=True``): half the decode
  bandwidth, which at fixed HBM doubles ``batch_size``;
- double-buffered dispatch: the next-token vector lives on device, so
  ``step()`` dispatches tick N+1 before syncing tick N — host-side
  work (result attribution, admission grouping, HTTP serving,
  streaming callbacks) overlaps device work instead of stalling it.
  Prefill-sampled first tokens flow into the decode chain on device;
  their host values sync lazily for emission.

Decode capacity: every engine decode step consumes one shared cache
slot (the scalar-write-slot design that keeps the step
bandwidth-bound — see inference.decode_step). Admission accounts for
the decode steps other slots will burn while a prompt is still
prefilling: a request is admitted only when
``max_new + ceil(prompt/chunk) * decode_chunk`` fits the remaining
region (or ``max_new`` alone when it would run solo — prefill-only
ticks dispatch no decode steps), which preserves the old guarantee
that every admitted request finishes. When the region is exhausted
and all slots are idle the engine resets the cache (steps=0) and
keeps admitting. Size ``max_seq`` several times the typical
``max_new`` so resets are rare.

Request lifecycle (docs/request_lifecycle.md): no admitted request is
immortal. ``Request.deadline`` bounds its lifetime — the tick loop
expires past-deadline slots AND queued requests; ``cancel()`` (thread
safe, applied at the tick boundary) frees a slot mid-prefill or
mid-decode, recycling its KV row for the next admission and surfacing
a partial ``Result`` (status='cancelled', tokens so far);
``estimate_wait_s()`` turns queue depth + prefill backlog + decode
width into the admission-time signal the HTTP front end sheds on; a
tick watchdog flags device hangs (``SKYTPU_TICK_HANG_SECONDS``).
Every terminal path produces exactly one ``Result``.
"""
from __future__ import annotations

import collections
import dataclasses
import functools
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Set

import jax
import jax.numpy as jnp
import numpy as np

from skypilot_tpu import metrics as metrics_lib
from skypilot_tpu import trace as trace_lib
from skypilot_tpu.models import inference
from skypilot_tpu.models.llama import LlamaConfig
from skypilot_tpu.utils import env_registry
from skypilot_tpu.utils import fault_injection
from skypilot_tpu.utils import lifecycle
from skypilot_tpu.utils import log as sky_logging
from skypilot_tpu.utils import qos as qos_lib

logger = sky_logging.init_logger(__name__)

# Serving metrics (docs/metrics.md): host-side only — nothing here
# touches the jitted programs, and each update is one dict op under a
# lock, negligible against a decode chunk's device time.
_M_QUEUE_DEPTH = metrics_lib.gauge(
    'skytpu_engine_queue_depth',
    'Requests queued for admission (not yet in a decode slot).')
_M_ACTIVE_SLOTS = metrics_lib.gauge(
    'skytpu_engine_active_slots',
    'Decode slots currently occupied by a live request.')
_M_REQUESTS = metrics_lib.counter(
    'skytpu_engine_requests_total',
    'Requests accepted by submit().')
_M_TOKENS = metrics_lib.counter(
    'skytpu_engine_tokens_total',
    'Output tokens emitted to requests (rate() of this is tokens/s).')
_M_PREFILL_TOKENS = metrics_lib.counter(
    'skytpu_engine_prefill_tokens_total',
    'Prompt tokens prefilled into slot KV caches (chunked prefill; '
    'per tick this never exceeds the prefill token budget).')
_M_RESETS = metrics_lib.counter(
    'skytpu_engine_cache_resets_total',
    'KV-cache rebuilds after decode-region exhaustion.')
_M_TTFT = metrics_lib.histogram(
    'skytpu_engine_ttft_seconds',
    'Submit-to-first-token latency (queue wait + prefill + sync).',
    buckets=metrics_lib.LATENCY_BUCKETS)
_M_ITL = metrics_lib.histogram(
    'skytpu_engine_itl_seconds',
    'Inter-token latency: gap between consecutive token batches '
    'surfaced to one request (the streaming stall a client feels). '
    'With chunked prefill its p99 is bounded by the tick budget, not '
    'by co-admitted prompt lengths. Acceptance-aware by '
    'construction: a speculative burst observes its full gap ONCE '
    '(never gap/burst-size) — accepted drafts widen bursts, they '
    'never shrink the reported stall.',
    buckets=metrics_lib.LATENCY_BUCKETS)
_M_CANCELS = metrics_lib.counter(
    'skytpu_engine_cancels_total',
    'Requests removed before natural completion, by reason '
    '(deadline, client_disconnect, shutdown, api, ...). The freed '
    'slot is recycled for the next admission '
    '(docs/request_lifecycle.md).',
    labels=('reason',))
_M_TICK_HANGS = metrics_lib.counter(
    'skytpu_engine_tick_hangs_total',
    'Engine ticks slower than SKYTPU_TICK_HANG_SECONDS (watchdog: a '
    'wedged device tick must be visible, not a silent stall).')
_M_TOKEN_LATENCY = metrics_lib.histogram(
    'skytpu_engine_per_token_seconds',
    'Decode latency per MODEL-STEP token: engine tick interval over '
    'tokens emitted that tick MINUS speculatively accepted draft '
    'tokens (chunk-granular; in steady state the tick interval IS '
    'the device chunk time, thanks to the double-buffered dispatch). '
    'Acceptance-aware: a 4-token accepted burst rides along free in '
    'wall-time and must not deflate the reported per-token latency '
    '4x — speculative throughput shows up in tokens_total and the '
    'spec counters instead.',
    buckets=metrics_lib.FAST_LATENCY_BUCKETS)
_M_SPEC_PROPOSED = metrics_lib.counter(
    'skytpu_engine_spec_proposed_tokens_total',
    'Draft tokens proposed to verify ticks by the prompt-lookup '
    'proposer (SKYTPU_SPEC_DECODE; accepted/proposed is the '
    'acceptance rate metrics.summary() derives).')
_M_SPEC_ACCEPTED = metrics_lib.counter(
    'skytpu_engine_spec_accepted_tokens_total',
    'Drafted tokens the batched verify pass accepted (each one is an '
    'output token that skipped a sequential decode step).')
# SLO telemetry (docs/load_testing.md): sliding-window p99 gauges the
# autoscaler scrapes. The cumulative TTFT/ITL histograms never forget,
# so their quantiles cannot come back down after a transient
# regression — these gauges re-estimate p99 over the last
# SKYTPU_SLO_WINDOW_S seconds and carry the trace id of the latest
# SLO-violating request as an exemplar.
_M_TTFT_P99 = metrics_lib.gauge(
    'skytpu_engine_ttft_p99_seconds',
    'Sliding-window p99 of submit-to-first-token latency '
    '(SKYTPU_SLO_WINDOW_S; exemplar = trace id of the latest request '
    'over the SKYTPU_SLO_TTFT_S threshold). The TTFT signal the SLO '
    'autoscaler scrapes.')
_M_ITL_P99 = metrics_lib.gauge(
    'skytpu_engine_itl_p99_seconds',
    'Sliding-window p99 of inter-token latency (SKYTPU_SLO_WINDOW_S; '
    'exemplar = trace id of the latest request over the '
    'SKYTPU_SLO_ITL_S threshold). The ITL signal the SLO autoscaler '
    'scrapes.')
_M_EST_WAIT = metrics_lib.gauge(
    'skytpu_engine_est_wait_seconds',
    'estimate_wait_s(0, 1) refreshed every tick: the queue-wait a '
    'minimal request arriving NOW would see, from queue depth + '
    'prefill backlog + decode width over the measured tick EWMA. '
    'The admission-pressure signal the SLO autoscaler scrapes — it '
    'rises with a traffic spike ticks before the 60 s QPS window '
    'does.')
_M_SLO_VIOLATIONS = metrics_lib.counter(
    'skytpu_engine_slo_violations_total',
    'Latency observations over their configured SLO threshold, by '
    'kind: one per request for ttft (SKYTPU_SLO_TTFT_S), one per '
    'inter-token gap for itl (SKYTPU_SLO_ITL_S) — a long stream '
    'with many slow gaps counts each stall it inflicted.',
    labels=('kind',))
# Multi-tenant QoS telemetry (docs/qos.md). Class labels are a
# closed 3-value set; tenant labels are caller-controlled, so that
# series is EXPLICITLY bounded — past max_series new tenants fold
# into the registry's '_other' bucket instead of growing it.
_M_SHEDS = metrics_lib.counter(
    'skytpu_engine_sheds_total',
    'Queued requests shed by the QoS queue-pressure bound '
    '(SKYTPU_QOS_MAX_QUEUE), by priority class — bulk sheds before '
    'standard before interactive (docs/qos.md).',
    labels=('class',), max_series=8)
_M_PREEMPTS = metrics_lib.counter(
    'skytpu_engine_preempted_total',
    'Decode slots preempt-cancelled (reason=preempted_by_priority) '
    'to unblock a sustained higher-priority admission stall '
    '(SKYTPU_QOS_PREEMPT_AFTER_S), by the VICTIM\'s priority class.',
    labels=('class',), max_series=8)
_M_TENANT_TOKENS = metrics_lib.counter(
    'skytpu_engine_tenant_tokens_total',
    'Output tokens emitted, by tenant (requests that name no tenant '
    'are not counted here — skytpu_engine_tokens_total is the '
    'all-traffic series). Bounded: past max_series tenants fold '
    'into _other.',
    labels=('tenant',), max_series=64)
_M_CLASS_TTFT_P99 = metrics_lib.gauge(
    'skytpu_engine_class_ttft_p99_seconds',
    'Sliding-window p99 of submit-to-first-token latency by '
    'priority class (SKYTPU_SLO_WINDOW_S): the per-class SLO signal '
    'the autoscaler scrapes when the ServiceSpec declares per-class '
    'targets (docs/qos.md).',
    labels=('class',), max_series=8)
_M_ATTN_IMPL = metrics_lib.gauge(
    'skytpu_engine_attn_impl',
    'Info gauge (value 1, impl label): the decode-attention impl '
    'this engine actually dispatches. A downgrade from the requested '
    "'paged' fast path (page-misaligned max_seq) is warned once and "
    'surfaces here in a scrape — a perf cliff must show up in '
    'monitoring, not in a roofline postmortem (docs/metrics.md).',
    labels=('impl',), max_series=4)

# Warn-once registry for attention-impl downgrades: every engine in a
# process shares the page/env configuration, so one warning per
# reason is signal and N are noise.
_ATTN_DOWNGRADE_WARNED: Set[str] = set()


def _warn_attn_downgrade(reason: str, detail: str) -> None:
    if reason in _ATTN_DOWNGRADE_WARNED:
        return
    _ATTN_DOWNGRADE_WARNED.add(reason)
    logger.warning(
        'Decode attention downgraded to the lax reference (%s): %s. '
        'The effective impl is exported as skytpu_engine_attn_impl '
        'and in bench detail.', reason, detail)


# Consecutive no-draft proposal rounds before the engine goes "dry":
# while dry, ticks stay fully pipelined (no flush) and proposals only
# probe for a re-arm — never-matching traffic pays a bounded number
# of flushes for speculation being enabled.
_SPEC_DRY_AFTER = 4
# Cap on the doubling re-arm cooldown (dry probe-hit rounds): keeps a
# reject-latched engine retrying speculation eventually — workloads
# shift as slots turn over — while bounding the steady-state waste.
_SPEC_COOLDOWN_MAX = 256


def _prompt_lookup(chain: Sequence[int], k: int,
                   max_ngram: int) -> List[int]:
    """Model-free n-gram draft proposer (prompt-lookup decoding,
    Saxena 2023): find the most recent EARLIER occurrence of the
    chain's trailing n-gram (longest n first, n = max_ngram..1) and
    propose the up-to-``k`` tokens that followed it. Pure host-side
    numpy — sliding-window equality, no device work, no model. Hot
    traffic that repeats prompt text (the prefix-cache workloads)
    is exactly where this hits. Returns [] when nothing matches.
    """
    n_total = len(chain)
    if n_total < 2 or k <= 0:
        return []
    arr = np.asarray(chain, np.int64)
    for n in range(min(max_ngram, n_total - 1), 0, -1):
        pat = arr[n_total - n:]
        # Windows at start positions [0, n_total - n): every strictly
        # earlier occurrence of the trailing n-gram (the window AT
        # n_total - n is the pattern itself).
        win = np.lib.stride_tricks.sliding_window_view(
            arr, n)[:n_total - n]
        hits = np.nonzero((win == pat).all(axis=1))[0]
        if hits.size:
            s = int(hits[-1])          # most recent match wins
            cont = chain[s + n:s + n + k]
            if len(cont):       # len(): chain may be a numpy view
                return [int(t) for t in cont]
    return []


class DuplicateRequestError(ValueError):
    """``submit()`` with a request_id already queued or in a slot.

    Admitting the duplicate would clobber the first request's
    ``_submitted_at``/``_req_spans`` tracking (leaking its open span
    and corrupting its TTFT) and make result attribution ambiguous —
    a typed reject lets HTTP front ends map it to a clean 400/409.
    """


@dataclasses.dataclass
class Request:
    request_id: Any
    tokens: Sequence[int]          # prompt token ids
    max_new: int
    # None -> the engine's default temperature. Per-request values are
    # traced (a [B] vector), so mixing them never recompiles.
    temperature: Optional[float] = None
    # Absolute ``time.time()`` deadline; the tick loop expires the
    # request (queued or mid-decode) once it passes, surfacing a
    # partial Result with status='expired'. None = immortal (legacy).
    deadline: Optional[float] = None
    # Multi-tenant QoS (docs/qos.md): the submitting tenant (None =
    # anonymous — exempt from token-bucket rate limiting) and the
    # priority class ('interactive' | 'standard' | 'bulk'; None =
    # standard). Requests that set neither ride the legacy FIFO path
    # bit-for-bit.
    tenant: Optional[str] = None
    priority_class: Optional[str] = None


@dataclasses.dataclass
class _SlotState:
    request_id: Any
    max_new: int
    generated: List[int]
    # The request's prompt tokens: the chunked prefill feeds
    # ``prefill_chunk``-sized slices of these per tick while
    # ``phase == 'prefill'``; ``prefill_pos`` is the cursor.
    prompt: List[int]
    prompt_len: int = 0
    phase: str = 'prefill'         # 'prefill' -> 'decode'
    prefill_pos: int = 0
    # Admission order: prefill scheduling is FIFO across slots.
    seq: int = 0
    # Occupancy generation: a tick snapshot only credits its tokens
    # to a slot whose epoch still matches — a slot freed and
    # re-admitted while the tick was in flight discards them.
    epoch: int = 0
    # perf_counter of the last host-side token emission (ITL anchor).
    last_emit_at: Optional[float] = None
    # The request's absolute deadline (copied from Request at
    # admission; the tick loop expires past-deadline slots).
    deadline: Optional[float] = None
    # Prompt tokens served from the prefix pool at admission (0
    # without the cache / on a miss): the prefill span's chunk count
    # and the publish path read these instead of recomputing.
    reused: int = 0
    # Chain hashes of the prompt's full pages, carried over from the
    # admission lookup so publish() never re-hashes the prompt.
    prompt_hashes: Optional[List[bytes]] = None
    # Speculative-decode draft for the NEXT tick (SKYTPU_SPEC_DECODE):
    # up to spec_k candidate tokens the prompt-lookup proposer
    # predicts follow the chain's current token. Re-proposed every
    # tick from the fresh chain; None = no match / sampling slot.
    draft: Optional[List[int]] = None
    # Incremental token-chain buffer for the proposer (int64 numpy,
    # doubling capacity): rebuilding prompt+generated as a fresh list
    # + array every tick would put O(chain) host work per slot on the
    # (unpipelined) spec critical path. chain_len tracks the filled
    # region; only newly generated tokens append per tick.
    chain_buf: Optional[np.ndarray] = None
    chain_len: int = 0
    # QoS identity, copied from the Request at admission: the
    # preemption victim choice and the per-tenant/per-class
    # telemetry read these off the slot.
    tenant: Optional[str] = None
    priority_class: Optional[str] = None


@dataclasses.dataclass
class Result:
    request_id: Any
    tokens: List[int]
    prompt_len: int
    submitted_at: float
    finished_at: float
    # Terminal state (docs/request_lifecycle.md): 'finished' |
    # 'cancelled' | 'expired'. Cancelled/expired results carry the
    # tokens decoded so far — partial output is still output.
    status: str = lifecycle.FINISHED
    # Why a non-finished request ended ('deadline', 'shutdown',
    # 'client_disconnect', ...). None for natural completion.
    reason: Optional[str] = None


class ServingEngine:
    """Host-side slot orchestrator; all device work is jitted."""

    def __init__(self,
                 params: Dict,
                 cfg: LlamaConfig,
                 batch_size: int = 8,
                 max_prompt: int = 512,
                 max_seq: Optional[int] = None,
                 kv_quant: bool = False,
                 weight_quant: bool = False,
                 eos_id: Optional[int] = None,
                 temperature: float = 0.0,
                 top_k: int = 0,
                 decode_chunk: int = 8,
                 mesh=None,
                 page: Optional[int] = None,
                 decode_attn: Optional[str] = None,
                 paged_dispatch: bool = True,
                 prefill_chunk: Optional[int] = None,
                 prefill_budget: Optional[int] = None,
                 prefix_cache: Optional[bool] = None,
                 prefix_pool_pages: Optional[int] = None,
                 spec_decode: Optional[bool] = None,
                 spec_k: Optional[int] = None,
                 spec_ngram: Optional[int] = None) -> None:
        # ``mesh``: serve a model larger than one chip — params shard
        # Megatron-style (tp on heads/ffn/vocab) and the KV cache's
        # kv-head axis shards over 'tp' (inference.CACHE_SPEC), the
        # slice-serving shape of the reference's JetStream demo. The
        # host-side slot orchestration is mesh-oblivious; only the
        # jitted programs carry shardings.
        self.mesh = mesh
        from skypilot_tpu.models import gpt2 as gpt2_mod
        from skypilot_tpu.models import quantization
        if isinstance(cfg, gpt2_mod.GPT2Config):
            # The KV-cache engine (models/inference.py) is structured
            # around the Llama/MoE param tree; without this gate a
            # GPT-2 config dies deep in prefill with KeyError
            # 'tok_emb'.
            from skypilot_tpu import exceptions
            raise exceptions.NotSupportedError(
                'The serving engine supports the Llama and MoE '
                'families; GPT-2 is a training family here.')
        if weight_quant and not quantization.is_quantized(params):
            # int8 weight-only quantization (per-output-channel
            # scales): ~2x less HBM per decode step — what lets an 8B
            # model serve on one 16 GB chip. NOT donated: norm leaves
            # pass through quantize_params unchanged, so donation
            # would delete buffers the caller's tree (and any other
            # tree built from it) still aliases. The transient
            # dense+int8 residency only affects models that fit in
            # HBM dense anyway — larger models arrive pre-quantized
            # (init_quantized_params / int8 checkpoints) and skip
            # this branch.
            params = jax.jit(quantization.quantize_params)(params)
        if mesh is not None:
            # Family-dispatched specs: MoE params carry 'router' +
            # 3-D expert weights that llama's dense tree lacks.
            from skypilot_tpu import models
            specs = models.family(cfg).param_specs(cfg)
            if quantization.is_quantized(params):
                specs = quantization.quantize_specs(specs, params)
            params = jax.device_put(
                params,
                jax.tree.map(
                    lambda spec: jax.sharding.NamedSharding(mesh, spec),
                    specs))
        self.params = params
        self.cfg = cfg
        self.batch_size = batch_size
        self.max_prompt = max_prompt
        self.max_seq = max_seq or cfg.max_seq
        if self.max_seq <= max_prompt:
            raise ValueError(
                f'max_seq ({self.max_seq}) must exceed max_prompt '
                f'({max_prompt}) to leave decode slots.')
        self.kv_quant = kv_quant
        self.eos_id = eos_id
        self.temperature = temperature
        self.top_k = top_k
        # Length-aware decode dispatch: decode cost should scale with
        # cache OCCUPANCY, not max_seq. The engine tracks the live
        # region (always [0, base + steps + chunk) — the prompt
        # region up to ``base`` is pinned live the moment any decode
        # slot exists, per-row raggedness below that is the kernel's
        # per-row early exit), rounds it up to page granularity and
        # passes the page count to the jitted decode as a static arg.
        # Page counts beyond the prompt region grow in powers of two
        # (ops.decode_attention.num_pages_for), so at most
        # log2(headroom/page) decode programs exist per chunk size —
        # the same compile discipline as the power-of-two chunks.
        from skypilot_tpu.ops import decode_attention as decode_attn_mod
        self._decode_attn_mod = decode_attn_mod
        self._page = page or decode_attn_mod.default_page()
        # Resolved NOW (not at trace time inside the jitted decode):
        # the engine's dispatch is bound at construction, and the jit
        # closures never depend on a later env change.
        self._attn_impl = decode_attn_mod.resolve_impl(decode_attn)
        self.paged_dispatch = paged_dispatch
        self._total_pages = -(-self.max_seq // self._page)
        self._base_pages = -(-max_prompt // self._page)
        # Resolve any dispatch downgrade HERE, observably —
        # inference.decode_step would silently fall back to 'lax' for
        # a page-misaligned cache; the engine instead warns once and
        # exports the EFFECTIVE impl to /metrics and bench detail.
        # (Meshes no longer downgrade: the sharded cache runs the
        # shard_map'd kernel.)
        if self._attn_impl == 'paged' and self.max_seq % self._page:
            _warn_attn_downgrade(
                'page_misaligned',
                f'max_seq {self.max_seq} is not a multiple of the '
                f'decode page size {self._page}')
            self._attn_impl = 'lax'
        self.attn_impl = self._attn_impl
        _M_ATTN_IMPL.set(1, impl=self._attn_impl)
        # Decode steps per host round-trip. Each tick scans `chunk`
        # steps on device and syncs token values once — slots that
        # finish mid-chunk idle until the tick ends (≈chunk/2 wasted
        # steps per request), but host dispatch/transfer amortizes
        # chunk-fold. 8 balances the two for max_new ~100s.
        self.decode_chunk = max(1, decode_chunk)
        # Chunked-prefill knobs (SKYTPU_PREFILL_CHUNK /
        # SKYTPU_PREFILL_BUDGET): prompts stream into their slot's KV
        # ``prefill_chunk`` tokens per tick; at most ``prefill_budget``
        # prompt tokens are processed per tick across all prefilling
        # slots. The budget folds to whole chunk rows
        # (G = budget // chunk rows of fixed [G, chunk] shape), so
        # exactly ONE prefill program shape exists — the pow2 bucket
        # set is gone.
        chunk = prefill_chunk or int(env_registry.get(
            env_registry.SKYTPU_PREFILL_CHUNK, '128'))
        budget = prefill_budget or int(env_registry.get(
            env_registry.SKYTPU_PREFILL_BUDGET, '256'))
        self.prefill_chunk = max(1, min(chunk, max_prompt))
        self._prefill_rows = max(
            1, min(budget // self.prefill_chunk, batch_size))
        self.prefill_budget = self._prefill_rows * self.prefill_chunk
        # Automatic prefix caching (SKYTPU_PREFIX_CACHE /
        # SKYTPU_PREFIX_POOL_PAGES; models/prefix_cache.py): pages are
        # hashed at the decode-dispatch page size, so the cache unit
        # and the paged-attention unit stay one concept. Off by
        # default — disabled, every path below is bit-identical to
        # the pre-cache engine.
        enable_prefix = prefix_cache
        if enable_prefix is None:
            enable_prefix = env_registry.is_enabled(
                env_registry.SKYTPU_PREFIX_CACHE)
        self.prefix = None
        if enable_prefix:
            # Mesh engines shard the pool on kv heads (the cache's
            # own 'tp' layout), so prefix hits, COW and
            # admission-suffix pricing compose under TP — no more
            # single-chip-only warn+disable.
            from skypilot_tpu.models import prefix_cache as prefix_mod
            pool_pages = prefix_pool_pages or int(env_registry.get(
                env_registry.SKYTPU_PREFIX_POOL_PAGES,
                str(prefix_mod.DEFAULT_POOL_PAGES)))
            self.prefix = prefix_mod.PrefixCache(
                cfg, page=self._page, pool_pages=pool_pages,
                kv_quant=kv_quant, mesh=mesh)
        # Speculative multi-token decoding (SKYTPU_SPEC_DECODE /
        # SKYTPU_SPEC_K / SKYTPU_SPEC_NGRAM; PERFORMANCE.md
        # "Speculative decoding"): a host-side prompt-lookup proposer
        # drafts up to spec_k tokens per greedy decode slot; the tick
        # verifies all of them in ONE forward and accepts the longest
        # prefix matching the model's own samples. Off by default —
        # disabled, every tick below is bit-identical to the
        # pre-speculation engine.
        enable_spec = spec_decode
        if enable_spec is None:
            enable_spec = env_registry.is_enabled(
                env_registry.SKYTPU_SPEC_DECODE)
        k_req = spec_k if spec_k is not None else int(env_registry.get(
            env_registry.SKYTPU_SPEC_K, '4'))
        if enable_spec and k_req < 1:
            # An explicit 0 (ctor, --spec-k, SKYTPU_SPEC_K) means "no
            # draft tokens" — honor it as spec-off rather than
            # silently substituting the default.
            logger.warning(
                'Speculative decoding disabled: spec_k=%d requests '
                'no draft tokens.', k_req)
            enable_spec = False
        self.spec_k = max(1, k_req)
        self._spec_ngram = max(1, spec_ngram or int(env_registry.get(
            env_registry.SKYTPU_SPEC_NGRAM, '3')))
        self._spec_v = self.spec_k + 1      # fed segment width
        if enable_spec and self._spec_v > self.decode_capacity():
            logger.warning(
                'Speculative decoding disabled: the verify segment '
                '(%d columns) exceeds the decode region (%d); raise '
                'max_seq or lower SKYTPU_SPEC_K.', self._spec_v,
                self.decode_capacity())
            enable_spec = False
        self.spec_decode = bool(enable_spec)
        # Host-side speculation accounting (bench.py spec detail).
        self.spec_proposed_total = 0
        self.spec_accepted_total = 0
        self.spec_emitted_total = 0
        self.spec_ticks = 0
        self.spec_row_steps = 0
        self.spec_draft_s = 0.0
        # Accepted drafts surfaced by the tick being processed: the
        # acceptance-aware divisor for skytpu_engine_per_token_seconds.
        self._tick_accepted = 0
        # Dry-spell latch with hysteresis: after _SPEC_DRY_AFTER
        # consecutive eligible proposal rounds matched nothing,
        # step() keeps the pipelined dispatch (no flush) and only
        # PROBES the chain for a re-arm — steady no-match traffic
        # pays a bounded number of flushes, then nothing, for
        # speculation being on.
        self._spec_dry = False
        self._spec_misses = 0
        # Re-arm cooldown, in dry probe-hit rounds: doubles each time
        # the latch re-arms without an accepted draft since, so a
        # proposer whose matches the model never confirms (spurious
        # short n-grams) decays to a vanishing fraction of verify
        # ticks instead of oscillating at the hysteresis period; any
        # accepted draft resets it to re-arm-immediately.
        self._spec_cooldown = 0
        self._spec_dry_rounds = 0

        self.queue: collections.deque = collections.deque()
        self.slots: List[Optional[_SlotState]] = [None] * batch_size
        self.results: Dict[Any, Result] = {}
        self._submitted_at: Dict[Any, float] = {}
        # Per-request span state (docs/tracing.md), populated only
        # when tracing is enabled at submit() and the engine is not
        # warming: {'request', 'queue', 'prefill', 'first_chunk'}
        # spans keyed by request_id. These decompose TTFT —
        # queue-wait, chunked prefill (with one subspan per dispatched
        # chunk), first-chunk decode — and the request span's start is
        # the single timing source the TTFT histogram observes (with
        # the trace id as exemplar).
        self._req_spans: Dict[Any, Dict[str, Any]] = {}
        self._key = jax.random.PRNGKey(0)
        self._steps_done = 0
        self._epoch = 0
        self._seq = 0
        # The in-flight tick (double buffering): step() dispatches
        # tick N+1 to the device BEFORE syncing tick N's tokens, so
        # host work — result sync, admission grouping, HTTP handling
        # between ticks — overlaps device work instead of serializing
        # with it.
        self._pending: Optional[Dict[str, Any]] = None
        # Optional streaming hook: called on the driving thread as
        # on_token(request_id, [new tokens]) every time a live
        # request's tokens reach the host (per tick).
        self.on_token: Optional[Callable[[Any, List[int]], None]] = None
        # Pending cancellations (request_id -> reason), recorded by
        # cancel() from any thread and applied by the driver at the
        # next tick boundary — the one place slot/queue state may be
        # mutated without racing an in-flight device tick.
        self._cancels: Dict[Any, str] = {}
        self._cancel_lock = threading.Lock()
        # Fetched remote KV pages awaiting import (docs/
        # disaggregation.md): HTTP threads enqueue [(hash, block)]
        # batches, the driver lands them into the prefix pool at the
        # next tick boundary BEFORE admission — a request submitted
        # after its pages were queued is guaranteed to see them at
        # its own admission. deque append/popleft are atomic; no
        # lock needed.
        self._kv_imports: collections.deque = collections.deque()
        # Serializes concurrent submit() callers so the duplicate-id
        # check and the queue append are one atomic step.
        self._submit_lock = threading.Lock()
        # Single-entry memo of the queue head's _fits suffix lookup:
        # (Request object, pool directory version, suffix).
        # Driver-thread only; the strong reference is what makes the
        # identity key collision-proof.
        self._fits_memo: Optional[tuple] = None
        # EWMA of recent working-tick durations: the time base for
        # estimate_wait_s()'s deadline-aware admission estimate.
        # None until the first measured tick (no signal -> admit).
        self._tick_ewma: Optional[float] = None
        # Tick watchdog threshold, resolved at construction like the
        # decode dispatch knobs (0 disables).
        self._tick_hang_s = lifecycle.tick_hang_s()

        cdt = cfg.compute_dtype
        kv_dtype = jnp.int8 if kv_quant else cdt
        kv_shape = (cfg.n_layers, batch_size, self.max_seq,
                    cfg.n_kv_heads, cfg.head_dim)

        def _make_empty():
            """Build a fresh zero cache ON DEMAND. No persistent
            empty template: a resident template plus the live cache
            would hold 2x the cache HBM for the engine's lifetime —
            at 8B serving shapes (3+ GB of int8 KV) exactly the
            difference between fitting a 16 GB chip and OOMing."""
            empty = {
                'k': jnp.zeros(kv_shape, kv_dtype),
                'v': jnp.zeros(kv_shape, kv_dtype),
                'length': jnp.zeros((batch_size,), jnp.int32),
                'dmask': jnp.zeros((batch_size, self.max_seq), bool),
                'base': jnp.asarray(max_prompt, jnp.int32),
                'steps': jnp.zeros((), jnp.int32),
            }
            if kv_quant:
                empty['k_scale'] = jnp.ones(kv_shape[:4], jnp.bfloat16)
                empty['v_scale'] = jnp.ones(kv_shape[:4], jnp.bfloat16)
            if mesh is not None:
                # Fresh caches adopt the EXACT sharding objects the
                # tick programs emit once warmup has captured them
                # (self._cache_shardings): jit keys its compile cache
                # on input shardings, and GSPMD normalizes specs on
                # program outputs (size-1 mesh axes dropped) while
                # device_put keeps the written spec verbatim — two
                # textual variants of one physical layout that would
                # otherwise retrace every warmed pair after reset().
                specs = inference.cache_specs(kv_quant)
                empty = {
                    f: jax.device_put(
                        v, self._cache_shardings.get(
                            f, jax.sharding.NamedSharding(
                                mesh, specs[f])))
                    for f, v in empty.items()
                }
            return empty

        self._cache_shardings: Dict[str, Any] = {}
        self._make_empty = _make_empty
        self.cache = _make_empty()

        def _decode_scan(params, cache, tokens, active, key,
                         temperature, n, num_pages):
            """Scan ``n`` decode steps on device, feeding each sampled
            token forward; shared by the decode-only and the mixed
            tick programs. ``num_pages`` (static) bounds the cache
            region attention reads — the length-aware dispatch knob.
            ``n == 0`` (static) skips the scan entirely (prefill-only
            ticks)."""

            def body(carry, _):
                cache, tok, key = carry
                key, sub = jax.random.split(key)
                logits, cache = inference.decode_step(
                    params, cache, tok, self.cfg, mesh=self.mesh,
                    active=active, attn_impl=self._attn_impl,
                    num_pages=num_pages, page=self._page)
                nxt = inference._sample(logits, sub, temperature,
                                        self.top_k)
                # Inactive rows FREEZE their token chain: a slot that
                # completed its prefill this very tick holds its
                # sampled first token in the vector and joins the
                # active mask only next tick — the scan must not
                # clobber it with garbage samples from its idle row.
                nxt = jnp.where(active, nxt, tok)
                return (cache, nxt, key), nxt

            (cache, last, _), toks = jax.lax.scan(
                body, (cache, tokens, key), None, length=n)
            return cache, toks, last    # toks: [n, B]; last: [B]

        @functools.partial(jax.jit, donate_argnums=(1,),
                           static_argnames=('n', 'num_pages'))
        def _decode(params, cache, tokens, active, key, temperature,
                    *, n, num_pages=None):
            """Decode-only tick: one host sync per ``n`` steps, not
            per token."""
            return _decode_scan(params, cache, tokens, active, key,
                                temperature, n, num_pages)

        self._decode = _decode

        @functools.partial(jax.jit, donate_argnums=(1, 2),
                           static_argnames=('n', 'num_pages', 'spec'))
        def _mixed(params, cache, cur_tokens, ctoks, cstarts, clens,
                   clive, clast, cslots, active, key, temperature,
                   drafts, spec_len, *, n, num_pages=None, spec=0):
            """ONE fused mixed tick: up to G prefill chunk rows
            (inference.prefill_chunk — [G, C] statically shaped, the
            per-tick token budget) PLUS the ``n``-step decode scan
            for active slots, one device round-trip total. Rows whose
            chunk completes its prompt (``clast``) get a first token
            sampled from the chunk's last-position logits, folded
            into the device-resident next-token vector so the
            following decode chunk consumes it WITHOUT a host sync;
            host values sync lazily for emission. Prefilling slots
            are decode-inactive, so chunk writes and decode
            reads/writes never touch the same row.

            ``spec`` (static, the verify segment width V = spec_k+1;
            0 = off) swaps the decode scan for the batched
            draft-and-verify pass (inference.verify_step): every
            active slot feeds its current token plus its drafted
            candidates, one forward scores them all, and each row
            advances by its accepted prefix + 1. Shapes are keyed on
            spec alone, so spec ticks compile once per page count in
            warmup() exactly like decode chunks."""
            key_p, key_d = jax.random.split(key)
            logits, cache = inference.prefill_chunk(
                params, cache, ctoks, cstarts, clens, clive,
                cslots, self.cfg, prompt_base=self.max_prompt,
                mesh=self.mesh)
            firsts = inference._sample(logits, key_p,
                                       temperature[cslots], self.top_k)
            take = clive & clast
            for j in range(self._prefill_rows):  # static unroll
                cur_tokens = jnp.where(
                    take[j],
                    cur_tokens.at[cslots[j]].set(firsts[j]),
                    cur_tokens)
            if spec:
                emit, counts, cur_tokens, cache = inference.verify_step(
                    params, cache, cur_tokens, drafts, spec_len,
                    self.cfg, key_d, temperature, self.top_k,
                    mesh=self.mesh, active=active,
                    num_pages=num_pages, page=self._page)
                return cache, emit, cur_tokens, firsts, counts
            cache, toks, last = _decode_scan(
                params, cache, cur_tokens, active, key_d, temperature,
                n, num_pages)
            return cache, toks, last, firsts, None

        self._mixed = _mixed

        @functools.partial(jax.jit, donate_argnums=(1,),
                           static_argnames=('num_pages',))
        def _spec_tick(params, cache, cur_tokens, drafts, spec_len,
                       active, key, temperature, *, num_pages=None):
            """Verify-only tick (no prefilling slot this tick): the
            batched draft-and-verify pass alone — the speculative
            counterpart of the decode-only fast path."""
            return inference.verify_step(
                params, cache, cur_tokens, drafts, spec_len, self.cfg,
                key, temperature, self.top_k, mesh=self.mesh,
                active=active, num_pages=num_pages, page=self._page)

        self._spec = _spec_tick
        # Per-slot current token fed into the next decode step —
        # DEVICE-resident: the token chain between chunks (and from
        # prefill into the first chunk) resolves on device, which is
        # what lets tick N+1 dispatch before tick N's host sync.
        self._tokens_dev = jnp.zeros((batch_size,), jnp.int32)
        # Per-slot sampling temperature (requests may override the
        # engine default; temperature is traced, so this never
        # recompiles).
        self._temps = np.full((batch_size,), temperature, np.float32)
        # All-zero draft arrays for non-speculative mixed ticks (the
        # traced args exist either way; only spec ticks fill them).
        self._drafts0 = jnp.zeros((batch_size, self.spec_k), jnp.int32)
        self._slen0 = jnp.zeros((batch_size,), jnp.int32)
        # SLO telemetry (docs/load_testing.md): sliding p99 windows
        # behind the cumulative histograms, and the violation
        # thresholds. 0 = no threshold (windows/gauges update
        # regardless; only violation accounting and exemplar pinning
        # are gated).
        window_s = float(env_registry.get(
            env_registry.SKYTPU_SLO_WINDOW_S, '60'))
        self._slo_ttft_s = float(env_registry.get(
            env_registry.SKYTPU_SLO_TTFT_S, '0'))
        self._slo_itl_s = float(env_registry.get(
            env_registry.SKYTPU_SLO_ITL_S, '0'))
        self._ttft_window = metrics_lib.SlidingWindowPercentile(
            window_s)
        self._itl_window = metrics_lib.SlidingWindowPercentile(
            window_s)
        # Multi-tenant QoS (docs/qos.md), resolved at construction
        # like every other dispatch knob. The scheduler stays DORMANT
        # — _admit runs the legacy FIFO pop bit-for-bit — until a
        # request actually names a tenant or a non-default class, or
        # the per-tenant token buckets are configured; _qos_active
        # latches on first sight and never clears (single-class
        # traffic therefore never pays the DRR scan).
        self._qos_cfg = qos_lib.qos_config_from_env()
        self._qos_weights = qos_lib.parse_weights()
        # DRR quantum = one decode chunk of tick-tokens per weight
        # unit per round: small enough that interleave granularity
        # tracks class weights, large enough that a typical charge
        # clears in a handful of rounds.
        self._drr = qos_lib.DeficitRoundRobin(
            self._qos_weights, quantum=float(self.decode_chunk))
        self._buckets: Dict[str, qos_lib.TokenBucket] = {}
        self._qos_active = (self._qos_cfg['tenant_rate'] > 0 and
                            not self._qos_cfg['disable'])
        # Monotonic timestamp since when the best-ranked queued
        # request has been admission-blocked while a strictly
        # lower-class slot runs (the preemption timer); None = not
        # currently blocked that way.
        self._qos_blocked_since: Optional[float] = None
        # Synthetic-burst id counter (engine.tenant.burst fault site).
        self._burst_seq = 0
        # Per-class sliding TTFT windows behind
        # skytpu_engine_class_ttft_p99_seconds.
        self._class_ttft_windows = {
            cls: metrics_lib.SlidingWindowPercentile(window_s)
            for cls in qos_lib.PRIORITY_CLASSES}
        # Next refresh_slo_gauges() deadline (perf_counter): bounds
        # the est-wait O(queue) scan to 4 Hz however hot the tick
        # loop runs.
        self._slo_refresh_at = 0.0
        # Gauges exist (as 0) from boot, so a scrape of an idle
        # replica still sees the full metric surface.
        _M_QUEUE_DEPTH.touch()
        _M_ACTIVE_SLOTS.touch()
        _M_TTFT_P99.touch()
        _M_ITL_P99.touch()
        _M_EST_WAIT.touch()
        if self.spec_decode:
            # Spec counters exist (as 0) the moment speculation is
            # on: an all-reject workload must still scrape a 0
            # accepted series, not a missing one (inc(0) is the
            # counter's touch()).
            _M_SPEC_PROPOSED.inc(0)
            _M_SPEC_ACCEPTED.inc(0)
        # Warmup's synthetic requests must not count: their "TTFT"
        # is multi-second XLA compiles, which would sit in the
        # cumulative histogram forever and poison every later p99.
        self._warming = False
        # Previous step() timestamp, the per-token latency anchor.
        self._last_tick_at: Optional[float] = None
        # Per-tick prefill-token accounting (bench serve reports
        # these; the budget invariant is last <= prefill_budget).
        self.last_tick_prefill_tokens = 0
        self.max_tick_prefill_tokens = 0
        self.prefill_tokens_total = 0
        self.prefill_ticks = 0

    # ------------------------------------------------------------------
    def warmup(self) -> None:
        """Compile every program a serving run can hit, then reset.
        Without this the first request of each shape pays
        multi-second XLA compiles inside its serving latency.

        Compile-count math: the chunked scheduler needs the
        decode-only and the mixed program per reachable
        (decode-steps, page-count) static pair, plus one prefill-only
        mixed program — 2 * |pairs| + 1, where |pairs| is
        log2-bounded exactly as before. The old monolithic admission
        additionally compiled one prefill+insert program per
        power-of-two prompt bucket; those are gone (one [G, C] chunk
        shape serves every prompt length)."""
        import numpy as _np
        rng = _np.random.default_rng(0)
        # One full-length and one sub-chunk prompt: exercises the
        # host paths end to end (multi-chunk prefill, completion,
        # decode handoff); every device program is then compiled
        # explicitly below.
        reqs = [
            Request(('warmup', 0),
                    list(rng.integers(0, self.cfg.vocab_size,
                                      self.max_prompt)), max_new=2),
            Request(('warmup', 1),
                    list(rng.integers(
                        0, self.cfg.vocab_size,
                        max(1, self.prefill_chunk // 2))), max_new=2),
        ]
        self._warming = True
        try:
            self.run(reqs)
        finally:
            self._warming = False
        # Compile every (chunk size, page count) static-arg pair a
        # run can dispatch — for BOTH tick programs — so no XLA
        # compile ever lands inside a live request's latency. Chunk
        # sizes fold to powers of two exactly as step() does. The
        # main chunk runs at any occupancy (page-stride enumeration —
        # the page count only changes at page boundaries, and
        # num_pages_for's pow2 headroom rounding keeps the set
        # log2-bounded); tail chunks fold only near region
        # exhaustion, where remaining slots are in [n, 2n) — the
        # count is monotone in occupancy, so that window's endpoints
        # cover it.
        n = self.decode_chunk
        while n & (n - 1):
            n &= n - 1
        chunk = n

        def count_for(steps_done: int, n_: int) -> Optional[int]:
            if not self.paged_dispatch:
                return None
            return self._decode_attn_mod.num_pages_for(
                self.max_prompt + steps_done + n_, self._page,
                self._total_pages, base_pages=self._base_pages)

        cap = self.decode_capacity()
        # With speculation on, verify ticks advance the frontier by V
        # columns, so steps_done is no longer chunk-granular: the
        # page-count enumeration walks EVERY reachable steps value
        # (host-side integer math — a few thousand adds into a small
        # set) instead of the page stride that suffices when all
        # ticks advance by chunk multiples.
        stride = 1 if self.spec_decode else max(1, self._page)
        pairs = set()
        for s in range(0, max(cap - chunk, 0) + 1, stride):
            pairs.add((chunk, count_for(s, chunk)))
        pairs.add((chunk, count_for(max(cap - chunk, 0), chunk)))
        while n > 1:
            n //= 2
            lo, hi = max(0, cap - 2 * n + 1), max(0, cap - n)
            if self.spec_decode:
                for s in range(lo, hi + 1):
                    pairs.add((n, count_for(s, n)))
            else:
                pairs.add((n, count_for(lo, n)))
                pairs.add((n, count_for(hi, n)))
        # Prefill-only mixed ticks dispatch with (n=0, num_pages=None)
        # — the canonical pair for "no decode scan this tick".
        mixed_pairs = sorted(pairs, key=lambda t: (t[0], t[1] or 0))
        mixed_pairs.insert(0, (0, None))
        # One live single-token chunk row aimed at slot 0 (the cache
        # is dirtied, then reset below): compiles the mixed program
        # for every pair without touching real requests.
        g, c = self._prefill_rows, self.prefill_chunk
        chunk_args = (jnp.zeros((g, c), jnp.int32),
                      jnp.zeros((g,), jnp.int32),
                      jnp.ones((g,), jnp.int32),
                      jnp.zeros((g,), bool).at[0].set(True),
                      jnp.zeros((g,), bool),
                      jnp.zeros((g,), jnp.int32))
        no_active = jnp.zeros((self.batch_size,), bool)
        # The SAME zero-draft arrays runtime dispatch passes: warmup
        # must compile against the exact shapes ticks will use.
        drafts0, slen0 = self._drafts0, self._slen0
        for n_, np_ in sorted(pairs, key=lambda t: (t[0], t[1] or 0)):
            self._key, sub = jax.random.split(self._key)
            self.cache, _, self._tokens_dev = self._decode(
                self.params, self.cache, self._tokens_dev, no_active,
                sub, jnp.asarray(self._temps), n=n_, num_pages=np_)
        for n_, np_ in mixed_pairs:
            self._key, sub = jax.random.split(self._key)
            self.cache, _, self._tokens_dev, _, _ = self._mixed(
                self.params, self.cache, self._tokens_dev,
                *chunk_args, no_active, sub,
                jnp.asarray(self._temps), drafts0, slen0,
                n=n_, num_pages=np_)
        if self.spec_decode:
            # Verify-tick programs: one _spec and one mixed-spec
            # variant per page count a verify segment can dispatch
            # with (steps in [0, cap - V], exhaustively enumerated —
            # spec ticks land at arbitrary steps values).
            v = self._spec_v
            spec_counts = set()
            for s in range(0, max(cap - v, 0) + 1):
                spec_counts.add(count_for(s, v))
            for np_ in sorted(spec_counts, key=lambda t: t or 0):
                self._key, sub = jax.random.split(self._key)
                _, _, self._tokens_dev, self.cache = self._spec(
                    self.params, self.cache, self._tokens_dev,
                    drafts0, slen0, no_active, sub,
                    jnp.asarray(self._temps), num_pages=np_)
                self._key, sub = jax.random.split(self._key)
                self.cache, _, self._tokens_dev, _, _ = self._mixed(
                    self.params, self.cache, self._tokens_dev,
                    *chunk_args, no_active, sub,
                    jnp.asarray(self._temps), drafts0, slen0,
                    n=0, num_pages=np_, spec=v)
        if self.mesh is not None:
            # Capture the tick-emitted shardings (BEFORE prefix.warm
            # — its copy programs stamp their own textual variants)
            # so _make_empty (every reset) and the post-admission
            # rewrap rebuild caches that hash identically to
            # post-tick ones — see the _make_empty comment.
            self._cache_shardings = {
                f: v.sharding for f, v in self.cache.items()
                if hasattr(v, 'sharding')}
        if self.prefix is not None:
            # Prefix-cache copy programs (page copy-in/out + the
            # dmask/length fix): fixed shapes with traced indices —
            # ONE program each, compiled here so a cache hit never
            # pays an XLA compile inside admission.
            self.cache = self.prefix.warm(self.cache)
            self.cache = self._recanon(self.cache)
        self.reset()

    def _recanon(self, cache: Dict) -> Dict:
        """Rewrap cache fields with the tick-emitted shardings
        captured in warmup. The prefix copy programs return arrays
        whose sharding specs are physically identical but TEXTUALLY
        different from the tick programs' GSPMD-normalized forms
        (e.g. P() vs P(None,) for a replicated vector), and jit keys
        its compile cache on input shardings — without this rewrap
        the first tick after a cache hit retraces. device_put onto an
        equivalent sharding moves no data."""
        if not self._cache_shardings:
            return cache
        return {
            f: (jax.device_put(v, self._cache_shardings[f])
                if f in self._cache_shardings else v)
            for f, v in cache.items()}

    def reset(self) -> None:
        """Drop all cache state (keeps compiled programs). Only valid
        when no requests are in flight."""
        if self.num_active() or self.queue or self._pending is not None:
            raise RuntimeError('reset() with requests in flight')
        # Drop the old cache BEFORE building the new one so the two
        # never coexist on device.
        self.cache = None
        self.cache = self._make_empty()
        self._steps_done = 0
        self.results = {}

    def submit(self, request: Request) -> None:
        if len(request.tokens) == 0:
            raise ValueError(
                'empty prompt: a request needs at least one token '
                '(prefill has no position to sample from).')
        if len(request.tokens) > self.max_prompt:
            raise ValueError(
                f'prompt ({len(request.tokens)}) exceeds max_prompt '
                f'({self.max_prompt}).')
        if request.max_new <= 0:
            raise ValueError(
                f'max_new ({request.max_new}) must be positive.')
        if request.max_new > self.decode_capacity():
            raise ValueError(
                f'max_new ({request.max_new}) exceeds the decode '
                f'capacity ({self.decode_capacity()}); raise max_seq.')
        if (not self._qos_active and
                not self._qos_cfg['disable'] and
                (request.tenant is not None or
                 (request.priority_class is not None and
                  request.priority_class != qos_lib.DEFAULT_CLASS))):
            # Sticky latch (GIL-atomic bool write; the driver reads
            # it at the next tick boundary): from the first request
            # that names a tenant or a non-default class, admission
            # switches from the legacy FIFO pop to the QoS scheduler.
            # SKYTPU_QOS_DISABLE=1 pins the legacy path regardless.
            self._qos_active = True
        # Duplicate check + tracking writes + append under one lock:
        # check-then-append without it lets two concurrent submitters
        # of the same id both pass the membership test — exactly the
        # span-leak/TTFT clobbering the typed reject exists to
        # prevent. Only submitters contend here; the driver's popleft
        # cannot mint a duplicate, so it stays lock-free.
        with self._submit_lock:
            # Exact O(1) in-flight test: _submitted_at gains the id
            # right below (under this lock) and loses it only when
            # the request's ONE terminal Result is recorded
            # (_terminal) — no queue/slot scan needed.
            if request.request_id in self._submitted_at:
                # Admitting the duplicate would clobber the first
                # request's _submitted_at/_req_spans entries and leak
                # its open span (regression-tested).
                raise DuplicateRequestError(
                    f'duplicate request_id {request.request_id!r}: a '
                    'request with this id is already in flight.')
            self._submitted_at[request.request_id] = time.time()
            if not self._warming and trace_lib.enabled():
                # Parent = the ambient span of the submitting thread
                # (the HTTP handler's http.generate span) or the
                # inherited process context; spans then live across
                # driver-loop ticks keyed by request_id, since no
                # call stack connects submit to the first decoded
                # token.
                req_span = trace_lib.start_span(
                    'engine.request',
                    request_id=str(request.request_id),
                    prompt_len=len(request.tokens),
                    max_new=request.max_new)
                self._req_spans[request.request_id] = {
                    'request': req_span,
                    'queue': trace_lib.start_span('engine.queue_wait',
                                                  parent=req_span),
                }
            self.queue.append(request)
        if not self._warming:
            _M_REQUESTS.inc()
            _M_QUEUE_DEPTH.set(len(self.queue))

    def decode_capacity(self) -> int:
        return self.max_seq - self.max_prompt

    def limits(self) -> Dict[str, int]:
        """The replica's static admission limits, advertised on
        /health (docs/failover.md): the LB's stream-resumption path
        re-submits prompt + tokens-emitted-so-far, and the grown
        prompt must fit THIS replica's max_prompt — publishing the
        limits lets callers (and the chaos bench) size workloads so
        resumes stay admissible instead of discovering a 400."""
        return {
            'max_prompt': self.max_prompt,
            'max_seq': self.max_seq,
            'decode_capacity': self.decode_capacity(),
            'batch_size': self.batch_size,
        }

    def _num_pages(self, n: int) -> Optional[int]:
        """Page count for the next ``n``-step decode chunk: covers the
        live region [0, base + steps_done + n) rounded up per
        ``num_pages_for`` (page-granular, pow2 headroom). None when
        length-aware dispatch is off (full cache)."""
        if not self.paged_dispatch:
            return None
        live = self.max_prompt + self._steps_done + n
        return self._decode_attn_mod.num_pages_for(
            live, self._page, self._total_pages,
            base_pages=self._base_pages)

    def remaining_slots(self) -> int:
        return self.decode_capacity() - self._steps_done

    def num_active(self) -> int:
        return sum(s is not None for s in self.slots)

    # ------------------------------------------------------------------
    def _prefill_ticks(self, tokens_left: int) -> int:
        return -(-tokens_left // self.prefill_chunk)

    def _suffix_len(self, prompt_len: int,
                    tokens: Optional[Sequence[int]] = None,
                    holder: Optional[Any] = None) -> int:
        """Prompt tokens that must actually be prefilled: with the
        prefix cache enabled and the token ids known, the cached
        prefix is served from the pool, so only the uncached suffix
        costs prefill ticks. Pure read — safe from HTTP threads (the
        deadline-shed estimate passes tokens through here).
        ``holder`` (the Request object, when there is one) caches the
        chain hashes so repeated estimates never re-hash a prompt."""
        if self.prefix is None or tokens is None:
            return prompt_len
        return prompt_len - self.prefix.reusable_tokens(
            tokens, self.prefill_chunk, holder=holder)

    def _fits(self, req: Request) -> bool:
        """May ``req`` be admitted without breaking the finish
        guarantee? Invariant: at every tick the remaining decode
        region covers the worst-case steps any occupied slot still
        needs — ``max_new`` left to decode plus ``decode_chunk``
        region steps other slots may burn per remaining prefill tick
        (the scheduler prefills every prefilling slot every tick, so
        admission caps prefilling slots at the budget's row count and
        the tick estimate is exact). Each tick consumes n <=
        decode_chunk while every slot's outstanding drops by >= n, so
        the invariant is preserved once established at admission.
        Solo exception: with no co-resident slots, prefill ticks
        dispatch no decode steps, so a lone request only needs
        ``max_new`` — which keeps max_new == capacity admissible."""
        remaining = self.remaining_slots()
        occupied = [s for s in self.slots if s is not None]
        if not occupied:
            return req.max_new <= remaining
        # Prefix-cache hits charge only the UNCACHED suffix: the
        # cached pages copy in without burning prefill ticks, so a
        # hit raises effective capacity, not just TTFT. (Consistent
        # with _admit: the same lookup runs there in the same tick,
        # and pages pinned at acquire cannot evict in between.)
        # Memoized on (Request IDENTITY, pool directory version) —
        # _fits re-runs for the queue head every tick it fails to
        # admit, and the lookup answer only changes when a page is
        # published or evicted. Object identity (not request_id):
        # ids may legally be reused across requests with different
        # tokens, and the held reference keeps the id() from being
        # recycled.
        if self.prefix is None or self._warming:
            suffix = len(req.tokens)
        else:
            memo = self._fits_memo
            if (memo is not None and memo[0] is req and
                    memo[1] == self.prefix.version):
                suffix = memo[2]
            else:
                suffix = self._suffix_len(len(req.tokens), req.tokens,
                                          holder=req)
                self._fits_memo = (req, self.prefix.version, suffix)
        charge = (req.max_new + self._prefill_ticks(suffix) *
                  self.decode_chunk)
        if charge > remaining:
            return False
        for s in occupied:
            left = s.max_new - len(s.generated)
            if s.phase == 'prefill':
                left += (self._prefill_ticks(
                    s.prompt_len - s.prefill_pos) * self.decode_chunk)
            if left > remaining:
                # An earlier solo admission's full (co-resident)
                # charge no longer fits: adding a decoder now could
                # strand it mid-prefill.
                return False
        return True

    def _admission_charge(self, req: Request) -> int:
        """The request's admission cost in tick-tokens — the SAME
        cost model _fits charges against the decode region: max_new
        decode steps plus decode_chunk region steps per prefill tick
        of the uncached suffix. This is the currency the QoS token
        buckets and DRR deficits are priced in (docs/qos.md), so
        rate limits and fairness track actual capacity consumption,
        not request counts."""
        if self.prefix is None or self._warming:
            suffix = len(req.tokens)
        else:
            suffix = self._suffix_len(len(req.tokens), req.tokens,
                                      holder=req)
        return (req.max_new +
                self._prefill_ticks(suffix) * self.decode_chunk)

    def _bucket_for(self, tenant: Optional[str]
                    ) -> Optional[qos_lib.TokenBucket]:
        """The tenant's token bucket (created full on first sight).
        None when rate limiting is off or the request is anonymous —
        tenancy is opt-in, and an unnamed request cannot be rate-
        limited against anyone in particular."""
        if tenant is None or self._qos_cfg['tenant_rate'] <= 0:
            return None
        bkt = self._buckets.get(tenant)
        if bkt is None:
            bkt = qos_lib.TokenBucket(
                rate=self._qos_cfg['tenant_rate'],
                burst=self._qos_cfg['tenant_burst'],
                updated=time.monotonic())
            self._buckets[tenant] = bkt
        return bkt

    def _qos_select(self) -> Optional[int]:
        """Queue index of the next request the QoS scheduler would
        admit, or None when every stream head is blocked by its
        token bucket or DRR deficit this round.

        One call = one DRR round: every live (tenant, class) stream
        earns quantum * weight deficit, then streams are visited in
        class-rank order (rotation within a rank) and the first head
        whose charge clears BOTH its bucket and its deficit wins.
        Nothing is spent here — _admit charges on actual admission,
        so a head later rejected by _fits keeps its budget. Index
        scan, not iteration: submit() may append concurrently
        (appends keep indexes valid; this driver is the sole popper).
        """
        heads: Dict[tuple, tuple] = {}
        for i in range(len(self.queue)):
            try:
                r = self.queue[i]
            except IndexError:
                break
            key = (r.tenant,
                   r.priority_class or qos_lib.DEFAULT_CLASS)
            if key not in heads:
                heads[key] = (i, r)
        if not heads:
            return None
        self._drr.earn(list(heads.keys()))
        now = time.monotonic()
        for key in self._drr.order():
            if key not in heads:
                continue
            idx, r = heads[key]
            charge = self._admission_charge(r)
            bkt = self._bucket_for(key[0])
            if bkt is not None and not bkt.peek(charge, now):
                continue
            if not self._drr.can_spend(key, charge):
                continue
            return idx
        return None

    def _qos_shed_queue(self) -> None:
        """Queue-pressure shedding (SKYTPU_QOS_MAX_QUEUE): while the
        queue exceeds the bound, cancel the NEWEST request of the
        LOWEST class — bulk sheds before standard before interactive,
        and within a class the most recently submitted goes first
        (it has waited least). Terminal status is 'cancelled' with
        reason='shed_by_priority' (lifecycle has exactly three
        terminal states; the reason is the QoS discriminator)."""
        bound = self._qos_cfg['max_queue']
        if bound <= 0 or len(self.queue) <= bound:
            return
        while len(self.queue) > bound:
            victim = None      # (rank, queue index, request)
            for i in range(len(self.queue)):
                try:
                    r = self.queue[i]
                except IndexError:
                    break
                cand = (qos_lib.class_rank(r.priority_class), i, r)
                if victim is None or cand > victim:
                    victim = cand
            if victim is None:
                return
            _, _, req = victim
            cls = req.priority_class or qos_lib.DEFAULT_CLASS
            self._cancel_now(req.request_id, 'shed_by_priority',
                             lifecycle.CANCELLED)
            if not self._warming:
                _M_SHEDS.inc(1, **{'class': cls})

    def _qos_maybe_preempt(self) -> None:
        """Sustained-overload preemption (SKYTPU_QOS_PREEMPT_AFTER_S):
        when the best-ranked queued request has been admission-
        blocked for the threshold while a STRICTLY lower class holds
        a decode slot, preempt-cancel the youngest lowest-class slot
        (reason='preempted_by_priority' — PR 7's cancel path frees
        the slot at this same tick boundary). At most one victim per
        tick: preemption is a pressure valve, not a scheduler."""
        threshold = self._qos_cfg['preempt_after_s']
        if threshold <= 0:
            return
        best = None            # (rank, request)
        for i in range(len(self.queue)):
            try:
                r = self.queue[i]
            except IndexError:
                break
            rank = qos_lib.class_rank(r.priority_class)
            if best is None or rank < best[0]:
                best = (rank, r)
        if best is None:
            self._qos_blocked_since = None
            return
        rank, head = best
        victim = None          # (victim rank, seq, slot state)
        for s in self.slots:
            if s is None:
                continue
            vrank = qos_lib.class_rank(s.priority_class)
            if vrank <= rank:
                continue
            cand = (vrank, s.seq, s)
            if victim is None or (cand[0], cand[1]) > (victim[0],
                                                       victim[1]):
                victim = cand
        blocked = (victim is not None and
                   (not any(s is None for s in self.slots) or
                    not self._fits(head)))
        if not blocked:
            self._qos_blocked_since = None
            return
        now = time.monotonic()
        if self._qos_blocked_since is None:
            self._qos_blocked_since = now
            return
        if now - self._qos_blocked_since < threshold:
            return
        state = victim[2]
        cls = state.priority_class or qos_lib.DEFAULT_CLASS
        self._cancel_now(state.request_id, 'preempted_by_priority',
                         lifecycle.CANCELLED)
        if not self._warming:
            _M_PREEMPTS.inc(1, **{'class': cls})
        self._qos_blocked_since = None

    def _inject_tenant_burst(self, params: Dict[str, Any]) -> None:
        """Act out a fired engine.tenant.burst fault: submit the
        params-described synthetic requests from the named tenant
        into our own queue. Deterministic (seeded rng, counter-
        unique ids) so chaos isolation tests replay bit-identically
        without a load generator (docs/qos.md)."""
        tenant = str(params.get('tenant', 'chaos-tenant'))
        cls = str(params.get('priority_class', 'bulk'))
        n = int(params.get('n', 8))
        prompt_len = min(int(params.get('prompt_len', 32)),
                         self.max_prompt)
        max_new = min(int(params.get('max_new', 16)),
                      self.decode_capacity())
        rng = np.random.default_rng(int(params.get('seed', 0)))
        for _ in range(max(0, n)):
            self._burst_seq += 1
            toks = rng.integers(
                1, max(2, self.cfg.vocab_size - 1),
                size=max(1, prompt_len)).tolist()
            self.submit(Request(
                request_id=f'burst-{tenant}-{self._burst_seq}',
                tokens=toks, max_new=max(1, max_new),
                tenant=tenant, priority_class=cls))

    def _admit(self) -> None:
        """Move queued requests into free slots (host-side only — no
        device call: prefill happens chunk-by-chunk in the tick
        loop). Prefilling slots are capped at the budget's row count
        so every one of them is scheduled every tick.

        Ordering: strict FIFO until QoS engages (_qos_active — a
        request named a tenant/non-default class, or token buckets
        are configured), then deficit-round-robin weighted-fair
        selection across (tenant, class) streams (_qos_select). The
        FIFO path below is bit-for-bit the pre-QoS admission loop —
        single-class traffic's regression guarantee."""
        free = [i for i, s in enumerate(self.slots) if s is None]
        n_prefilling = sum(1 for s in self.slots
                           if s is not None and s.phase == 'prefill')
        admitted = False
        qos_on = self._qos_active
        while (self.queue and free and
               n_prefilling < self._prefill_rows):
            if qos_on:
                idx = self._qos_select()
                if idx is None:
                    break   # every stream head is budget-blocked
            else:
                idx = 0
            req = self.queue[idx]
            if not self._fits(req):
                if (self.num_active() == 0 and not admitted and
                        self._pending is None and self._steps_done):
                    # Region exhausted, nothing running (and no tick
                    # still in flight): fresh cache (old one dropped
                    # first — see reset()), then re-check the fit.
                    self.cache = None
                    self.cache = self._make_empty()
                    self._steps_done = 0
                    _M_RESETS.inc()
                    continue
                break  # wait for running requests to drain
            if qos_on:
                # Spend ONLY on actual admission: the charge clears
                # the stream's DRR deficit and (when rate limiting
                # is on and the request names a tenant) its bucket.
                charge = self._admission_charge(req)
                key = (req.tenant,
                       req.priority_class or qos_lib.DEFAULT_CLASS)
                self._drr.spend(key, charge)
                bkt = self._bucket_for(req.tenant)
                if bkt is not None:
                    bkt.spend(charge, time.monotonic())
            # Slot assignment BEFORE popleft: the request must never
            # be in neither container, or a concurrent submit() of
            # the same id passes the duplicate check in that window
            # (briefly being in BOTH is harmless — _inflight_ids is a
            # set, and only this driver thread pops or cancels).
            slot_idx = free.pop(0)
            self._epoch += 1
            self._seq += 1
            self.slots[slot_idx] = _SlotState(
                request_id=req.request_id, max_new=req.max_new,
                generated=[], prompt=list(req.tokens),
                prompt_len=len(req.tokens), phase='prefill',
                prefill_pos=0, seq=self._seq, epoch=self._epoch,
                deadline=req.deadline, tenant=req.tenant,
                priority_class=req.priority_class)
            if idx:
                del self.queue[idx]
            else:
                self.queue.popleft()
            self._temps[slot_idx] = (
                req.temperature if req.temperature is not None
                else self.temperature)
            n_prefilling += 1
            admitted = True
            # TTFT decomposition: queue-wait ends exactly where the
            # prefill phase begins (no gap between the spans).
            ts = self._req_spans.get(req.request_id)
            if ts is not None:
                qs = ts.pop('queue', None)
                if qs is not None:
                    qs.finish()
                ts['prefill'] = trace_lib.start_span(
                    'engine.prefill', parent=ts['request'],
                    slot=slot_idx, prompt_len=len(req.tokens))
            if self.prefix is not None and not self._warming:
                # Longest-cached-prefix lookup + page copy-in: the
                # matched pages land in the slot's prompt-region KV
                # through warmed fixed-shape programs, and the chunk
                # cursor starts at the cached boundary — the uncached
                # suffix is all that prefills.
                sp = trace_lib.start_span(
                    'engine.prefix_lookup',
                    parent=None if ts is None else ts.get('prefill'))
                reuse, pages, hashes = self.prefix.acquire(
                    req.request_id, req.tokens, self.prefill_chunk,
                    holder=req)
                st = self.slots[slot_idx]
                # The admission lookup's chain hashes ride on the
                # slot so the terminal publish never re-hashes the
                # prompt.
                st.prompt_hashes = hashes
                if reuse:
                    self.cache = self._recanon(self.prefix.copy_into(
                        self.cache, slot_idx, pages, reuse))
                    st.prefill_pos = reuse
                    st.reused = reuse
                sp.finish(matched_pages=len(pages),
                          reuse_tokens=reuse, hit=bool(reuse))

    def _retire_prefix(self, state: _SlotState,
                       slot_idx: Optional[int]) -> None:
        """Terminal-slot prefix bookkeeping: publish the slot's
        finalized prompt pages to the shared pool (only pages its
        prefill cursor actually passed — a cancel mid-prefill
        publishes the finished prefix) and release its pins. No-op
        without the cache; queued-only requests (slot_idx None) hold
        no pins and have no finalized pages."""
        if self.prefix is None:
            return
        if slot_idx is not None and not self._warming:
            self.prefix.publish(state.prompt, state.prefill_pos,
                                self.cache, slot_idx,
                                hashes=state.prompt_hashes)
        self.prefix.release(state.request_id)

    def _finish(self, slot_idx: int) -> None:
        state = self.slots[slot_idx]
        self._retire_prefix(state, slot_idx)
        self._terminal(state.request_id, state.generated,
                       state.prompt_len, lifecycle.FINISHED, None)
        self.slots[slot_idx] = None

    def _terminal(self, rid: Any, tokens: List[int], prompt_len: int,
                  status: str, reason: Optional[str]) -> None:
        """Record the request's ONE terminal Result (any status) and
        close its span tree. Callers free the slot / queue entry."""
        self.results[rid] = Result(
            request_id=rid,
            tokens=list(tokens),
            prompt_len=prompt_len,
            submitted_at=self._submitted_at.pop(rid, 0.0),
            finished_at=time.time(),
            status=status,
            reason=reason)
        ts = self._req_spans.pop(rid, None)
        if ts is not None:
            if status != lifecycle.FINISHED:
                # The cancel event is its own span under the request
                # span, so it carries the request's trace id — a
                # cancelled request's trace shows WHERE in its
                # lifecycle the cut landed.
                trace_lib.start_span(
                    'engine.cancel', parent=ts['request'],
                    request_id=str(rid), status=status,
                    reason=reason or '').finish()
            # A request can end without ever surfacing a first token
            # through the normal path (max_new reached in the same
            # chunk, or cancelled mid-prefill): close any stragglers
            # before the root.
            for name in ('queue', 'prefill', 'first_chunk'):
                sp = ts.pop(name, None)
                if sp is not None:
                    sp.finish()
            if status == lifecycle.FINISHED:
                # Keep the legacy span shape for natural completion.
                ts['request'].finish(tokens=len(tokens))
            else:
                ts['request'].finish(tokens=len(tokens), status=status)

    # ------------------------------------------------- cancellation
    def cancel(self, request_id: Any,
               reason: str = 'api') -> bool:
        """Request cancellation of a queued or in-flight request.

        Thread-safe: the cancellation is recorded here and APPLIED at
        the next tick boundary by the driving thread (the only place
        slot state may change without racing an in-flight device
        tick). The freed decode slot is recycled for the next
        admission — the next occupant's first prefill chunk clears
        the row's dmask, so no stale K/V is ever read. The terminal
        ``Result`` (status='cancelled', tokens so far) surfaces
        through ``drain_results()`` after that tick.

        Returns True when the request was in flight at the time of
        the call (best-effort: a race with natural completion still
        yields exactly one terminal Result, whichever lands first).
        """
        # Exact O(1) in-flight test (see submit): membership in
        # _submitted_at is GIL-atomic and holds from submit until the
        # terminal Result is recorded — no queue/slot scan, no race
        # with the driver's pops.
        if request_id not in self._submitted_at:
            return False
        with self._cancel_lock:
            self._cancels[request_id] = reason
        return True

    def _apply_cancellations(self) -> None:
        if not self._cancels:
            return
        with self._cancel_lock:
            cancels, self._cancels = self._cancels, {}
        for rid, reason in cancels.items():
            self._cancel_now(rid, reason, lifecycle.CANCELLED)

    def queue_kv_import(self, items) -> bool:
        """Queue fetched remote KV pages (``[(chain_hash, {field:
        np.ndarray})]``, the kv_transfer decode shape) for import
        into the prefix pool. Any-thread safe; returns False when no
        prefix cache is configured (the caller's cue that imports
        can never help here). The driver lands queued batches at the
        next tick boundary, BEFORE admission — pages queued before a
        submit are visible to that request's own admission lookup
        (docs/disaggregation.md)."""
        if self.prefix is None or not items:
            return self.prefix is not None
        self._kv_imports.append(list(items))
        return True

    def _apply_kv_imports(self) -> None:
        """Driver-thread boundary work: land every queued KV import
        batch into the prefix pool (dedup/alloc/eviction semantics
        are import_pages' — identical to publish)."""
        while self._kv_imports:
            try:
                batch = self._kv_imports.popleft()
            except IndexError:
                break
            self.prefix.import_pages(batch)

    def _cancel_now(self, rid: Any, reason: str,
                    status: str) -> bool:
        """Driver-thread cancellation: remove the request wherever it
        lives. A request already terminal (its natural completion
        landed first, or a second cancel raced this one) is left
        untouched — exactly one terminal Result per request."""
        # Index-based queue scan: submit() may append from another
        # thread mid-scan (appends keep existing indexes valid; this
        # driver thread is the only popper), where iteration would
        # raise "deque mutated during iteration".
        for i in range(len(self.queue)):
            req = self.queue[i]
            if req.request_id == rid:
                del self.queue[i]
                self._terminal(rid, [], len(req.tokens), status, reason)
                if not self._warming:
                    _M_CANCELS.inc(1, reason=reason)
                return True
        for slot_idx, state in enumerate(self.slots):
            if state is not None and state.request_id == rid:
                # Row deactivated: the in-flight tick's tokens for
                # this slot are discarded by the epoch check, and the
                # next admission recycles the slot (its first prefill
                # chunk clears the row dmask).
                self._retire_prefix(state, slot_idx)
                self._terminal(rid, state.generated, state.prompt_len,
                               status, reason)
                self.slots[slot_idx] = None
                if not self._warming:
                    _M_CANCELS.inc(1, reason=reason)
                return True
        return False

    def cancel_all(self, reason: str = 'shutdown') -> List[Any]:
        """Driver-thread bulk cancel (graceful drain): every queued
        and in-slot request gets its terminal cancelled Result NOW.
        Returns the cancelled request ids."""
        self._apply_cancellations()
        rids = [r.request_id for r in self.queue]
        rids += [s.request_id for s in self.slots if s is not None]
        for rid in rids:
            self._cancel_now(rid, reason, lifecycle.CANCELLED)
        return rids

    def _expire_deadlines(self) -> None:
        now = time.time()
        expired = []
        for i in range(len(self.queue)):   # index scan: see above
            r = self.queue[i]
            if r.deadline is not None and now >= r.deadline:
                expired.append(r.request_id)
        expired += [s.request_id for s in self.slots
                    if s is not None and s.deadline is not None and
                    now >= s.deadline]
        for rid in expired:
            self._cancel_now(rid, 'deadline', lifecycle.EXPIRED)

    def estimate_wait_s(self, prompt_len: int, max_new: int,
                        tokens: Optional[Sequence[int]] = None,
                        priority_class: Optional[str] = None
                        ) -> float:
        """Estimated submit-to-finish seconds for a request arriving
        NOW, from pending queue depth, prefill backlog and decode
        capacity — the deadline-aware admission signal
        (docs/request_lifecycle.md). Heuristic but monotone in load:
        per-tick time is the measured EWMA; the request's own work is
        its prefill ticks plus its decode ticks; everything already
        queued or occupying a slot adds its remaining ticks divided
        by the decode width (slots run batch_size-wide). Returns 0
        before the first measured tick (no signal -> admit).

        With the prefix cache enabled and ``tokens`` provided, the
        request's (and each queued request's) prefill work is charged
        over the post-lookup UNCACHED suffix — high-hit-rate traffic
        must not be spuriously shed with ``wont_make_deadline`` for
        prefill it will never run.

        Class-aware when ``priority_class`` is given AND the QoS
        scheduler is live: queued work of STRICTLY lower priority is
        excluded from the backlog, because weighted-fair ordering
        will jump this request over it — an interactive arrival must
        not be shed with ``wont_make_deadline`` for bulk work it
        would never wait behind (docs/qos.md). Slot-resident work is
        always charged (running requests cannot be jumped, only
        preempted, and the estimate stays conservative). None keeps
        the legacy all-backlog estimate."""
        tick = self._tick_ewma
        if tick is None:
            return 0.0
        skip_below = None
        if priority_class is not None and self._qos_active:
            skip_below = qos_lib.class_rank(priority_class)
        own = (self._prefill_ticks(self._suffix_len(prompt_len,
                                                    tokens)) +
               -(-max_new // self.decode_chunk))
        backlog = 0
        slot_ids = set()
        for s in list(self.slots):
            if s is None:
                continue
            slot_ids.add(s.request_id)
            backlog += -(-max(0, s.max_new - len(s.generated)) //
                         self.decode_chunk)
            if s.phase == 'prefill':
                backlog += self._prefill_ticks(
                    max(0, s.prompt_len - s.prefill_pos))
        # Index scan (not iteration): the driver thread pops from the
        # left concurrently; a skipped/repeated element only perturbs
        # an estimate that is heuristic anyway.
        for i in range(len(self.queue)):
            try:
                r = self.queue[i]
            except IndexError:
                break
            if r.request_id in slot_ids:
                # _admit assigns the slot BEFORE popping the queue, so
                # a request being admitted right now is briefly in
                # both containers — counting it twice would inflate
                # the estimate and spuriously shed deadline'd work.
                continue
            if (skip_below is not None and
                    qos_lib.class_rank(r.priority_class) > skip_below):
                continue    # work this class would jump via DRR
            backlog += (self._prefill_ticks(
                self._suffix_len(len(r.tokens), r.tokens, holder=r)) +
                        -(-r.max_new // self.decode_chunk))
        wait_ticks = own + backlog / max(1, self.batch_size)
        return wait_ticks * tick

    def _is_done(self, state: _SlotState) -> bool:
        return (len(state.generated) >= state.max_new or
                (self.eos_id is not None and state.generated and
                 state.generated[-1] == self.eos_id))

    # ------------------------------------------------- speculation
    def _spec_candidates(self) -> bool:
        """Any slot that could draft this tick? Greedy decode-phase
        WITH draft budget left (a slot one token from done cannot
        speculate) — sampling batches and short-output tails keep
        the pipelined fast path. Generated counts may lag an
        in-flight tick here, so the budget test can briefly
        over-estimate near a slot's end: at most one spare flush,
        never a sustained pipeline loss."""
        return any(
            s is not None and s.phase == 'decode' and
            self._temps[i] <= 0.0 and
            s.max_new - len(s.generated) > 1
            for i, s in enumerate(self.slots))

    def _lookup(self, chain: Sequence[int], k: int) -> List[int]:
        """Draft proposer hook: up to ``k`` candidate continuations
        of ``chain`` (prompt + generated as an int array, ending at
        the current token). Prompt-lookup by default; tests override
        this to drive deterministic acceptance patterns —
        correctness never depends on draft quality (rejections fall
        back to the model's own sample), only throughput does."""
        return _prompt_lookup(chain, k, self._spec_ngram)

    @staticmethod
    def _slot_chain(st: _SlotState) -> np.ndarray:
        """The slot's prompt+generated chain as an int64 view over an
        incrementally maintained buffer — per tick only the freshly
        generated tokens are appended (no full-chain list rebuild on
        the spec critical path)."""
        n = st.prompt_len + len(st.generated)
        if st.chain_buf is None or st.chain_buf.shape[0] < n:
            cap = max(64, st.chain_buf.shape[0] if st.chain_buf
                      is not None else 0)
            while cap < n:
                cap *= 2
            buf = np.empty((cap,), np.int64)
            buf[:st.prompt_len] = st.prompt
            buf[st.prompt_len:n] = st.generated
            st.chain_buf = buf
        elif st.chain_len < n:
            st.chain_buf[st.chain_len:n] = \
                st.generated[st.chain_len - st.prompt_len:]
        st.chain_len = n
        return st.chain_buf[:n]

    def _propose_drafts(self) -> tuple:
        """Refresh every greedy decode slot's draft from its token
        chain (fresh when no tick is in flight — then the chain's
        last element IS the device-resident current token; a stale
        chain is only ever PROBED, for the dry-spell re-arm). Draft
        length is clipped to the slot's remaining need minus one —
        the final token needs no speculation. Returns (eligible,
        found): how many slots could draft, and whether any did."""
        t0 = time.perf_counter()
        eligible = 0
        found = False
        for i, st in enumerate(self.slots):
            if st is None or st.phase != 'decode':
                continue
            st.draft = None
            if self._temps[i] > 0.0:
                continue            # sampling slots bypass speculation
            budget = min(self.spec_k,
                         st.max_new - len(st.generated) - 1)
            if budget < 1:
                continue
            eligible += 1
            drafts = self._lookup(self._slot_chain(st), budget)
            st.draft = drafts or None
            found = found or bool(drafts)
        self.spec_draft_s += time.perf_counter() - t0
        return eligible, found

    def _spec_may_run(self) -> bool:
        """May this tick run a verify segment without breaking the
        finish guarantee? The segment consumes V shared columns while
        its worst-case (all-reject) advance is ONE token per decode
        row — so speculation only runs when the region left AFTER the
        tick still covers every occupant's pessimistic remaining
        need. When it cannot, the tick falls back to the plain decode
        chunk, which preserves the admission invariant by
        construction — speculation never strands an admitted
        request."""
        after = self.remaining_slots() - self._spec_v
        if after < 0:
            return False
        for s in self.slots:
            if s is None:
                continue
            left = s.max_new - len(s.generated)
            if s.phase == 'prefill':
                # Pessimistic: no credit for the prefill chunk this
                # very tick may advance.
                left += (self._prefill_ticks(
                    s.prompt_len - s.prefill_pos) * self.decode_chunk)
            else:
                left -= 1           # every decode row advances >= 1
            if left > after:
                return False
        return True

    def _observe_per_token(self, interval: float,
                           emitted: int) -> None:
        """skytpu_engine_per_token_seconds, acceptance-aware: the
        divisor is the tick's MODEL-STEP tokens — emitted minus the
        speculatively accepted drafts that rode along free in
        wall-time. Without it a 4-token accepted burst would report
        a 4x-optimistic per-token latency; with it the histogram
        keeps meaning "wall time per serial model step" and the
        speculation win shows up where it belongs: tokens_total rate
        and the spec counters. Bitwise-identical behavior with
        speculation off (accepted is always 0)."""
        _M_TOKEN_LATENCY.observe(
            interval / max(1, emitted - self._tick_accepted))

    def mesh_info(self) -> Optional[Dict[str, Any]]:
        """Mesh shape / device count for /health and bench detail.

        None for single-chip engines. The harness computes per-chip
        normalization (tok/s/chip, req/s/chip) from ``devices``
        instead of hand-deriving it in PERFORMANCE.md.
        """
        if self.mesh is None:
            return None
        axes = {str(name): int(size) for name, size in
                zip(self.mesh.axis_names, self.mesh.devices.shape)}
        return {
            'devices': int(self.mesh.size),
            'axes': {k: v for k, v in axes.items() if v > 1},
            'tp': axes.get('tp', 1),
        }

    def spec_stats(self) -> Dict[str, Any]:
        """Speculation accounting for bench detail / introspection."""
        prop, acc = self.spec_proposed_total, self.spec_accepted_total
        return {
            'enabled': self.spec_decode,
            'k': self.spec_k if self.spec_decode else 0,
            'proposed': prop,
            'accepted': acc,
            'acceptance_rate': (round(acc / prop, 4) if prop
                                else None),
            'spec_ticks': self.spec_ticks,
            'tokens_per_step': (
                round(self.spec_emitted_total / self.spec_row_steps, 3)
                if self.spec_row_steps else None),
            'draft_time_s': round(self.spec_draft_s, 4),
        }

    def step(self) -> int:
        """One pipelined engine tick.

        Admit queued requests, DISPATCH tick N+1 (device: up to
        ``prefill_budget`` prompt tokens across prefilling slots
        fused with the decode chunk for active slots), then sync and
        process tick N. The device is already working on the next
        tick while the host attributes tokens, finishes requests,
        runs streaming callbacks and serves HTTP — device work never
        waits on host work (double buffering).

        Results therefore surface one tick after their final decode
        chunk. Returns the number of tokens emitted this tick.

        Lifecycle work happens at the tick boundary, before
        admission: pending cancellations are applied (slots freed,
        partial Results recorded) and past-deadline requests —
        queued or mid-decode — are expired. A tick slower than
        ``SKYTPU_TICK_HANG_SECONDS`` trips the watchdog (warning log
        tagged with the active requests' trace ids + counter).
        """
        t0 = time.perf_counter()
        hang = None
        burst = None
        if not self._warming:
            # Warmup ticks never poll: compile-time ticks would burn
            # a chaos plan's counters before serving even starts.
            hang = fault_injection.poll(
                'engine.tick.hang',
                kinds=(fault_injection.FaultKind.HANG,))
            burst = fault_injection.poll(
                'engine.tenant.burst',
                kinds=(fault_injection.FaultKind.TENANT_BURST,))
        if hang is not None:
            # Act out a wedged device tick: the watchdog (below) must
            # see the stall exactly as it would a real one.
            time.sleep(float(hang.params.get('seconds', 0.05)))
        if burst is not None:
            # A misbehaving tenant materializes: the fault plan's
            # synthetic requests hit the queue before this tick's
            # lifecycle work, exactly like a client burst landing
            # between ticks (docs/qos.md).
            self._inject_tenant_burst(burst.params)
        if self._kv_imports:
            # Land fetched remote KV pages before anything else at
            # the boundary: a request whose pages were queued ahead
            # of its submit must see them in THIS tick's admission
            # lookup (docs/disaggregation.md).
            self._apply_kv_imports()
        self._apply_cancellations()
        self._expire_deadlines()
        if self._qos_active:
            # QoS lifecycle work at the same boundary: queue-pressure
            # shedding (bulk first), then the sustained-overload
            # preemption timer — both act through _cancel_now, so a
            # freed slot is admissible in THIS tick's _admit.
            self._qos_shed_queue()
            self._qos_maybe_preempt()
        self._admit()
        self._tick_accepted = 0
        emitted = 0
        # Capacity guard, checked BEFORE the flush with generated
        # counts that may lag the in-flight tick — which only makes
        # it more conservative (left is over-estimated). A workload
        # whose guard cannot pass — e.g. one slot needing the whole
        # decode region, where the verify segment has no column
        # headroom — keeps the double-buffered fast path and skips
        # the proposer outright instead of paying a useless flush
        # plus O(chain) lookup work every tick for verify ticks that
        # can never dispatch.
        spec_may = (self.spec_decode and not self._warming and
                    self._spec_may_run())
        if (spec_may and self._pending is not None and
                self._spec_candidates() and not self._spec_dry):
            # Drafting needs the FRESH chain: the proposer matches the
            # suffix ending at the device-resident current token,
            # which only aligns with host state when no tick is in
            # flight. Speculation therefore trades the double-buffered
            # dispatch for bigger ticks — the host work hidden by
            # pipelining is small against a verify tick's device time,
            # and stale drafts (offset by an in-flight tick's tokens)
            # would never be accepted anyway.
            prev, self._pending = self._pending, None
            emitted += self._process_tick(prev)
            # The flush may have finished slots: admit into them now
            # rather than burning a tick (spec mode has no pipeline
            # overlap to preserve).
            self._admit()
        if spec_may:
            eligible, found = self._propose_drafts()
            if eligible:
                if found:
                    if self._spec_dry and self._pending is not None:
                        # Dry spell: the flush above was skipped (the
                        # pipelined fast path stays intact for
                        # no-match traffic) and this round only
                        # PROBED the stale chain. A hit re-arms
                        # speculation — next tick flushes and
                        # proposes fresh — but the stale drafts
                        # themselves are unusable (offset by the
                        # in-flight tick's tokens). Re-arming waits
                        # out the cooldown: a reject-latched dry
                        # spell (drafts found, never accepted) must
                        # not oscillate back in at the hysteresis
                        # period.
                        for s in self.slots:
                            if s is not None:
                                s.draft = None
                        self._spec_dry_rounds += 1
                        if (self._spec_dry_rounds >=
                                self._spec_cooldown):
                            self._spec_misses = 0
                            self._spec_dry_rounds = 0
                            self._spec_cooldown = min(
                                _SPEC_COOLDOWN_MAX,
                                max(1, self._spec_cooldown * 2))
                    # Armed rounds deliberately do NOT reset the
                    # streak on mere draft presence: the reset
                    # belongs to acceptance (_process_tick), so a
                    # workload whose spurious n-gram matches the
                    # model never confirms still latches dry instead
                    # of paying 1-token-advance verify ticks forever.
                else:
                    self._spec_misses += 1
                # Hysteresis, and only over rounds that HAD a
                # draftable slot: a single fresh miss must not kill
                # the armed window (organic matches are sparse), and
                # rounds before any decode slot exists must not delay
                # the first verify.
                self._spec_dry = (self._spec_misses >=
                                  _SPEC_DRY_AFTER)
        new_entry = self._dispatch_tick()
        prev, self._pending = self._pending, new_entry
        emitted += self._process_tick(prev)
        # Per-token latency at tick granularity: the interval between
        # consecutive ticks over the tokens this tick surfaced. Host
        # timestamps within one tick would be sync artifacts (a
        # request finishing inside a single chunk shows ~0s/token);
        # the tick interval is the real pipeline rate. Acceptance-
        # aware: speculatively accepted drafts ride along free in
        # wall-time, so they are excluded from the divisor — the
        # histogram keeps reporting the serial model-step rate while
        # the speedup shows in tokens_total and the spec counters.
        tick_at = time.perf_counter()
        if (emitted and not self._warming and
                self._last_tick_at is not None):
            self._observe_per_token(tick_at - self._last_tick_at,
                                    emitted)
        self._last_tick_at = tick_at
        dur = tick_at - t0
        if (new_entry is not None or prev is not None) and \
                not self._warming:
            # Working ticks only: idle step() calls would drag the
            # admission estimate toward zero. Warmup ticks are
            # excluded for the same reason warmup is excluded from
            # the TTFT histogram — their durations are XLA compiles,
            # and an EWMA seeded with compile time would shed
            # deadline'd requests from a completely idle engine.
            self._tick_ewma = (dur if self._tick_ewma is None else
                               0.8 * self._tick_ewma + 0.2 * dur)
        if (self._tick_hang_s > 0 and dur > self._tick_hang_s and
                not self._warming):
            _M_TICK_HANGS.inc()
            # Snapshot first (C-atomic): submit() inserts into
            # _req_spans from the HTTP thread, and a comprehension
            # iterating the live dict could die with 'dict changed
            # size during iteration' — turning a slow tick into a
            # dead replica.
            traces = sorted({
                ts['request'].trace_id
                for ts in list(self._req_spans.values())
                if 'request' in ts})
            logger.warning(
                'Engine tick took %.3fs (SKYTPU_TICK_HANG_SECONDS='
                '%.1f): device hang or severe contention; active=%d '
                'queued=%d traces=%s', dur, self._tick_hang_s,
                self.num_active(), len(self.queue), traces[:4] or None)
        _M_QUEUE_DEPTH.set(len(self.queue))
        _M_ACTIVE_SLOTS.set(self.num_active())
        if not self._warming:
            self.refresh_slo_gauges()
        return emitted

    def refresh_slo_gauges(self, force: bool = False) -> None:
        """Re-derive the scraped SLO gauges from live state, at most
        4x/second: the sliding p99s (a quiet window must DECAY the
        gauge to 0, never freeze it at the last violating value — the
        SLO autoscaler keeps scraping, and a frozen breach would pin
        the fleet at max_replicas on zero traffic) and the est-wait
        admission-pressure estimate (throttled because its O(queue)
        scan must not ride every tick of an overloaded engine — the
        exact load the open-loop bench creates). Called per working
        tick and from the HTTP driver's idle loop; ``force`` skips
        the throttle (end-of-replay flush, so a scrape right after a
        short run sees the run, not the previous refresh window)."""
        now_pc = time.perf_counter()
        if not force and now_pc < self._slo_refresh_at:
            return
        self._slo_refresh_at = now_pc + 0.25
        p99 = self._ttft_window.quantile(0.99)
        _M_TTFT_P99.set(p99 if p99 is not None else 0.0)
        p99 = self._itl_window.quantile(0.99)
        _M_ITL_P99.set(p99 if p99 is not None else 0.0)
        # Per-class TTFT p99 (docs/qos.md): same decay-to-0 contract
        # as the aggregate gauge, one series per priority class.
        for cls, win in self._class_ttft_windows.items():
            p99 = win.quantile(0.99)
            _M_CLASS_TTFT_P99.set(p99 if p99 is not None else 0.0,
                                  **{'class': cls})
        # Rises with a burst the moment the queue does — ticks before
        # the 60 s QPS window moves.
        _M_EST_WAIT.set(self.estimate_wait_s(0, 1))

    def flush(self) -> int:
        """Sync and process the in-flight tick without dispatching a
        new one (pipeline drain at shutdown / idle)."""
        prev, self._pending = self._pending, None
        return self._process_tick(prev)

    @property
    def has_pending(self) -> bool:
        return self._pending is not None

    def _dispatch_tick(self) -> Optional[Dict[str, Any]]:
        active_list = [s is not None and s.phase == 'decode'
                       for s in self.slots]
        prefilling = sorted(
            ((i, s) for i, s in enumerate(self.slots)
             if s is not None and s.phase == 'prefill'),
            key=lambda t: t[1].seq)
        any_active = any(active_list)
        if not prefilling and not any_active:
            return None
        # Speculation: when any decode slot holds a draft (greedy
        # slots only — the proposer skips sampling slots) and the
        # capacity guard passes, the verify segment REPLACES the
        # decode scan this tick: every active slot feeds its current
        # token (+ drafts, when it has them) through ONE batched
        # verify pass and advances by its accepted prefix + 1. No
        # drafts -> the decode-only fast path below runs untouched.
        spec_rows: List[tuple] = []
        if self.spec_decode and any_active:
            spec_rows = [(i, s) for i, s in enumerate(self.slots)
                         if s is not None and s.phase == 'decode' and
                         s.draft]
        run_spec = bool(spec_rows) and self._spec_may_run()
        # Decode chunk size: bounded by global capacity (admission
        # guarantees every active request fits in the remaining
        # region) and kept to power-of-two tails so at most
        # log2(chunk) programs exist per tick flavor. Prefill-only
        # ticks (or region-exhausted pipelining tails) run n == 0.
        n = 0
        if any_active and not run_spec:
            n = min(self.decode_chunk, self.remaining_slots())
            if n < 1:
                # Region exhausted while slots are still occupied.
                # Because slots free one tick AFTER their final chunk
                # (pipelining), this is the normal end state of a
                # request whose max_new consumed the region exactly:
                # every active slot has already decoded its full
                # max_new in flight — admission guarantees capacity
                # >= the largest outstanding need, and all slots
                # advance together. Dispatch no decode steps;
                # processing the pending tick frees them.
                if self._pending is None and not prefilling:
                    raise RuntimeError(
                        'capacity accounting violated: region '
                        'exhausted with active slots and no tick in '
                        'flight')
                n = 0
            while n & (n - 1):
                n &= n - 1
        if not prefilling and n == 0 and not run_spec:
            return None
        self._key, sub = jax.random.split(self._key)
        if run_spec:
            num_pages = self._num_pages(self._spec_v)
        else:
            num_pages = self._num_pages(n) if n else None

        counts = None
        drafts, slen = self._drafts0, self._slen0
        proposed = 0
        if run_spec:
            drafts_np = np.zeros((self.batch_size, self.spec_k),
                                 np.int32)
            slen_np = np.zeros((self.batch_size,), np.int32)
            for i, st in spec_rows:
                d = st.draft[:self.spec_k]
                drafts_np[i, :len(d)] = d
                slen_np[i] = len(d)
                proposed += len(d)
                st.draft = None            # consumed by this tick
            drafts = jnp.asarray(drafts_np)
            slen = jnp.asarray(slen_np)
            if not self._warming:
                _M_SPEC_PROPOSED.inc(proposed)
                self.spec_proposed_total += proposed
                self.spec_ticks += 1
                self.spec_row_steps += sum(active_list)
                # Host-side dispatch window (docs/tracing.md): one
                # span per verify tick, like engine.prefill.chunk.
                trace_lib.start_span(
                    'engine.spec_verify', rows=len(spec_rows),
                    proposed=proposed, k=self.spec_k).finish()

        if not prefilling and not run_spec:
            # Decode-only fast path: identical to the pre-chunking
            # engine's tick.
            self.cache, toks, self._tokens_dev = self._decode(
                self.params, self.cache, self._tokens_dev,
                jnp.asarray(active_list), sub,
                jnp.asarray(self._temps), n=n, num_pages=num_pages)
            firsts = None
            chunk_meta: List[Dict[str, Any]] = []
            self.last_tick_prefill_tokens = 0
        elif not prefilling:
            # Verify-only tick: the speculative counterpart of the
            # decode-only fast path.
            toks, counts, self._tokens_dev, self.cache = self._spec(
                self.params, self.cache, self._tokens_dev, drafts,
                slen, jnp.asarray(active_list), sub,
                jnp.asarray(self._temps), num_pages=num_pages)
            firsts = None
            chunk_meta = []
            self.last_tick_prefill_tokens = 0
        else:
            g, c = self._prefill_rows, self.prefill_chunk
            ctoks = np.zeros((g, c), np.int32)
            cstarts = np.zeros((g,), np.int32)
            clens = np.ones((g,), np.int32)   # dead rows: len 1 slack
            clive = np.zeros((g,), bool)
            clast = np.zeros((g,), bool)
            cslots = np.zeros((g,), np.int32)
            chunk_meta = []
            budget_used = 0
            for j, (slot_idx, st) in enumerate(prefilling[:g]):
                ln = min(c, st.prompt_len - st.prefill_pos)
                ctoks[j, :ln] = st.prompt[st.prefill_pos:
                                          st.prefill_pos + ln]
                cstarts[j] = st.prefill_pos
                clens[j] = ln
                clive[j] = True
                clast[j] = st.prefill_pos + ln == st.prompt_len
                cslots[j] = slot_idx
                budget_used += ln
                chunk_meta.append({
                    'row': j, 'slot': slot_idx, 'epoch': st.epoch,
                    'n': ln, 'last': bool(clast[j]),
                    'start': int(st.prefill_pos)})
            # ``spec`` is only passed when a verify segment runs: an
            # explicit spec=0 and the omitted default hash to
            # DIFFERENT jit cache keys, and warmup compiled the
            # non-spec programs with the kwarg omitted.
            spec_kw = {'spec': self._spec_v} if run_spec else {}
            self.cache, toks, self._tokens_dev, firsts, counts = \
                self._mixed(
                    self.params, self.cache, self._tokens_dev,
                    jnp.asarray(ctoks), jnp.asarray(cstarts),
                    jnp.asarray(clens), jnp.asarray(clive),
                    jnp.asarray(clast), jnp.asarray(cslots),
                    jnp.asarray(active_list), sub,
                    jnp.asarray(self._temps), drafts, slen, n=n,
                    num_pages=num_pages, **spec_kw)
            # Host bookkeeping: advance cursors, flip completed slots
            # into the decode phase (they join the active mask next
            # tick; their first token is already in the device token
            # vector), record spans.
            self.last_tick_prefill_tokens = budget_used
            if not self._warming:
                _M_PREFILL_TOKENS.inc(budget_used)
                self.prefill_tokens_total += budget_used
                self.prefill_ticks += 1
                self.max_tick_prefill_tokens = max(
                    self.max_tick_prefill_tokens, budget_used)
            for m in chunk_meta:
                st = self.slots[m['slot']]
                st.prefill_pos += m['n']
                ts = self._req_spans.get(st.request_id)
                if ts is not None and 'prefill' in ts:
                    # Host-side dispatch window per chunk; the
                    # device-side completion folds into the
                    # first-chunk span started below.
                    trace_lib.start_span(
                        'engine.prefill.chunk', parent=ts['prefill'],
                        start=m['start'], tokens=m['n'],
                        slot=m['slot']).finish()
                if m['last']:
                    st.phase = 'decode'
                    if ts is not None:
                        ps = ts.pop('prefill', None)
                        if ps is not None:
                            # Chunks that actually RAN: a prefix-
                            # cache hit starts at the cached
                            # boundary, so the count excludes the
                            # reused region (the cache-off count
                            # would overstate per-chunk math 4x for
                            # exactly the traffic the cache serves).
                            ps.finish(chunks=self._prefill_ticks(
                                st.prompt_len - st.reused),
                                reused_tokens=st.reused)
                        ts['first_chunk'] = trace_lib.start_span(
                            'engine.decode.first_chunk',
                            parent=ts['request'], slot=m['slot'])
        self._steps_done += self._spec_v if run_spec else n
        # Snapshot which occupant each decoded column belongs to: by
        # the time this tick is synced the slot may have finished and
        # been recycled (its column decoded garbage — discarded by
        # the epoch check).
        snapshot = [(i, s.epoch) for i, s in enumerate(self.slots)
                    if s is not None and active_list[i]]
        return {'toks': toks, 'n': n, 'snapshot': snapshot,
                'chunks': chunk_meta, 'firsts': firsts,
                'spec': self._spec_v if run_spec else 0,
                'counts': counts}

    def _emit_first_token(self, state: _SlotState, tok: int,
                          now: float) -> List[int]:
        state.generated.append(tok)
        if not self._warming:
            # Single timing source: with tracing on, TTFT is the
            # request span's age at first token — exactly what the
            # span tree decomposes — and the trace id rides on the
            # histogram as an exemplar.
            ts = self._req_spans.get(state.request_id)
            if ts is not None:
                fc = ts.pop('first_chunk', None)
                if fc is not None:
                    fc.finish()
                ttft = now - ts['request'].start_time
                _M_TTFT.observe(ttft, exemplar=ts['request'].exemplar)
                self._observe_slo('ttft', ttft,
                                  ts['request'].exemplar)
            else:
                ttft = now - self._submitted_at.get(
                    state.request_id, now)
                _M_TTFT.observe(ttft)
                self._observe_slo('ttft', ttft, None)
            # Per-class window behind
            # skytpu_engine_class_ttft_p99_seconds (docs/qos.md):
            # classless requests observe as DEFAULT_CLASS, so the
            # per-class signal covers all traffic.
            cls = state.priority_class or qos_lib.DEFAULT_CLASS
            win = self._class_ttft_windows.get(cls)
            if win is not None:
                win.observe(ttft)
        return [tok]

    def _observe_slo(self, kind: str, value: float,
                     exemplar: Optional[str]) -> None:
        """Feed the sliding p99 window behind the cumulative
        histogram and refresh the scraped gauge. A value past the
        configured threshold counts a violation and pins its trace id
        on the gauge (sticky exemplar: Gauge.set keeps it across
        unremarkable updates) — the number that trips an alert
        carries the span tree that explains it."""
        if kind == 'ttft':
            win, gauge, thr = (self._ttft_window, _M_TTFT_P99,
                               self._slo_ttft_s)
        else:
            win, gauge, thr = (self._itl_window, _M_ITL_P99,
                               self._slo_itl_s)
        win.observe(value)
        violated = thr > 0 and value > thr
        if not violated:
            # Steady state leaves the gauge to the 4 Hz refresher:
            # recomputing the window p99 per emitted token is pure
            # overhead on the decode hot path.
            return
        _M_SLO_VIOLATIONS.inc(1, kind=kind)
        p99 = win.quantile(0.99)
        gauge.set(value if p99 is None else p99, exemplar=exemplar)

    def _process_tick(self, entry: Optional[Dict[str, Any]]) -> int:
        if entry is None:
            return 0
        emitted = 0
        now = time.time()
        now_pc = time.perf_counter()
        fresh_by_slot: Dict[int, List[int]] = {}
        # Completed prefill chunks first: their sampled first token
        # was computed strictly before this tick's decode scan on
        # device, so the sync order matches generation order.
        firsts_host: Optional[np.ndarray] = None
        for m in entry['chunks']:
            if not m['last']:
                continue
            state = self.slots[m['slot']]
            if state is None or state.epoch != m['epoch']:
                continue          # freed/recycled mid-flight
            if firsts_host is None:
                firsts_host = np.asarray(entry['firsts'])  # THE sync
            fresh_by_slot[m['slot']] = self._emit_first_token(
                state, int(firsts_host[m['row']]), now)
            emitted += 1
        if entry.get('spec'):
            # Verify tick: each active row surfaced counts[b] tokens —
            # its accepted drafts plus the model's own token for the
            # first rejected (or bonus) position. Tokens beyond
            # counts are rejected-candidate garbage; their K/V were
            # rolled back on device via the dmask.
            toks_host = np.asarray(entry['toks'])       # [B, V]
            counts_host = np.asarray(entry['counts'])   # [B]
            tick_acc = 0
            for slot_idx, epoch in entry['snapshot']:
                state = self.slots[slot_idx]
                if state is None or state.epoch != epoch:
                    continue      # freed/recycled mid-flight
                if self._is_done(state):
                    continue
                fresh = fresh_by_slot.setdefault(slot_idx, [])
                e = int(counts_host[slot_idx])
                for t in range(e):
                    tok = int(toks_host[slot_idx, t])
                    state.generated.append(tok)
                    fresh.append(tok)
                    emitted += 1
                    if self._is_done(state):
                        # Tokens past max_new/EOS within the burst
                        # are discarded.
                        break
                # Accepted drafts that actually SURFACED: burst
                # positions 0..e-2 are drafts, e-1 is the model's own
                # token — an EOS mid-burst truncates the emission, and
                # discarded drafts must inflate neither the acceptance
                # counters nor the per-token-latency divisor.
                accepted = min(len(fresh), max(0, e - 1))
                tick_acc += accepted
                if not self._warming:
                    if accepted:
                        _M_SPEC_ACCEPTED.inc(accepted)
                        self.spec_accepted_total += accepted
                        self._tick_accepted += accepted
                    self.spec_emitted_total += len(fresh)
            if not self._warming:
                # Acceptance feedback for the dry-spell latch: a
                # verify tick whose drafts were ALL rejected is a
                # miss exactly like a zero-draft proposal round — a
                # proposer that keeps matching n-grams the model
                # never confirms must latch dry rather than replace
                # the n-step decode scan with 1-token-advance verify
                # ticks forever. Any accepted draft re-arms fully.
                if tick_acc:
                    self._spec_misses = 0
                    self._spec_cooldown = 0
                    self._spec_dry_rounds = 0
                else:
                    self._spec_misses += 1
                self._spec_dry = (self._spec_misses >=
                                  _SPEC_DRY_AFTER)
        elif entry['n']:
            toks_host = np.asarray(entry['toks'])   # [n, B] — THE sync
            for slot_idx, epoch in entry['snapshot']:
                state = self.slots[slot_idx]
                if state is None or state.epoch != epoch:
                    continue      # freed/recycled mid-flight
                if self._is_done(state):
                    continue
                fresh = fresh_by_slot.setdefault(slot_idx, [])
                for t in range(entry['n']):
                    tok = int(toks_host[t, slot_idx])
                    state.generated.append(tok)
                    fresh.append(tok)
                    emitted += 1
                    if self._is_done(state):
                        # Tokens past max_new/EOS within the chunk
                        # are discarded.
                        break
        for slot_idx, fresh in fresh_by_slot.items():
            state = self.slots[slot_idx]
            if state is None or not fresh:
                continue
            if (not self._warming and
                    state.last_emit_at is not None):
                # ITL: the gap a streaming client sees between
                # consecutive token batches of one request. Bounded
                # by the tick time — i.e. by the prefill token
                # budget, not by co-admitted prompt lengths.
                ts = self._req_spans.get(state.request_id)
                itl = now_pc - state.last_emit_at
                itl_exemplar = (ts['request'].exemplar
                                if ts is not None else None)
                _M_ITL.observe(itl, exemplar=itl_exemplar)
                self._observe_slo('itl', itl, itl_exemplar)
            state.last_emit_at = now_pc
            if state.tenant is not None and not self._warming:
                # Bounded per-tenant attribution (max_series=64, then
                # the registry folds to _other): anonymous traffic
                # stays out — tokens_total is the all-traffic series.
                _M_TENANT_TOKENS.inc(len(fresh), tenant=state.tenant)
            if self.on_token is not None:
                self.on_token(state.request_id, fresh)
            if self._is_done(state):
                self._finish(slot_idx)
        if emitted and not self._warming:
            _M_TOKENS.inc(emitted)
        return emitted

    def drain_results(self) -> Dict[Any, Result]:
        """Pop and return all finished results. Long-running servers
        MUST drain (rather than read ``results``) or every request's
        tokens are archived forever."""
        out = self.results
        self.results = {}
        return out

    def _inflight_ids(self) -> set:
        """Best-effort in-flight id set for bulk introspection (run()
        prechecks, the HTTP drain sweep). Exactness-critical checks
        (submit's duplicate reject, cancel) use the O(1)
        ``_submitted_at`` map instead. A plain set comprehension is a
        consistent snapshot when it completes (deque iteration raises
        on ANY concurrent mutation) — retry a few times; under
        pathological churn fall back to a right-anchored scan, which
        driver poplefts cannot shift (popped requests are already in
        their slot — _admit assigns before popping) though a
        concurrent append can shadow one deep element per append."""
        for _ in range(4):
            try:
                return ({r.request_id for r in self.queue} |
                        {s.request_id for s in self.slots
                         if s is not None})
            except RuntimeError:
                continue        # deque mutated mid-iteration: retry
        ids = set()
        for k in range(1, len(self.queue) + 1):
            try:
                ids.add(self.queue[-k].request_id)
            except IndexError:
                break
        ids.update(s.request_id for s in self.slots if s is not None)
        return ids

    def run(self,
            requests: Sequence[Request],
            on_result: Optional[Callable[[Result], None]] = None
            ) -> Dict[Any, Result]:
        """Serve ``requests`` to completion (continuous batching).

        Returns (and fires ``on_result`` for) only THIS call's
        requests; finished results are drained, not archived.
        """
        wanted = set()
        inflight = self._inflight_ids()
        for r in requests:
            if r.request_id in wanted or r.request_id in inflight:
                raise ValueError(
                    f'duplicate request_id {r.request_id!r}')
            wanted.add(r.request_id)
        for r in requests:
            self.submit(r)
        collected: Dict[Any, Result] = {}
        while self.queue or self.num_active() or self.has_pending:
            self.step()
            for rid, res in self.drain_results().items():
                collected[rid] = res
                if on_result and rid in wanted:
                    on_result(res)
        return {rid: collected[rid] for rid in wanted}
