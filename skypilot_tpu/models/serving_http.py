"""HTTP front end for the continuous-batching ServingEngine.

The replica-side process of a served model: what JetStream's server
is to the reference's serving recipe
(/root/reference/examples/tpu/v6e/serve-llama2-7b.yaml launches a
JetStream HTTP server per replica; the serve stack's load balancer
fronts it). A replica task runs

    python -m skypilot_tpu.models.serving_http --port 8801 ...

and the serve stack probes ``/health`` for readiness and proxies
generation traffic to ``/generate``.

Structure: aiohttp handlers submit requests into the ServingEngine
queue and await an asyncio future; a single engine thread drives
``engine.step()`` continuously (the engine is a host-side orchestrator
over jitted device programs — one driver thread is the device-order
guarantee) and resolves futures as requests finish.

Streaming: ``{"stream": true}`` in the /generate body switches the
response to server-sent events — each decode chunk's tokens are
flushed the moment they reach the host (``engine.on_token``), ending
with a ``done`` event. The serve load balancer proxies response bodies
chunk-by-chunk, so first tokens reach the client while the request is
still decoding (reference analog: sky/serve/load_balancer.py:22
proxies streaming responses).

Request lifecycle (docs/request_lifecycle.md): /generate accepts a
deadline (``X-Request-Deadline`` remaining-budget header stamped by
the LB, or body ``timeout_s``) and sheds requests that cannot make it
(429, reason='wont_make_deadline'); ``POST /cancel/<request_id>``
cancels by X-Request-ID; a streaming client that hangs up cancels its
engine request; SIGTERM/SIGINT (or ``POST /drain``) flip the server
into draining mode — /health reports 'draining', new work is shed
with 503 + Retry-After, and in-flight requests run to completion or
cancellation under ``SKYTPU_DRAIN_TIMEOUT_SECONDS``.
"""
from __future__ import annotations

import argparse
import asyncio
import json
import signal
import threading
import time
from typing import Any, Dict, Optional

from aiohttp import web

from skypilot_tpu import metrics as metrics_lib
from skypilot_tpu import trace as trace_lib
from skypilot_tpu.utils import env_registry
from skypilot_tpu.utils import fault_injection
from skypilot_tpu.utils import lifecycle
from skypilot_tpu.utils import log as sky_logging
from skypilot_tpu.utils import qos as qos_lib

logger = sky_logging.init_logger(__name__)

_M_REJECTS = metrics_lib.counter(
    'skytpu_engine_rejects_total',
    'Generate requests shed with HTTP 429 (pending queue full).')
_M_SHEDS = metrics_lib.counter(
    'skytpu_http_sheds_total',
    'Generate requests shed before admission, by reason: queue_full '
    '(pending queue at max_pending), wont_make_deadline (estimated '
    'queue wait exceeds the request deadline), draining (replica is '
    'shutting down). See docs/request_lifecycle.md.',
    labels=('reason',))
_M_DRAIN = metrics_lib.histogram(
    'skytpu_http_drain_seconds',
    'Graceful-drain duration: SIGTERM/drain-request to every '
    'in-flight request reaching a terminal state (bounded by '
    'SKYTPU_DRAIN_TIMEOUT_SECONDS plus the force-cancel sweep).',
    buckets=metrics_lib.LATENCY_BUCKETS)
_M_ROLE = metrics_lib.gauge(
    'skytpu_engine_role',
    "Info gauge (value 1, role label): this replica's serving role "
    "in a disaggregated pool — 'prefill', 'decode' or 'mixed' "
    '(docs/disaggregation.md). Also advertised on /health; the LB '
    'routes tagged requests prefill→decode by it.',
    labels=('role',), max_series=4)


def _rid_headers(req_id: str) -> Dict[str, str]:
    """Echo headers: every /generate response — success, 400, 429,
    503 — carries the request's X-Request-ID so clients and the LB
    can correlate logs without parsing bodies."""
    return {trace_lib.REQUEST_ID_HEADER: req_id}


class EngineServer:
    """aiohttp app over a ServingEngine; one background driver thread.

    ``max_pending`` bounds the engine's admission queue: when that
    many requests are already queued (not yet admitted to a decode
    slot), /generate answers 429 with a ``Retry-After`` hint instead
    of queueing unboundedly — an overloaded replica should shed load
    to the load balancer's other replicas, not grow a queue whose
    tail latency is unbounded (and whose memory is, too). ``None``
    keeps the legacy unbounded behavior (benches).
    """

    def __init__(self, engine, max_pending: Optional[int] = None,
                 warmup: bool = True) -> None:
        self.engine = engine
        self.max_pending = max_pending
        self.warmup = warmup
        self._futures: Dict[Any, asyncio.Future] = {}
        # rid -> asyncio.Queue of token batches for streaming requests.
        self._streams: Dict[Any, asyncio.Queue] = {}
        # External X-Request-ID -> engine rid, the POST /cancel lookup
        # surface. skytpu-lint: disable=STL004 — same discipline as
        # _futures: loop-thread-only mutation, atomic cross-thread get.
        self._by_reqid: Dict[str, Any] = {}
        self._lock = threading.Lock()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._stop = threading.Event()
        self._ready = threading.Event()
        # Flipped by the SIGTERM/SIGINT handler (flag-only: STL009)
        # or POST /drain; the moment it is set, /health reports
        # 'draining' and /generate sheds — drain() then runs the
        # bounded wait + force-cancel sequence.
        self._drain_requested = threading.Event()
        # Flipped by POST /preempt_notice (the cloud-style spot
        # reclaim warning, docs/spot_serving.md): /health reports
        # 'preempting' and new /generate requests shed, but in-flight
        # streams KEEP RUNNING until the SIGKILL lands — the LB uses
        # the notice window to migrate them to survivors.
        self._preempt_requested = threading.Event()
        # Advertised on /health so the LB's tie-break can prefer
        # on-demand survivors (docs/spot_serving.md).
        self.is_spot = False
        # Serving role in a disaggregated pool
        # (docs/disaggregation.md): 'prefill' replicas answer
        # kv_prefill manifests and export pages on /kv/fetch;
        # 'decode' replicas pull pages and stream; 'mixed' (default)
        # does both. Advertised on /health — a routing hint, never
        # enforced, so a degraded pool can still route anything
        # anywhere.
        self.role = 'mixed'
        _M_ROLE.set(1, role=self.role)
        # True once drain()/stop() ended with every in-flight request
        # terminal and the driver thread joined.
        self.clean_shutdown: Optional[bool] = None
        self._dead: Optional[str] = None
        self._thread = threading.Thread(target=self._drive, daemon=True)

    # ---------------------------------------------------------- engine
    def _push_stream(self, rid: Any, item: Any) -> None:
        q = self._streams.get(rid)
        if q is not None and self._loop is not None:
            self._loop.call_soon_threadsafe(q.put_nowait, item)

    def _drive(self) -> None:
        try:
            if self.warmup:
                self.engine.warmup()
        except Exception as e:  # pylint: disable=broad-except
            logger.exception('Engine warmup failed')
            self._die(f'warmup failed: {e}')
            return
        self.engine.on_token = self._push_stream
        self._ready.set()
        while not self._stop.is_set():
            with self._lock:
                busy = bool(self.engine.queue or
                            self.engine.num_active())
            if not busy:
                if self.engine.has_pending:
                    # Drain the double-buffered chunk so its requests
                    # finish even when no new work arrives.
                    try:
                        self.engine.flush()
                    except Exception as e:  # pylint: disable=broad-except
                        logger.exception('Engine flush failed')
                        self._die(str(e))
                        return
                    self._resolve_finished()
                    continue
                # Idle ticks still decay the scraped SLO gauges: a
                # p99 frozen at its last (violating) value after
                # traffic stops would keep breaching the autoscaler
                # forever (internally throttled to 4 Hz).
                self.engine.refresh_slo_gauges()
                # skytpu-lint: disable=STL002 — idle tick of the
                # driver loop, not a retry: errors kill the driver
                # (_die), they are never retried here.
                time.sleep(0.002)
                continue
            try:
                self.engine.step()
            except Exception as e:  # pylint: disable=broad-except
                # A dead engine must not look healthy: fail every
                # in-flight request and flip /health so the load
                # balancer stops routing here (a silently-wedged
                # replica hangs every future request instead).
                logger.exception('Engine step failed')
                self._die(str(e))
                return
            self._resolve_finished()

    def _resolve_finished(self) -> None:
        # Drain (not read) so a long-lived replica never accumulates
        # every past request's tokens.
        for rid, res in self.engine.drain_results().items():
            self._push_stream(rid, ('done', res))
            fut = self._futures.pop(rid, None)
            if fut is not None and self._loop is not None:
                self._loop.call_soon_threadsafe(
                    lambda f=fut, r=res: (not f.done() and
                                          f.set_result(r)))

    def _die(self, reason: str) -> None:
        # skytpu-lint: disable=STL004 — one-shot GIL-atomic str write;
        # readers (health/generate) only compare against None.
        self._dead = reason
        self._ready.set()      # unblock anything waiting on readiness
        if self._loop is None:
            return

        def fail_all():
            err = RuntimeError(f'serving engine died: {reason}')
            for fut in self._futures.values():
                if not fut.done():
                    fut.set_exception(err)
            self._futures.clear()
            for q in self._streams.values():
                q.put_nowait(('error', reason))

        self._loop.call_soon_threadsafe(fail_all)

    # ----------------------------------------------------------- drain
    @property
    def draining(self) -> bool:
        return self._drain_requested.is_set()

    @property
    def preempting(self) -> bool:
        return self._preempt_requested.is_set()

    def request_preempt(self) -> None:
        """Flip the server into preempting mode (idempotent, safe
        from any thread): the spot reclaim notice arrived and the
        SIGKILL follows in SKYTPU_PREEMPT_NOTICE_S seconds. /health
        reports 'preempting' (503) so the probe demotes this replica
        and the LB stops routing here; new /generate requests shed;
        in-flight streams run on — the LB proactively migrates them
        during the window, so no drain sequence runs."""
        self._preempt_requested.set()

    def set_role(self, role: str) -> None:
        """Assign this replica's disaggregation role and re-point the
        skytpu_engine_role info gauge at it (the stale series zeroes
        so a scrape sees exactly one role at 1)."""
        if role not in ('mixed', 'prefill', 'decode'):
            raise ValueError(f'unknown replica role {role!r}')
        if role != self.role:
            _M_ROLE.set(0, role=self.role)
        # skytpu-lint: disable=STL004 — GIL-atomic str write, set once
        # at process start (CLI --role) before the server thread runs;
        # readers (/health, the gauge) tolerate either value mid-swap.
        self.role = role
        _M_ROLE.set(1, role=self.role)

    def request_drain(self) -> None:
        """Flip the server into draining mode (idempotent, safe from
        any thread and from signal handlers): /health reports
        'draining' so the LB and replica manager stop routing here,
        and new /generate requests are shed with 503 + Retry-After.
        The actual bounded wait + force-cancel runs in drain()."""
        self._drain_requested.set()

    def install_signal_handlers(self) -> None:
        """SIGTERM/SIGINT start a graceful drain. The handler body
        only sets an event (STL009): the drain sequence itself —
        waiting, cancelling, joining — runs on the main task, never
        inside the signal frame. A SECOND signal while a drain is
        already in progress escalates to an immediate exit — an
        operator hammering Ctrl-C on a wedged drain must not be
        ignored for the whole drain budget."""

        def _handler(signum, frame):
            del signum, frame
            if self._drain_requested.is_set():
                # Second signal: out NOW. A bare raise (no blocking
                # work) unwinds the main task wherever it is.
                raise KeyboardInterrupt
            self._drain_requested.set()

        signal.signal(signal.SIGTERM, _handler)
        signal.signal(signal.SIGINT, _handler)

    def _inflight_rids(self) -> set:
        rids = set(self._futures) | set(self._streams)
        try:
            rids |= self.engine._inflight_ids()  # pylint: disable=protected-access
        except RuntimeError:
            pass  # queue mutated mid-scan; futures/streams cover it
        return rids

    def _engine_idle(self) -> bool:
        return not (self.engine.queue or self.engine.num_active() or
                    self.engine.has_pending)

    async def drain(self) -> bool:
        """Graceful drain (docs/request_lifecycle.md): let in-flight
        requests run to completion for up to
        ``SKYTPU_DRAIN_TIMEOUT_SECONDS``, then force-cancel the
        stragglers (partial results, status='cancelled',
        reason='shutdown'), stop the driver thread and report whether
        shutdown was clean. Every in-flight request ends in exactly
        one terminal state either way."""
        self._drain_requested.set()
        budget = max(0.0, lifecycle.drain_timeout_s())
        t0 = time.perf_counter()
        # A drain landing DURING warmup has no client work to wait
        # for: /generate sheds 503 until _ready, so everything the
        # engine holds is warmup's own synthetic requests — waiting
        # the budget out (or force-cancelling them) would stall a
        # perfectly normal startup-time termination and mis-report
        # it as unclean.
        warming = (self._thread.is_alive() and
                   not self._ready.is_set() and self._dead is None)
        # Chaos site: a fired 'hang' fault acts out in-flight work
        # that refuses to finish for params['seconds'] — the
        # force-cancel path must bound it exactly like a real stall.
        # Not polled while warming: the warming branch skips the wait
        # loop, and a one-shot spec must never be consumed without
        # the stall being acted out.
        fault = None
        if not warming:
            fault = fault_injection.poll(
                'serve.replica.drain',
                kinds=(fault_injection.FaultKind.HANG,))
        stall_until = (t0 + float(fault.params.get('seconds', 0.0))
                       if fault is not None else t0)
        with trace_lib.span('http.drain', budget_s=budget,
                            warming=warming) as sp:
            deadline = t0 + budget
            while not warming and time.perf_counter() < deadline:
                busy = (self._inflight_rids() or
                        not self._engine_idle() or
                        time.perf_counter() < stall_until)
                if not busy:
                    break
                await asyncio.sleep(0.02)
            cancelled = ([] if warming else
                         sorted(map(str, self._inflight_rids())))
            if cancelled or (not warming and not self._engine_idle()):
                logger.warning(
                    'Drain budget (%.1fs) exhausted with %d request(s) '
                    'in flight: force-cancelling (trace=%s).', budget,
                    len(cancelled), trace_lib.current_trace_id())
                if self._thread.is_alive():
                    for rid in self._inflight_rids():
                        self.engine.cancel(rid, reason='shutdown')
                else:
                    # No driver is ticking (never started / already
                    # dead): nothing will apply deferred cancels, so
                    # play the driver's role directly.
                    self.engine.cancel_all(reason='shutdown')
                    self._resolve_finished()
                # The cancels surface as terminal Results within a
                # tick; bound the sweep so a wedged device cannot
                # hold the process hostage.
                sweep = time.perf_counter() + max(2.0, budget or 1.0)
                while time.perf_counter() < sweep:
                    if not self._inflight_rids() and self._engine_idle():
                        break
                    await asyncio.sleep(0.02)
            terminal = warming or (not self._inflight_rids() and
                                   self._engine_idle())
            joined = await asyncio.to_thread(self.stop)
            if warming and not joined:
                # The driver is still inside a warmup compile: no
                # client work was ever in flight, and the daemon
                # thread dies with the process exactly as it always
                # did at exit — a startup-time SIGTERM is not an
                # unclean shutdown.
                logger.info('Driver still finishing warmup compiles '
                            'at exit; no client work was in flight.')
                joined = True
            dur = time.perf_counter() - t0
            _M_DRAIN.observe(dur, exemplar=sp.exemplar
                             if sp is not None else None)
            if sp is not None:
                sp.set_attr(cancelled=len(cancelled),
                            terminal=terminal, clean=joined)
        # skytpu-lint: disable=STL004 — one-shot bool written after
        # the driver thread has been joined (stop() above).
        self.clean_shutdown = terminal and joined
        if not self.clean_shutdown:
            logger.warning(
                'Drain finished NOT clean (terminal=%s joined=%s) '
                'after %.2fs.', terminal, joined, dur)
        else:
            logger.info('Drained cleanly in %.2fs.', dur)
        return self.clean_shutdown

    # ------------------------------------------------------------ http
    def _overloaded_response(self, req_id: str
                             ) -> Optional[web.Response]:
        """429 + Retry-After when the pending queue is full, else
        None. Host-side only (safe pre-warmup); checked before the
        readiness gate so a warming replica still sheds queue
        overflow instead of 503-ing it ambiguously. The reject echoes
        the request id so a shed request stays correlatable."""
        if self.max_pending is None:
            return None
        with self._lock:
            depth = len(self.engine.queue)
        if depth < self.max_pending:
            return None
        # Rough drain-time hint: pending requests over the number of
        # decode slots, one second per queued batch, clamped sane.
        retry = max(1, min(30, depth //
                           max(1, getattr(self.engine, 'batch_size',
                                          1))))
        _M_REJECTS.inc()
        _M_SHEDS.inc(1, reason='queue_full')
        logger.warning('Shedding /generate (pending=%d) request=%s '
                       'trace=%s', depth, req_id,
                       trace_lib.current_trace_id())
        return web.json_response(
            {'error': 'server overloaded: pending queue is full',
             'reason': 'queue_full',
             'pending': depth, 'max_pending': self.max_pending,
             'request_id': req_id},
            status=429, headers={'Retry-After': str(retry),
                                 **_rid_headers(req_id)})

    def _draining_response(self, req_id: str
                           ) -> Optional[web.Response]:
        """503 + Retry-After while draining: the LB should take its
        retry to another replica; this one is going away."""
        if not self.draining:
            return None
        _M_SHEDS.inc(1, reason='draining')
        return web.json_response(
            {'error': 'replica is draining', 'status': 'draining',
             'reason': 'draining', 'request_id': req_id},
            status=503, headers={'Retry-After': '1',
                                 **_rid_headers(req_id)})

    def _preempting_response(self, req_id: str
                             ) -> Optional[web.Response]:
        """503 + Retry-After once the preemption notice arrived: this
        replica dies within the notice window, so new work belongs on
        a survivor (in-flight work keeps running — the LB migrates
        it)."""
        if not self.preempting:
            return None
        _M_SHEDS.inc(1, reason='preempting')
        return web.json_response(
            {'error': 'replica received a preemption notice',
             'status': 'preempting', 'reason': 'preempting',
             'request_id': req_id},
            status=503, headers={'Retry-After': '1',
                                 **_rid_headers(req_id)})

    def _deadline_shed_response(self, req_id: str,
                                deadline: Optional[float],
                                tokens, max_new: int,
                                priority_class: Optional[str] = None
                                ) -> Optional[web.Response]:
        """Deadline-aware admission (docs/request_lifecycle.md):
        shed a request whose ESTIMATED queue wait already exceeds its
        remaining budget — strictly better than the blind max_pending
        bound, because a no-deadline request at the same queue depth
        is still admitted, and a tight-deadline request is told
        immediately instead of timing out after burning a slot. The
        token ids flow into the estimate so a prefix-cache hit is
        charged only its uncached suffix — high-hit-rate traffic must
        not be shed for prefill it will never run.

        Class-aware (docs/qos.md): the estimate excludes queued work
        of strictly lower priority — at the same queue depth an
        interactive request is admitted while a bulk one sheds,
        because DRR ordering really will jump it over that backlog.
        The Retry-After hint scales by class rank (interactive x1,
        standard x2, bulk x4): lower classes should back off longer
        from a contended replica."""
        if deadline is None:
            return None
        left = deadline - time.time()
        est = self.engine.estimate_wait_s(
            len(tokens), max_new, tokens=tokens,
            priority_class=priority_class)
        if est <= left:
            return None
        _M_SHEDS.inc(1, reason='wont_make_deadline')
        # Classless requests keep the legacy hint bit-for-bit.
        scale = (1 if priority_class is None
                 else 1 << qos_lib.class_rank(priority_class))
        retry = max(1, min(30,
                           (int(est - max(left, 0.0)) + 1) * scale))
        logger.warning(
            'Shedding /generate (estimated wait %.2fs > remaining '
            'budget %.2fs) request=%s trace=%s', est, left, req_id,
            trace_lib.current_trace_id())
        return web.json_response(
            {'error': 'deadline cannot be met: estimated wait '
                      f'{est:.2f}s exceeds remaining budget '
                      f'{max(left, 0.0):.2f}s',
             'reason': 'wont_make_deadline',
             'estimated_wait_s': round(est, 3),
             'request_id': req_id},
            status=429, headers={'Retry-After': str(retry),
                                 **_rid_headers(req_id)})

    @staticmethod
    def _parse_generate(body: Any) -> tuple:
        """Validate a /generate body; raises ValueError with a
        client-safe message (-> 400). The engine driver thread must
        never see malformed input: an exception there kills serving
        for every in-flight request."""
        if not isinstance(body, dict):
            raise ValueError('body must be a JSON object')
        tokens = body.get('tokens')
        if (not isinstance(tokens, list) or not tokens or
                not all(isinstance(t, int) and not isinstance(t, bool)
                        for t in tokens)):
            raise ValueError("'tokens' must be a non-empty list of "
                             'integer token ids')
        max_new = body.get('max_new', 64)
        if not isinstance(max_new, int) or isinstance(max_new, bool) \
                or max_new < 1:
            raise ValueError("'max_new' must be a positive integer")
        temperature = body.get('temperature')
        if temperature is not None and \
                not isinstance(temperature, (int, float)):
            raise ValueError("'temperature' must be a number")
        timeout_s = body.get('timeout_s')
        if timeout_s is not None:
            if (not isinstance(timeout_s, (int, float)) or
                    isinstance(timeout_s, bool) or timeout_s <= 0):
                raise ValueError("'timeout_s' must be a positive "
                                 'number of seconds')
        return (tokens, max_new, temperature,
                bool(body.get('stream')), timeout_s)

    @staticmethod
    def _resolve_qos(headers, body: Any) -> tuple:
        """Tenant + priority class for a /generate request
        (docs/qos.md): the X-Tenant-ID / X-Priority-Class headers
        win (the LB forwards and re-stamps them per attempt, so a
        hedged/resumed/migrated stream keeps its identity); body
        keys 'tenant' / 'priority_class' are the direct-client
        fallback. Raises ValueError (-> 400) on a malformed tenant
        id or an unknown class. Returns (tenant|None, class|None) —
        None class means "never stated", which the engine treats as
        standard but the Retry-After scaling leaves on the legacy
        path."""
        tenant_raw = headers.get(qos_lib.TENANT_HEADER)
        if tenant_raw is None and isinstance(body, dict):
            tenant_raw = body.get('tenant')
        cls_raw = headers.get(qos_lib.CLASS_HEADER)
        if cls_raw is None and isinstance(body, dict):
            cls_raw = body.get('priority_class')
        tenant = qos_lib.validate_tenant(tenant_raw)
        if cls_raw is None or cls_raw == '':
            return tenant, None
        return tenant, qos_lib.validate_class(cls_raw)

    async def _import_remote_kv(self, url: str,
                                tokens) -> Optional[int]:
        """Pull this prompt's KV pages from ``url`` (a prefill peer)
        and queue them for import at the next tick boundary — the
        queue is drained BEFORE admission, so a request submitted
        after this call sees the pages in its reuse lookup
        (docs/disaggregation.md). Returns the expected reused-token
        count (the X-KV-Reused-Tokens surface), or None when the
        fetch failed and the request falls back to a plain local
        prefill — a fetch failure slows a request down but never
        fails it."""
        from skypilot_tpu.models import prefix_cache as prefix_mod
        from skypilot_tpu.serve import kv_transfer
        prefix = self.engine.prefix
        page = prefix.page
        n_full = len(tokens) // page
        if n_full <= 0:
            return 0
        hashes = prefix_mod.page_hashes(tokens[:n_full * page], page)
        # skytpu-lint: disable=STL004 — read-only membership probe;
        # pylint: disable=protected-access — same-package peek, the
        # same discipline would_reuse uses internally.
        need = [h for h in hashes if h not in prefix._by_hash]
        fetched = []
        if need:
            try:
                fetched = await asyncio.to_thread(
                    kv_transfer.fetch, url, need,
                    expect_sig=prefix.page_signature())
            except kv_transfer.KVFetchError as e:
                logger.warning(
                    'KV fetch from %s failed (%s): falling back to '
                    'local prefill. trace=%s', url, e,
                    trace_lib.current_trace_id())
                return None
            if fetched:
                self.engine.queue_kv_import(fetched)
        return prefix.would_reuse(
            tokens, self.engine.prefill_chunk,
            extra_hashes=[h for h, _ in fetched])

    async def _generate_prefill_manifest(
            self, rid: Any, req_id: str, tokens, temperature,
            deadline: Optional[float],
            tenant: Optional[str] = None,
            priority_class: Optional[str] = None) -> web.Response:
        """The prefill half of a disaggregated handoff
        (docs/disaggregation.md): run the prompt through the normal
        chunked-prefill path with a single decode step — the
        terminal retire is what publishes the prompt's full pages
        into the prefix pool — then answer with a page MANIFEST
        instead of a token stream: the chain hashes now exported on
        /kv/fetch, the pool's page signature, and the page size. The
        decode side recomputes the same chain hashes from the same
        tokens; the manifest is the router's receipt that they are
        fetchable here."""
        from skypilot_tpu.models import prefix_cache as prefix_mod
        from skypilot_tpu.models.serving_engine import (
            DuplicateRequestError, Request)
        fut = asyncio.get_event_loop().create_future()
        # skytpu-lint: disable=STL004 — same discipline as the
        # non-streaming path: loop-thread mutation, driver-side pop.
        self._futures[rid] = fut
        try:
            with self._lock:
                self.engine.submit(Request(
                    rid, tokens, 1, temperature=temperature,
                    deadline=deadline, tenant=tenant,
                    priority_class=priority_class))
        except DuplicateRequestError as e:
            self._futures.pop(rid, None)
            return web.json_response(
                {'error': str(e), 'reason': 'duplicate_request',
                 'request_id': req_id},
                status=409, headers=_rid_headers(req_id))
        except ValueError as e:
            self._futures.pop(rid, None)
            return web.json_response({'error': str(e)}, status=400,
                                     headers=_rid_headers(req_id))
        if self._dead is not None:
            self._futures.pop(rid, None)
            return web.json_response(
                {'error': f'engine dead: {self._dead}'}, status=503,
                headers=_rid_headers(req_id))
        try:
            result = await fut
        except asyncio.CancelledError:
            self._futures.pop(rid, None)
            self.engine.cancel(rid, reason='client_disconnect')
            raise
        prefix = self.engine.prefix
        page = prefix.page
        n_full = len(tokens) // page
        hashes = prefix_mod.page_hashes(tokens[:n_full * page], page)
        return web.json_response(
            {'manifest': True,
             'page': page,
             'prompt_len': len(tokens),
             'hashes': [h.hex() for h in hashes],
             'sig': prefix.page_signature(),
             'tokens': result.tokens,
             'status': result.status,
             'reason': result.reason},
            headers=_rid_headers(req_id))

    async def handle_generate(self, request: web.Request
                              ) -> web.StreamResponse:
        # Correlation surface (docs/tracing.md): accept (or mint) an
        # X-Request-ID echoed on every response, and continue the
        # caller's trace from its traceparent header — the request
        # span parents under the LB's proxy span, and the engine's
        # TTFT-decomposition spans parent under this one.
        req_id = (request.headers.get(trace_lib.REQUEST_ID_HEADER) or
                  trace_lib.new_request_id())
        ctx = trace_lib.context_from_headers(request.headers)
        with trace_lib.span('http.generate', parent=ctx,
                            request_id=req_id):
            return await self._handle_generate(request, req_id)

    async def _handle_generate(self, request: web.Request,
                               req_id: str) -> web.StreamResponse:
        from skypilot_tpu.models.serving_engine import (
            DuplicateRequestError, Request)
        if self._dead is not None:
            return web.json_response(
                {'error': f'engine dead: {self._dead}'}, status=503,
                headers=_rid_headers(req_id))
        try:
            body = await request.json()
            tokens, max_new, temperature, stream, timeout_s = \
                self._parse_generate(body)
            # Static-limit checks are host-side and safe pre-warmup;
            # rejecting here keeps them 400s even while warming.
            if len(tokens) > self.engine.max_prompt:
                raise ValueError(
                    f'prompt ({len(tokens)}) exceeds max_prompt '
                    f'({self.engine.max_prompt}).')
            if max_new > self.engine.decode_capacity():
                raise ValueError(
                    f'max_new ({max_new}) exceeds the decode '
                    f'capacity ({self.engine.decode_capacity()}).')
            tenant, priority_class = self._resolve_qos(
                request.headers, body)
        except (ValueError, UnicodeDecodeError) as e:
            return web.json_response({'error': str(e)}, status=400,
                                     headers=_rid_headers(req_id))
        # Deadline resolution: the LB-stamped remaining-budget header
        # wins (it reflects time already burned upstream); a direct
        # client may send body timeout_s instead.
        deadline = lifecycle.deadline_from_headers(request.headers)
        if deadline is None and timeout_s is not None:
            deadline = time.time() + timeout_s
        draining = self._draining_response(req_id)
        if draining is not None:
            return draining
        preempting = self._preempting_response(req_id)
        if preempting is not None:
            return preempting
        overloaded = self._overloaded_response(req_id)
        if overloaded is not None:
            return overloaded
        shed = self._deadline_shed_response(req_id, deadline,
                                            tokens, max_new,
                                            priority_class)
        if shed is not None:
            return shed
        if not self._ready.is_set():
            # Requests submitted during warmup would be drained by
            # warmup's own run() and silently lost.
            return web.json_response({'status': 'warming'}, status=503,
                                     headers=_rid_headers(req_id))
        # The engine request id IS the external X-Request-ID (minted
        # above when the client sent none): the engine's
        # DuplicateRequestError then guarantees at most one in-flight
        # execution per id on THIS replica — the invariant the LB's
        # hedge/retry machinery leans on (docs/failover.md). A
        # duplicate is answered 409, a clean "already running" signal
        # distinct from a 400 bad request.
        rid = req_id
        if req_id in self._by_reqid:
            return web.json_response(
                {'error': f'request {req_id!r} is already in flight '
                          'on this replica',
                 'reason': 'duplicate_request', 'request_id': req_id},
                status=409, headers=_rid_headers(req_id))
        # skytpu-lint: disable=STL004 — _by_reqid is mutated only on
        # the event-loop thread; handle_cancel does an atomic get.
        self._by_reqid[req_id] = rid
        try:
            has_prefix = getattr(self.engine, 'prefix', None) is not None
            if body.get('kv_prefill'):
                # Disaggregated handoff, prefill half: publish pages,
                # answer a manifest (docs/disaggregation.md).
                if not has_prefix:
                    return web.json_response(
                        {'error': 'kv_prefill requires a prefix '
                                  'cache on this replica',
                         'reason': 'no_prefix_cache',
                         'request_id': req_id},
                        status=409, headers=_rid_headers(req_id))
                return await self._generate_prefill_manifest(
                    rid, req_id, tokens, temperature, deadline,
                    tenant=tenant, priority_class=priority_class)
            kv_source = body.get('kv_source')
            kv_reused: Optional[int] = None
            if (isinstance(kv_source, str) and kv_source and
                    has_prefix):
                # Disaggregated handoff, decode half: pull the
                # prompt's pages from the prefill peer before submit.
                kv_reused = await self._import_remote_kv(
                    kv_source, tokens)
            if stream:
                return await self._generate_stream(
                    request, rid, req_id, tokens, max_new, temperature,
                    deadline, tenant=tenant,
                    priority_class=priority_class,
                    kv_reused=kv_reused)
            fut = asyncio.get_event_loop().create_future()
            # skytpu-lint: disable=STL004 — _futures is mutated and
            # iterated only on the event-loop thread (fail_all runs
            # via call_soon_threadsafe); the driver thread does
            # atomic pops.
            self._futures[rid] = fut
            try:
                with self._lock:
                    self.engine.submit(Request(
                        rid, tokens, max_new, temperature=temperature,
                        deadline=deadline, tenant=tenant,
                        priority_class=priority_class))
            except DuplicateRequestError as e:
                # Raced past the _by_reqid check (e.g. a hedge
                # duplicate landing in the same loop turn): the
                # engine's own in-flight set is the authority.
                self._futures.pop(rid, None)
                return web.json_response(
                    {'error': str(e), 'reason': 'duplicate_request',
                     'request_id': req_id},
                    status=409, headers=_rid_headers(req_id))
            except ValueError as e:
                self._futures.pop(rid, None)
                return web.json_response({'error': str(e)}, status=400,
                                         headers=_rid_headers(req_id))
            if self._dead is not None:
                # The engine died between the entry check and our
                # future registration (both on the loop thread, but
                # the body await yields): _die's fail_all may already
                # have swept _futures, so this future would hang
                # forever.
                self._futures.pop(rid, None)
                return web.json_response(
                    {'error': f'engine dead: {self._dead}'}, status=503,
                    headers=_rid_headers(req_id))
            try:
                result = await fut
            except asyncio.CancelledError:
                # The client hung up while we awaited the engine:
                # free the slot NOW instead of decoding tokens nobody
                # will read.
                self._futures.pop(rid, None)
                self.engine.cancel(rid, reason='client_disconnect')
                raise
            return web.json_response(
                {
                    'tokens': result.tokens,
                    'latency_s': (result.finished_at -
                                  result.submitted_at),
                    'status': result.status,
                    'reason': result.reason,
                },
                headers=_rid_headers(req_id))
        finally:
            if self._by_reqid.get(req_id) == rid:
                self._by_reqid.pop(req_id, None)

    async def _generate_stream(self, request: web.Request, rid: Any,
                               req_id: str, tokens, max_new,
                               temperature,
                               deadline: Optional[float] = None,
                               tenant: Optional[str] = None,
                               priority_class: Optional[str] = None,
                               kv_reused: Optional[int] = None
                               ) -> web.StreamResponse:
        """SSE: one ``data:`` event per decode chunk, then ``done``.

        A client that disconnects mid-stream cancels the engine
        request (reason='client_disconnect'): its slot frees within a
        tick instead of decoding to max_new for nobody. aiohttp
        surfaces the disconnect either as ConnectionResetError from
        ``write`` or by cancelling this handler task.
        """
        from skypilot_tpu.models.serving_engine import (
            DuplicateRequestError, Request)
        q: asyncio.Queue = asyncio.Queue()
        # skytpu-lint: disable=STL004 — same discipline as _futures:
        # loop-thread-only mutation/iteration, atomic cross-thread get.
        self._streams[rid] = q
        try:
            with self._lock:
                self.engine.submit(Request(
                    rid, tokens, max_new, temperature=temperature,
                    deadline=deadline, tenant=tenant,
                    priority_class=priority_class))
        except DuplicateRequestError as e:
            self._streams.pop(rid, None)
            return web.json_response(
                {'error': str(e), 'reason': 'duplicate_request',
                 'request_id': req_id},
                status=409, headers=_rid_headers(req_id))
        except ValueError as e:
            self._streams.pop(rid, None)
            return web.json_response({'error': str(e)}, status=400,
                                     headers=_rid_headers(req_id))
        if self._dead is not None:
            # Same race as the non-streaming path: registered after
            # fail_all swept the stream registry -> would hang.
            self._streams.pop(rid, None)
            return web.json_response(
                {'error': f'engine dead: {self._dead}'}, status=503,
                headers=_rid_headers(req_id))
        headers = {
            'Content-Type': 'text/event-stream',
            'Cache-Control': 'no-cache',
            'X-Accel-Buffering': 'no',
            **_rid_headers(req_id),
        }
        if kv_reused is not None:
            # Disaggregated/KV-assisted streams advertise how many
            # prompt tokens the fetched pages will cover, BEFORE the
            # first byte: the LB attaches it to its resume span and
            # the skytpu_lb_resume_kv_reused_tokens_total counter
            # (docs/disaggregation.md).
            headers['X-KV-Reused-Tokens'] = str(kv_reused)
        resp = web.StreamResponse(headers=headers)
        try:
            # prepare() is INSIDE the guarded region: a client that
            # hangs up this early cancels the handler right here, and
            # the engine request + stream registration must not leak.
            await resp.prepare(request)
            while True:
                item = await q.get()
                if isinstance(item, tuple) and item[0] == 'done':
                    res = item[1]
                    payload = {
                        'done': True,
                        'tokens': res.tokens,
                        'latency_s': (res.finished_at -
                                      res.submitted_at),
                        'status': res.status,
                        'reason': res.reason,
                    }
                    await resp.write(
                        f'data: {json.dumps(payload)}\n\n'.encode())
                    break
                if isinstance(item, tuple) and item[0] == 'error':
                    payload = {'error': item[1]}
                    await resp.write(
                        f'data: {json.dumps(payload)}\n\n'.encode())
                    break
                await resp.write(
                    f'data: {json.dumps({"tokens": item})}\n\n'
                    .encode())
        except (asyncio.CancelledError, ConnectionResetError):
            self.engine.cancel(rid, reason='client_disconnect')
            logger.info('Client disconnected mid-stream; cancelled '
                        'request=%s trace=%s', req_id,
                        trace_lib.current_trace_id())
            raise
        finally:
            self._streams.pop(rid, None)
            if self._by_reqid.get(req_id) == rid:
                self._by_reqid.pop(req_id, None)
        await resp.write_eof()
        return resp

    async def handle_cancel(self, request: web.Request) -> web.Response:
        """POST /cancel/<request_id>: cancel a live request by its
        X-Request-ID. 202 when the cancel was accepted (the terminal
        'cancelled' Result lands within a tick), 404 when no such
        request is in flight (unknown id, or already terminal)."""
        req_id = request.match_info['request_id']
        rid = self._by_reqid.get(req_id)
        if rid is None or not self.engine.cancel(rid, reason='api'):
            return web.json_response(
                {'error': f'no in-flight request {req_id!r}'},
                status=404, headers=_rid_headers(req_id))
        return web.json_response(
            {'cancelling': True, 'request_id': req_id}, status=202,
            headers=_rid_headers(req_id))

    async def handle_drain(self, request: web.Request) -> web.Response:
        """POST /drain: flip into draining mode (the replica manager's
        drain-then-kill hook). Returns immediately; the process's main
        task runs the bounded drain sequence. The body echoes THIS
        replica's drain budget so the caller waits on the replica's
        clock, not its own SKYTPU_DRAIN_TIMEOUT_SECONDS (env skew
        between controller and replica hosts must not cut a drain
        short)."""
        del request
        self.request_drain()
        return web.json_response(
            {'status': 'draining',
             'budget_s': max(0.0, lifecycle.drain_timeout_s())},
            status=202)

    async def handle_preempt_notice(self, request: web.Request
                                    ) -> web.Response:
        """POST /preempt_notice: the cloud-style spot reclaim warning
        (docs/spot_serving.md). Flips /health to 'preempting' and
        sheds new work; in-flight streams keep running until the
        kill — the caller (notice harness / LB) owns migrating them.
        Returns immediately; the body echoes the notice lead time so
        the caller knows the window it is working with."""
        del request
        self.request_preempt()
        return web.json_response(
            {'status': 'preempting',
             'notice_s': lifecycle.preempt_notice_s()},
            status=202)

    async def handle_kv_fetch(self, request: web.Request
                              ) -> web.Response:
        """POST /kv/fetch: serve prefix-cache pages by chain hash
        (docs/disaggregation.md). Body ``{'hashes': [hex, ...]}``;
        the response is one SKKV1 payload holding every requested
        page the pool still has — whole pages only, bounded by
        SKYTPU_KV_FETCH_MAX_BYTES. Absence of a page is the miss
        signal (the peer re-prefills those positions), so a cold
        hash never 404s; 400 on malformed bodies, 503 while warming
        or when this replica has no prefix cache."""
        from skypilot_tpu.serve import kv_transfer
        if self._dead is not None:
            return web.json_response(
                {'error': f'engine dead: {self._dead}'}, status=503)
        if not self._ready.is_set():
            return web.json_response({'status': 'warming'},
                                     status=503)
        prefix = getattr(self.engine, 'prefix', None)
        if prefix is None:
            return web.json_response(
                {'error': 'no prefix cache on this replica'},
                status=503)
        try:
            body = await request.json()
            if not isinstance(body, dict):
                raise ValueError('body must be a JSON object')
            hashes = body.get('hashes')
            if (not isinstance(hashes, list) or
                    not all(isinstance(h, str) for h in hashes)):
                raise ValueError(
                    "'hashes' must be a list of hex chain hashes")
        except (ValueError, UnicodeDecodeError) as e:
            return web.json_response({'error': str(e)}, status=400)
        # Off-loop: pack_pages does device->host copies per page.
        payload = await asyncio.to_thread(
            kv_transfer.pack_pages, prefix, hashes)
        return web.Response(
            body=payload,
            headers={'Content-Type': 'application/octet-stream'})

    async def handle_kv_warm(self, request: web.Request
                             ) -> web.Response:
        """POST /kv/warm: peer cache warming
        (docs/affinity_routing.md). Body ``{'donor': url, 'hashes':
        [hex, ...]}`` — pull the named pages from the donor replica
        over /kv/fetch and queue them for import at the next tick
        boundary (the same ``queue_kv_import`` path a disagg handoff
        uses, so the already-warmed jit programs serve the copies
        with zero recompiles). Answers the fetched-page count; a
        donor failure answers ``imported: 0`` with the error named —
        a 200 either way, so a dead donor degrades the caller to a
        cold start instead of an error that could block readiness."""
        from skypilot_tpu.serve import kv_transfer
        if self._dead is not None:
            return web.json_response(
                {'error': f'engine dead: {self._dead}'}, status=503)
        if not self._ready.is_set():
            return web.json_response({'status': 'warming'},
                                     status=503)
        prefix = getattr(self.engine, 'prefix', None)
        if prefix is None:
            return web.json_response(
                {'error': 'no prefix cache on this replica'},
                status=503)
        try:
            body = await request.json()
            if not isinstance(body, dict):
                raise ValueError('body must be a JSON object')
            donor = body.get('donor')
            if not isinstance(donor, str) or not donor:
                raise ValueError("'donor' must be a replica URL")
            hashes = body.get('hashes')
            if (not isinstance(hashes, list) or
                    not all(isinstance(h, str) for h in hashes)):
                raise ValueError(
                    "'hashes' must be a list of hex chain hashes")
            want = [bytes.fromhex(h) for h in hashes]
        except (ValueError, UnicodeDecodeError) as e:
            return web.json_response({'error': str(e)}, status=400)
        # skytpu-lint: disable=STL004 — read-only membership probe;
        # pylint: disable=protected-access — same-package peek, the
        # same discipline _import_remote_kv uses.
        need = [h for h in want if h not in prefix._by_hash]
        fetched = []
        if need:
            try:
                fetched = await asyncio.to_thread(
                    kv_transfer.fetch, donor, need,
                    expect_sig=prefix.page_signature())
            except kv_transfer.KVFetchError as e:
                logger.warning(
                    'Peer-warm fetch from donor %s failed (%s): '
                    'starting cold.', donor, e)
                return web.json_response(
                    {'imported': 0, 'error': str(e)})
            if fetched:
                self.engine.queue_kv_import(fetched)
        return web.json_response({'imported': len(fetched),
                                  'already': len(want) - len(need)})

    async def handle_health(self, request: web.Request) -> web.Response:
        if self._dead is not None:
            return web.json_response(
                {'status': 'dead', 'reason': self._dead}, status=503)
        if self.draining:
            # 503 so the LB and the replica manager's probe both stop
            # routing here; the body names the reason so a deliberate
            # drain is distinguishable from a crash.
            return web.json_response({'status': 'draining'}, status=503)
        if self.preempting:
            # Same contract as draining: deliberate, not a failure —
            # the probe demotes without feeding the terminate streak.
            return web.json_response({'status': 'preempting',
                                      'is_spot': self.is_spot},
                                     status=503)
        if not self._ready.is_set():
            return web.json_response({'status': 'warming'}, status=503)
        # The admission-pressure estimate rides on /health so probes
        # (and humans curling a replica) see queue pressure without a
        # full /metrics parse; the scraped gauge form is
        # skytpu_engine_est_wait_seconds. The static admission limits
        # ride along (docs/failover.md) so callers can size resumable
        # workloads against THIS replica's max_prompt.
        body = {'status': 'ok',
                'est_wait_s': round(self.engine.estimate_wait_s(0, 1),
                                    4),
                'is_spot': self.is_spot,
                'role': self.role}
        limits = getattr(self.engine, 'limits', None)
        if limits is not None:
            body['limits'] = limits()
        # Mesh shape / device count (None single-chip): the harness
        # computes per-chip normalization from this, and probes see
        # at a glance whether a replica is a pod slice or one chip.
        mesh_info = getattr(self.engine, 'mesh_info', None)
        if mesh_info is not None:
            body['mesh'] = mesh_info()
        # Versioned prefix digest (pool occupancy + a recency-ordered
        # bounded hash list): the LB's cache-aware routing scores
        # replicas from exactly this surface on the probe cadence
        # (docs/affinity_routing.md), and humans curling a replica
        # see cache heat without a /metrics parse.
        prefix = getattr(self.engine, 'prefix', None)
        if prefix is not None:
            body['prefix'] = prefix.prefix_summary()
        return web.json_response(body)

    async def handle_metrics(self, request: web.Request
                             ) -> web.Response:
        """Prometheus exposition of the replica's engine metrics
        (docs/metrics.md). Host-side only — safe during warmup and
        after engine death (a dying replica's last counters are
        exactly what an operator wants to scrape). This process's
        registry only: spool merging belongs to ONE endpoint per
        host (the API server) or scraping two endpoints would count
        every spooled controller twice."""
        text = metrics_lib.render_exposition()
        return web.Response(
            text=text, headers={'Content-Type': metrics_lib.CONTENT_TYPE})

    def make_app(self) -> web.Application:
        app = web.Application()
        app.router.add_post('/generate', self.handle_generate)
        app.router.add_post('/cancel/{request_id}', self.handle_cancel)
        app.router.add_post('/drain', self.handle_drain)
        app.router.add_post('/preempt_notice',
                            self.handle_preempt_notice)
        app.router.add_post('/kv/fetch', self.handle_kv_fetch)
        app.router.add_post('/kv/warm', self.handle_kv_warm)
        app.router.add_get('/health', self.handle_health)
        app.router.add_get('/metrics', self.handle_metrics)
        return app

    async def start(self, port: int) -> web.AppRunner:
        # skytpu-lint: disable=STL004 — written once before the driver
        # thread starts on the next line (Thread.start happens-before).
        self._loop = asyncio.get_event_loop()
        self._thread.start()
        runner = web.AppRunner(self.make_app())
        await runner.setup()
        site = web.TCPSite(runner, '0.0.0.0', port)
        await site.start()
        logger.info('Engine server on :%d', port)
        return runner

    def stop(self) -> bool:
        """Stop the driver thread; True when it actually exited.

        Join so interpreter teardown never kills the driver thread
        mid-device-call (which aborts with an unraisable C++
        exception). Bounded: warmup compiles can outlast it — and a
        join timing out means the thread is STILL RUNNING, which the
        old code silently ignored. Now the leak is checked
        (is_alive after the join), logged with the active trace id,
        and reported to the caller so the exit path can surface a
        non-clean shutdown instead of pretending the join succeeded.
        """
        self._stop.set()
        if self._thread.ident is None or not self._thread.is_alive():
            return True
        self._thread.join(timeout=10)
        if self._thread.is_alive():
            logger.warning(
                'Engine driver thread still alive after a 10s join '
                '(trace=%s): a device call is hung; shutdown is NOT '
                'clean.', trace_lib.current_trace_id())
            return False
        return True


def _build_engine(args) -> 'Any':
    import jax
    import jax.numpy as jnp

    from skypilot_tpu import models
    from skypilot_tpu.models.serving_engine import ServingEngine
    # Cross-family preset lookup: 'tiny'/'tpu_1b' (dense) and
    # 'tiny_moe'/'mixtral_8x7b' (MoE) all serve through this front
    # end.
    cfg_fn = models.config_preset(args.model)
    cfg = cfg_fn(max_seq=args.max_seq)
    if jax.default_backend() != 'cpu':
        cfg = cfg_fn(max_seq=args.max_seq,
                     param_dtype=jnp.bfloat16)
    mesh = None
    if args.tp > 1:
        # Serve a model larger than one chip: Megatron tp over the
        # replica's local chips (params + kv-head cache axis shard).
        from skypilot_tpu.parallel import make_mesh, plan_mesh
        mesh = make_mesh(plan_mesh(args.tp, tp=args.tp),
                         devices=jax.devices()[:args.tp])
    if args.checkpoint:
        import os

        import orbax.checkpoint as ocp

        from skypilot_tpu.models import quantization
        fam = models.family(cfg)
        ckpt_quantized = getattr(args, 'checkpoint_quantized', False)
        if ckpt_quantized:
            # int8 checkpoint (models.quantization CLI output): the
            # restore target is the QUANTIZED tree shape, so an 8B
            # model loads straight to a 16 GB chip without its bf16
            # form ever existing in HBM.
            target = jax.eval_shape(
                lambda: quantization.init_quantized_params(
                    cfg, jax.random.PRNGKey(0)))
        else:
            target = jax.eval_shape(
                lambda: fam.init_params(cfg, jax.random.PRNGKey(0)))
        if mesh is not None:
            # The whole point of --tp is a model LARGER than one chip:
            # the restore target must carry shardings so orbax loads
            # each shard straight to its device instead of
            # materializing the full tree on one chip (OOM).
            specs = fam.param_specs(cfg)
            if ckpt_quantized:
                specs = quantization.quantize_specs(specs, target)
            target = jax.tree.map(
                lambda shape_dtype, spec: jax.ShapeDtypeStruct(
                    shape_dtype.shape, shape_dtype.dtype,
                    sharding=jax.sharding.NamedSharding(mesh, spec)),
                target, specs)
        else:
            # Explicit serving-device sharding: an unsharded target
            # makes orbax re-use the checkpoint's SAVED sharding, so
            # a host-quantized int8 checkpoint (saved CPU-committed
            # by the quantize CLI) would restore onto the CPU and
            # every jitted step would fight a committed-device
            # mismatch.
            dev = jax.sharding.SingleDeviceSharding(jax.devices()[0])
            target = jax.tree.map(
                lambda sd: jax.ShapeDtypeStruct(sd.shape, sd.dtype,
                                                sharding=dev),
                target)
        params = ocp.StandardCheckpointer().restore(
            os.path.abspath(os.path.expanduser(args.checkpoint)),
            target)
    else:
        logger.warning('No --checkpoint: serving randomly initialized '
                       'weights (benchmark / smoke mode).')
        if getattr(args, 'weight_quant', False):
            # Born-int8 tree: an 8B bf16 tree (16 GB) cannot
            # materialize on a 16 GB chip, but its int8 form serves
            # (models/quantization.py).
            from skypilot_tpu.models import quantization
            params = quantization.init_quantized_params(
                cfg, jax.random.PRNGKey(0))
        else:
            params = models.family(cfg).init_params(
                cfg, jax.random.PRNGKey(0))
    return ServingEngine(params, cfg, batch_size=args.batch,
                         max_prompt=args.max_prompt,
                         max_seq=args.max_seq,
                         kv_quant=args.kv_quant,
                         weight_quant=getattr(args, 'weight_quant',
                                              False),
                         decode_chunk=args.decode_chunk,
                         prefill_chunk=getattr(args, 'prefill_chunk',
                                               None),
                         prefill_budget=getattr(args, 'prefill_budget',
                                                None),
                         prefix_cache=getattr(args, 'prefix_cache',
                                              None),
                         prefix_pool_pages=getattr(
                             args, 'prefix_pool_pages', None),
                         spec_decode=getattr(args, 'spec_decode',
                                             None),
                         spec_k=getattr(args, 'spec_k', None),
                         spec_ngram=getattr(args, 'spec_ngram', None),
                         mesh=mesh)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--port', type=int, default=8801)
    parser.add_argument('--model', default='tiny',
                        help='LlamaConfig classmethod name')
    parser.add_argument('--checkpoint', default=None)
    parser.add_argument('--checkpoint-quantized', action='store_true',
                        help='The checkpoint holds an int8 tree '
                        '(models.quantization CLI output); restore '
                        'it directly without a bf16 intermediate.')
    parser.add_argument('--batch', type=int, default=8)
    parser.add_argument('--max-prompt', type=int, default=512)
    parser.add_argument('--max-seq', type=int, default=1024)
    parser.add_argument('--decode-chunk', type=int, default=16)
    parser.add_argument('--prefill-chunk', type=int, default=None,
                        help='Chunked-prefill slice size in prompt '
                        'tokens (default: SKYTPU_PREFILL_CHUNK or '
                        '128, clamped to --max-prompt).')
    parser.add_argument('--prefill-budget', type=int, default=None,
                        help='Per-tick prefill token budget across '
                        'prefilling slots — bounds decode inter-token '
                        'latency under admission churn (default: '
                        'SKYTPU_PREFILL_BUDGET or 256).')
    parser.add_argument('--prefix-cache', action='store_true',
                        default=None,
                        help='Enable automatic prefix caching '
                        '(block-hash shared page pool; hits skip the '
                        'cached prefill and charge admission only '
                        'the uncached suffix). Default: '
                        'SKYTPU_PREFIX_CACHE.')
    parser.add_argument('--prefix-pool-pages', type=int, default=None,
                        help='Prefix-pool capacity in pages '
                        '(default: SKYTPU_PREFIX_POOL_PAGES or 512).')
    parser.add_argument('--spec-decode', action='store_true',
                        default=None,
                        help='Enable speculative multi-token decoding '
                        '(prompt-lookup drafts + batched verify in '
                        'the fused tick; greedy outputs stay bitwise '
                        'identical to speculation-off). Default: '
                        'SKYTPU_SPEC_DECODE.')
    parser.add_argument('--spec-k', type=int, default=None,
                        help='Max drafted tokens per decode slot per '
                        'verify tick (default: SKYTPU_SPEC_K or 4).')
    parser.add_argument('--spec-ngram', type=int, default=None,
                        help='Max n-gram the prompt-lookup proposer '
                        'matches (default: SKYTPU_SPEC_NGRAM or 3).')
    parser.add_argument('--kv-quant', action='store_true')
    parser.add_argument('--weight-quant', action='store_true',
                        help='int8 weight-only quantization: serve '
                        '8B-class models on one 16 GB chip. With '
                        '--checkpoint the bf16 tree loads then '
                        'quantizes (must fit dense); without, a '
                        'born-int8 random tree serves (bench mode).')
    parser.add_argument('--tp', type=int,
                        default=int(env_registry.get(
                            env_registry.SKYTPU_TP, '1')),
                        help='Tensor-parallel ways over local chips '
                        '(serve models larger than one chip). '
                        'Defaults to SKYTPU_TP.')
    parser.add_argument('--max-pending', type=int, default=256,
                        help='Max queued (unadmitted) requests before '
                        '/generate answers 429 + Retry-After; '
                        '<= 0 means unbounded.')
    parser.add_argument('--is-spot', action='store_true',
                        help='Advertise this replica as spot capacity '
                        'on /health: the LB tie-break prefers '
                        'on-demand survivors for hedges/resumes '
                        '(docs/spot_serving.md).')
    parser.add_argument('--role',
                        choices=('mixed', 'prefill', 'decode'),
                        default='mixed',
                        help='Serving role in a disaggregated pool '
                        '(docs/disaggregation.md): prefill replicas '
                        'answer kv_prefill manifests and export KV '
                        'pages on /kv/fetch; decode replicas pull '
                        'pages from prefill peers and stream. '
                        'Advertised on /health — a routing hint, '
                        'never enforced.')
    args = parser.parse_args()

    # Name this replica's span-spool file (docs/tracing.md).
    trace_lib.set_component(f'engine.{args.port}')
    server = EngineServer(
        _build_engine(args),
        max_pending=(args.max_pending if args.max_pending > 0
                     else None))
    server.is_spot = bool(args.is_spot)
    server.set_role(args.role)
    # SIGTERM/SIGINT flow into a graceful drain
    # (docs/request_lifecycle.md): the handler only sets a flag; the
    # main task below notices and runs the bounded drain sequence.
    server.install_signal_handlers()

    async def _run() -> bool:
        runner = await server.start(args.port)
        while not server.draining:
            await asyncio.sleep(0.1)
        logger.info('Drain requested (signal or /drain): shutting '
                    'down gracefully.')
        clean = await server.drain()
        await runner.cleanup()
        return clean

    try:
        clean = asyncio.run(_run())
    except KeyboardInterrupt:
        # Second signal during the drain: the operator asked to skip
        # the graceful path. 130 = killed by signal, by convention.
        logger.warning('Second signal received: exiting immediately; '
                       'in-flight work was abandoned.')
        import sys
        sys.exit(130)
    if not clean:
        # Non-clean shutdown (in-flight work never reached a terminal
        # state, or the driver thread leaked past its join): exit
        # non-zero so supervisors see it — never pretend.
        import sys
        sys.exit(1)


if __name__ == '__main__':
    main()
