"""HTTP front end for the continuous-batching ServingEngine.

The replica-side process of a served model: what JetStream's server
is to the reference's serving recipe
(/root/reference/examples/tpu/v6e/serve-llama2-7b.yaml launches a
JetStream HTTP server per replica; the serve stack's load balancer
fronts it). A replica task runs

    python -m skypilot_tpu.models.serving_http --port 8801 ...

and the serve stack probes ``/health`` for readiness and proxies
generation traffic to ``/generate``.

Structure: aiohttp handlers submit requests into the ServingEngine
queue and await an asyncio future; a single engine thread drives
``engine.step()`` continuously (the engine is a host-side orchestrator
over jitted device programs — one driver thread is the device-order
guarantee) and resolves futures as requests finish.
"""
from __future__ import annotations

import argparse
import asyncio
import threading
import time
from typing import Any, Dict, Optional

from aiohttp import web

from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)


class EngineServer:
    """aiohttp app over a ServingEngine; one background driver thread."""

    def __init__(self, engine) -> None:
        self.engine = engine
        self._futures: Dict[Any, asyncio.Future] = {}
        self._next_id = 0
        self._lock = threading.Lock()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._stop = threading.Event()
        self._ready = threading.Event()
        self._thread = threading.Thread(target=self._drive, daemon=True)

    # ---------------------------------------------------------- engine
    def _drive(self) -> None:
        self.engine.warmup()
        self._ready.set()
        while not self._stop.is_set():
            with self._lock:
                busy = bool(self.engine.queue or
                            self.engine.num_active())
            if not busy:
                time.sleep(0.002)
                continue
            self.engine.step()
            # Drain (not read) so a long-lived replica never
            # accumulates every past request's tokens.
            for rid, res in self.engine.drain_results().items():
                fut = self._futures.pop(rid, None)
                if fut is not None and self._loop is not None:
                    self._loop.call_soon_threadsafe(
                        lambda f=fut, r=res: (not f.done() and
                                              f.set_result(r)))

    # ------------------------------------------------------------ http
    async def handle_generate(self, request: web.Request
                              ) -> web.Response:
        from skypilot_tpu.models.serving_engine import Request
        body = await request.json()
        tokens = body['tokens']
        max_new = int(body.get('max_new', 64))
        temperature = body.get('temperature')
        with self._lock:
            rid = self._next_id
            self._next_id += 1
        fut = asyncio.get_event_loop().create_future()
        self._futures[rid] = fut
        try:
            with self._lock:
                self.engine.submit(Request(rid, tokens, max_new,
                                           temperature=temperature))
        except ValueError as e:
            self._futures.pop(rid, None)
            return web.json_response({'error': str(e)}, status=400)
        result = await fut
        return web.json_response({
            'tokens': result.tokens,
            'latency_s': result.finished_at - result.submitted_at,
        })

    async def handle_health(self, request: web.Request) -> web.Response:
        if not self._ready.is_set():
            return web.json_response({'status': 'warming'}, status=503)
        return web.json_response({'status': 'ok'})

    def make_app(self) -> web.Application:
        app = web.Application()
        app.router.add_post('/generate', self.handle_generate)
        app.router.add_get('/health', self.handle_health)
        return app

    async def start(self, port: int) -> web.AppRunner:
        self._loop = asyncio.get_event_loop()
        self._thread.start()
        runner = web.AppRunner(self.make_app())
        await runner.setup()
        site = web.TCPSite(runner, '0.0.0.0', port)
        await site.start()
        logger.info('Engine server on :%d', port)
        return runner

    def stop(self) -> None:
        self._stop.set()
        # Join so interpreter teardown never kills the driver thread
        # mid-device-call (which aborts with an unraisable C++
        # exception). Bounded: warmup compiles can outlast it, and a
        # daemon thread dying later is only unclean at exit.
        if self._thread.ident is not None and self._thread.is_alive():
            self._thread.join(timeout=10)


def _build_engine(args) -> 'Any':
    import jax
    import jax.numpy as jnp

    from skypilot_tpu import models
    from skypilot_tpu.models.serving_engine import ServingEngine
    cfg_fn = getattr(models.LlamaConfig, args.model)
    cfg = cfg_fn(max_seq=args.max_seq)
    if jax.default_backend() != 'cpu':
        cfg = cfg_fn(max_seq=args.max_seq,
                     param_dtype=jnp.bfloat16)
    if args.checkpoint:
        import os

        import orbax.checkpoint as ocp
        target = jax.eval_shape(
            lambda: models.init_params(cfg, jax.random.PRNGKey(0)))
        params = ocp.StandardCheckpointer().restore(
            os.path.abspath(os.path.expanduser(args.checkpoint)),
            target)
    else:
        logger.warning('No --checkpoint: serving randomly initialized '
                       'weights (benchmark / smoke mode).')
        params = models.init_params(cfg, jax.random.PRNGKey(0))
    return ServingEngine(params, cfg, batch_size=args.batch,
                         max_prompt=args.max_prompt,
                         max_seq=args.max_seq,
                         kv_quant=args.kv_quant,
                         decode_chunk=args.decode_chunk)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--port', type=int, default=8801)
    parser.add_argument('--model', default='tiny',
                        help='LlamaConfig classmethod name')
    parser.add_argument('--checkpoint', default=None)
    parser.add_argument('--batch', type=int, default=8)
    parser.add_argument('--max-prompt', type=int, default=512)
    parser.add_argument('--max-seq', type=int, default=1024)
    parser.add_argument('--decode-chunk', type=int, default=8)
    parser.add_argument('--kv-quant', action='store_true')
    args = parser.parse_args()

    server = EngineServer(_build_engine(args))

    async def _run():
        await server.start(args.port)
        while True:
            await asyncio.sleep(3600)

    asyncio.run(_run())


if __name__ == '__main__':
    main()
