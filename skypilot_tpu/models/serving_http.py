"""HTTP front end for the continuous-batching ServingEngine.

The replica-side process of a served model: what JetStream's server
is to the reference's serving recipe
(/root/reference/examples/tpu/v6e/serve-llama2-7b.yaml launches a
JetStream HTTP server per replica; the serve stack's load balancer
fronts it). A replica task runs

    python -m skypilot_tpu.models.serving_http --port 8801 ...

and the serve stack probes ``/health`` for readiness and proxies
generation traffic to ``/generate``.

Structure: aiohttp handlers submit requests into the ServingEngine
queue and await an asyncio future; a single engine thread drives
``engine.step()`` continuously (the engine is a host-side orchestrator
over jitted device programs — one driver thread is the device-order
guarantee) and resolves futures as requests finish.

Streaming: ``{"stream": true}`` in the /generate body switches the
response to server-sent events — each decode chunk's tokens are
flushed the moment they reach the host (``engine.on_token``), ending
with a ``done`` event. The serve load balancer proxies response bodies
chunk-by-chunk, so first tokens reach the client while the request is
still decoding (reference analog: sky/serve/load_balancer.py:22
proxies streaming responses).
"""
from __future__ import annotations

import argparse
import asyncio
import json
import threading
import time
from typing import Any, Dict, Optional

from aiohttp import web

from skypilot_tpu import metrics as metrics_lib
from skypilot_tpu import trace as trace_lib
from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)

_M_REJECTS = metrics_lib.counter(
    'skytpu_engine_rejects_total',
    'Generate requests shed with HTTP 429 (pending queue full).')


def _rid_headers(req_id: str) -> Dict[str, str]:
    """Echo headers: every /generate response — success, 400, 429,
    503 — carries the request's X-Request-ID so clients and the LB
    can correlate logs without parsing bodies."""
    return {trace_lib.REQUEST_ID_HEADER: req_id}


class EngineServer:
    """aiohttp app over a ServingEngine; one background driver thread.

    ``max_pending`` bounds the engine's admission queue: when that
    many requests are already queued (not yet admitted to a decode
    slot), /generate answers 429 with a ``Retry-After`` hint instead
    of queueing unboundedly — an overloaded replica should shed load
    to the load balancer's other replicas, not grow a queue whose
    tail latency is unbounded (and whose memory is, too). ``None``
    keeps the legacy unbounded behavior (benches).
    """

    def __init__(self, engine, max_pending: Optional[int] = None
                 ) -> None:
        self.engine = engine
        self.max_pending = max_pending
        self._futures: Dict[Any, asyncio.Future] = {}
        # rid -> asyncio.Queue of token batches for streaming requests.
        self._streams: Dict[Any, asyncio.Queue] = {}
        self._next_id = 0
        self._lock = threading.Lock()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._stop = threading.Event()
        self._ready = threading.Event()
        self._dead: Optional[str] = None
        self._thread = threading.Thread(target=self._drive, daemon=True)

    # ---------------------------------------------------------- engine
    def _push_stream(self, rid: Any, item: Any) -> None:
        q = self._streams.get(rid)
        if q is not None and self._loop is not None:
            self._loop.call_soon_threadsafe(q.put_nowait, item)

    def _drive(self) -> None:
        try:
            self.engine.warmup()
        except Exception as e:  # pylint: disable=broad-except
            logger.exception('Engine warmup failed')
            self._die(f'warmup failed: {e}')
            return
        self.engine.on_token = self._push_stream
        self._ready.set()
        while not self._stop.is_set():
            with self._lock:
                busy = bool(self.engine.queue or
                            self.engine.num_active())
            if not busy:
                if self.engine.has_pending:
                    # Drain the double-buffered chunk so its requests
                    # finish even when no new work arrives.
                    try:
                        self.engine.flush()
                    except Exception as e:  # pylint: disable=broad-except
                        logger.exception('Engine flush failed')
                        self._die(str(e))
                        return
                    self._resolve_finished()
                    continue
                # skytpu-lint: disable=STL002 — idle tick of the
                # driver loop, not a retry: errors kill the driver
                # (_die), they are never retried here.
                time.sleep(0.002)
                continue
            try:
                self.engine.step()
            except Exception as e:  # pylint: disable=broad-except
                # A dead engine must not look healthy: fail every
                # in-flight request and flip /health so the load
                # balancer stops routing here (a silently-wedged
                # replica hangs every future request instead).
                logger.exception('Engine step failed')
                self._die(str(e))
                return
            self._resolve_finished()

    def _resolve_finished(self) -> None:
        # Drain (not read) so a long-lived replica never accumulates
        # every past request's tokens.
        for rid, res in self.engine.drain_results().items():
            self._push_stream(rid, ('done', res))
            fut = self._futures.pop(rid, None)
            if fut is not None and self._loop is not None:
                self._loop.call_soon_threadsafe(
                    lambda f=fut, r=res: (not f.done() and
                                          f.set_result(r)))

    def _die(self, reason: str) -> None:
        # skytpu-lint: disable=STL004 — one-shot GIL-atomic str write;
        # readers (health/generate) only compare against None.
        self._dead = reason
        self._ready.set()      # unblock anything waiting on readiness
        if self._loop is None:
            return

        def fail_all():
            err = RuntimeError(f'serving engine died: {reason}')
            for fut in self._futures.values():
                if not fut.done():
                    fut.set_exception(err)
            self._futures.clear()
            for q in self._streams.values():
                q.put_nowait(('error', reason))

        self._loop.call_soon_threadsafe(fail_all)

    # ------------------------------------------------------------ http
    def _overloaded_response(self, req_id: str
                             ) -> Optional[web.Response]:
        """429 + Retry-After when the pending queue is full, else
        None. Host-side only (safe pre-warmup); checked before the
        readiness gate so a warming replica still sheds queue
        overflow instead of 503-ing it ambiguously. The reject echoes
        the request id so a shed request stays correlatable."""
        if self.max_pending is None:
            return None
        with self._lock:
            depth = len(self.engine.queue)
        if depth < self.max_pending:
            return None
        # Rough drain-time hint: pending requests over the number of
        # decode slots, one second per queued batch, clamped sane.
        retry = max(1, min(30, depth //
                           max(1, getattr(self.engine, 'batch_size',
                                          1))))
        _M_REJECTS.inc()
        logger.warning('Shedding /generate (pending=%d) request=%s '
                       'trace=%s', depth, req_id,
                       trace_lib.current_trace_id())
        return web.json_response(
            {'error': 'server overloaded: pending queue is full',
             'pending': depth, 'max_pending': self.max_pending,
             'request_id': req_id},
            status=429, headers={'Retry-After': str(retry),
                                 **_rid_headers(req_id)})

    @staticmethod
    def _parse_generate(body: Any) -> tuple:
        """Validate a /generate body; raises ValueError with a
        client-safe message (-> 400). The engine driver thread must
        never see malformed input: an exception there kills serving
        for every in-flight request."""
        if not isinstance(body, dict):
            raise ValueError('body must be a JSON object')
        tokens = body.get('tokens')
        if (not isinstance(tokens, list) or not tokens or
                not all(isinstance(t, int) and not isinstance(t, bool)
                        for t in tokens)):
            raise ValueError("'tokens' must be a non-empty list of "
                             'integer token ids')
        max_new = body.get('max_new', 64)
        if not isinstance(max_new, int) or isinstance(max_new, bool) \
                or max_new < 1:
            raise ValueError("'max_new' must be a positive integer")
        temperature = body.get('temperature')
        if temperature is not None and \
                not isinstance(temperature, (int, float)):
            raise ValueError("'temperature' must be a number")
        return tokens, max_new, temperature, bool(body.get('stream'))

    async def handle_generate(self, request: web.Request
                              ) -> web.StreamResponse:
        # Correlation surface (docs/tracing.md): accept (or mint) an
        # X-Request-ID echoed on every response, and continue the
        # caller's trace from its traceparent header — the request
        # span parents under the LB's proxy span, and the engine's
        # TTFT-decomposition spans parent under this one.
        req_id = (request.headers.get(trace_lib.REQUEST_ID_HEADER) or
                  trace_lib.new_request_id())
        ctx = trace_lib.context_from_headers(request.headers)
        with trace_lib.span('http.generate', parent=ctx,
                            request_id=req_id):
            return await self._handle_generate(request, req_id)

    async def _handle_generate(self, request: web.Request,
                               req_id: str) -> web.StreamResponse:
        from skypilot_tpu.models.serving_engine import Request
        if self._dead is not None:
            return web.json_response(
                {'error': f'engine dead: {self._dead}'}, status=503,
                headers=_rid_headers(req_id))
        try:
            body = await request.json()
            tokens, max_new, temperature, stream = \
                self._parse_generate(body)
            # Static-limit checks are host-side and safe pre-warmup;
            # rejecting here keeps them 400s even while warming.
            if len(tokens) > self.engine.max_prompt:
                raise ValueError(
                    f'prompt ({len(tokens)}) exceeds max_prompt '
                    f'({self.engine.max_prompt}).')
            if max_new > self.engine.decode_capacity():
                raise ValueError(
                    f'max_new ({max_new}) exceeds the decode '
                    f'capacity ({self.engine.decode_capacity()}).')
        except (ValueError, UnicodeDecodeError) as e:
            return web.json_response({'error': str(e)}, status=400,
                                     headers=_rid_headers(req_id))
        overloaded = self._overloaded_response(req_id)
        if overloaded is not None:
            return overloaded
        if not self._ready.is_set():
            # Requests submitted during warmup would be drained by
            # warmup's own run() and silently lost.
            return web.json_response({'status': 'warming'}, status=503,
                                     headers=_rid_headers(req_id))
        with self._lock:
            rid = self._next_id
            self._next_id += 1
        if stream:
            return await self._generate_stream(
                request, rid, req_id, tokens, max_new, temperature)
        fut = asyncio.get_event_loop().create_future()
        # skytpu-lint: disable=STL004 — _futures is mutated and
        # iterated only on the event-loop thread (fail_all runs via
        # call_soon_threadsafe); the driver thread does atomic pops.
        self._futures[rid] = fut
        try:
            with self._lock:
                self.engine.submit(Request(rid, tokens, max_new,
                                           temperature=temperature))
        except ValueError as e:
            self._futures.pop(rid, None)
            return web.json_response({'error': str(e)}, status=400,
                                     headers=_rid_headers(req_id))
        if self._dead is not None:
            # The engine died between the entry check and our future
            # registration (both on the loop thread, but the body
            # await yields): _die's fail_all may already have swept
            # _futures, so this future would hang forever.
            self._futures.pop(rid, None)
            return web.json_response(
                {'error': f'engine dead: {self._dead}'}, status=503,
                headers=_rid_headers(req_id))
        result = await fut
        return web.json_response(
            {
                'tokens': result.tokens,
                'latency_s': result.finished_at - result.submitted_at,
            },
            headers=_rid_headers(req_id))

    async def _generate_stream(self, request: web.Request, rid: Any,
                               req_id: str, tokens, max_new,
                               temperature) -> web.StreamResponse:
        """SSE: one ``data:`` event per decode chunk, then ``done``."""
        from skypilot_tpu.models.serving_engine import Request
        q: asyncio.Queue = asyncio.Queue()
        # skytpu-lint: disable=STL004 — same discipline as _futures:
        # loop-thread-only mutation/iteration, atomic cross-thread get.
        self._streams[rid] = q
        try:
            with self._lock:
                self.engine.submit(Request(rid, tokens, max_new,
                                           temperature=temperature))
        except ValueError as e:
            self._streams.pop(rid, None)
            return web.json_response({'error': str(e)}, status=400,
                                     headers=_rid_headers(req_id))
        if self._dead is not None:
            # Same race as the non-streaming path: registered after
            # fail_all swept the stream registry -> would hang.
            self._streams.pop(rid, None)
            return web.json_response(
                {'error': f'engine dead: {self._dead}'}, status=503,
                headers=_rid_headers(req_id))
        resp = web.StreamResponse(headers={
            'Content-Type': 'text/event-stream',
            'Cache-Control': 'no-cache',
            'X-Accel-Buffering': 'no',
            **_rid_headers(req_id),
        })
        await resp.prepare(request)
        try:
            while True:
                item = await q.get()
                if isinstance(item, tuple) and item[0] == 'done':
                    res = item[1]
                    payload = {
                        'done': True,
                        'tokens': res.tokens,
                        'latency_s': (res.finished_at -
                                      res.submitted_at),
                    }
                    await resp.write(
                        f'data: {json.dumps(payload)}\n\n'.encode())
                    break
                if isinstance(item, tuple) and item[0] == 'error':
                    payload = {'error': item[1]}
                    await resp.write(
                        f'data: {json.dumps(payload)}\n\n'.encode())
                    break
                await resp.write(
                    f'data: {json.dumps({"tokens": item})}\n\n'
                    .encode())
        finally:
            self._streams.pop(rid, None)
        await resp.write_eof()
        return resp

    async def handle_health(self, request: web.Request) -> web.Response:
        if self._dead is not None:
            return web.json_response(
                {'status': 'dead', 'reason': self._dead}, status=503)
        if not self._ready.is_set():
            return web.json_response({'status': 'warming'}, status=503)
        return web.json_response({'status': 'ok'})

    async def handle_metrics(self, request: web.Request
                             ) -> web.Response:
        """Prometheus exposition of the replica's engine metrics
        (docs/metrics.md). Host-side only — safe during warmup and
        after engine death (a dying replica's last counters are
        exactly what an operator wants to scrape). This process's
        registry only: spool merging belongs to ONE endpoint per
        host (the API server) or scraping two endpoints would count
        every spooled controller twice."""
        text = metrics_lib.render_exposition()
        return web.Response(
            text=text, headers={'Content-Type': metrics_lib.CONTENT_TYPE})

    def make_app(self) -> web.Application:
        app = web.Application()
        app.router.add_post('/generate', self.handle_generate)
        app.router.add_get('/health', self.handle_health)
        app.router.add_get('/metrics', self.handle_metrics)
        return app

    async def start(self, port: int) -> web.AppRunner:
        # skytpu-lint: disable=STL004 — written once before the driver
        # thread starts on the next line (Thread.start happens-before).
        self._loop = asyncio.get_event_loop()
        self._thread.start()
        runner = web.AppRunner(self.make_app())
        await runner.setup()
        site = web.TCPSite(runner, '0.0.0.0', port)
        await site.start()
        logger.info('Engine server on :%d', port)
        return runner

    def stop(self) -> None:
        self._stop.set()
        # Join so interpreter teardown never kills the driver thread
        # mid-device-call (which aborts with an unraisable C++
        # exception). Bounded: warmup compiles can outlast it, and a
        # daemon thread dying later is only unclean at exit.
        if self._thread.ident is not None and self._thread.is_alive():
            self._thread.join(timeout=10)


def _build_engine(args) -> 'Any':
    import jax
    import jax.numpy as jnp

    from skypilot_tpu import models
    from skypilot_tpu.models.serving_engine import ServingEngine
    # Cross-family preset lookup: 'tiny'/'tpu_1b' (dense) and
    # 'tiny_moe'/'mixtral_8x7b' (MoE) all serve through this front
    # end.
    cfg_fn = models.config_preset(args.model)
    cfg = cfg_fn(max_seq=args.max_seq)
    if jax.default_backend() != 'cpu':
        cfg = cfg_fn(max_seq=args.max_seq,
                     param_dtype=jnp.bfloat16)
    mesh = None
    if args.tp > 1:
        # Serve a model larger than one chip: Megatron tp over the
        # replica's local chips (params + kv-head cache axis shard).
        from skypilot_tpu.parallel import make_mesh, plan_mesh
        mesh = make_mesh(plan_mesh(args.tp, tp=args.tp),
                         devices=jax.devices()[:args.tp])
    if args.checkpoint:
        import os

        import orbax.checkpoint as ocp

        from skypilot_tpu.models import quantization
        fam = models.family(cfg)
        ckpt_quantized = getattr(args, 'checkpoint_quantized', False)
        if ckpt_quantized:
            # int8 checkpoint (models.quantization CLI output): the
            # restore target is the QUANTIZED tree shape, so an 8B
            # model loads straight to a 16 GB chip without its bf16
            # form ever existing in HBM.
            target = jax.eval_shape(
                lambda: quantization.init_quantized_params(
                    cfg, jax.random.PRNGKey(0)))
        else:
            target = jax.eval_shape(
                lambda: fam.init_params(cfg, jax.random.PRNGKey(0)))
        if mesh is not None:
            # The whole point of --tp is a model LARGER than one chip:
            # the restore target must carry shardings so orbax loads
            # each shard straight to its device instead of
            # materializing the full tree on one chip (OOM).
            specs = fam.param_specs(cfg)
            if ckpt_quantized:
                specs = quantization.quantize_specs(specs, target)
            target = jax.tree.map(
                lambda shape_dtype, spec: jax.ShapeDtypeStruct(
                    shape_dtype.shape, shape_dtype.dtype,
                    sharding=jax.sharding.NamedSharding(mesh, spec)),
                target, specs)
        else:
            # Explicit serving-device sharding: an unsharded target
            # makes orbax re-use the checkpoint's SAVED sharding, so
            # a host-quantized int8 checkpoint (saved CPU-committed
            # by the quantize CLI) would restore onto the CPU and
            # every jitted step would fight a committed-device
            # mismatch.
            dev = jax.sharding.SingleDeviceSharding(jax.devices()[0])
            target = jax.tree.map(
                lambda sd: jax.ShapeDtypeStruct(sd.shape, sd.dtype,
                                                sharding=dev),
                target)
        params = ocp.StandardCheckpointer().restore(
            os.path.abspath(os.path.expanduser(args.checkpoint)),
            target)
    else:
        logger.warning('No --checkpoint: serving randomly initialized '
                       'weights (benchmark / smoke mode).')
        if getattr(args, 'weight_quant', False):
            # Born-int8 tree: an 8B bf16 tree (16 GB) cannot
            # materialize on a 16 GB chip, but its int8 form serves
            # (models/quantization.py).
            from skypilot_tpu.models import quantization
            params = quantization.init_quantized_params(
                cfg, jax.random.PRNGKey(0))
        else:
            params = models.family(cfg).init_params(
                cfg, jax.random.PRNGKey(0))
    return ServingEngine(params, cfg, batch_size=args.batch,
                         max_prompt=args.max_prompt,
                         max_seq=args.max_seq,
                         kv_quant=args.kv_quant,
                         weight_quant=getattr(args, 'weight_quant',
                                              False),
                         decode_chunk=args.decode_chunk,
                         prefill_chunk=getattr(args, 'prefill_chunk',
                                               None),
                         prefill_budget=getattr(args, 'prefill_budget',
                                                None),
                         mesh=mesh)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--port', type=int, default=8801)
    parser.add_argument('--model', default='tiny',
                        help='LlamaConfig classmethod name')
    parser.add_argument('--checkpoint', default=None)
    parser.add_argument('--checkpoint-quantized', action='store_true',
                        help='The checkpoint holds an int8 tree '
                        '(models.quantization CLI output); restore '
                        'it directly without a bf16 intermediate.')
    parser.add_argument('--batch', type=int, default=8)
    parser.add_argument('--max-prompt', type=int, default=512)
    parser.add_argument('--max-seq', type=int, default=1024)
    parser.add_argument('--decode-chunk', type=int, default=16)
    parser.add_argument('--prefill-chunk', type=int, default=None,
                        help='Chunked-prefill slice size in prompt '
                        'tokens (default: SKYTPU_PREFILL_CHUNK or '
                        '128, clamped to --max-prompt).')
    parser.add_argument('--prefill-budget', type=int, default=None,
                        help='Per-tick prefill token budget across '
                        'prefilling slots — bounds decode inter-token '
                        'latency under admission churn (default: '
                        'SKYTPU_PREFILL_BUDGET or 256).')
    parser.add_argument('--kv-quant', action='store_true')
    parser.add_argument('--weight-quant', action='store_true',
                        help='int8 weight-only quantization: serve '
                        '8B-class models on one 16 GB chip. With '
                        '--checkpoint the bf16 tree loads then '
                        'quantizes (must fit dense); without, a '
                        'born-int8 random tree serves (bench mode).')
    parser.add_argument('--tp', type=int, default=1,
                        help='Tensor-parallel ways over local chips '
                        '(serve models larger than one chip).')
    parser.add_argument('--max-pending', type=int, default=256,
                        help='Max queued (unadmitted) requests before '
                        '/generate answers 429 + Retry-After; '
                        '<= 0 means unbounded.')
    args = parser.parse_args()

    # Name this replica's span-spool file (docs/tracing.md).
    trace_lib.set_component(f'engine.{args.port}')
    server = EngineServer(
        _build_engine(args),
        max_pending=(args.max_pending if args.max_pending > 0
                     else None))

    async def _run():
        await server.start(args.port)
        while True:
            await asyncio.sleep(3600)

    asyncio.run(_run())


if __name__ == '__main__':
    main()
