"""Sharded training step: pjit over a (dp, fsdp, sp, tp) mesh.

Replaces the reference's delegate-to-torchtune training path
(llm/llama-3_1-finetuning/lora.yaml) with a native JAX step: AdamW via
optax, gradients reduced by XLA-inserted collectives (psum over
dp/fsdp from the sharded batch dim; fsdp params all-gathered per layer
by the scan), donated state for in-place HBM updates.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from skypilot_tpu.models import llama


def _family(cfg):
    """Family dispatch — delegates to the package-level single source
    (models.family)."""
    from skypilot_tpu import models
    return models.family(cfg)


@dataclasses.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jax.Array


jax.tree_util.register_dataclass(TrainState,
                                 data_fields=['params', 'opt_state',
                                              'step'],
                                 meta_fields=[])


def make_optimizer(lr: float = 3e-4,
                   weight_decay: float = 0.1,
                   b1: float = 0.9,
                   b2: float = 0.95,
                   grad_clip: float = 1.0) -> optax.GradientTransformation:
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(lr, b1=b1, b2=b2, weight_decay=weight_decay),
    )


def _state_specs(cfg: llama.LlamaConfig, optimizer, params_shape,
                 pp: bool = False):
    pspecs = _family(cfg).param_specs(cfg, pp=pp)
    opt_shape = jax.eval_shape(optimizer.init, params_shape)

    # Optimizer moments mirror the param tree inside each optax state
    # leaf-tree. Match by TREE PATH SUFFIX, not by array shape — e.g.
    # wq and wo have identical shapes (dim == n_heads*head_dim) but
    # transposed PartitionSpecs, so shape matching would mis-shard one
    # of them and insert all-to-alls every step.
    path_to_spec = {}
    for path, spec in jax.tree_util.tree_flatten_with_path(pspecs)[0]:
        path_to_spec[tuple(str(k) for k in path)] = spec

    def match(path, x):
        keys = tuple(str(k) for k in path)
        for start in range(len(keys)):
            spec = path_to_spec.get(keys[start:])
            if spec is not None and hasattr(x, 'shape'):
                return spec
        return P()

    opt_specs = jax.tree_util.tree_map_with_path(match, opt_shape)
    return TrainState(params=pspecs, opt_state=opt_specs,
                      step=P())


def init_train_state(cfg: llama.LlamaConfig,
                     key: jax.Array,
                     mesh=None,
                     optimizer: Optional[
                         optax.GradientTransformation] = None
                     ) -> Tuple[TrainState, Any]:
    """Init params + opt state, sharded over mesh if given.

    Returns (state, optimizer). Uses jit-with-out_shardings so large
    models initialize directly into their sharded layout (no host
    gather)."""
    optimizer = optimizer or make_optimizer()

    def _init(key):
        params = _family(cfg).init_params(cfg, key)
        return TrainState(params=params,
                          opt_state=optimizer.init(params),
                          step=jnp.zeros((), jnp.int32))

    if mesh is None:
        return jax.jit(_init)(key), optimizer
    params_shape = jax.eval_shape(functools.partial(_family(cfg).init_params,
                                                    cfg), key)
    specs = _state_specs(cfg, optimizer, params_shape,
                         pp=mesh.shape.get('pp', 1) > 1)
    shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P))
    state = jax.jit(_init, out_shardings=shardings)(key)
    return state, optimizer


def make_train_step(cfg: llama.LlamaConfig,
                    optimizer: optax.GradientTransformation,
                    mesh=None):
    """Returns jitted (state, batch) -> (state, metrics)."""

    def train_step(state: TrainState, batch: Dict[str, jax.Array]):
        loss, grads = jax.value_and_grad(_family(cfg).loss_fn)(
            state.params, batch, cfg, mesh)
        updates, new_opt = optimizer.update(grads, state.opt_state,
                                            state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = {
            'loss': loss,
            'grad_norm': optax.global_norm(grads),
            'step': state.step,
        }
        return TrainState(params=new_params, opt_state=new_opt,
                          step=state.step + 1), metrics

    # State shardings flow through from the (donated) input state;
    # callers shard the batch with shard_batch().
    return jax.jit(train_step, donate_argnums=(0,))


def shard_batch(batch: Dict[str, jax.Array], mesh):
    """Shard a host batch with [batch, seq] dp/sp sharding.

    Single-process: ``batch`` is the global batch (device_put).
    Multi-process (pod slice / hybrid DCN×ICI mesh): ``batch`` holds
    THIS process's rows — the global array is assembled from the
    per-process shards, so dp rides the process (DCN) axis without any
    host ever materializing the global batch.
    """
    sharding = NamedSharding(mesh, P(('dp', 'fsdp'), 'sp'))
    if jax.process_count() > 1:
        import numpy as np
        return jax.tree.map(
            lambda x: jax.make_array_from_process_local_data(
                sharding, np.asarray(x)), batch)
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)


def make_eval_step(cfg: llama.LlamaConfig, mesh=None):
    def eval_step(params, batch):
        return _family(cfg).loss_fn(params, batch, cfg, mesh)
    return jax.jit(eval_step)
