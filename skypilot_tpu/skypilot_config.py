"""Layered user/config system.

Re-design of reference ``sky/skypilot_config.py`` (:1-60): a YAML config
at ``~/.skytpu/config.yaml`` (override with env SKYTPU_CONFIG), nested
get/set by dotted path, plus an override context used by the API server
to apply per-request config (reference server/requests/executor.py:171).
"""
from __future__ import annotations

import contextlib
import copy
import os
import threading
from typing import Any, Dict, Iterator, Optional

from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import schemas

ENV_VAR_CONFIG_PATH = 'SKYTPU_CONFIG'
DEFAULT_CONFIG_PATH = '~/.skytpu/config.yaml'

_lock = threading.Lock()
_loaded = False
_config: Dict[str, Any] = {}
_overrides = threading.local()


def config_path() -> str:
    return os.path.expanduser(
        os.environ.get(ENV_VAR_CONFIG_PATH, DEFAULT_CONFIG_PATH))


def _load() -> None:
    global _loaded, _config
    with _lock:
        if _loaded:
            return
        path = config_path()
        if os.path.exists(path):
            config = common_utils.read_yaml(path) or {}
            schemas.validate_config(config)
            _config = config
        else:
            _config = {}
        _loaded = True


def reload_config() -> None:
    global _loaded
    _loaded = False
    _load()


def _active_config() -> Dict[str, Any]:
    _load()
    override = getattr(_overrides, 'config', None)
    if override is not None:
        return override
    return _config


def get_nested(keys, default_value: Any = None) -> Any:
    """get_nested(('gcp', 'project_id')) -> value or default."""
    if isinstance(keys, str):
        keys = keys.split('.')
    node: Any = _active_config()
    for k in keys:
        if not isinstance(node, dict) or k not in node:
            return default_value
        node = node[k]
    return node


def set_nested(keys, value: Any) -> Dict[str, Any]:
    """Pure update: returns a new config dict with keys set."""
    if isinstance(keys, str):
        keys = keys.split('.')
    config = copy.deepcopy(_active_config())
    node = config
    for k in keys[:-1]:
        node = node.setdefault(k, {})
    node[keys[-1]] = value
    return config


def to_dict() -> Dict[str, Any]:
    return copy.deepcopy(_active_config())


@contextlib.contextmanager
def override_config(config: Optional[Dict[str, Any]]) -> Iterator[None]:
    """Thread-local full-config override (API-server per-request config)."""
    if config is not None:
        schemas.validate_config(config)
    previous = getattr(_overrides, 'config', None)
    _overrides.config = config
    try:
        yield
    finally:
        _overrides.config = previous


def loaded_config_exists() -> bool:
    return os.path.exists(config_path())
