"""Thin az-CLI client for the Azure provision plugin.

Re-design of reference ``sky/provision/azure/`` (1,301 LoC of Azure
SDK + ARM template deploys) on this framework's CLI-not-SDK stance
(same as the GCS/S3 storage layer): every operation is one ``az``
invocation with ``-o json``, so the plugin needs no azure-* pip
packages, and tests drive the full lifecycle through the ``runner``
seam with canned JSON — the same seam pattern as
``provision/aws/instance.py``'s ``client_factory``.

Error taxonomy: Azure's capacity/quota failures surface as error
codes in az's stderr; :func:`translate_error` maps them onto the
typed exceptions the failover provisioner keys on (reference
``FailoverCloudErrorHandlerV2`` decodes the same codes from the SDK).
"""
from __future__ import annotations

import json
import subprocess
from typing import Any, Callable, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)

# Azure allocation-failure codes = stockout; quota codes = quota
# (reference sky/backends azure failover handler decodes these).
_STOCKOUT_CODES = ('skunotavailable', 'allocationfailed',
                   'overconstrainedallocationrequest',
                   'zonalallocationfailed', 'allocationtimeout')
_QUOTA_CODES = ('quotaexceeded', 'operationnotallowed')


class AzCliError(Exception):

    def __init__(self, argv: List[str], returncode: int,
                 stderr: str) -> None:
        super().__init__(
            f'az {" ".join(argv)} failed ({returncode}): {stderr}')
        self.argv = argv
        self.returncode = returncode
        self.stderr = stderr


def _subprocess_runner(argv: List[str],
                       timeout: float = 600.0) -> Optional[Any]:
    proc = subprocess.run(['az'] + argv + ['-o', 'json'],
                          capture_output=True, text=True,
                          timeout=timeout, check=False)
    if proc.returncode != 0:
        raise AzCliError(argv, proc.returncode, proc.stderr)
    out = proc.stdout.strip()
    return json.loads(out) if out else None


# Test seam: replaced with a fake az in tests (canned JSON responses).
runner: Callable[..., Optional[Any]] = _subprocess_runner


def run_az(argv: List[str], timeout: float = 600.0) -> Optional[Any]:
    """Run one az command, returning parsed JSON (None if empty)."""
    return runner(argv, timeout)


def translate_error(exc: Exception,
                    what: str) -> exceptions.ProvisionError:
    """Map an az failure onto the stockout/quota/provision taxonomy."""
    blob = str(exc).lower()
    if any(code in blob for code in _QUOTA_CODES) or 'quota' in blob:
        return exceptions.QuotaExceededError(f'{what}: {exc}')
    if any(code in blob for code in _STOCKOUT_CODES) or (
            'capacity' in blob and 'insufficient' in blob):
        return exceptions.StockoutError(f'{what}: {exc}')
    return exceptions.ProvisionError(f'{what}: {exc}')
