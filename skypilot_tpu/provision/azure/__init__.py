"""Azure provision plugin (az-CLI based)."""
