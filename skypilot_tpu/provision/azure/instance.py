"""Azure VM provision ops.

Re-design of reference ``sky/provision/azure/instance.py`` (ARM
template deployment + SDK polling) as az-CLI calls against one
RESOURCE GROUP per cluster: creation is idempotent against the
group's VM list, teardown is one group delete (nothing can leak —
NICs, disks and IPs die with the group), and STOP maps to
``az vm deallocate`` (compute billing stops, disks persist — the real
stop semantics the reference's Azure supports and the reason Azure
carries the STOP capability flag here, unlike Kubernetes).

State mapping: Azure ``powerState`` ('VM running'/'VM deallocated'/
'VM stopped'/...) -> the provider-neutral 'running'/'stopped'/
'pending' statuses the reconciler consumes.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision import common
from skypilot_tpu.provision.azure import api
from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)

_CLUSTER_TAG = 'skypilot-tpu-cluster'

_WAIT_TIMEOUT = 1200.0
_POLL_INTERVAL = 5.0

DEFAULT_IMAGE = 'Ubuntu2204'
SSH_USER = 'skytpu'


def resource_group(cluster_name_on_cloud: str) -> str:
    return f'skytpu-{cluster_name_on_cloud}'


def _vm_name(cluster: str, idx: int) -> str:
    return f'{cluster}-{idx}'


def _list_vms(rg: str) -> List[Dict[str, Any]]:
    """VMs in the cluster's group with powerState (-d), or [] when the
    group does not exist yet."""
    try:
        return api.run_az(['vm', 'list', '-g', rg, '-d']) or []
    except api.AzCliError as e:
        if 'resourcegroupnotfound' in str(e).lower():
            return []
        raise api.translate_error(e, 'vm list') from e


def _power_state(vm: Dict[str, Any]) -> str:
    return (vm.get('powerState') or '').lower()


def bootstrap_instances(
        config: common.ProvisionConfig) -> common.ProvisionConfig:
    """Ensure the cluster's resource group exists (the unit of both
    placement and teardown)."""
    rg = resource_group(config.cluster_name_on_cloud)
    try:
        api.run_az(['group', 'create', '-n', rg, '-l', config.region,
                    '--tags', f'{_CLUSTER_TAG}='
                    f'{config.cluster_name_on_cloud}'])
    except api.AzCliError as e:
        raise api.translate_error(e, 'group create') from e
    return config


def run_instances(
        config: common.ProvisionConfig) -> common.ProvisionRecord:
    node = config.node_config
    cluster = config.cluster_name_on_cloud
    rg = resource_group(cluster)
    existing = {vm['name']: vm for vm in _list_vms(rg)}
    created: List[str] = []
    resumed: List[str] = []
    to_create: List[str] = []
    for idx in range(config.count):
        name = _vm_name(cluster, idx)
        vm = existing.get(name)
        if vm is not None:
            state = _power_state(vm)
            if 'deallocated' in state or 'stopped' in state:
                try:
                    api.run_az(['vm', 'start', '-g', rg, '-n', name])
                except api.AzCliError as e:
                    raise api.translate_error(e, 'vm start') from e
                resumed.append(name)
            continue
        to_create.append(name)

    def _create(name: str) -> None:
        argv = [
            'vm', 'create', '-g', rg, '-n', name,
            '--image', node.get('image_id') or DEFAULT_IMAGE,
            '--size', node['instance_type'],
            '--admin-username', SSH_USER,
            '--os-disk-size-gb', str(node.get('disk_size') or 256),
            '--public-ip-sku', 'Standard',
        ]
        # ONE --tags flag taking space-separated k=v pairs: repeated
        # --tags occurrences overwrite each other in the az CLI (last
        # wins), which would silently drop the cluster tag.
        argv += ['--tags', f'{_CLUSTER_TAG}={cluster}']
        argv += [f'{k}={v}'
                 for k, v in (node.get('labels') or {}).items()]
        # The framework public key, injected by gang_backend (plugins
        # must not fall back to provider-generated keys: post-
        # provision SSH connects with ~/.skytpu/keys).
        if not node.get('ssh_public_key'):
            raise exceptions.ProvisionError(
                'azure: node_config.ssh_public_key missing — the '
                'backend injects the framework keypair; direct '
                'plugin callers must supply one.')
        argv += ['--ssh-key-values', node['ssh_public_key']]
        if node.get('use_spot'):
            # Deallocate on eviction: the jobs controller's preemption
            # reconciler sees a 'stopped' VM and recovers (same signal
            # shape as a GCP TPU preemption).
            argv += ['--priority', 'Spot',
                     '--eviction-policy', 'Deallocate']
        try:
            api.run_az(argv)
        except api.AzCliError as e:
            raise api.translate_error(e, 'vm create') from e

    if to_create:
        # Parallel synchronous creates: `az vm create` blocks 1-3 min
        # per VM (serial = tens of minutes for a pod-scale cluster),
        # while --no-wait would defer allocation errors past the
        # create call and lose the stockout/quota taxonomy the
        # failover provisioner keys on. Threads keep both.
        import concurrent.futures
        with concurrent.futures.ThreadPoolExecutor(
                max_workers=min(16, len(to_create))) as pool:
            futures = {pool.submit(_create, n): n for n in to_create}
            for fut in concurrent.futures.as_completed(futures):
                fut.result()   # re-raise the first typed error
                created.append(futures[fut])
    all_names = sorted(set(existing) | set(created))
    if not all_names:
        raise exceptions.ProvisionError('run_instances created nothing')
    return common.ProvisionRecord(
        provider_name='azure',
        cluster_name_on_cloud=cluster,
        region=config.region,
        zone=config.zone,
        created_instance_ids=created,
        resumed_instance_ids=resumed,
        head_instance_id=_vm_name(cluster, 0),
    )


def wait_instances(cluster_name_on_cloud: str, region: str,
                   zone: Optional[str], state: Optional[str]) -> None:
    del region, zone
    rg = resource_group(cluster_name_on_cloud)
    want = state or 'running'
    deadline = time.time() + _WAIT_TIMEOUT
    while time.time() < deadline:
        vms = _list_vms(rg)
        if want == 'terminated':
            if not vms:
                return
        elif vms and all(want in _power_state(vm) or
                         (want == 'stopped' and
                          'deallocated' in _power_state(vm))
                         for vm in vms):
            return
        time.sleep(_POLL_INTERVAL)
    raise exceptions.ProvisionError(
        f'Timed out waiting for {cluster_name_on_cloud} VMs to reach '
        f'{want!r}.')


def query_instances(
        cluster_name_on_cloud: str, region: str, zone: Optional[str],
        non_terminated_only: bool = True) -> Dict[str, Optional[str]]:
    del region, zone
    out: Dict[str, Optional[str]] = {}
    for vm in _list_vms(resource_group(cluster_name_on_cloud)):
        state = _power_state(vm)
        if 'running' in state:
            status = 'running'
        elif 'deallocated' in state or 'stopped' in state:
            status = 'stopped'
        elif 'deleting' in state:
            status = 'terminated'
        else:  # starting / creating / unknown
            status = 'pending'
        if non_terminated_only and status == 'terminated':
            continue
        out[vm['name']] = status
    return out


def get_cluster_info(cluster_name_on_cloud: str, region: str,
                     zone: Optional[str]) -> common.ClusterInfo:
    rg = resource_group(cluster_name_on_cloud)
    infos: Dict[str, List[common.InstanceInfo]] = {}
    for vm in sorted(_list_vms(rg), key=lambda v: v['name']):
        infos[vm['name']] = [
            common.InstanceInfo(
                instance_id=vm['name'],
                internal_ip=vm.get('privateIps', '').split(',')[0],
                external_ip=(vm.get('publicIps') or '').split(',')[0]
                or None,
                host_index=0,
                tags=vm.get('tags') or {},
            )
        ]
    head = min(infos) if infos else None
    return common.ClusterInfo(
        provider_name='azure',
        cluster_name_on_cloud=cluster_name_on_cloud,
        region=region,
        zone=zone,
        instances=infos,
        head_instance_id=head,
        ssh_user=SSH_USER,
    )


def stop_instances(cluster_name_on_cloud: str, region: str,
                   zone: Optional[str]) -> None:
    del region, zone
    rg = resource_group(cluster_name_on_cloud)
    for vm in _list_vms(rg):
        if 'running' in _power_state(vm) or \
                'starting' in _power_state(vm):
            try:
                # Deallocate (not 'vm stop'): a stopped-but-allocated
                # Azure VM still bills compute.
                api.run_az(['vm', 'deallocate', '-g', rg, '-n',
                            vm['name'], '--no-wait'])
            except api.AzCliError as e:
                raise api.translate_error(e, 'vm deallocate') from e


def terminate_instances(cluster_name_on_cloud: str, region: str,
                        zone: Optional[str]) -> None:
    del region, zone
    rg = resource_group(cluster_name_on_cloud)
    try:
        # The group owns every resource (VMs, NICs, IPs, disks):
        # one delete, nothing leaks.
        api.run_az(['group', 'delete', '-n', rg, '--yes', '--no-wait'])
    except api.AzCliError as e:
        if 'resourcegroupnotfound' in str(e).lower():
            return
        raise api.translate_error(e, 'group delete') from e


def _free_nsg_priorities(rg: str, n: int) -> List[int]:
    """First ``n`` NSG rule priorities >= 900 unused by ANY rule in
    the group's NSGs. ``az vm open-port`` defaults every rule to
    priority 900, so a second open_ports call on the same cluster
    (ports added on a later launch/update) — or two VMs whose NICs
    share a subnet-level NSG within ONE call — would violate Azure's
    unique-priority constraint; explicit fresh priorities avoid it."""
    used = set()
    try:
        nsgs = api.run_az(['network', 'nsg', 'list', '-g', rg]) or []
        used = {r.get('priority') for nsg in nsgs
                for r in (nsg.get('securityRules') or [])}
    except api.AzCliError:
        pass
    out, p = [], 900
    while len(out) < n:
        if p not in used:
            out.append(p)
        p += 1
    return out


def open_ports(cluster_name_on_cloud: str, ports: List[str],
               region: str, zone: Optional[str]) -> None:
    del region, zone
    if not ports:
        return
    rg = resource_group(cluster_name_on_cloud)
    # One call per VM with a comma-joined port list (per-port calls
    # would each need their own priority), each VM at its own fresh
    # priority: when NICs share an NSG (subnet-level NSG), reusing one
    # priority across the VM loop would trip Azure's unique-priority
    # constraint on the second VM.
    port_arg = ','.join(str(p) for p in ports)
    vms = _list_vms(rg)
    priorities = _free_nsg_priorities(rg, len(vms))
    for vm, priority in zip(vms, priorities):
        try:
            api.run_az(['vm', 'open-port', '-g', rg, '-n',
                        vm['name'], '--port', port_arg,
                        '--priority', str(priority)])
        except api.AzCliError as e:
            raise api.translate_error(e, 'vm open-port') from e


def cleanup_ports(cluster_name_on_cloud: str, region: str,
                  zone: Optional[str]) -> None:
    pass
