"""RunPod provision ops (nine-op contract).

Role of reference ``sky/provision/runpod/instance.py``, re-designed on
this framework's stateless seam: NAME-scoped membership (pods are
named ``<cluster>-<idx>``), one GraphQL deploy per missing index,
stop/resume supported (unlike Lambda), terminate by pod id.

Status mapping: RunPod ``desiredStatus`` CREATED/RUNNING/EXITED/
TERMINATED -> 'pending'/'running'/'stopped'/'terminated'.
"""
from __future__ import annotations

import re
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision import common
from skypilot_tpu.provision.runpod import api
from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)

_WAIT_TIMEOUT = 1800.0
_POLL_INTERVAL = 5.0

SSH_USER = 'root'


def _vm_name(cluster: str, idx: int) -> str:
    return f'{cluster}-{idx}'


def _cluster_pods(client: api.RunPodClient,
                  cluster: str) -> Dict[str, Dict[str, Any]]:
    """name -> pod, EXACT ``<cluster>-<rank>`` match (a prefix sweep
    could pull a foreign cluster into this one's terminate)."""
    member = re.compile(re.escape(cluster) + r'-\d+\Z')
    out: Dict[str, Dict[str, Any]] = {}
    for pod in client.list_pods():
        name = pod.get('name') or ''
        if member.fullmatch(name):
            out[name] = pod
    return out


def _gpu_parts(instance_type: str) -> Dict[str, Any]:
    """'1x_A100-80GB_SECURE'-style catalog names -> deploy args."""
    m = re.match(r'(\d+)x_(.+?)(?:_SECURE|_COMMUNITY)?\Z',
                 instance_type or '')
    if not m:
        raise exceptions.ProvisionError(
            f'Unparseable RunPod instance type {instance_type!r} '
            "(expected '<n>x_<GPU>[_SECURE]').")
    return {'gpu_count': int(m.group(1)), 'gpu_type': m.group(2)}


def bootstrap_instances(
        config: common.ProvisionConfig) -> common.ProvisionConfig:
    """Nothing to pre-create (no VPCs/security groups on RunPod)."""
    return config


def run_instances(
        config: common.ProvisionConfig) -> common.ProvisionRecord:
    node = config.node_config
    cluster = config.cluster_name_on_cloud
    client = api.RunPodClient()
    gpu = _gpu_parts(node['instance_type'])
    created: List[str] = []
    resumed: List[str] = []
    existing = _cluster_pods(client, cluster)
    for idx in range(config.count):
        name = _vm_name(cluster, idx)
        pod = existing.get(name)
        if pod is not None:
            if pod.get('desiredStatus') == 'EXITED':
                client.resume(pod['id'])
                resumed.append(pod['id'])
            continue
        created.append(client.deploy(
            name=name,
            gpu_type=gpu['gpu_type'],
            gpu_count=gpu['gpu_count'],
            region=config.region,
            disk_gb=int(node.get('disk_size') or 100),
            public_key=node.get('ssh_public_key')))
    return common.ProvisionRecord(
        provider_name='runpod',
        cluster_name_on_cloud=cluster,
        region=config.region,
        zone=config.zone,
        created_instance_ids=created,
        resumed_instance_ids=resumed,
        head_instance_id=_vm_name(cluster, 0),
    )


def _status(pod: Dict[str, Any]) -> str:
    return {
        'RUNNING': 'running',
        'CREATED': 'pending',
        'RESTARTING': 'pending',
        'EXITED': 'stopped',
        'TERMINATED': 'terminated',
    }.get(pod.get('desiredStatus', ''), 'pending')


def wait_instances(cluster_name_on_cloud: str, region: str,
                   zone: Optional[str], state: Optional[str]) -> None:
    del region, zone
    client = api.RunPodClient()
    want = state or 'running'
    deadline = time.time() + _WAIT_TIMEOUT
    while time.time() < deadline:
        pods = _cluster_pods(client, cluster_name_on_cloud)
        if want == 'terminated':
            if not pods or all(_status(p) == 'terminated'
                               for p in pods.values()):
                return
        elif pods and all(_status(p) == want for p in pods.values()):
            return
        time.sleep(_POLL_INTERVAL)
    raise exceptions.ProvisionError(
        f'Timed out waiting for {cluster_name_on_cloud} to reach '
        f'{want!r}.')


def query_instances(
        cluster_name_on_cloud: str, region: str, zone: Optional[str],
        non_terminated_only: bool = True) -> Dict[str, Optional[str]]:
    del region, zone
    client = api.RunPodClient()
    out: Dict[str, Optional[str]] = {}
    for name, pod in _cluster_pods(client,
                                   cluster_name_on_cloud).items():
        status = _status(pod)
        if non_terminated_only and status == 'terminated':
            continue
        out[name] = status
    return out


def _pod_ips(pod: Dict[str, Any]) -> Dict[str, Optional[str]]:
    """Public/private IP from the runtime port map (RunPod exposes
    SSH on the public IP's mapped port; private IP inside the DC)."""
    public = private = None
    runtime = pod.get('runtime') or {}
    for port in runtime.get('ports') or []:
        if port.get('isIpPublic'):
            public = public or port.get('ip')
        else:
            private = private or port.get('ip')
    return {'external': public, 'internal': private or public or ''}


def get_cluster_info(cluster_name_on_cloud: str, region: str,
                     zone: Optional[str]) -> common.ClusterInfo:
    client = api.RunPodClient()
    infos: Dict[str, List[common.InstanceInfo]] = {}
    for name, pod in sorted(
            _cluster_pods(client, cluster_name_on_cloud).items()):
        ips = _pod_ips(pod)
        infos[name] = [
            common.InstanceInfo(
                instance_id=pod.get('id', name),
                internal_ip=ips['internal'],
                external_ip=ips['external'],
                host_index=0,
                tags={'name': name},
            )
        ]
    head = min(infos) if infos else None
    return common.ClusterInfo(
        provider_name='runpod',
        cluster_name_on_cloud=cluster_name_on_cloud,
        region=region,
        zone=zone,
        instances=infos,
        head_instance_id=head,
        ssh_user=SSH_USER,
    )


def stop_instances(cluster_name_on_cloud: str, region: str,
                   zone: Optional[str]) -> None:
    del region, zone
    client = api.RunPodClient()
    for pod in _cluster_pods(client, cluster_name_on_cloud).values():
        if pod.get('desiredStatus') == 'RUNNING':
            client.stop(pod['id'])


def terminate_instances(cluster_name_on_cloud: str, region: str,
                        zone: Optional[str]) -> None:
    del region, zone
    client = api.RunPodClient()
    for pod in _cluster_pods(client, cluster_name_on_cloud).values():
        if pod.get('desiredStatus') != 'TERMINATED':
            client.terminate(pod['id'])


def open_ports(cluster_name_on_cloud: str, ports: List[str],
               region: str, zone: Optional[str]) -> None:
    logger.info('runpod: ports are exposed per-pod at deploy time; '
                'open_ports(%s) is a no-op.', ports)


def cleanup_ports(cluster_name_on_cloud: str, region: str,
                  zone: Optional[str]) -> None:
    pass
