"""RunPod provision plugin."""
