"""Minimal RunPod GraphQL client.

Role of reference ``sky/provision/runpod/utils.py`` (which wraps the
``runpod`` SDK); re-designed as a dependency-free GraphQL-over-HTTP
client against ``api.runpod.io/graphql``. Pods are the unit: deployed
with ``podFindAndDeployOnDemand``, stopped/resumed/terminated with
``podStop``/``podResume``/``podTerminate``, listed via ``myself {
pods }``. Cluster membership rides pod NAMES (``<cluster>-<idx>``).

The ``session_factory`` seam is replaced with a fake in tests, same
pattern as the lambda_cloud plugin.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions

API_ENDPOINT = 'https://api.runpod.io/graphql'
CREDENTIALS_PATH = '~/.runpod/config.toml'


def read_api_key() -> Optional[str]:
    key = os.environ.get('RUNPOD_API_KEY')
    if key:
        return key
    try:
        with open(os.path.expanduser(CREDENTIALS_PATH),
                  encoding='utf-8') as f:
            for line in f:
                if line.strip().startswith('api_key'):
                    return line.split('=', 1)[1].strip().strip('"\'')
    except OSError:
        pass
    return None


def _requests_session():
    import requests
    return requests.Session()


# Test seam.
session_factory = _requests_session


class RunPodClient:

    def __init__(self, api_key: Optional[str] = None) -> None:
        self.api_key = api_key or read_api_key()
        if not self.api_key:
            raise exceptions.ProvisionError(
                'No RunPod API key (set RUNPOD_API_KEY or write '
                f'{CREDENTIALS_PATH}).')
        self.http = session_factory()

    def _gql(self, query: str,
             variables: Optional[Dict[str, Any]] = None) -> Any:
        resp = self.http.request(
            'POST', API_ENDPOINT,
            json={'query': query, 'variables': variables or {}},
            headers={'Authorization': f'Bearer {self.api_key}'},
            timeout=60)
        try:
            body = resp.json()
        except ValueError:
            body = {}
        errors = body.get('errors') or (
            [{'message': resp.text[:200]}] if resp.status_code >= 400
            else [])
        if errors:
            raise translate_error(errors[0].get('message', ''),
                                  query.split('(')[0].strip())
        return body.get('data', {})

    # ------------------------------------------------------------ ops
    def list_pods(self) -> List[Dict[str, Any]]:
        data = self._gql(
            'query { myself { pods { id name desiredStatus costPerHr '
            'runtime { ports { ip isIpPublic privatePort publicPort } '
            '} machine { gpuDisplayName } dataCenterId } } }')
        return (data.get('myself') or {}).get('pods', [])

    def deploy(self, *, name: str, gpu_type: str, gpu_count: int,
               region: str, disk_gb: int,
               public_key: Optional[str]) -> str:
        env = ''
        if public_key:
            env = ('env: [{ key: "PUBLIC_KEY", value: "%s" }], '
                   % public_key.replace('"', ''))
        data = self._gql(
            'mutation { podFindAndDeployOnDemand(input: { '
            f'name: "{name}", gpuTypeId: "{gpu_type}", '
            f'gpuCount: {gpu_count}, dataCenterId: "{region}", '
            f'volumeInGb: {disk_gb}, containerDiskInGb: {disk_gb}, '
            f'{env}'
            'cloudType: SECURE }) { id } }')
        return data['podFindAndDeployOnDemand']['id']

    def stop(self, pod_id: str) -> None:
        self._gql('mutation { podStop(input: { podId: "%s" }) '
                  '{ id desiredStatus } }' % pod_id)

    def resume(self, pod_id: str) -> None:
        self._gql('mutation { podResume(input: { podId: "%s" }) '
                  '{ id desiredStatus } }' % pod_id)

    def terminate(self, pod_id: str) -> None:
        self._gql('mutation { podTerminate(input: { podId: "%s" }) }'
                  % pod_id)


def translate_error(message: str, what: str) -> Exception:
    blob = message.lower()
    if ('no longer any instances available' in blob or
            'not enough' in blob or 'unavailable' in blob or
            'out of stock' in blob):
        return exceptions.StockoutError(f'{what}: {message}')
    if 'quota' in blob or 'limit exceeded' in blob or 'spend' in blob:
        return exceptions.QuotaExceededError(f'{what}: {message}')
    return exceptions.ProvisionError(f'{what}: {message}')
