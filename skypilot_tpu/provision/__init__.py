"""Provision layer — stateless per-cloud modules behind a router.

Re-design of reference ``sky/provision/__init__.py:37-197``: every
operation ``<op>(provider_name, ...)`` routes to
``skypilot_tpu.provision.<provider>.instance.<op>``. Plugins are
stateless; all cluster state lives with the cloud provider (queried
fresh) and in the client DB.
"""
from __future__ import annotations

import functools
import importlib
from typing import Any, Dict, List, Optional

from skypilot_tpu import trace as trace_lib
from skypilot_tpu.provision import common
from skypilot_tpu.utils import fault_injection
from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)


def _route(op_name: str):
    """Decorator: dispatch to the provider module's same-named function."""

    def decorator(stub):

        @functools.wraps(stub)
        def wrapper(provider_name: str, *args, **kwargs):
            # One span per provider op, named exactly like the chaos
            # site (`provision.local.run_instances`): a launch trace
            # decomposes into the same vocabulary fault plans and
            # docs already use, and an injected fault's record
            # carries this span's trace id.
            with trace_lib.span(
                    f'provision.{provider_name}.{op_name}',
                    slow_ok=True):
                # Chaos site for every provider op — a fired fault
                # raises the typed error (quota/stockout/...) the
                # failover machinery dispatches on.
                fault_injection.inject(
                    f'provision.{provider_name}.{op_name}',
                    provider=provider_name)
                module = importlib.import_module(
                    f'skypilot_tpu.provision.{provider_name}.instance')
                impl = getattr(module, op_name, None)
                if impl is None:
                    raise NotImplementedError(
                        f'Provider {provider_name!r} does not '
                        f'implement {op_name}()')
                return impl(*args, **kwargs)

        return wrapper

    return decorator


@_route('bootstrap_instances')
def bootstrap_instances(provider_name: str,
                        config: common.ProvisionConfig
                        ) -> common.ProvisionConfig:
    """Provider-specific pre-launch setup (networks, firewalls, ...)."""
    raise AssertionError  # replaced by router


@_route('run_instances')
def run_instances(provider_name: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    """Create (or reuse/restart) instances. Idempotent."""
    raise AssertionError


@_route('wait_instances')
def wait_instances(provider_name: str, cluster_name_on_cloud: str,
                   region: str, zone: Optional[str],
                   state: Optional[str]) -> None:
    """Block until all instances reach `state` ('running'/'stopped')."""
    raise AssertionError


@_route('query_instances')
def query_instances(
        provider_name: str, cluster_name_on_cloud: str, region: str,
        zone: Optional[str],
        non_terminated_only: bool = True) -> Dict[str, Optional[str]]:
    """instance_id -> status string ('running'/'stopped'/'terminated')."""
    raise AssertionError


@_route('get_cluster_info')
def get_cluster_info(provider_name: str, cluster_name_on_cloud: str,
                     region: str,
                     zone: Optional[str]) -> common.ClusterInfo:
    raise AssertionError


@_route('stop_instances')
def stop_instances(provider_name: str, cluster_name_on_cloud: str,
                   region: str, zone: Optional[str]) -> None:
    raise AssertionError


@_route('terminate_instances')
def terminate_instances(provider_name: str, cluster_name_on_cloud: str,
                        region: str, zone: Optional[str]) -> None:
    raise AssertionError


@_route('open_ports')
def open_ports(provider_name: str, cluster_name_on_cloud: str,
               ports: List[str], region: str,
               zone: Optional[str]) -> None:
    raise AssertionError


@_route('cleanup_ports')
def cleanup_ports(provider_name: str, cluster_name_on_cloud: str,
                  region: str, zone: Optional[str]) -> None:
    raise AssertionError
