"""Provision orchestration: bulk_provision + post-provision runtime setup.

Re-design of reference ``sky/provision/provisioner.py:101,349,639``.
bulk_provision drives one provider attempt (bootstrap -> run -> wait ->
cluster info); post_provision_runtime_setup turns raw hosts into a
usable cluster: reachability check, hosts.json for the gang driver,
framework runtime install (real clouds), and the agentd daemon on the
head host. TPU pods arrive gang-provisioned, so there is no Ray
cluster to assemble (design delta (a) of SURVEY.md §7).
"""
from __future__ import annotations

import json
import os
import shlex
import traceback
from typing import Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import provision
from skypilot_tpu import trace as trace_lib
from skypilot_tpu.agent import constants as agent_constants
from skypilot_tpu.provision import common
from skypilot_tpu.utils import command_runner as runner_lib
from skypilot_tpu.utils import fault_injection
from skypilot_tpu.utils import log as sky_logging
from skypilot_tpu.utils import subprocess_utils

logger = sky_logging.init_logger(__name__)


@trace_lib.span('provisioner.bulk_provision', slow_ok=True)
def bulk_provision(config: common.ProvisionConfig
                   ) -> common.ProvisionRecord:
    """One provisioning attempt against one (region, zone)."""
    provider = config.provider_name
    config = provision.bootstrap_instances(provider, config)
    record = provision.run_instances(provider, config)
    provision.wait_instances(provider, record.cluster_name_on_cloud,
                             record.region, record.zone, state='running')
    if config.ports_to_open:
        provision.open_ports(provider, record.cluster_name_on_cloud,
                             config.ports_to_open, record.region,
                             record.zone)
    return record


def host_entries(cluster_info: common.ClusterInfo,
                 ssh_private_key: Optional[str]) -> List[Dict]:
    """hosts.json content: one entry per host in stable rank order."""
    entries = []
    docker_config = cluster_info.docker_config
    for host in cluster_info.all_hosts():
        host_dir = host.tags.get('host_dir')
        if host_dir is not None:
            entries.append({
                'kind': 'local',
                'host_id': f'{host.instance_id}-h{host.host_index}',
                'ip': host.get_feasible_ip(),
                'host_dir': host_dir,
            })
        elif host.tags.get('k8s_pod') is not None:
            entry = {
                'kind': 'k8s',
                'host_id': f'{host.instance_id}-h{host.host_index}',
                'ip': host.get_feasible_ip(),
                'pod': host.tags['k8s_pod'],
                'namespace': host.tags.get('k8s_namespace', 'default'),
                'context': host.tags.get('k8s_context'),
            }
            # Exec-less clusters (admission policy denies kubectl
            # exec): the provisioner tags hosts with the port-forward
            # runner mode (kubernetes.runner: port-forward in config),
            # and commands go over SSH through a kubectl tunnel.
            if host.tags.get('k8s_runner_mode'):
                entry['mode'] = host.tags['k8s_runner_mode']
                entry['user'] = cluster_info.ssh_user
                entry['key'] = ssh_private_key
            entries.append(entry)
        else:
            entries.append({
                'kind': 'ssh',
                'host_id': f'{host.instance_id}-h{host.host_index}',
                'ip': host.get_feasible_ip(),
                'user': cluster_info.ssh_user,
                'key': ssh_private_key,
                'port': host.ssh_port,
            })
    if docker_config:
        for entry in entries:
            cfg = dict(docker_config)
            if entry['kind'] == 'local':
                # Simulated hosts share this machine's one docker
                # daemon; a per-host suffix keeps their containers (and
                # rm -f during bootstrap) from colliding. Real hosts
                # each run their own daemon, so the shared name stands.
                safe = ''.join(c if c.isalnum() or c in '_-' else '-'
                               for c in entry['host_id'])
                cfg['container'] = f"{cfg['container']}-{safe}"
            entry['docker'] = cfg
    return entries


def make_runners(cluster_info: common.ClusterInfo,
                 ssh_private_key: Optional[str]
                 ) -> List[runner_lib.CommandRunner]:
    """Host-level runners (control plane: file sync, job submission,
    log tail). Job commands go through the driver's own
    runner_from_host_entry call, which applies the docker wrap."""
    return [
        runner_lib.runner_from_host_entry(e, in_container=False)
        for e in host_entries(cluster_info, ssh_private_key)
    ]


def head_state_dir(cluster_info: common.ClusterInfo) -> str:
    """Agent state dir on the head host.

    Local clusters get a per-cluster dir (many clusters share this
    machine); real clusters use the canonical home-dir location.
    """
    cluster_dir = cluster_info.provider_config.get('cluster_dir')
    if cluster_dir is not None:
        return os.path.join(cluster_dir, 'agent')
    return agent_constants.DEFAULT_STATE_DIR


def write_file_via_runner(runner: runner_lib.CommandRunner, path: str,
                          content: str) -> None:
    """Write a file on the host, safe against quoting (base64 transport)."""
    import base64
    encoded = base64.b64encode(content.encode()).decode()
    quoted = runner_lib.shell_path(path)
    runner.run(
        f'mkdir -p $(dirname {quoted}) && '
        f'echo {encoded} | base64 -d > {quoted}',
        check=True)


def wait_for_connectivity(runners: List[runner_lib.CommandRunner],
                          timeout: float = 300.0) -> None:
    """All hosts reachable (reference wait_for_ssh :349)."""

    def check(runner: runner_lib.CommandRunner) -> None:
        subprocess_utils.wait_for(runner.check_connection,
                                  timeout=timeout,
                                  interval=2.0,
                                  desc=f'connectivity to {runner.host_id}')

    subprocess_utils.run_in_parallel(check, runners)


_RUNTIME_SETUP_SENTINEL = '~/.skytpu_runtime_ready'

# Installs the framework on a real TPU-VM host. The package is rsynced
# (not pip-published), mirroring the reference's wheel build+ship
# (sky/backends/wheel_utils.py:140) with plain file sync.
_REMOTE_PKG_DIR = '~/.skytpu_runtime/skypilot_tpu'


def setup_runtime_on_cluster(runners: List[runner_lib.CommandRunner],
                             log_dir: str) -> None:
    """Ship the framework package to every host (skip if current)."""
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def setup_one(pair) -> None:
        idx, runner = pair
        log_path = os.path.join(log_dir, f'runtime_setup-{idx}.log')
        if isinstance(runner, runner_lib.LocalProcessRunner):
            return  # already importable locally
        runner.rsync(pkg_root + '/', _REMOTE_PKG_DIR, up=True,
                     log_path=log_path)
        sentinel = runner_lib.shell_path(_RUNTIME_SETUP_SENTINEL)
        # Idempotent: the bashrc line is appended at most once.
        runner.run(
            f'if [ ! -f {sentinel} ]; then '
            'echo "export PYTHONPATH=\\"$HOME/.skytpu_runtime:'
            '$PYTHONPATH\\"" >> ~/.bashrc && '
            f'touch {sentinel}; fi',
            log_path=log_path, check=True)

    subprocess_utils.run_in_parallel(setup_one, list(enumerate(runners)))


def setup_docker_on_cluster(cluster_info: common.ClusterInfo,
                            ssh_private_key: Optional[str],
                            log_dir: str) -> None:
    """Bring up the task container on every host in parallel
    (idempotent — cluster reuse and exec fast paths skip the pull).
    Built from host entries so each host gets its per-host container
    name (the same names the gang driver will exec into)."""

    def bootstrap_one(pair) -> None:
        idx, entry = pair
        docker_runner = runner_lib.runner_from_host_entry(entry)
        assert isinstance(docker_runner, runner_lib.DockerCommandRunner)
        docker_runner.bootstrap(
            log_path=os.path.join(log_dir, f'docker_setup-{idx}.log'))

    entries = host_entries(cluster_info, ssh_private_key)
    subprocess_utils.run_in_parallel(bootstrap_one,
                                     list(enumerate(entries)))


def start_agent_on_head(head_runner: runner_lib.CommandRunner,
                        state_dir: str, log_dir: str) -> None:
    """Start (or restart) agentd detached on the head host."""
    pid_file = runner_lib.shell_path(
        os.path.join(state_dir, agent_constants.AGENT_PID_FILE))
    agent_log = runner_lib.shell_path(
        os.path.join(state_dir, agent_constants.AGENT_LOG))
    state_q = runner_lib.shell_path(state_dir)
    interval = agent_constants.EVENT_INTERVAL_SECONDS
    cmd = (
        f'mkdir -p {state_q} && '
        f'if [ -f {pid_file} ] && '
        f'kill -0 $(cat {pid_file}) 2>/dev/null; then '
        f'echo agentd already running; else '
        f'nohup python -u -m skypilot_tpu.agent.agentd '
        f'--state-dir {state_q} --interval {interval} '
        f'>> {agent_log} 2>&1 & '
        f'echo started agentd pid $!; fi')
    head_runner.run(cmd,
                    log_path=os.path.join(log_dir, 'agent_start.log'),
                    check=True)


@trace_lib.span('provisioner.post_provision_runtime_setup',
                slow_ok=True)
def post_provision_runtime_setup(
        cluster_info: common.ClusterInfo,
        ssh_private_key: Optional[str],
        log_dir: str) -> str:
    """Returns the head state dir after the cluster is fully usable."""
    # Chaos site: a fired ssh_failure here plays a host that came up
    # but cannot be set up (flaky runner) — callers see the typed
    # CommandError and retry the whole launch boundedly.
    fault_injection.inject(
        'provisioner.post_provision_runtime_setup',
        cluster=cluster_info.cluster_name_on_cloud)
    os.makedirs(os.path.expanduser(log_dir), exist_ok=True)
    runners = make_runners(cluster_info, ssh_private_key)
    if not runners:
        raise exceptions.ProvisionError('Cluster has no hosts.')
    wait_for_connectivity(runners)
    setup_runtime_on_cluster(runners, log_dir)
    if cluster_info.docker_config:
        setup_docker_on_cluster(cluster_info, ssh_private_key, log_dir)
    state_dir = head_state_dir(cluster_info)
    head_runner = runners[0]
    entries = host_entries(cluster_info, ssh_private_key)
    hosts_path = os.path.join(state_dir, agent_constants.HOSTS_FILE)
    if isinstance(head_runner, runner_lib.LocalProcessRunner):
        os.makedirs(os.path.expanduser(state_dir), exist_ok=True)
        with open(os.path.expanduser(hosts_path), 'w',
                  encoding='utf-8') as f:
            json.dump(entries, f)
    else:
        write_file_via_runner(head_runner, hosts_path,
                              json.dumps(entries))
    start_agent_on_head(head_runner, state_dir, log_dir)
    return state_dir


def teardown_cluster(provider_name: str, cluster_name_on_cloud: str,
                     region: str, zone: Optional[str],
                     terminate: bool) -> None:
    if terminate:
        # Before the instances go away: port cleanup may need them to
        # resolve which security groups carry this cluster's rules
        # (rules on shared/default SGs outlive the instances).
        try:
            provision.cleanup_ports(provider_name,
                                    cluster_name_on_cloud, region,
                                    zone)
        except Exception:  # pylint: disable=broad-except
            logger.warning('cleanup_ports failed for %s:\n%s',
                           cluster_name_on_cloud,
                           traceback.format_exc())
        provision.terminate_instances(provider_name, cluster_name_on_cloud,
                                      region, zone)
    else:
        provision.stop_instances(provider_name, cluster_name_on_cloud,
                                 region, zone)
