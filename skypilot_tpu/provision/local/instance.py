"""Local provider — simulated instances backed by the local filesystem.

The hermetic counterpart of a cloud plugin (SURVEY.md §4: the fake
provisioner the reference lacks). A "cluster" is a directory under
``$SKYTPU_DATA_DIR/local_cloud/<cluster>``; a simulated pod slice of N
hosts is N host slots that all resolve to 127.0.0.1. Fault injection:
``skypilot_tpu.provision.local.instance.preempt(cluster)`` flips the
cluster to terminated, exactly like a spot reclaim, which the managed
jobs tests use to exercise recovery.
"""
from __future__ import annotations

import json
import os
import shutil
import time
from typing import Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision import common


def _root() -> str:
    base = os.environ.get('SKYTPU_DATA_DIR',
                          os.path.expanduser('~/.skytpu'))
    return os.path.join(os.path.expanduser(base), 'local_cloud')


def _cluster_dir(cluster_name_on_cloud: str) -> str:
    return os.path.join(_root(), cluster_name_on_cloud)


def _meta_path(cluster_name_on_cloud: str) -> str:
    return os.path.join(_cluster_dir(cluster_name_on_cloud), 'metadata.json')


def _read_meta(cluster_name_on_cloud: str) -> Optional[dict]:
    try:
        with open(_meta_path(cluster_name_on_cloud), encoding='utf-8') as f:
            return json.load(f)
    except FileNotFoundError:
        return None


def _write_meta(cluster_name_on_cloud: str, meta: dict) -> None:
    os.makedirs(_cluster_dir(cluster_name_on_cloud), exist_ok=True)
    with open(_meta_path(cluster_name_on_cloud), 'w', encoding='utf-8') as f:
        json.dump(meta, f)


# ----------------------------------------------------------------------
def bootstrap_instances(
        config: common.ProvisionConfig) -> common.ProvisionConfig:
    return config


def run_instances(config: common.ProvisionConfig) -> common.ProvisionRecord:
    name = config.cluster_name_on_cloud
    meta = _read_meta(name)
    num_hosts = int(config.node_config.get('num_hosts', 1)) * config.count
    created, resumed = [], []
    if meta is None or meta.get('status') == 'terminated':
        # A cluster re-created after termination is a brand-new set of
        # VMs: fresh filesystem, no stale agent pid file / jobs DB.
        # (Without this, a relaunch racing the preemption kill can see
        # the doomed old agentd as "already running" and end up with a
        # cluster that has no scheduler at all.)
        if meta is not None:
            shutil.rmtree(_cluster_dir(name), ignore_errors=True)
        meta = {
            'status': 'running',
            'num_hosts': num_hosts,
            'launched_at': time.time(),
            'node_config': config.node_config,
            'cluster_name': config.cluster_name,
        }
        created = [f'local-{name}-{i}' for i in range(num_hosts)]
    elif meta.get('status') == 'stopped':
        meta['status'] = 'running'
        resumed = [f'local-{name}-{i}' for i in range(meta['num_hosts'])]
    else:
        if meta.get('num_hosts') != num_hosts:
            raise exceptions.ProvisionError(
                f'Cluster {name} exists with {meta.get("num_hosts")} hosts; '
                f'requested {num_hosts}.')
    _write_meta(name, meta)
    # Per-host state dirs (simulated filesystems for rank isolation).
    for i in range(meta['num_hosts']):
        os.makedirs(os.path.join(_cluster_dir(name), f'host{i}'),
                    exist_ok=True)
    return common.ProvisionRecord(
        provider_name='local',
        cluster_name_on_cloud=name,
        region=config.region,
        zone=config.zone,
        created_instance_ids=created,
        resumed_instance_ids=resumed,
        head_instance_id=f'local-{name}-0',
    )


def wait_instances(cluster_name_on_cloud: str, region: str,
                   zone: Optional[str], state: Optional[str]) -> None:
    meta = _read_meta(cluster_name_on_cloud)
    want = state or 'running'
    have = meta.get('status') if meta else 'terminated'
    if want != have:
        raise exceptions.ProvisionError(
            f'Local cluster {cluster_name_on_cloud} is {have}, '
            f'expected {want}.')


def query_instances(
        cluster_name_on_cloud: str, region: str, zone: Optional[str],
        non_terminated_only: bool = True) -> Dict[str, Optional[str]]:
    meta = _read_meta(cluster_name_on_cloud)
    if meta is None:
        return {}
    status = meta['status']
    if non_terminated_only and status == 'terminated':
        return {}
    dead = set(meta.get('dead_hosts') or [])
    out = {}
    for i in range(meta['num_hosts']):
        host_status = 'terminated' if i in dead else status
        if non_terminated_only and host_status == 'terminated':
            continue
        out[f'local-{cluster_name_on_cloud}-{i}'] = host_status
    return out


def get_cluster_info(cluster_name_on_cloud: str, region: str,
                     zone: Optional[str]) -> common.ClusterInfo:
    meta = _read_meta(cluster_name_on_cloud)
    if meta is None or meta['status'] != 'running':
        raise exceptions.ProvisionError(
            f'Local cluster {cluster_name_on_cloud} is not running.')
    instance_id = f'local-{cluster_name_on_cloud}'
    hosts = [
        common.InstanceInfo(
            instance_id=instance_id,
            internal_ip='127.0.0.1',
            external_ip='127.0.0.1',
            host_index=i,
            tags={'host_dir': os.path.join(_cluster_dir(
                cluster_name_on_cloud), f'host{i}')},
        ) for i in range(meta['num_hosts'])
    ]
    return common.ClusterInfo(
        provider_name='local',
        cluster_name_on_cloud=cluster_name_on_cloud,
        region=region,
        zone=zone,
        instances={instance_id: hosts},
        head_instance_id=instance_id,
        ssh_user=os.environ.get('USER', 'root'),
        provider_config={
            'tpu_topology': meta.get('node_config', {}).get(
                'tpu_topology', ''),
            'cluster_dir': _cluster_dir(cluster_name_on_cloud),
        },
    )


def _matches(pid: int, module: str, agent_dir: str, me: int) -> bool:
    """True iff `pid` really is this cluster's `module` process —
    guards every kill against the OS having reused a recorded pid."""
    import psutil
    if not pid or pid == me:
        return False
    try:
        cmdline = psutil.Process(pid).cmdline()
    except (psutil.NoSuchProcess, psutil.AccessDenied):
        return False
    return module in cmdline and agent_dir in cmdline


def _collect_agentd_pids(cluster_name_on_cloud: str) -> List[int]:
    """This cluster's agentd pids: pid file (validated), plus a cmdline
    sweep (the pid file may be stale after an agentd restart racing a
    teardown)."""
    import psutil
    agent_dir = os.path.join(_cluster_dir(cluster_name_on_cloud), 'agent')
    # Autostop runs teardown *inside* agentd — never collect the
    # caller (it exits itself after the stop completes).
    me = os.getpid()
    pids: List[int] = []
    try:
        with open(os.path.join(agent_dir, 'agentd.pid'),
                  encoding='utf-8') as f:
            pid = int(f.read().strip())
        if _matches(pid, 'skypilot_tpu.agent.agentd', agent_dir, me):
            pids.append(pid)
    except (FileNotFoundError, ValueError):
        pass
    for proc in psutil.process_iter(['cmdline']):
        try:
            cmdline = proc.info['cmdline'] or []
            if proc.pid != me and (
                    'skypilot_tpu.agent.agentd' in cmdline) and (
                    agent_dir in cmdline):
                pids.append(proc.pid)
        except (psutil.NoSuchProcess, psutil.AccessDenied):
            continue
    return sorted(set(pids))


def _collect_driver_pids(cluster_name_on_cloud: str) -> List[int]:
    """This cluster's live job-driver pids, from its jobs DB.

    Drivers are daemonized (own session, reparented to init), so they
    are NOT in agentd's process tree — on a real cloud they die with
    the VM, but here they would outlive teardown, leak the replica's
    ports, and wedge later tests (root cause of the round-1 red serve
    test: orphaned replica HTTP servers squatting on the probe ports).
    """
    from skypilot_tpu.agent import job_lib
    agent_dir = os.path.join(_cluster_dir(cluster_name_on_cloud), 'agent')
    me = os.getpid()
    if not os.path.isdir(agent_dir):
        return []
    try:
        jobs = job_lib.get_jobs(
            agent_dir, job_lib.JobStatus.nonterminal_statuses())
    except Exception:  # pylint: disable=broad-except
        return []
    return sorted({
        job['driver_pid'] for job in jobs
        if _matches(job.get('driver_pid'), 'skypilot_tpu.agent.driver',
                    agent_dir, me)
    })


def _kill_pids(pids: List[int]) -> None:
    from skypilot_tpu.utils import subprocess_utils
    for pid in pids:
        subprocess_utils.kill_process_tree(pid)


def _kill_cluster_processes(cluster_name_on_cloud: str) -> None:
    # agentd dies first so it cannot schedule a fresh driver for a
    # PENDING job after the driver snapshot is taken.
    _kill_pids(_collect_agentd_pids(cluster_name_on_cloud))
    _kill_pids(_collect_driver_pids(cluster_name_on_cloud))


def stop_instances(cluster_name_on_cloud: str, region: str,
                   zone: Optional[str]) -> None:
    _kill_cluster_processes(cluster_name_on_cloud)
    meta = _read_meta(cluster_name_on_cloud)
    if meta is not None:
        meta['status'] = 'stopped'
        _write_meta(cluster_name_on_cloud, meta)


def terminate_instances(cluster_name_on_cloud: str, region: str,
                        zone: Optional[str]) -> None:
    _kill_cluster_processes(cluster_name_on_cloud)
    shutil.rmtree(_cluster_dir(cluster_name_on_cloud), ignore_errors=True)


def open_ports(cluster_name_on_cloud: str, ports: List[str], region: str,
               zone: Optional[str]) -> None:
    pass


def cleanup_ports(cluster_name_on_cloud: str, region: str,
                  zone: Optional[str]) -> None:
    pass


def preempt_host(cluster_name_on_cloud: str, host_index: int) -> None:
    """Fault injection: kill ONE host of a slice (partial loss). The
    cluster degrades — cloud truth shows a mixed
    running/terminated host set, which status reconciliation must
    surface as DEGRADED, not as a vanished cluster."""
    meta = _read_meta(cluster_name_on_cloud)
    if meta is None:
        return
    dead = set(meta.get('dead_hosts') or [])
    dead.add(host_index)
    meta['dead_hosts'] = sorted(dead)
    _write_meta(cluster_name_on_cloud, meta)


# ----------------------------------------------------------------------
# Fault injection (test-only API, mirrors a spot preemption).
def preempt(cluster_name_on_cloud: str) -> None:
    """Fault injection: spot reclaim — hosts die, jobs die with them.

    Ordering matters three ways: (a) the old agentd dies before the
    driver snapshot, so it cannot spawn a fresh driver for a PENDING
    job after the snapshot; (b) cloud truth flips BEFORE the drivers
    die, so an observer can never see a dead job on a cluster that
    still reports running (that window reads as a user failure, not a
    preemption); (c) the doomed driver pids are snapshotted BEFORE the
    flip, so a recovery relaunch racing this function (the jobs
    controller can relaunch within milliseconds of the flip) never has
    its fresh processes swept up in the kill.
    """
    _kill_pids(_collect_agentd_pids(cluster_name_on_cloud))
    doomed = _collect_driver_pids(cluster_name_on_cloud)
    meta = _read_meta(cluster_name_on_cloud)
    if meta is not None:
        meta['status'] = 'terminated'
        _write_meta(cluster_name_on_cloud, meta)
    _kill_pids(doomed)
