"""Lambda Cloud provision ops.

Re-design of reference ``sky/provision/lambda_cloud/instance.py`` on
this framework's seam: NAME-scoped cluster membership (the API has no
tags — instances are named ``<cluster>-<idx>`` and listed by prefix),
one launch call per missing index, terminate by collected ids. The
cloud cannot stop instances, so the cloud layer declares STOP
unsupported and ``stop_instances`` raises.

Status mapping: Lambda's ``booting``/``active``/``unhealthy``/
``terminating`` -> 'pending'/'running'/'pending'/'terminated'.
"""
from __future__ import annotations

import hashlib
import os
import re
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision import common
from skypilot_tpu.provision.lambda_cloud import api
from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)

_WAIT_TIMEOUT = 1800.0   # GPU boxes can take a while to boot
_POLL_INTERVAL = 5.0

SSH_USER = 'ubuntu'


def _vm_name(cluster: str, idx: int) -> str:
    return f'{cluster}-{idx}'


def _cluster_instances(client: api.LambdaClient,
                       cluster: str) -> Dict[str, Dict[str, Any]]:
    """name -> instance for this cluster's members.

    Membership is an EXACT ``<cluster>-<rank>`` match, not a prefix
    test: cluster names may extend each other (``prod`` vs
    ``prod-eu``), and a prefix sweep would pull a foreign cluster's
    instances into this one's status — and, worse, its terminate.

    When a dying and a live instance briefly share a name (relaunch
    right after a terminate), the LIVE one wins the key so status/
    info paths never report the corpse."""
    member = re.compile(re.escape(cluster) + r'-\d+\Z')
    out: Dict[str, Dict[str, Any]] = {}
    for inst in client.list_instances():
        name = inst.get('name') or ''
        if not member.fullmatch(name):
            continue
        prev = out.get(name)
        if prev is not None and prev.get('status') not in (
                'terminating', 'terminated'):
            continue
        out[name] = inst
    return out


def _ensure_ssh_key(client: api.LambdaClient,
                    public_key: Optional[str]) -> List[str]:
    """Register (once) and return the ssh key name to launch with."""
    if not public_key:
        keys = client.list_ssh_keys()
        if not keys:
            raise exceptions.ProvisionError(
                'No SSH keys registered with Lambda Cloud and no '
                'ssh_public_key provided.')
        return [keys[0]['name']]
    digest = hashlib.sha256(public_key.encode()).hexdigest()[:12]
    key_name = f'skytpu-{digest}'
    if not any(k.get('name') == key_name
               for k in client.list_ssh_keys()):
        client.add_ssh_key(key_name, public_key)
    return [key_name]


def bootstrap_instances(
        config: common.ProvisionConfig) -> common.ProvisionConfig:
    """Nothing to pre-create (no VPCs/groups on Lambda)."""
    return config


def run_instances(
        config: common.ProvisionConfig) -> common.ProvisionRecord:
    node = config.node_config
    cluster = config.cluster_name_on_cloud
    client = api.LambdaClient()
    # ssh_public_key is the framework keypair, injected by
    # gang_backend for every cloud (post-provision SSH connects with
    # ~/.skytpu/keys); _ensure_ssh_key still tolerates None for
    # direct plugin use.
    key_names = _ensure_ssh_key(client, node.get('ssh_public_key'))
    created: List[str] = []
    existing = _cluster_instances(client, cluster)
    for idx in range(config.count):
        name = _vm_name(cluster, idx)
        inst = existing.get(name)
        if inst is not None:
            status = inst.get('status')
            if status not in ('terminating', 'terminated'):
                continue
            if status == 'terminating':
                # Same-named launch while the old instance is dying
                # would collide in the name-keyed membership map
                # (down immediately followed by launch): wait for
                # the name to free, and REFUSE to launch a duplicate
                # if it never does.
                deadline = time.time() + 300
                while True:
                    cur = _cluster_instances(client, cluster).get(name)
                    if cur is None or cur.get('status') == 'terminated':
                        break
                    if time.time() > deadline:
                        raise exceptions.ProvisionError(
                            f'Instance {name} stuck terminating; '
                            'refusing to launch a same-named '
                            'duplicate. Retry once it is gone.')
                    time.sleep(_POLL_INTERVAL)
        ids = client.launch(region=config.region,
                            instance_type=node['instance_type'],
                            name=name,
                            ssh_key_names=key_names)
        created.extend(ids)
    return common.ProvisionRecord(
        provider_name='lambda_cloud',
        cluster_name_on_cloud=cluster,
        region=config.region,
        zone=config.zone,
        created_instance_ids=created,
        head_instance_id=_vm_name(cluster, 0),
    )


def _status(inst: Dict[str, Any]) -> str:
    return {
        'active': 'running',
        'booting': 'pending',
        'unhealthy': 'pending',
        'terminating': 'terminated',
        'terminated': 'terminated',
    }.get(inst.get('status', ''), 'pending')


def wait_instances(cluster_name_on_cloud: str, region: str,
                   zone: Optional[str], state: Optional[str]) -> None:
    del region, zone
    client = api.LambdaClient()
    want = state or 'running'
    deadline = time.time() + _WAIT_TIMEOUT
    while time.time() < deadline:
        insts = _cluster_instances(client, cluster_name_on_cloud)
        if want == 'terminated':
            if not insts or all(_status(i) == 'terminated'
                                for i in insts.values()):
                return
        elif insts and all(_status(i) == want
                           for i in insts.values()):
            return
        time.sleep(_POLL_INTERVAL)
    raise exceptions.ProvisionError(
        f'Timed out waiting for {cluster_name_on_cloud} to reach '
        f'{want!r}.')


def query_instances(
        cluster_name_on_cloud: str, region: str, zone: Optional[str],
        non_terminated_only: bool = True) -> Dict[str, Optional[str]]:
    del region, zone
    client = api.LambdaClient()
    out: Dict[str, Optional[str]] = {}
    for name, inst in _cluster_instances(client,
                                         cluster_name_on_cloud).items():
        status = _status(inst)
        if non_terminated_only and status == 'terminated':
            continue
        out[name] = status
    return out


def get_cluster_info(cluster_name_on_cloud: str, region: str,
                     zone: Optional[str]) -> common.ClusterInfo:
    client = api.LambdaClient()
    infos: Dict[str, List[common.InstanceInfo]] = {}
    for name, inst in sorted(
            _cluster_instances(client, cluster_name_on_cloud).items()):
        infos[name] = [
            common.InstanceInfo(
                instance_id=inst.get('id', name),
                internal_ip=inst.get('private_ip') or
                inst.get('ip', ''),
                external_ip=inst.get('ip'),
                host_index=0,
                tags={'name': name},
            )
        ]
    head = min(infos) if infos else None
    return common.ClusterInfo(
        provider_name='lambda_cloud',
        cluster_name_on_cloud=cluster_name_on_cloud,
        region=region,
        zone=zone,
        instances=infos,
        head_instance_id=head,
        ssh_user=SSH_USER,
    )


def stop_instances(cluster_name_on_cloud: str, region: str,
                   zone: Optional[str]) -> None:
    raise exceptions.NotSupportedError(
        'Lambda Cloud cannot stop instances, only terminate '
        '(the cloud layer declares STOP unsupported).')


def terminate_instances(cluster_name_on_cloud: str, region: str,
                        zone: Optional[str]) -> None:
    del region, zone
    client = api.LambdaClient()
    ids = [
        inst.get('id') for inst in
        _cluster_instances(client, cluster_name_on_cloud).values()
        if inst.get('status') not in ('terminating', 'terminated')
    ]
    if ids:
        client.terminate(ids)


def open_ports(cluster_name_on_cloud: str, ports: List[str],
               region: str, zone: Optional[str]) -> None:
    logger.info('lambda_cloud: instances have open ingress by '
                'default; open_ports(%s) is a no-op.', ports)


def cleanup_ports(cluster_name_on_cloud: str, region: str,
                  zone: Optional[str]) -> None:
    pass
