"""Lambda Cloud provision plugin (REST)."""
