"""Minimal Lambda Cloud REST client.

Re-design of reference ``sky/provision/lambda_cloud/lambda_utils.py``
(metadata client): bearer-token REST against
``cloud.lambdalabs.com/api/v1`` — instances are launched/terminated
through ``instance-operations`` and listed via ``/instances``; the
cloud has no tags, so cluster membership rides instance NAMES
(``<cluster>-<idx>``), and no stop operation exists (terminate only).

The ``http`` seam (a requests.Session-alike) is replaced with a fake
in tests, same pattern as the aws/azure plugins.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional

from skypilot_tpu import exceptions

API_ENDPOINT = 'https://cloud.lambdalabs.com/api/v1'
CREDENTIALS_PATH = '~/.lambda_cloud/lambda_keys'


class LambdaApiError(Exception):
    pass


def read_api_key() -> Optional[str]:
    """api_key from the env or the reference-compatible keys file
    (``api_key = <value>`` lines)."""
    key = os.environ.get('LAMBDA_API_KEY')
    if key:
        return key
    try:
        with open(os.path.expanduser(CREDENTIALS_PATH),
                  encoding='utf-8') as f:
            for line in f:
                if line.strip().startswith('api_key'):
                    return line.split('=', 1)[1].strip()
    except OSError:
        pass
    return None


def _requests_session():
    import requests
    return requests.Session()


# Test seam.
session_factory = _requests_session


class LambdaClient:

    def __init__(self, api_key: Optional[str] = None) -> None:
        self.api_key = api_key or read_api_key()
        if not self.api_key:
            raise exceptions.ProvisionError(
                'No Lambda Cloud API key (set LAMBDA_API_KEY or '
                f'write {CREDENTIALS_PATH}).')
        self.http = session_factory()

    def _call(self, method: str, path: str,
              json: Optional[Dict[str, Any]] = None) -> Any:
        resp = self.http.request(
            method, f'{API_ENDPOINT}{path}', json=json,
            headers={'Authorization': f'Bearer {self.api_key}'},
            timeout=60)
        try:
            body = resp.json()
        except ValueError:
            body = {}
        if resp.status_code >= 400:
            err = body.get('error', {})
            raise translate_error(
                f"{err.get('code', resp.status_code)}: "
                f"{err.get('message', resp.text[:200])}", path)
        return body.get('data')

    # ------------------------------------------------------------ ops
    def list_instances(self) -> list:
        return self._call('GET', '/instances') or []

    def launch(self, *, region: str, instance_type: str, name: str,
               ssh_key_names: list) -> list:
        data = self._call(
            'POST', '/instance-operations/launch',
            json={
                'region_name': region,
                'instance_type_name': instance_type,
                'ssh_key_names': ssh_key_names,
                'quantity': 1,
                'name': name,
            })
        return (data or {}).get('instance_ids', [])

    def terminate(self, instance_ids: list) -> None:
        self._call('POST', '/instance-operations/terminate',
                   json={'instance_ids': instance_ids})

    def list_ssh_keys(self) -> list:
        return self._call('GET', '/ssh-keys') or []

    def add_ssh_key(self, name: str, public_key: str) -> None:
        self._call('POST', '/ssh-keys',
                   json={'name': name, 'public_key': public_key})


def translate_error(message: str, what: str) -> Exception:
    blob = message.lower()
    if ('insufficient-capacity' in blob or 'capacity' in blob or
            'not enough' in blob):
        return exceptions.StockoutError(f'{what}: {message}')
    if 'quota' in blob or 'limit' in blob:
        return exceptions.QuotaExceededError(f'{what}: {message}')
    return exceptions.ProvisionError(f'{what}: {message}')
