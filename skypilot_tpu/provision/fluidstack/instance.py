"""FluidStack provision ops (nine-op contract).

Role of reference ``sky/provision/fluidstack/instance.py``,
re-designed stateless: NAME-scoped membership (``<cluster>-<idx>``),
one create per missing index with an idempotently-registered ssh key,
stop/start supported, delete by id.

Status mapping: FluidStack ``pending``/``provisioning``/``running``/
``stopping``/``stopped``/``terminated`` -> framework
'pending'/'running'/'stopped'/'terminated'.
"""
from __future__ import annotations

import hashlib
import re
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision import common
from skypilot_tpu.provision.fluidstack import api
from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)

_WAIT_TIMEOUT = 1800.0
_POLL_INTERVAL = 5.0

SSH_USER = 'ubuntu'


def _vm_name(cluster: str, idx: int) -> str:
    return f'{cluster}-{idx}'


def _cluster_instances(client: api.FluidstackClient,
                       cluster: str) -> Dict[str, Dict[str, Any]]:
    """name -> instance, EXACT ``<cluster>-<rank>`` match."""
    member = re.compile(re.escape(cluster) + r'-\d+\Z')
    out: Dict[str, Dict[str, Any]] = {}
    for inst in client.list_instances():
        name = inst.get('name') or ''
        if member.fullmatch(name):
            out[name] = inst
    return out


def _ensure_ssh_key(client: api.FluidstackClient,
                    public_key: Optional[str]) -> str:
    if not public_key:
        keys = client.list_ssh_keys()
        if not keys:
            raise exceptions.ProvisionError(
                'No SSH keys registered with FluidStack and no '
                'ssh_public_key provided.')
        return keys[0]['name']
    digest = hashlib.sha256(public_key.encode()).hexdigest()[:12]
    key_name = f'skytpu-{digest}'
    if not any(k.get('name') == key_name
               for k in client.list_ssh_keys()):
        client.add_ssh_key(key_name, public_key)
    return key_name


def _gpu_parts(instance_type: str) -> Dict[str, Any]:
    """'4x_H100_SXM5'-style catalog names -> create args."""
    m = re.match(r'(\d+)x_(.+)\Z', instance_type or '')
    if not m:
        raise exceptions.ProvisionError(
            f'Unparseable FluidStack instance type {instance_type!r} '
            "(expected '<n>x_<GPU>').")
    return {'gpu_count': int(m.group(1)), 'gpu_type': m.group(2)}


def bootstrap_instances(
        config: common.ProvisionConfig) -> common.ProvisionConfig:
    return config


def run_instances(
        config: common.ProvisionConfig) -> common.ProvisionRecord:
    node = config.node_config
    cluster = config.cluster_name_on_cloud
    client = api.FluidstackClient()
    key_name = _ensure_ssh_key(client, node.get('ssh_public_key'))
    gpu = _gpu_parts(node['instance_type'])
    created: List[str] = []
    resumed: List[str] = []
    existing = _cluster_instances(client, cluster)
    for idx in range(config.count):
        name = _vm_name(cluster, idx)
        inst = existing.get(name)
        if inst is not None:
            if inst.get('status') == 'stopped':
                client.start(inst['id'])
                resumed.append(inst['id'])
            continue
        created.append(client.create(
            name=name,
            gpu_type=gpu['gpu_type'],
            gpu_count=gpu['gpu_count'],
            region=config.region,
            ssh_key_name=key_name))
    return common.ProvisionRecord(
        provider_name='fluidstack',
        cluster_name_on_cloud=cluster,
        region=config.region,
        zone=config.zone,
        created_instance_ids=created,
        resumed_instance_ids=resumed,
        head_instance_id=_vm_name(cluster, 0),
    )


def _status(inst: Dict[str, Any]) -> str:
    return {
        'running': 'running',
        'pending': 'pending',
        'provisioning': 'pending',
        'stopping': 'stopped',
        'stopped': 'stopped',
        'terminated': 'terminated',
    }.get(inst.get('status', ''), 'pending')


def wait_instances(cluster_name_on_cloud: str, region: str,
                   zone: Optional[str], state: Optional[str]) -> None:
    del region, zone
    client = api.FluidstackClient()
    want = state or 'running'
    deadline = time.time() + _WAIT_TIMEOUT
    while time.time() < deadline:
        insts = _cluster_instances(client, cluster_name_on_cloud)
        if want == 'terminated':
            if not insts or all(_status(i) == 'terminated'
                                for i in insts.values()):
                return
        elif insts and all(_status(i) == want
                           for i in insts.values()):
            return
        time.sleep(_POLL_INTERVAL)
    raise exceptions.ProvisionError(
        f'Timed out waiting for {cluster_name_on_cloud} to reach '
        f'{want!r}.')


def query_instances(
        cluster_name_on_cloud: str, region: str, zone: Optional[str],
        non_terminated_only: bool = True) -> Dict[str, Optional[str]]:
    del region, zone
    client = api.FluidstackClient()
    out: Dict[str, Optional[str]] = {}
    for name, inst in _cluster_instances(
            client, cluster_name_on_cloud).items():
        status = _status(inst)
        if non_terminated_only and status == 'terminated':
            continue
        out[name] = status
    return out


def get_cluster_info(cluster_name_on_cloud: str, region: str,
                     zone: Optional[str]) -> common.ClusterInfo:
    client = api.FluidstackClient()
    infos: Dict[str, List[common.InstanceInfo]] = {}
    for name, inst in sorted(
            _cluster_instances(client, cluster_name_on_cloud).items()):
        infos[name] = [
            common.InstanceInfo(
                instance_id=inst.get('id', name),
                internal_ip=inst.get('private_ip') or
                inst.get('ip_address', ''),
                external_ip=inst.get('ip_address'),
                host_index=0,
                tags={'name': name},
            )
        ]
    head = min(infos) if infos else None
    return common.ClusterInfo(
        provider_name='fluidstack',
        cluster_name_on_cloud=cluster_name_on_cloud,
        region=region,
        zone=zone,
        instances=infos,
        head_instance_id=head,
        ssh_user=SSH_USER,
    )


def stop_instances(cluster_name_on_cloud: str, region: str,
                   zone: Optional[str]) -> None:
    del region, zone
    client = api.FluidstackClient()
    for inst in _cluster_instances(client,
                                   cluster_name_on_cloud).values():
        if _status(inst) == 'running':
            client.stop(inst['id'])


def terminate_instances(cluster_name_on_cloud: str, region: str,
                        zone: Optional[str]) -> None:
    del region, zone
    client = api.FluidstackClient()
    for inst in _cluster_instances(client,
                                   cluster_name_on_cloud).values():
        if _status(inst) != 'terminated':
            client.delete(inst['id'])


def open_ports(cluster_name_on_cloud: str, ports: List[str],
               region: str, zone: Optional[str]) -> None:
    logger.info('fluidstack: instances have open ingress by default; '
                'open_ports(%s) is a no-op.', ports)


def cleanup_ports(cluster_name_on_cloud: str, region: str,
                  zone: Optional[str]) -> None:
    pass
