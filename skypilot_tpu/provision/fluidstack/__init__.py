"""FluidStack provision plugin."""
