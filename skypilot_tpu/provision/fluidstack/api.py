"""Minimal FluidStack REST client.

Role of reference ``sky/provision/fluidstack/fluidstack_utils.py``,
re-designed: api-key REST against ``platform.fluidstack.io``.
Instances are created with POST /instances, stopped/started with
``/instances/<id>/stop|start``, deleted with DELETE, listed via
GET /instances. Cluster membership rides instance NAMES
(``<cluster>-<idx>``). Same fake-session test seam as the
lambda_cloud/runpod plugins.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions

API_ENDPOINT = 'https://platform.fluidstack.io'
CREDENTIALS_PATH = '~/.fluidstack/api_key'


def read_api_key() -> Optional[str]:
    key = os.environ.get('FLUIDSTACK_API_KEY')
    if key:
        return key
    try:
        with open(os.path.expanduser(CREDENTIALS_PATH),
                  encoding='utf-8') as f:
            return f.read().strip() or None
    except OSError:
        return None


def _requests_session():
    import requests
    return requests.Session()


# Test seam.
session_factory = _requests_session


class FluidstackClient:

    def __init__(self, api_key: Optional[str] = None) -> None:
        self.api_key = api_key or read_api_key()
        if not self.api_key:
            raise exceptions.ProvisionError(
                'No FluidStack API key (set FLUIDSTACK_API_KEY or '
                f'write {CREDENTIALS_PATH}).')
        self.http = session_factory()

    def _call(self, method: str, path: str,
              json: Optional[Dict[str, Any]] = None) -> Any:
        resp = self.http.request(
            method, f'{API_ENDPOINT}{path}', json=json,
            headers={'api-key': self.api_key}, timeout=60)
        try:
            body = resp.json()
        except ValueError:
            body = {}
        if resp.status_code >= 400:
            msg = (body.get('message') or body.get('error') or
                   resp.text[:200])
            raise translate_error(str(msg), path)
        return body

    # ------------------------------------------------------------ ops
    def list_instances(self) -> List[Dict[str, Any]]:
        return self._call('GET', '/instances') or []

    def create(self, *, name: str, gpu_type: str, gpu_count: int,
               region: str, ssh_key_name: str) -> str:
        body = self._call(
            'POST', '/instances',
            json={
                'name': name,
                'gpu_type': gpu_type,
                'gpu_count': gpu_count,
                'region': region,
                'ssh_key': ssh_key_name,
            })
        return body['id']

    def stop(self, instance_id: str) -> None:
        self._call('POST', f'/instances/{instance_id}/stop')

    def start(self, instance_id: str) -> None:
        self._call('POST', f'/instances/{instance_id}/start')

    def delete(self, instance_id: str) -> None:
        self._call('DELETE', f'/instances/{instance_id}')

    def list_ssh_keys(self) -> List[Dict[str, Any]]:
        return self._call('GET', '/ssh_keys') or []

    def add_ssh_key(self, name: str, public_key: str) -> None:
        self._call('POST', '/ssh_keys',
                   json={'name': name, 'public_key': public_key})


def translate_error(message: str, what: str) -> Exception:
    blob = message.lower()
    if ('insufficient capacity' in blob or 'no capacity' in blob or
            'out of stock' in blob or 'sold out' in blob):
        return exceptions.StockoutError(f'{what}: {message}')
    if 'quota' in blob or 'limit' in blob:
        return exceptions.QuotaExceededError(f'{what}: {message}')
    return exceptions.ProvisionError(f'{what}: {message}')
