"""Provision-layer dataclasses.

Re-design of reference ``sky/provision/common.py:39-109``
(ProvisionConfig / ProvisionRecord / InstanceInfo / ClusterInfo), with
TPU pod semantics: one *instance* may expose several *hosts* (the TPU-VM
workers of a slice), each of which becomes a gang rank.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class ProvisionConfig:
    """Everything a provider plugin needs to create a cluster."""
    provider_name: str
    cluster_name: str
    cluster_name_on_cloud: str
    region: str
    zone: Optional[str]
    # Output of Cloud.make_deploy_resources_variables().
    node_config: Dict[str, Any]
    # Logical node count (slices for TPU; VMs otherwise).
    count: int
    # Authentication / ssh info.
    ssh_user: str = 'skytpu'
    ssh_private_key: Optional[str] = None
    ports_to_open: Optional[List[str]] = None


@dataclasses.dataclass
class ProvisionRecord:
    """Result of run_instances."""
    provider_name: str
    cluster_name_on_cloud: str
    region: str
    zone: Optional[str]
    # instance ids created or reused in this call
    created_instance_ids: List[str] = dataclasses.field(default_factory=list)
    resumed_instance_ids: List[str] = dataclasses.field(default_factory=list)
    head_instance_id: Optional[str] = None

    def is_instance_just_booted(self, instance_id: str) -> bool:
        return (instance_id in self.created_instance_ids or
                instance_id in self.resumed_instance_ids)


@dataclasses.dataclass
class InstanceInfo:
    """One host (a TPU-VM worker or a VM)."""
    instance_id: str
    internal_ip: str
    external_ip: Optional[str]
    # index of this host within its instance (TPU worker index).
    host_index: int = 0
    tags: Dict[str, str] = dataclasses.field(default_factory=dict)
    # ssh port (command runner)
    ssh_port: int = 22

    def get_feasible_ip(self) -> str:
        return self.external_ip or self.internal_ip


@dataclasses.dataclass
class ClusterInfo:
    """Full description of a provisioned cluster's hosts."""
    provider_name: str
    cluster_name_on_cloud: str
    region: str
    zone: Optional[str]
    # instance_id -> hosts of that instance (len>1 for TPU pod slices).
    instances: Dict[str, List[InstanceInfo]]
    head_instance_id: Optional[str]
    ssh_user: str = 'skytpu'
    # Provider-specific extras (e.g. TPU topology string).
    provider_config: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # Set by the backend when the task's image_id names a docker image
    # (docker_utils.make_docker_config): every host then runs job
    # commands inside this container.
    docker_config: Optional[Dict[str, Any]] = None

    def all_hosts(self) -> List[InstanceInfo]:
        """Hosts in stable rank order: head instance first, then by id;
        within an instance, by host_index.

        Rank = position in this list (reference rank assignment via
        sorted stable IP list, cloud_vm_ray_backend.py:536-541).
        """
        out: List[InstanceInfo] = []
        ids = sorted(self.instances)
        if self.head_instance_id in self.instances:
            ids.remove(self.head_instance_id)
            ids.insert(0, self.head_instance_id)
        for instance_id in ids:
            hosts = sorted(self.instances[instance_id],
                           key=lambda h: h.host_index)
            out.extend(hosts)
        return out

    def ip_list(self) -> List[str]:
        return [h.get_feasible_ip() for h in self.all_hosts()]

    def num_hosts(self) -> int:
        return sum(len(v) for v in self.instances.values())

    def get_head_instance(self) -> Optional[InstanceInfo]:
        if self.head_instance_id is None:
            return None
        hosts = self.instances.get(self.head_instance_id)
        return hosts[0] if hosts else None
