"""Minimal Nebius compute REST client.

Role of reference ``sky/provision/nebius/utils.py`` (which drives the
``nebius`` SDK); re-designed as a token-bearer JSON client against the
compute endpoint. Instances carry gRPC-style SCREAMING statuses
(PROVISIONING/RUNNING/STOPPING/STOPPED/DELETING) and errors carry a
``code`` in the same vocabulary (RESOURCE_EXHAUSTED, QUOTA_EXCEEDED)
— the error taxonomy maps codes, not prose. Cluster membership rides
instance NAMES (``<cluster>-<idx>``). Same fake-session test seam as
the other REST plugins.
"""
from __future__ import annotations

import json as json_lib
import os
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions

API_ENDPOINT = 'https://compute.api.nebius.cloud/v1'
CREDENTIALS_PATH = '~/.nebius/credentials.json'


def read_token() -> Optional[str]:
    token = os.environ.get('NEBIUS_IAM_TOKEN')
    if token:
        return token
    try:
        with open(os.path.expanduser(CREDENTIALS_PATH),
                  encoding='utf-8') as f:
            return json_lib.load(f).get('token')
    except (OSError, ValueError):
        return None


def _requests_session():
    import requests
    return requests.Session()


# Test seam.
session_factory = _requests_session


class NebiusClient:

    def __init__(self, token: Optional[str] = None) -> None:
        self.token = token or read_token()
        if not self.token:
            raise exceptions.ProvisionError(
                'No Nebius IAM token (set NEBIUS_IAM_TOKEN or write '
                f'{CREDENTIALS_PATH}).')
        self.http = session_factory()

    def _call(self, method: str, path: str,
              json: Optional[Dict[str, Any]] = None) -> Any:
        resp = self.http.request(
            method, f'{API_ENDPOINT}{path}', json=json,
            headers={'Authorization': f'Bearer {self.token}'},
            timeout=60)
        try:
            body = resp.json()
        except ValueError:
            body = {}
        if resp.status_code >= 400:
            raise translate_error(body.get('code', ''),
                                  body.get('message',
                                           resp.text[:200]), path)
        return body

    # ------------------------------------------------------------ ops
    def list_instances(self) -> List[Dict[str, Any]]:
        return self._call('GET', '/instances').get('items', [])

    def create(self, *, name: str, platform: str, preset: str,
               region: str, public_key: Optional[str]) -> str:
        body = self._call(
            'POST', '/instances',
            json={
                'name': name,
                'platform': platform,         # e.g. gpu-h100-sxm
                'preset': preset,             # e.g. 8gpu-128vcpu
                'region': region,
                'ssh_public_key': public_key or '',
            })
        return body['id']

    def start(self, instance_id: str) -> None:
        self._call('POST', f'/instances/{instance_id}:start')

    def stop(self, instance_id: str) -> None:
        self._call('POST', f'/instances/{instance_id}:stop')

    def delete(self, instance_id: str) -> None:
        self._call('DELETE', f'/instances/{instance_id}')


def translate_error(code: str, message: str, what: str) -> Exception:
    """Nebius errors carry structured codes — map those, not prose."""
    code = (code or '').upper()
    if code == 'RESOURCE_EXHAUSTED':
        return exceptions.StockoutError(f'{what}: {message}')
    if code == 'QUOTA_EXCEEDED':
        return exceptions.QuotaExceededError(f'{what}: {message}')
    return exceptions.ProvisionError(
        f'{what}: {code or "ERROR"}: {message}')
