"""Nebius provision ops (nine-op contract).

Role of reference ``sky/provision/nebius/instance.py``, re-designed
stateless: NAME-scoped membership (``<cluster>-<idx>``), catalog
instance types of the form ``<platform>_<preset>`` split into the
API's (platform, preset) pair, stop/start supported, delete by id.

Status mapping: PROVISIONING/STARTING -> 'pending', RUNNING ->
'running', STOPPING/STOPPED -> 'stopped', DELETING/DELETED ->
'terminated'.
"""
from __future__ import annotations

import re
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision import common
from skypilot_tpu.provision.nebius import api
from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)

_WAIT_TIMEOUT = 1800.0
_POLL_INTERVAL = 5.0

SSH_USER = 'ubuntu'


def _vm_name(cluster: str, idx: int) -> str:
    return f'{cluster}-{idx}'


def _cluster_instances(client: api.NebiusClient,
                       cluster: str) -> Dict[str, Dict[str, Any]]:
    """name -> instance, EXACT ``<cluster>-<rank>`` match."""
    member = re.compile(re.escape(cluster) + r'-\d+\Z')
    out: Dict[str, Dict[str, Any]] = {}
    for inst in client.list_instances():
        name = inst.get('name') or ''
        if member.fullmatch(name):
            out[name] = inst
    return out


def _platform_preset(instance_type: str) -> Dict[str, str]:
    """'gpu-h100-sxm_8gpu-128vcpu' catalog names -> API pair."""
    parts = (instance_type or '').split('_', 1)
    if len(parts) != 2:
        raise exceptions.ProvisionError(
            f'Unparseable Nebius instance type {instance_type!r} '
            "(expected '<platform>_<preset>').")
    return {'platform': parts[0], 'preset': parts[1]}


def bootstrap_instances(
        config: common.ProvisionConfig) -> common.ProvisionConfig:
    return config


def run_instances(
        config: common.ProvisionConfig) -> common.ProvisionRecord:
    node = config.node_config
    cluster = config.cluster_name_on_cloud
    client = api.NebiusClient()
    pp = _platform_preset(node['instance_type'])
    created: List[str] = []
    resumed: List[str] = []
    existing = _cluster_instances(client, cluster)
    for idx in range(config.count):
        name = _vm_name(cluster, idx)
        inst = existing.get(name)
        if inst is not None:
            if _status(inst) == 'stopped':
                client.start(inst['id'])
                resumed.append(inst['id'])
            continue
        created.append(client.create(
            name=name,
            platform=pp['platform'],
            preset=pp['preset'],
            region=config.region,
            public_key=node.get('ssh_public_key')))
    return common.ProvisionRecord(
        provider_name='nebius',
        cluster_name_on_cloud=cluster,
        region=config.region,
        zone=config.zone,
        created_instance_ids=created,
        resumed_instance_ids=resumed,
        head_instance_id=_vm_name(cluster, 0),
    )


def _status(inst: Dict[str, Any]) -> str:
    return {
        'PROVISIONING': 'pending',
        'STARTING': 'pending',
        'RUNNING': 'running',
        'STOPPING': 'stopped',
        'STOPPED': 'stopped',
        'DELETING': 'terminated',
        'DELETED': 'terminated',
    }.get(inst.get('status', ''), 'pending')


def wait_instances(cluster_name_on_cloud: str, region: str,
                   zone: Optional[str], state: Optional[str]) -> None:
    del region, zone
    client = api.NebiusClient()
    want = state or 'running'
    deadline = time.time() + _WAIT_TIMEOUT
    while time.time() < deadline:
        insts = _cluster_instances(client, cluster_name_on_cloud)
        if want == 'terminated':
            if not insts or all(_status(i) == 'terminated'
                                for i in insts.values()):
                return
        elif insts and all(_status(i) == want
                           for i in insts.values()):
            return
        time.sleep(_POLL_INTERVAL)
    raise exceptions.ProvisionError(
        f'Timed out waiting for {cluster_name_on_cloud} to reach '
        f'{want!r}.')


def query_instances(
        cluster_name_on_cloud: str, region: str, zone: Optional[str],
        non_terminated_only: bool = True) -> Dict[str, Optional[str]]:
    del region, zone
    client = api.NebiusClient()
    out: Dict[str, Optional[str]] = {}
    for name, inst in _cluster_instances(
            client, cluster_name_on_cloud).items():
        status = _status(inst)
        if non_terminated_only and status == 'terminated':
            continue
        out[name] = status
    return out


def get_cluster_info(cluster_name_on_cloud: str, region: str,
                     zone: Optional[str]) -> common.ClusterInfo:
    client = api.NebiusClient()
    infos: Dict[str, List[common.InstanceInfo]] = {}
    for name, inst in sorted(
            _cluster_instances(client, cluster_name_on_cloud).items()):
        infos[name] = [
            common.InstanceInfo(
                instance_id=inst.get('id', name),
                internal_ip=inst.get('private_ipv4', ''),
                external_ip=inst.get('public_ipv4'),
                host_index=0,
                tags={'name': name},
            )
        ]
    head = min(infos) if infos else None
    return common.ClusterInfo(
        provider_name='nebius',
        cluster_name_on_cloud=cluster_name_on_cloud,
        region=region,
        zone=zone,
        instances=infos,
        head_instance_id=head,
        ssh_user=SSH_USER,
    )


def stop_instances(cluster_name_on_cloud: str, region: str,
                   zone: Optional[str]) -> None:
    del region, zone
    client = api.NebiusClient()
    for inst in _cluster_instances(client,
                                   cluster_name_on_cloud).values():
        if _status(inst) == 'running':
            client.stop(inst['id'])


def terminate_instances(cluster_name_on_cloud: str, region: str,
                        zone: Optional[str]) -> None:
    del region, zone
    client = api.NebiusClient()
    for inst in _cluster_instances(client,
                                   cluster_name_on_cloud).values():
        if _status(inst) != 'terminated':
            client.delete(inst['id'])


def open_ports(cluster_name_on_cloud: str, ports: List[str],
               region: str, zone: Optional[str]) -> None:
    logger.info('nebius: default security group allows ingress; '
                'open_ports(%s) is a no-op.', ports)


def cleanup_ports(cluster_name_on_cloud: str, region: str,
                  zone: Optional[str]) -> None:
    pass
