"""Nebius provision plugin."""
