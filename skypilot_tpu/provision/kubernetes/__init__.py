"""Kubernetes provision plugin (pods-as-hosts, GKE TPU slices)."""
