"""Kubernetes provision ops: pods-as-hosts, GKE TPU slices.

Re-design of reference ``sky/provision/kubernetes/instance.py`` (pods
as nodes) + GKE TPU label handling from
``sky/provision/kubernetes/utils.py`` (GKELabelFormatter): every host
of a cluster is a pod labeled with the cluster name and host index;
TPU slice hosts add GKE's node selectors
(``cloud.google.com/gke-tpu-accelerator`` / ``gke-tpu-topology``) and
request ``google.com/tpu`` chips. Ops are stateless: the label
selector against the API server is the source of truth.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision import common
from skypilot_tpu.provision.kubernetes import api
from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)

_CLUSTER_LABEL = 'skypilot-tpu/cluster'
_ROLE_LABEL = 'skypilot-tpu/role'
_HOST_INDEX_LABEL = 'skypilot-tpu/host-index'

# GKE node selectors for TPU slices (reference
# sky/provision/kubernetes/utils.py GKELabelFormatter).
GKE_TPU_ACCEL_LABEL = 'cloud.google.com/gke-tpu-accelerator'
GKE_TPU_TOPO_LABEL = 'cloud.google.com/gke-tpu-topology'
TPU_RESOURCE = 'google.com/tpu'

# generation -> GKE accelerator label value (GKE docs; reference
# utils.py GKE_TPU_ACCELERATOR_TO_GENERATION inverse).
GKE_TPU_ACCELERATORS = {
    'v4': 'tpu-v4-podslice',
    'v5e': 'tpu-v5-lite-podslice',
    'v5p': 'tpu-v5p-slice',
    'v6e': 'tpu-v6e-slice',
}

DEFAULT_IMAGE = 'python:3.11-slim'

_WAIT_TIMEOUT = 1200.0
_POLL_INTERVAL = 5.0


def _client(context: Optional[str] = None) -> api.KubeClient:
    return api.KubeClient(context)


def _pod_name(cluster: str, idx: int) -> str:
    return f'{cluster}-{idx}' if idx else f'{cluster}-head'


def _selector(cluster: str) -> str:
    return f'{_CLUSTER_LABEL}={cluster}'


def bootstrap_instances(
        config: common.ProvisionConfig) -> common.ProvisionConfig:
    """No networks/firewalls to set up: pod-to-pod traffic is open
    inside a cluster; ports_to_open is a no-op (reference exposes
    services via ingress, out of scope for the compute path)."""
    return config


def _pod_manifest(config: common.ProvisionConfig, name: str,
                  idx: int) -> Dict[str, Any]:
    node = config.node_config
    labels = {
        _CLUSTER_LABEL: config.cluster_name_on_cloud,
        _ROLE_LABEL: 'head' if idx == 0 else 'worker',
        _HOST_INDEX_LABEL: str(idx),
    }
    labels.update(node.get('labels') or {})
    resources: Dict[str, Any] = {}
    if node.get('cpus'):
        # '8+' style requests become the lower bound as a k8s quantity.
        resources['cpu'] = str(node['cpus']).rstrip('+')
    if node.get('memory'):
        resources['memory'] = f"{str(node['memory']).rstrip('+')}Gi"
    container: Dict[str, Any] = {
        'name': 'skytpu',
        'image': node.get('image_id') or DEFAULT_IMAGE,
        'command': ['/bin/sh', '-c', 'sleep infinity'],
    }
    spec: Dict[str, Any] = {
        'restartPolicy': 'Never',
        'containers': [container],
    }
    if node.get('tpu_vm'):
        # GKE TPU slice: schedule onto the right podslice node pool
        # and claim this host's chips. GKE's device plugin wires the
        # slice topology env (TPU_WORKER_ID etc.) from these.
        spec['nodeSelector'] = {
            GKE_TPU_ACCEL_LABEL: node['gke_accelerator'],
            GKE_TPU_TOPO_LABEL: node['tpu_topology'],
        }
        resources[TPU_RESOURCE] = str(node['chips_per_host'])
        container['env'] = [
            {'name': 'TPU_WORKER_ID', 'value': str(idx)},
        ]
    if resources:
        container['resources'] = {'requests': dict(resources),
                                  'limits': dict(resources)}
    return {
        'apiVersion': 'v1',
        'kind': 'Pod',
        'metadata': {'name': name, 'labels': labels},
        'spec': spec,
    }


def run_instances(
        config: common.ProvisionConfig) -> common.ProvisionRecord:
    """Create missing pods up to count*num_hosts. Idempotent."""
    client = _client(config.node_config.get('context'))
    cluster = config.cluster_name_on_cloud
    num_hosts = int(config.node_config.get('num_hosts') or 1)
    want = config.count * num_hosts
    existing = {
        p['metadata']['name']: p
        for p in client.list_pods(_selector(cluster))
        if p.get('metadata', {}).get('deletionTimestamp') is None
    }
    created: List[str] = []
    for idx in range(want):
        name = _pod_name(cluster, idx)
        if name in existing:
            phase = existing[name].get('status', {}).get('phase')
            if phase in ('Succeeded', 'Failed'):
                client.delete_pod(name)
                # Deletion is asynchronous (grace period); creating
                # the same name while the old pod is Terminating 409s
                # into create_pod's idempotent path and returns the
                # DYING pod. Wait for the name to free first.
                deadline = time.time() + 120
                while (client.get_pod(name) is not None and
                       time.time() < deadline):
                    time.sleep(_POLL_INTERVAL)
            else:
                continue
        client.create_pod(_pod_manifest(config, name, idx))
        created.append(name)
    return common.ProvisionRecord(
        provider_name='kubernetes',
        cluster_name_on_cloud=cluster,
        region=config.region,
        zone=config.zone,
        created_instance_ids=created,
        head_instance_id=_pod_name(cluster, 0),
    )


def wait_instances(cluster_name_on_cloud: str, region: str,
                   zone: Optional[str], state: Optional[str]) -> None:
    del zone
    # Provider contract (matches aws/local): state=None waits for
    # 'running'; teardown waits must pass state='terminated' explicitly.
    state = state or 'running'
    client = _client(region)
    deadline = time.time() + _WAIT_TIMEOUT
    want_gone = state == 'terminated'
    while time.time() < deadline:
        pods = client.list_pods(_selector(cluster_name_on_cloud))
        if state == 'running':
            bad = [
                p for p in pods
                if p.get('status', {}).get('phase') != 'Running'
            ]
            if pods and not bad:
                return
            # A pod the scheduler cannot place is a capacity signal —
            # surface it as stockout for the failover provisioner.
            for p in bad:
                for cond in p.get('status', {}).get('conditions', []):
                    if (cond.get('reason') == 'Unschedulable' and
                            'Insufficient' in str(cond.get('message'))):
                        raise exceptions.StockoutError(
                            f"pod {p['metadata']['name']}: "
                            f"{cond.get('message')}")
        elif want_gone and not pods:
            return
        time.sleep(_POLL_INTERVAL)
    raise exceptions.ProvisionError(
        f'Timed out waiting for {cluster_name_on_cloud} pods to reach '
        f'{state!r}.')


def query_instances(
        cluster_name_on_cloud: str, region: str, zone: Optional[str],
        non_terminated_only: bool = True) -> Dict[str, Optional[str]]:
    """pod name -> 'running'|'pending'|'terminated' (pods never
    'stop': no STOP support on kubernetes)."""
    del zone
    client = _client(region)
    out: Dict[str, Optional[str]] = {}
    for pod in client.list_pods(_selector(cluster_name_on_cloud)):
        phase = pod.get('status', {}).get('phase', '')
        if pod.get('metadata', {}).get('deletionTimestamp') is not None:
            status = 'terminated'
        elif phase == 'Running':
            status = 'running'
        elif phase == 'Pending':
            status = 'pending'
        else:  # Succeeded / Failed / Unknown
            status = 'terminated'
        if non_terminated_only and status == 'terminated':
            continue
        out[pod['metadata']['name']] = status
    return out


def get_cluster_info(cluster_name_on_cloud: str, region: str,
                     zone: Optional[str]) -> common.ClusterInfo:
    from skypilot_tpu import skypilot_config
    client = _client(region)
    pods = client.list_pods(_selector(cluster_name_on_cloud))
    # Exec-less clusters: `kubernetes: {runner: port-forward}` in
    # ~/.skytpu/config.yaml routes commands over SSH through a
    # kubectl port-forward tunnel instead of the exec channel
    # (KubernetesPortForwardRunner; the pod must run sshd).
    runner_mode = skypilot_config.get_nested(('kubernetes', 'runner'),
                                             None)
    instances: Dict[str, List[common.InstanceInfo]] = {}
    head_id = None
    for pod in sorted(
            pods,
            key=lambda p: int(p['metadata'].get('labels', {}).get(
                _HOST_INDEX_LABEL, 0))):
        meta = pod['metadata']
        name = meta['name']
        if meta.get('labels', {}).get(_ROLE_LABEL) == 'head':
            head_id = name
        instances[name] = [
            common.InstanceInfo(
                instance_id=name,
                internal_ip=pod.get('status', {}).get('podIP', ''),
                external_ip=None,
                host_index=0,
                tags={
                    # Host-entry routing: command runner goes through
                    # kubectl exec, not ssh (no sshd in the pods) —
                    # unless runner_mode requests the port-forward
                    # tunnel for exec-less clusters.
                    'k8s_pod': name,
                    'k8s_namespace': client.namespace,
                    'k8s_context': client.ctx.context_name,
                    **({'k8s_runner_mode': runner_mode}
                       if runner_mode else {}),
                },
            )
        ]
    return common.ClusterInfo(
        provider_name='kubernetes',
        cluster_name_on_cloud=cluster_name_on_cloud,
        region=region,
        zone=zone,
        instances=instances,
        head_instance_id=head_id,
        ssh_user='root',
        provider_config={'namespace': client.namespace},
    )


def stop_instances(cluster_name_on_cloud: str, region: str,
                   zone: Optional[str]) -> None:
    raise exceptions.NotSupportedError(
        'Kubernetes pods cannot be stopped, only terminated '
        '(the cloud layer declares STOP unsupported).')


def terminate_instances(cluster_name_on_cloud: str, region: str,
                        zone: Optional[str]) -> None:
    del zone
    client = _client(region)
    for pod in client.list_pods(_selector(cluster_name_on_cloud)):
        client.delete_pod(pod['metadata']['name'])


def open_ports(cluster_name_on_cloud: str, ports: List[str],
               region: str, zone: Optional[str]) -> None:
    """Pod-to-pod traffic is open in-cluster; external exposure would
    be a Service/Ingress (reference parity gap, tracked)."""
    logger.info('kubernetes: open_ports(%s) is a no-op in-cluster.',
                ports)


def cleanup_ports(cluster_name_on_cloud: str, region: str,
                  zone: Optional[str]) -> None:
    pass
