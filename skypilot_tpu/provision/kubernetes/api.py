"""Minimal Kubernetes REST client driven from kubeconfig.

Re-design of the reference's Kubernetes access
(``sky/adaptors/kubernetes.py`` + ``sky/provision/kubernetes/``): the
reference lazy-imports the official ``kubernetes`` client library;
here the API surface we need (pods + nodes in one namespace) is small
enough to drive with plain ``requests`` against the API server from a
parsed kubeconfig — no client library, and the same fake-session test
seam as the GCP plugin (``provision/gcp/api.py``).
"""
from __future__ import annotations

import base64
import dataclasses
import json
import os
import tempfile
from typing import Any, Callable, Dict, List, Optional, Tuple

from skypilot_tpu import exceptions
from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)

DEFAULT_NAMESPACE = 'default'


@dataclasses.dataclass
class KubeContext:
    """Connection info resolved from one kubeconfig context."""
    context_name: str
    server: str
    namespace: str = DEFAULT_NAMESPACE
    token: Optional[str] = None
    # Paths (possibly materialized from inline base64 data).
    ca_cert: Optional[str] = None
    client_cert: Optional[str] = None
    client_key: Optional[str] = None
    insecure: bool = False


def kubeconfig_path() -> str:
    return os.path.expanduser(
        os.environ.get('KUBECONFIG', '~/.kube/config'))


def _materialize(data_b64: Optional[str],
                 path: Optional[str]) -> Optional[str]:
    """kubeconfig allows certs inline (-data) or as file paths."""
    if path:
        return os.path.expanduser(path)
    if data_b64:
        f = tempfile.NamedTemporaryFile(delete=False, suffix='.pem')
        f.write(base64.b64decode(data_b64))
        f.close()
        return f.name
    return None


def load_kubeconfig(context: Optional[str] = None) -> KubeContext:
    """Parse kubeconfig and resolve one context to connection info."""
    import yaml
    path = kubeconfig_path()
    if not os.path.exists(path):
        raise exceptions.ProvisionError(
            f'No kubeconfig at {path}; set KUBECONFIG or create '
            '~/.kube/config.')
    with open(path, encoding='utf-8') as f:
        cfg = yaml.safe_load(f) or {}
    ctx_name = context or cfg.get('current-context')
    if not ctx_name:
        raise exceptions.ProvisionError(
            f'kubeconfig {path} has no current-context.')
    by_name = lambda items: {i['name']: i for i in (items or [])}
    contexts = by_name(cfg.get('contexts'))
    clusters = by_name(cfg.get('clusters'))
    users = by_name(cfg.get('users'))
    if ctx_name not in contexts:
        raise exceptions.ProvisionError(
            f'Context {ctx_name!r} not in kubeconfig {path}.')
    ctx = contexts[ctx_name]['context']
    cluster = clusters.get(ctx.get('cluster'), {}).get('cluster', {})
    user = users.get(ctx.get('user'), {}).get('user', {})
    token = user.get('token')
    return KubeContext(
        context_name=ctx_name,
        server=cluster.get('server', ''),
        namespace=ctx.get('namespace') or DEFAULT_NAMESPACE,
        token=token,
        ca_cert=_materialize(cluster.get('certificate-authority-data'),
                             cluster.get('certificate-authority')),
        client_cert=_materialize(user.get('client-certificate-data'),
                                 user.get('client-certificate')),
        client_key=_materialize(user.get('client-key-data'),
                                user.get('client-key')),
        insecure=bool(cluster.get('insecure-skip-tls-verify')),
    )


def _session_factory(ctx: KubeContext):
    import requests
    session = requests.Session()
    if ctx.token:
        session.headers['Authorization'] = f'Bearer {ctx.token}'
    if ctx.client_cert and ctx.client_key:
        session.cert = (ctx.client_cert, ctx.client_key)
    if ctx.insecure:
        session.verify = False
    elif ctx.ca_cert:
        session.verify = ctx.ca_cert
    return session


# Test seam: tests replace this with a fake session maker.
session_factory: Callable = _session_factory


def translate_error(status_code: int, body: Dict[str, Any],
                    what: str) -> exceptions.ProvisionError:
    """Map a Kubernetes Status error onto typed provision errors.

    Unschedulable / exhausted-quota surface as stockout/quota so the
    failover provisioner blocks the right granularity (same taxonomy
    as provision/gcp/api.py translate_error).
    """
    message = str(body.get('message', body)) if isinstance(
        body, dict) else str(body)
    low = message.lower()
    if status_code == 403 and 'exceeded quota' in low:
        return exceptions.QuotaExceededError(f'{what}: {message}')
    if 'insufficient' in low or 'unschedulable' in low:
        return exceptions.StockoutError(f'{what}: {message}')
    return exceptions.ProvisionError(
        f'{what}: HTTP {status_code}: {message}')


class KubeClient:
    """Pods/nodes operations in one namespace."""

    def __init__(self, context: Optional[str] = None) -> None:
        self.ctx = load_kubeconfig(context)
        self._session = None

    @property
    def session(self):
        if self._session is None:
            self._session = session_factory(self.ctx)
        return self._session

    @property
    def namespace(self) -> str:
        return self.ctx.namespace

    def _request(self, method: str, path: str,
                 body: Optional[Dict] = None,
                 params: Optional[Dict] = None,
                 what: str = 'kubernetes api'
                 ) -> Tuple[int, Dict[str, Any]]:
        url = self.ctx.server.rstrip('/') + path
        # Explicit bounded (connect, read) timeout (skytpu-lint
        # STL012): an unresponsive apiserver must fail the call, not
        # hang the provisioner.
        resp = self.session.request(method, url, json=body,
                                    params=params, timeout=(10, 120))
        try:
            payload = resp.json()
        except (ValueError, json.JSONDecodeError):
            payload = {'message': resp.text}
        return resp.status_code, payload

    def _check(self, status: int, body: Dict[str, Any],
               what: str, ok_missing: bool = False) -> Dict[str, Any]:
        if status == 404 and ok_missing:
            return {}
        if status >= 400:
            raise translate_error(status, body, what)
        return body

    # ------------------------------------------------------------ pods
    def _pods_path(self, name: str = '') -> str:
        base = f'/api/v1/namespaces/{self.namespace}/pods'
        return f'{base}/{name}' if name else base

    def create_pod(self, manifest: Dict[str, Any]) -> Dict[str, Any]:
        status, body = self._request('POST', self._pods_path(),
                                     body=manifest)
        if status == 409:  # already exists — idempotent create
            return self.get_pod(manifest['metadata']['name'])
        return self._check(status, body,
                           f"create pod {manifest['metadata']['name']}")

    def get_pod(self, name: str) -> Optional[Dict[str, Any]]:
        status, body = self._request('GET', self._pods_path(name))
        if status == 404:
            return None
        return self._check(status, body, f'get pod {name}')

    def list_pods(self, label_selector: str) -> List[Dict[str, Any]]:
        status, body = self._request(
            'GET', self._pods_path(),
            params={'labelSelector': label_selector})
        body = self._check(status, body, 'list pods')
        return body.get('items', [])

    def delete_pod(self, name: str) -> None:
        status, body = self._request('DELETE', self._pods_path(name))
        self._check(status, body, f'delete pod {name}',
                    ok_missing=True)

    # ----------------------------------------------------------- nodes
    def list_nodes(self) -> List[Dict[str, Any]]:
        status, body = self._request('GET', '/api/v1/nodes')
        body = self._check(status, body, 'list nodes')
        return body.get('items', [])

    def healthz(self) -> bool:
        try:
            status, _ = self._request('GET', '/readyz')
            return status < 400
        except Exception:  # pylint: disable=broad-except
            return False
