"""GCP provision plugin: TPU pod slices (first-class) + plain GCE VMs.

Re-design of reference ``sky/provision/gcp/instance.py`` +
``instance_utils.py:1191`` (GCPTPUVMInstance): a TPU *node* is an
atomic pod slice — one create call gang-provisions all hosts, and its
``networkEndpoints`` ARE the gang rank order. GCE VMs serve CPU tasks
and controllers. All ops are stateless module functions dispatched by
``skypilot_tpu.provision`` (the ProvisionConfig/ClusterInfo contract).

Naming: a cluster maps to TPU node id ``{cluster_name_on_cloud}`` (one
slice per logical node; multi-slice clusters use ``-{i}`` suffixes) or
GCE instances ``{cluster_name_on_cloud}-{i}``. Everything is labeled
``skytpu-cluster={cluster_name_on_cloud}`` for reconciliation queries.
"""
from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional

from skypilot_tpu import authentication
from skypilot_tpu import exceptions
from skypilot_tpu.provision import common
from skypilot_tpu.provision.gcp import api
from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)

_LABEL = 'skytpu-cluster'

# TPU node states (cloud.google.com/tpu/docs/reference/rest/v2).
_TPU_RUNNING = ('READY',)
_TPU_PENDING = ('CREATING', 'STARTING', 'REPAIRING', 'RESTARTING',
                'REIMAGING', 'UNKNOWN', 'STATE_UNSPECIFIED')
_TPU_STOPPED = ('STOPPED', 'STOPPING', 'SUSPENDED', 'SUSPENDING')
_TPU_TERMINAL = ('DELETING', 'TERMINATED', 'PREEMPTED')
# GCE instance states. Note GCE 'TERMINATED' means *stopped* (the VM
# still exists and is restartable); deleted VMs vanish from list.
_GCE_RUNNING = ('RUNNING',)
_GCE_PENDING = ('PROVISIONING', 'STAGING', 'REPAIRING')
_GCE_STOPPED = ('STOPPING', 'TERMINATED', 'SUSPENDED', 'SUSPENDING')

_DEFAULT_IMAGE = ('projects/debian-cloud/global/images/family/'
                  'debian-12')


@functools.lru_cache()
def _project() -> str:
    import google.auth
    _, project = google.auth.default()
    if not project:
        raise exceptions.ProvisionError(
            'No default GCP project; run '
            '`gcloud auth application-default login`.')
    return project


def _tpu() -> api.TpuClient:
    return api.TpuClient(_project())


def _gce() -> api.GceClient:
    return api.GceClient(_project())


def _network_tag(cluster_name_on_cloud: str) -> str:
    return f'skytpu-{cluster_name_on_cloud}'


def _slice_ids(name: str, count: int) -> List[str]:
    """TPU node ids for `count` logical nodes (slices)."""
    if count == 1:
        return [name]
    return [f'{name}-{i}' for i in range(count)]


# ---------------------------------------------------------------- ops


def bootstrap_instances(
        config: common.ProvisionConfig) -> common.ProvisionConfig:
    """Nothing to pre-create: default VPC, metadata-injected SSH keys."""
    return config


def run_instances(config: common.ProvisionConfig) -> common.ProvisionRecord:
    if config.node_config.get('tpu_vm'):
        return _run_tpu_nodes(config)
    return _run_gce_instances(config)


def _tpu_create_body(config: common.ProvisionConfig) -> Dict[str, Any]:
    nc = config.node_config
    body: Dict[str, Any] = {
        'acceleratorType': nc['tpu_type'],
        'runtimeVersion': nc['runtime_version'],
        'networkConfig': {
            'enableExternalIps': True,
        },
        'labels': {
            _LABEL: config.cluster_name_on_cloud,
            **nc.get('labels', {}),
        },
        'metadata': {
            'ssh-keys': authentication.ssh_keys_metadata_value(
                config.ssh_user),
        },
        'tags': [_network_tag(config.cluster_name_on_cloud)],
    }
    if nc.get('use_spot'):
        body['schedulingConfig'] = {'preemptible': True}
    if nc.get('network_tier') == 'best':
        body['networkConfig']['networkTier'] = 'PREMIUM'
    return body


def _run_tpu_nodes(config: common.ProvisionConfig) -> common.ProvisionRecord:
    zone = config.zone
    assert zone is not None, 'TPU provisioning requires a zone.'
    tpu = _tpu()
    created, resumed = [], []
    pending_ops = []  # (op, what) — issued concurrently, awaited below
    for node_id in _slice_ids(config.cluster_name_on_cloud, config.count):
        try:
            node = tpu.get_node(zone, node_id)
        except exceptions.ClusterDoesNotExist:
            node = None
        if node is None:
            logger.info('Creating TPU node %s (%s) in %s...', node_id,
                        config.node_config['tpu_type'], zone)
            pending_ops.append(
                (tpu.create_node_async(zone, node_id,
                                       _tpu_create_body(config)),
                 f'create TPU {node_id}'))
            created.append(node_id)
        elif node.get('state') in _TPU_STOPPED:
            logger.info('Starting stopped TPU node %s...', node_id)
            pending_ops.append((tpu.start_node_async(zone, node_id),
                                f'start TPU {node_id}'))
            resumed.append(node_id)
        elif node.get('state') in _TPU_RUNNING + _TPU_PENDING:
            logger.info('Reusing TPU node %s (state %s).', node_id,
                        node.get('state'))
        else:
            raise exceptions.ProvisionError(
                f'TPU node {node_id} in unexpected state '
                f'{node.get("state")}; delete it first.')
    # All slices create in parallel; stockouts surface at wait time
    # instead of serializing slice-by-slice.
    for op, what in pending_ops:
        tpu.wait_operation(op, what)
    ids = _slice_ids(config.cluster_name_on_cloud, config.count)
    return common.ProvisionRecord(
        provider_name='gcp',
        cluster_name_on_cloud=config.cluster_name_on_cloud,
        region=config.region,
        zone=zone,
        created_instance_ids=created,
        resumed_instance_ids=resumed,
        head_instance_id=ids[0],
    )


def _gce_create_body(config: common.ProvisionConfig,
                     name: str) -> Dict[str, Any]:
    nc = config.node_config
    zone = config.zone
    machine = nc['instance_type']
    body: Dict[str, Any] = {
        'name': name,
        'machineType': f'zones/{zone}/machineTypes/{machine}',
        'disks': [{
            'boot': True,
            'autoDelete': True,
            'initializeParams': {
                'sourceImage': nc.get('image_id') or _DEFAULT_IMAGE,
                'diskSizeGb': str(nc.get('disk_size', 256)),
            },
        }],
        'networkInterfaces': [{
            'network': 'global/networks/default',
            'accessConfigs': [{
                'name': 'External NAT',
                'type': 'ONE_TO_ONE_NAT',
            }],
        }],
        'labels': {
            _LABEL: config.cluster_name_on_cloud,
            **nc.get('labels', {}),
        },
        'metadata': {
            'items': [{
                'key': 'ssh-keys',
                'value': authentication.ssh_keys_metadata_value(
                    config.ssh_user),
            }],
        },
        'tags': {'items': [_network_tag(config.cluster_name_on_cloud)]},
    }
    if nc.get('use_spot'):
        body['scheduling'] = {
            'provisioningModel': 'SPOT',
            'instanceTerminationAction': 'TERMINATE',
        }
    return body


def _run_gce_instances(
        config: common.ProvisionConfig) -> common.ProvisionRecord:
    zone = config.zone
    assert zone is not None, 'GCE provisioning requires a zone.'
    gce = _gce()
    existing = {
        inst['name']: inst
        for inst in gce.list_instances(
            zone, f'labels.{_LABEL}={config.cluster_name_on_cloud}')
    }
    created, resumed = [], []
    pending_ops = []
    names = [
        f'{config.cluster_name_on_cloud}-{i}' for i in range(config.count)
    ]
    for name in names:
        inst = existing.get(name)
        if inst is None:
            logger.info('Creating VM %s in %s...', name, zone)
            pending_ops.append(
                (gce.insert_instance_async(zone,
                                           _gce_create_body(config, name)),
                 f'create VM {name}'))
            created.append(name)
        elif inst.get('status') in _GCE_STOPPED:
            logger.info('Starting stopped VM %s...', name)
            gce.start_instance(zone, name)
            resumed.append(name)
    for op, what in pending_ops:
        gce.wait_zone_operation(zone, op, what)
    return common.ProvisionRecord(
        provider_name='gcp',
        cluster_name_on_cloud=config.cluster_name_on_cloud,
        region=config.region,
        zone=zone,
        created_instance_ids=created,
        resumed_instance_ids=resumed,
        head_instance_id=names[0],
    )


def _find_cluster(cluster_name_on_cloud: str, zone: str):
    """Returns ('tpu'|'gce'|None, [raw instance/node dicts])."""
    tpu_nodes = [
        n for n in _tpu().list_nodes(zone)
        if n.get('labels', {}).get(_LABEL) == cluster_name_on_cloud
    ]
    if tpu_nodes:
        return 'tpu', tpu_nodes
    vms = _gce().list_instances(
        zone, f'labels.{_LABEL}={cluster_name_on_cloud}')
    if vms:
        return 'gce', vms
    return None, []


def wait_instances(cluster_name_on_cloud: str, region: str,
                   zone: Optional[str], state: Optional[str]) -> None:
    import time
    assert zone is not None
    want = state or 'running'
    deadline = time.time() + 1200
    while True:
        statuses = query_instances(cluster_name_on_cloud, region, zone,
                                   non_terminated_only=False)
        if not statuses:
            raise exceptions.ProvisionError(
                f'No instances found for {cluster_name_on_cloud}.')
        if all(s == want for s in statuses.values()):
            return
        if time.time() > deadline:
            raise exceptions.ProvisionError(
                f'{cluster_name_on_cloud}: instances stuck in '
                f'{statuses}; wanted {want}.')
        time.sleep(5)


def query_instances(
        cluster_name_on_cloud: str, region: str, zone: Optional[str],
        non_terminated_only: bool = True) -> Dict[str, Optional[str]]:
    """instance_id -> 'running'|'pending'|'stopped'|'terminated'."""
    del region
    assert zone is not None
    kind, items = _find_cluster(cluster_name_on_cloud, zone)
    out: Dict[str, Optional[str]] = {}
    for item in items:
        raw = item.get('state' if kind == 'tpu' else 'status', '')
        if raw in (_TPU_RUNNING + _GCE_RUNNING):
            status = 'running'
        elif raw in (_TPU_STOPPED + _GCE_STOPPED):
            status = 'stopped'
        elif raw in _TPU_TERMINAL:
            status = 'terminated'
        else:
            # Transients and future/unknown states stay visible as
            # 'pending' — mapping them to 'terminated' would make
            # reconciliation drop a billable instance from view.
            status = 'pending'
        if non_terminated_only and status == 'terminated':
            continue
        name = item['name'].split('/')[-1]
        out[name] = status
    return out


def get_cluster_info(cluster_name_on_cloud: str, region: str,
                     zone: Optional[str]) -> common.ClusterInfo:
    assert zone is not None
    kind, items = _find_cluster(cluster_name_on_cloud, zone)
    if kind is None:
        raise exceptions.ProvisionError(
            f'Cluster {cluster_name_on_cloud} not found in {zone}.')
    instances: Dict[str, List[common.InstanceInfo]] = {}
    provider_config: Dict[str, Any] = {}
    if kind == 'tpu':
        for node in sorted(items, key=lambda n: n['name']):
            node_id = node['name'].split('/')[-1]
            hosts = []
            for i, ep in enumerate(node.get('networkEndpoints', [])):
                ext = (ep.get('accessConfig') or {}).get('externalIp')
                hosts.append(
                    common.InstanceInfo(
                        instance_id=node_id,
                        internal_ip=ep.get('ipAddress', ''),
                        external_ip=ext,
                        host_index=i,
                    ))
            instances[node_id] = hosts
        provider_config['tpu_topology'] = items[0].get(
            'acceleratorConfig', {}).get('topology', '')
    else:
        for vm in sorted(items, key=lambda v: v['name']):
            nic = (vm.get('networkInterfaces') or [{}])[0]
            ext = None
            for ac in nic.get('accessConfigs', []):
                ext = ac.get('natIP') or ext
            instances[vm['name']] = [
                common.InstanceInfo(
                    instance_id=vm['name'],
                    internal_ip=nic.get('networkIP', ''),
                    external_ip=ext,
                )
            ]
    head = sorted(instances)[0]
    return common.ClusterInfo(
        provider_name='gcp',
        cluster_name_on_cloud=cluster_name_on_cloud,
        region=region,
        zone=zone,
        instances=instances,
        head_instance_id=head,
        ssh_user=authentication.DEFAULT_SSH_USER,
        provider_config=provider_config,
    )


def stop_instances(cluster_name_on_cloud: str, region: str,
                   zone: Optional[str]) -> None:
    del region
    assert zone is not None
    kind, items = _find_cluster(cluster_name_on_cloud, zone)
    if kind == 'tpu':
        # Validate the whole cluster BEFORE stopping anything, so a
        # pod-slice restriction never leaves it half-stopped.
        for node in items:
            if len(node.get('networkEndpoints', [])) > 1:
                raise exceptions.NotSupportedError(
                    'TPU pod slices cannot be stopped; use down.')
        tpu = _tpu()
        for node in items:
            tpu.stop_node(zone, node['name'].split('/')[-1])
    elif kind == 'gce':
        gce = _gce()
        for vm in items:
            gce.stop_instance(zone, vm['name'])


def terminate_instances(cluster_name_on_cloud: str, region: str,
                        zone: Optional[str]) -> None:
    del region
    assert zone is not None
    kind, items = _find_cluster(cluster_name_on_cloud, zone)
    if kind == 'tpu':
        tpu = _tpu()
        for node in items:
            tpu.delete_node(zone, node['name'].split('/')[-1])
    elif kind == 'gce':
        gce = _gce()
        for vm in items:
            gce.delete_instance(zone, vm['name'])
    # The cluster firewall (if any) must go regardless of kind.
    _gce().delete_firewall(_firewall_name(cluster_name_on_cloud))


def _firewall_name(cluster_name_on_cloud: str) -> str:
    return f'skytpu-{cluster_name_on_cloud}-ports'


def open_ports(cluster_name_on_cloud: str, ports: List[str], region: str,
               zone: Optional[str]) -> None:
    del region, zone
    allowed = [{
        'IPProtocol': 'tcp',
        'ports': [str(p) for p in ports],
    }]
    rule = {
        'name': _firewall_name(cluster_name_on_cloud),
        'network': 'global/networks/default',
        'direction': 'INGRESS',
        'sourceRanges': ['0.0.0.0/0'],
        'allowed': allowed,
        # Scoped to this cluster's instances only via network tag.
        'targetTags': [_network_tag(cluster_name_on_cloud)],
    }
    gce = _gce()
    try:
        gce.insert_firewall(rule)
    except exceptions.ProvisionError as e:
        if 'already exists' not in str(e).lower():
            raise
        # Re-launch with a (possibly changed) port list: update the
        # existing rule rather than keeping the stale config.
        gce.patch_firewall(rule['name'],
                           {'allowed': allowed,
                            'targetTags': rule['targetTags']})


def cleanup_ports(cluster_name_on_cloud: str, region: str,
                  zone: Optional[str]) -> None:
    del region, zone
    _gce().delete_firewall(_firewall_name(cluster_name_on_cloud))
