"""Minimal authorized REST clients for the TPU and GCE APIs.

Re-design of reference ``sky/provision/gcp/instance_utils.py:1191``
(GCPTPUVMInstance drives ``tpu.googleapis.com`` v2alpha1 through the
googleapiclient discovery stack). Here: plain REST via
``google.auth``'s AuthorizedSession — no discovery documents, no
client-library surface to lazy-import — with one error-translation
point mapping GCP error bodies onto the framework's typed provision
errors (quota vs stockout vs generic), which is what the failover
provisioner keys its blocked-set granularity on.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)

TPU_API = 'https://tpu.googleapis.com/v2'
GCE_API = 'https://compute.googleapis.com/compute/v1'

_OP_POLL_INTERVAL = 5.0
_OP_TIMEOUT = 1800.0

# Messages seen from the TPU/GCE APIs for capacity-vs-quota failures
# (reference FailoverCloudErrorHandlerV2._gcp_handler:920 decodes the
# same taxonomy from logs; we decode from structured error bodies).
_STOCKOUT_MARKERS = (
    'no more capacity',                  # TPU: zone out of capacity
    'zone_resource_pool_exhausted',      # GCE stockout
    'does not have enough resources',    # GCE stockout variant
    'resource_pool_exhausted',
    'stockout',
)
_QUOTA_MARKERS = (
    'quota',
    'rate_limit_exceeded',
    'resource_exhausted',
)


def _session_factory():
    """Returns an AuthorizedSession; separated for test monkeypatching."""
    import google.auth
    from google.auth.transport.requests import AuthorizedSession
    credentials, _ = google.auth.default(
        scopes=['https://www.googleapis.com/auth/cloud-platform'])
    return AuthorizedSession(credentials)


# Test seam: tests replace this with a fake session maker.
session_factory: Callable = _session_factory


def translate_error(status_code: int, body: Dict[str, Any],
                    what: str) -> exceptions.ProvisionError:
    """Map a GCP error response onto the typed provision errors."""
    err = body.get('error', {}) if isinstance(body, dict) else {}
    message = str(err.get('message', body))
    status = str(err.get('status', ''))
    blob = f'{status} {message}'.lower()
    if any(m in blob for m in _STOCKOUT_MARKERS):
        return exceptions.StockoutError(
            f'{what}: out of capacity: {message}')
    if status_code == 429 or any(m in blob for m in _QUOTA_MARKERS):
        return exceptions.QuotaExceededError(f'{what}: {message}')
    return exceptions.ProvisionError(
        f'{what}: HTTP {status_code}: {message}')


# One authorized session per factory (clients are constructed
# per-call by the provision ops; without this cache every status poll
# would redo the google-auth handshake). Keyed by the factory object
# so tests that monkeypatch ``session_factory`` get a fresh session —
# which is why this is not a plain adaptors.CachedSession. Locked:
# the API server runs provision ops on an 8-thread pool.
import threading as _threading

_session_cache: Dict[Any, Any] = {}
_session_lock = _threading.Lock()


def _get_session():
    factory = session_factory
    with _session_lock:
        if factory not in _session_cache:
            _session_cache.clear()  # replaced factory obsoletes old
            _session_cache[factory] = factory()
        return _session_cache[factory]


class RestClient:
    """Shared request/poll plumbing for the TPU and GCE clients."""

    def __init__(self, base_url: str, project: str) -> None:
        self.base = base_url
        self.project = project

    @property
    def session(self):
        return _get_session()

    def request(self, method: str, path: str, *,
                json_body: Optional[Dict] = None,
                params: Optional[Dict] = None,
                ok_statuses=(200,),
                what: str = '') -> Dict[str, Any]:
        url = path if path.startswith('http') else self.base + path
        # Explicit bounded (connect, read) timeout (skytpu-lint
        # STL012): a wedged metadata/API endpoint must surface as a
        # typed RequestException the provision retry machinery can
        # act on, never hang a controller thread forever.
        resp = self.session.request(method, url, json=json_body,
                                    params=params, timeout=(10, 120))
        try:
            body = resp.json() if resp.content else {}
        except ValueError:
            body = {'error': {'message': resp.text}}
        if resp.status_code == 404:
            raise exceptions.ClusterDoesNotExist(
                f'{what or url}: not found')
        if resp.status_code not in ok_statuses:
            raise translate_error(resp.status_code, body, what or url)
        return body


class TpuClient(RestClient):
    """tpu.googleapis.com v2: TPU-VM node lifecycle.

    One TPU *node* is a whole pod slice; its networkEndpoints list the
    per-host IPs in worker order — exactly the gang rank order.
    """

    def __init__(self, project: str) -> None:
        super().__init__(TPU_API, project)

    def _loc(self, zone: str) -> str:
        return f'/projects/{self.project}/locations/{zone}'

    def create_node_async(self, zone: str, node_id: str,
                          body: Dict[str, Any]) -> Dict[str, Any]:
        """Issue the create; returns the long-running operation."""
        return self.request('POST', f'{self._loc(zone)}/nodes',
                            params={'nodeId': node_id}, json_body=body,
                            what=f'create TPU {node_id}')

    def create_node(self, zone: str, node_id: str,
                    body: Dict[str, Any]) -> Dict[str, Any]:
        op = self.create_node_async(zone, node_id, body)
        return self.wait_operation(op, f'create TPU {node_id}')

    def get_node(self, zone: str, node_id: str) -> Dict[str, Any]:
        return self.request('GET', f'{self._loc(zone)}/nodes/{node_id}',
                            what=f'get TPU {node_id}')

    def list_nodes(self, zone: str) -> List[Dict[str, Any]]:
        nodes: List[Dict[str, Any]] = []
        token = None
        while True:
            params = {'pageToken': token} if token else None
            body = self.request('GET', f'{self._loc(zone)}/nodes',
                                params=params, what='list TPUs')
            nodes.extend(body.get('nodes', []))
            token = body.get('nextPageToken')
            if not token:
                return nodes

    def delete_node(self, zone: str, node_id: str) -> None:
        try:
            op = self.request('DELETE',
                              f'{self._loc(zone)}/nodes/{node_id}',
                              what=f'delete TPU {node_id}')
        except exceptions.ClusterDoesNotExist:
            return
        self.wait_operation(op, f'delete TPU {node_id}')

    def stop_node(self, zone: str, node_id: str) -> None:
        op = self.request('POST',
                          f'{self._loc(zone)}/nodes/{node_id}:stop',
                          json_body={}, what=f'stop TPU {node_id}')
        self.wait_operation(op, f'stop TPU {node_id}')

    def start_node_async(self, zone: str, node_id: str) -> Dict[str, Any]:
        return self.request('POST',
                            f'{self._loc(zone)}/nodes/{node_id}:start',
                            json_body={}, what=f'start TPU {node_id}')

    def start_node(self, zone: str, node_id: str) -> None:
        op = self.start_node_async(zone, node_id)
        self.wait_operation(op, f'start TPU {node_id}')

    def wait_operation(self, op: Dict[str, Any], what: str,
                       timeout: float = _OP_TIMEOUT) -> Dict[str, Any]:
        """Poll a long-running operation to completion."""
        deadline = time.time() + timeout
        while not op.get('done'):
            if time.time() > deadline:
                raise exceptions.ProvisionError(
                    f'{what}: operation timed out after {timeout}s')
            time.sleep(_OP_POLL_INTERVAL)
            op = self.request('GET', f'/{op["name"]}', what=what)
        if 'error' in op:
            raise translate_error(200, {'error': op['error']}, what)
        return op.get('response', {})


class GceClient(RestClient):
    """compute.googleapis.com v1: plain VMs (controllers, CPU tasks)."""

    def __init__(self, project: str) -> None:
        super().__init__(GCE_API, project)

    def _zone(self, zone: str) -> str:
        return f'/projects/{self.project}/zones/{zone}'

    def insert_instance_async(self, zone: str,
                              body: Dict[str, Any]) -> Dict[str, Any]:
        return self.request('POST', f'{self._zone(zone)}/instances',
                            json_body=body,
                            what=f'create VM {body.get("name")}')

    def insert_instance(self, zone: str,
                        body: Dict[str, Any]) -> Dict[str, Any]:
        op = self.insert_instance_async(zone, body)
        return self.wait_zone_operation(zone, op,
                                        f'create VM {body.get("name")}')

    def list_instances(self, zone: str,
                       label_filter: str) -> List[Dict[str, Any]]:
        items: List[Dict[str, Any]] = []
        token = None
        while True:
            params = {'filter': label_filter}
            if token:
                params['pageToken'] = token
            body = self.request('GET', f'{self._zone(zone)}/instances',
                                params=params, what='list VMs')
            items.extend(body.get('items', []))
            token = body.get('nextPageToken')
            if not token:
                return items

    def get_instance(self, zone: str, name: str) -> Dict[str, Any]:
        return self.request('GET',
                            f'{self._zone(zone)}/instances/{name}',
                            what=f'get VM {name}')

    def _instance_op(self, zone: str, name: str, verb: str) -> None:
        try:
            op = self.request(
                'POST' if verb != 'delete' else 'DELETE',
                f'{self._zone(zone)}/instances/{name}' +
                ('' if verb == 'delete' else f'/{verb}'),
                json_body=None,
                what=f'{verb} VM {name}')
        except exceptions.ClusterDoesNotExist:
            return
        self.wait_zone_operation(zone, op, f'{verb} VM {name}')

    def delete_instance(self, zone: str, name: str) -> None:
        self._instance_op(zone, name, 'delete')

    def stop_instance(self, zone: str, name: str) -> None:
        self._instance_op(zone, name, 'stop')

    def start_instance(self, zone: str, name: str) -> None:
        self._instance_op(zone, name, 'start')

    def insert_firewall(self, body: Dict[str, Any]) -> None:
        op = self.request('POST',
                          f'/projects/{self.project}/global/firewalls',
                          json_body=body,
                          what=f'firewall {body.get("name")}')
        self.wait_global_operation(op, f'firewall {body.get("name")}')

    def patch_firewall(self, name: str, body: Dict[str, Any]) -> None:
        op = self.request(
            'PATCH',
            f'/projects/{self.project}/global/firewalls/{name}',
            json_body=body, what=f'patch firewall {name}')
        self.wait_global_operation(op, f'patch firewall {name}')

    def delete_firewall(self, name: str) -> None:
        try:
            op = self.request(
                'DELETE',
                f'/projects/{self.project}/global/firewalls/{name}',
                what=f'delete firewall {name}')
        except exceptions.ClusterDoesNotExist:
            return
        self.wait_global_operation(op, f'delete firewall {name}')

    def _wait(self, url: str, what: str) -> None:
        deadline = time.time() + _OP_TIMEOUT
        while True:
            op = self.request('GET', url, what=what)
            if op.get('status') == 'DONE':
                if op.get('error'):
                    errs = op['error'].get('errors', [])
                    msg = '; '.join(e.get('message', '') for e in errs)
                    code = ' '.join(e.get('code', '') for e in errs)
                    raise translate_error(
                        200, {'error': {'message': msg, 'status': code}},
                        what)
                return
            if time.time() > deadline:
                raise exceptions.ProvisionError(f'{what}: timed out')
            time.sleep(_OP_POLL_INTERVAL)

    def wait_zone_operation(self, zone: str, op: Dict[str, Any],
                            what: str) -> Dict[str, Any]:
        self._wait(f'{self._zone(zone)}/operations/{op["name"]}', what)
        return op

    def wait_global_operation(self, op: Dict[str, Any],
                              what: str) -> Dict[str, Any]:
        self._wait(
            f'/projects/{self.project}/global/operations/{op["name"]}',
            what)
        return op
