"""Minimal Vast.ai REST client.

Role of reference ``sky/provision/vast/utils.py`` (which wraps the
``vastai_sdk``); re-designed as a plain REST client against
``console.vast.ai/api/v0``. Vast is a MARKETPLACE: machines are not
created from a type name but rented from a searched OFFER — launch is
two-phase (search bundles matching the GPU ask, then PUT
/asks/{offer_id}/ on the cheapest hit). Cluster membership rides the
instance LABEL (vast has first-class labels; the name-based pattern
the other neoclouds use is unnecessary here). Same fake-session test
seam as the other REST plugins.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions

API_ENDPOINT = 'https://console.vast.ai/api/v0'
CREDENTIALS_PATH = '~/.vast_api_key'


def read_api_key() -> Optional[str]:
    key = os.environ.get('VAST_API_KEY')
    if key:
        return key
    try:
        with open(os.path.expanduser(CREDENTIALS_PATH),
                  encoding='utf-8') as f:
            return f.read().strip() or None
    except OSError:
        return None


def _requests_session():
    import requests
    return requests.Session()


# Test seam.
session_factory = _requests_session


class VastClient:

    def __init__(self, api_key: Optional[str] = None) -> None:
        self.api_key = api_key or read_api_key()
        if not self.api_key:
            raise exceptions.ProvisionError(
                'No Vast.ai API key (set VAST_API_KEY or write '
                f'{CREDENTIALS_PATH}).')
        self.http = session_factory()

    def _call(self, method: str, path: str,
              json: Optional[Dict[str, Any]] = None) -> Any:
        resp = self.http.request(
            method, f'{API_ENDPOINT}{path}', json=json,
            headers={'Authorization': f'Bearer {self.api_key}'},
            timeout=60)
        try:
            body = resp.json()
        except ValueError:
            body = {}
        if resp.status_code >= 400 or body.get('success') is False:
            raise translate_error(
                str(body.get('error') or body.get('msg') or
                    resp.text[:200]), path)
        return body

    # ------------------------------------------------------------ ops
    def search_offers(self, *, gpu_name: str, num_gpus: int,
                      region: Optional[str] = None
                      ) -> List[Dict[str, Any]]:
        """Rentable offers matching the GPU ask, cheapest first."""
        query: Dict[str, Any] = {
            'gpu_name': {'eq': gpu_name},
            'num_gpus': {'eq': num_gpus},
            'rentable': {'eq': True},
            'order': [['dph_total', 'asc']],
            'type': 'on-demand',
        }
        if region:
            query['geolocation'] = {'eq': region}
        body = self._call('PUT', '/bundles/', json={'q': query})
        return body.get('offers', [])

    def create_from_offer(self, offer_id: int, *, label: str,
                          disk_gb: int,
                          public_key: Optional[str]) -> int:
        body = self._call(
            'PUT', f'/asks/{offer_id}/',
            json={
                'client_id': 'me',
                'image': 'ubuntu:22.04',
                'disk': disk_gb,
                'label': label,
                'onstart': None,
                'runtype': 'ssh',
                'env': ({'SSH_PUBLIC_KEY': public_key}
                        if public_key else {}),
            })
        return int(body['new_contract'])

    def list_instances(self) -> List[Dict[str, Any]]:
        return self._call('GET', '/instances/').get('instances', [])

    def start(self, instance_id: int) -> None:
        self._call('PUT', f'/instances/{instance_id}/',
                   json={'state': 'running'})

    def stop(self, instance_id: int) -> None:
        self._call('PUT', f'/instances/{instance_id}/',
                   json={'state': 'stopped'})

    def delete(self, instance_id: int) -> None:
        self._call('DELETE', f'/instances/{instance_id}/')


def translate_error(message: str, what: str) -> Exception:
    blob = message.lower()
    if ('no_such_ask' in blob or 'no longer available' in blob or
            'no offers' in blob or 'unavailable' in blob):
        return exceptions.StockoutError(f'{what}: {message}')
    if 'quota' in blob or 'insufficient credit' in blob or \
            'balance' in blob:
        return exceptions.QuotaExceededError(f'{what}: {message}')
    return exceptions.ProvisionError(f'{what}: {message}')
