"""Vast.ai provision plugin."""
