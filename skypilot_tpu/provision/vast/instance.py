"""Vast.ai provision ops (nine-op contract).

Role of reference ``sky/provision/vast/instance.py``, re-designed
stateless for the MARKETPLACE shape: ``run_instances`` first searches
the offer market for machines matching the catalog GPU ask
(cheapest-first), then rents each missing rank from an offer —
an empty market IS the stockout signal. Membership rides instance
LABELS (``<cluster>-<idx>``, exact match); stop/start supported.

Status mapping: ``loading``/``running``/``stopped``/``exited``/
``offline`` -> 'pending'/'running'/'stopped'/'stopped'/'pending'.
"""
from __future__ import annotations

import re
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision import common
from skypilot_tpu.provision.vast import api
from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)

_WAIT_TIMEOUT = 1800.0
_POLL_INTERVAL = 5.0

SSH_USER = 'root'


def _label(cluster: str, idx: int) -> str:
    return f'{cluster}-{idx}'


def _cluster_instances(client: api.VastClient,
                       cluster: str) -> Dict[str, Dict[str, Any]]:
    """label -> instance, EXACT ``<cluster>-<rank>`` match."""
    member = re.compile(re.escape(cluster) + r'-\d+\Z')
    out: Dict[str, Dict[str, Any]] = {}
    for inst in client.list_instances():
        label = inst.get('label') or ''
        if member.fullmatch(label):
            out[label] = inst
    return out


def _gpu_parts(instance_type: str) -> Dict[str, Any]:
    """'2x_RTX_4090'-style catalog names -> market search args."""
    m = re.match(r'(\d+)x_(.+)\Z', instance_type or '')
    if not m:
        raise exceptions.ProvisionError(
            f'Unparseable Vast instance type {instance_type!r} '
            "(expected '<n>x_<GPU>').")
    return {'num_gpus': int(m.group(1)),
            'gpu_name': m.group(2).replace('_', ' ')}


def bootstrap_instances(
        config: common.ProvisionConfig) -> common.ProvisionConfig:
    return config


def run_instances(
        config: common.ProvisionConfig) -> common.ProvisionRecord:
    node = config.node_config
    cluster = config.cluster_name_on_cloud
    client = api.VastClient()
    gpu = _gpu_parts(node['instance_type'])
    created: List[str] = []
    resumed: List[str] = []
    existing = _cluster_instances(client, cluster)
    offers: Optional[List[Dict[str, Any]]] = None
    for idx in range(config.count):
        label = _label(cluster, idx)
        inst = existing.get(label)
        if inst is not None:
            if _status(inst) == 'stopped':
                client.start(inst['id'])
                resumed.append(str(inst['id']))
            continue
        if offers is None:
            # ONE market search covers every missing rank (offers is
            # cheapest-first; each rent consumes its head).
            offers = client.search_offers(gpu_name=gpu['gpu_name'],
                                          num_gpus=gpu['num_gpus'],
                                          region=config.region)
        if not offers:
            # The marketplace has nothing matching the ask — Vast's
            # form of a stockout, which drives the provisioner's
            # cross-region/cloud failover.
            raise exceptions.StockoutError(
                f'No rentable Vast offers for '
                f"{gpu['num_gpus']}x {gpu['gpu_name']} in "
                f'{config.region!r}.')
        offer = offers.pop(0)
        created.append(str(client.create_from_offer(
            offer['id'], label=label,
            disk_gb=int(node.get('disk_size') or 100),
            public_key=node.get('ssh_public_key'))))
    return common.ProvisionRecord(
        provider_name='vast',
        cluster_name_on_cloud=cluster,
        region=config.region,
        zone=config.zone,
        created_instance_ids=created,
        resumed_instance_ids=resumed,
        head_instance_id=_label(cluster, 0),
    )


def _status(inst: Dict[str, Any]) -> str:
    return {
        'running': 'running',
        'loading': 'pending',
        'created': 'pending',
        'offline': 'pending',
        'stopped': 'stopped',
        'exited': 'stopped',
    }.get(inst.get('actual_status', ''), 'pending')


def wait_instances(cluster_name_on_cloud: str, region: str,
                   zone: Optional[str], state: Optional[str]) -> None:
    del region, zone
    client = api.VastClient()
    want = state or 'running'
    deadline = time.time() + _WAIT_TIMEOUT
    while time.time() < deadline:
        insts = _cluster_instances(client, cluster_name_on_cloud)
        if want == 'terminated':
            if not insts:
                return
        elif insts and all(_status(i) == want
                           for i in insts.values()):
            return
        time.sleep(_POLL_INTERVAL)
    raise exceptions.ProvisionError(
        f'Timed out waiting for {cluster_name_on_cloud} to reach '
        f'{want!r}.')


def query_instances(
        cluster_name_on_cloud: str, region: str, zone: Optional[str],
        non_terminated_only: bool = True) -> Dict[str, Optional[str]]:
    del region, zone, non_terminated_only
    client = api.VastClient()
    # Deleted rentals vanish from /instances — anything listed is
    # non-terminated by construction.
    return {
        label: _status(inst)
        for label, inst in _cluster_instances(
            client, cluster_name_on_cloud).items()
    }


def get_cluster_info(cluster_name_on_cloud: str, region: str,
                     zone: Optional[str]) -> common.ClusterInfo:
    client = api.VastClient()
    infos: Dict[str, List[common.InstanceInfo]] = {}
    for label, inst in sorted(
            _cluster_instances(client, cluster_name_on_cloud).items()):
        infos[label] = [
            common.InstanceInfo(
                instance_id=str(inst.get('id', label)),
                # 'local_ipaddrs' is a SPACE-SEPARATED string of the
                # rental's private addresses; take the first one (the
                # raw field would embed every address in env contracts
                # and ssh configs).
                internal_ip=(
                    (inst.get('local_ipaddrs') or '').split() +
                    [inst.get('public_ipaddr', '')])[0],
                external_ip=inst.get('public_ipaddr'),
                # Vast exposes sshd on a mapped high port.
                ssh_port=int(inst.get('ssh_port') or 22),
                host_index=0,
                tags={'label': label},
            )
        ]
    head = min(infos) if infos else None
    return common.ClusterInfo(
        provider_name='vast',
        cluster_name_on_cloud=cluster_name_on_cloud,
        region=region,
        zone=zone,
        instances=infos,
        head_instance_id=head,
        ssh_user=SSH_USER,
    )


def stop_instances(cluster_name_on_cloud: str, region: str,
                   zone: Optional[str]) -> None:
    del region, zone
    client = api.VastClient()
    for inst in _cluster_instances(client,
                                   cluster_name_on_cloud).values():
        if _status(inst) == 'running':
            client.stop(inst['id'])


def terminate_instances(cluster_name_on_cloud: str, region: str,
                        zone: Optional[str]) -> None:
    del region, zone
    client = api.VastClient()
    for inst in _cluster_instances(client,
                                   cluster_name_on_cloud).values():
        client.delete(inst['id'])


def open_ports(cluster_name_on_cloud: str, ports: List[str],
               region: str, zone: Optional[str]) -> None:
    logger.info('vast: port mappings are assigned per rental; '
                'open_ports(%s) is a no-op.', ports)


def cleanup_ports(cluster_name_on_cloud: str, region: str,
                  zone: Optional[str]) -> None:
    pass
