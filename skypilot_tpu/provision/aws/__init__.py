"""AWS (EC2) provision plugin."""
