"""EC2 provision ops.

Re-design of reference ``sky/provision/aws/instance.py`` (boto3 fleet
launch): instances are tagged with the cluster name, created
idempotently (existing non-terminated instances are reused, stopped
ones restarted), and errors translate into the stockout/quota
taxonomy the failover provisioner keys on
(InsufficientInstanceCapacity -> StockoutError, *LimitExceeded ->
QuotaExceededError — the same signals reference
FailoverCloudErrorHandlerV2's AWS handler decodes).

boto3 is reached only through ``client_factory`` so tests (and images
without boto3) drive the full lifecycle against a fake EC2.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision import common
from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)

_CLUSTER_TAG = 'skypilot-tpu-cluster'
_ROLE_TAG = 'skypilot-tpu-role'

_WAIT_TIMEOUT = 1200.0
_POLL_INTERVAL = 5.0

# Default Ubuntu 22.04 AMIs would normally come from an SSM lookup;
# kept as a parameter (node_config['image_id']) with SSM alias default.
_DEFAULT_AMI_SSM = ('/aws/service/canonical/ubuntu/server/22.04/'
                    'stable/current/amd64/hvm/ebs-gp2/ami-id')


def _ec2_factory(region: str):
    import boto3
    return boto3.client('ec2', region_name=region)


# Test seam: replaced with a fake EC2 client maker in tests.
client_factory: Callable = _ec2_factory


def translate_error(exc: Exception, what: str) -> exceptions.ProvisionError:
    """Map a botocore ClientError(-shaped) exception onto typed errors."""
    code = ''
    resp = getattr(exc, 'response', None)
    if isinstance(resp, dict):
        code = str(resp.get('Error', {}).get('Code', ''))
    blob = f'{code} {exc}'.lower()
    # Quota first: AWS quota messages mention "vCPU capacity ...
    # limit", which would false-match a bare "capacity" stockout test.
    if 'limitexceeded' in blob or 'quota' in blob:
        return exceptions.QuotaExceededError(f'{what}: {exc}')
    if ('insufficientinstancecapacity' in blob or
            'insufficient capacity' in blob or
            'insufficient' in blob and 'capacity' in blob):
        return exceptions.StockoutError(f'{what}: {exc}')
    return exceptions.ProvisionError(f'{what}: {exc}')


def _tag_filters(cluster_name_on_cloud: str) -> List[Dict[str, Any]]:
    return [
        {'Name': f'tag:{_CLUSTER_TAG}',
         'Values': [cluster_name_on_cloud]},
        {'Name': 'instance-state-name',
         'Values': ['pending', 'running', 'stopping', 'stopped']},
    ]


def _list_instances(ec2, cluster_name_on_cloud: str) -> List[Dict]:
    out = []
    resp = ec2.describe_instances(
        Filters=_tag_filters(cluster_name_on_cloud))
    for reservation in resp.get('Reservations', []):
        out.extend(reservation.get('Instances', []))
    return out


def bootstrap_instances(
        config: common.ProvisionConfig) -> common.ProvisionConfig:
    """Security groups / VPC discovery would go here; the default VPC
    with its default security group is assumed (reference
    sky/provision/aws/config.py does full discovery)."""
    return config


def run_instances(
        config: common.ProvisionConfig) -> common.ProvisionRecord:
    node = config.node_config
    ec2 = client_factory(config.region)
    existing = _list_instances(ec2, config.cluster_name_on_cloud)
    alive = [i for i in existing
             if i['State']['Name'] in ('pending', 'running')]
    stopped = [i for i in existing if i['State']['Name'] in
               ('stopping', 'stopped')]
    created, resumed = [], []

    if stopped:
        # 'stopping' instances cannot be started yet — wait for them
        # to settle (EC2 raises IncorrectInstanceState otherwise).
        deadline = time.time() + 300
        while (any(i['State']['Name'] == 'stopping' for i in stopped)
               and time.time() < deadline):
            time.sleep(_POLL_INTERVAL)
            stopped = [i for i in
                       _list_instances(ec2, config.cluster_name_on_cloud)
                       if i['State']['Name'] in ('stopping', 'stopped')]
        ids = [i['InstanceId'] for i in stopped]
        if ids:  # all may have terminated while settling
            try:
                ec2.start_instances(InstanceIds=ids)
            except Exception as e:  # pylint: disable=broad-except
                raise translate_error(e, 'start_instances') from e
            resumed = ids
            alive += stopped

    missing = config.count - len(alive)
    if missing > 0:
        placement: Dict[str, Any] = {}
        if config.zone:
            placement['AvailabilityZone'] = config.zone
        market: Dict[str, Any] = {}
        if node.get('use_spot'):
            market = {'MarketType': 'spot',
                      'SpotOptions': {
                          'InstanceInterruptionBehavior': 'terminate'}}
        tags = [{'Key': _CLUSTER_TAG,
                 'Value': config.cluster_name_on_cloud},
                {'Key': 'Name',
                 'Value': config.cluster_name_on_cloud}]
        for k, v in (node.get('labels') or {}).items():
            tags.append({'Key': k, 'Value': v})
        kwargs: Dict[str, Any] = dict(
            ImageId=node.get('image_id') or f'resolve:ssm:{_DEFAULT_AMI_SSM}',
            InstanceType=node['instance_type'],
            MinCount=missing,
            MaxCount=missing,
            TagSpecifications=[{'ResourceType': 'instance',
                                'Tags': tags}],
            BlockDeviceMappings=[{
                'DeviceName': '/dev/sda1',
                'Ebs': {'VolumeSize': node.get('disk_size') or 256,
                        'VolumeType': 'gp3'},
            }],
        )
        if placement:
            kwargs['Placement'] = placement
        if market:
            kwargs['InstanceMarketOptions'] = market
        try:
            resp = ec2.run_instances(**kwargs)
        except Exception as e:  # pylint: disable=broad-except
            raise translate_error(e, 'run_instances') from e
        created = [i['InstanceId'] for i in resp['Instances']]

    all_ids = sorted([i['InstanceId'] for i in alive
                      if i['InstanceId'] not in resumed] +
                     resumed + created)
    if not all_ids:
        raise exceptions.ProvisionError('run_instances created nothing')
    # Stable head: lexicographically-first instance id (tags would race
    # on concurrent creates; id order is what rank order uses too).
    return common.ProvisionRecord(
        provider_name='aws',
        cluster_name_on_cloud=config.cluster_name_on_cloud,
        region=config.region,
        zone=config.zone,
        created_instance_ids=created,
        resumed_instance_ids=resumed,
        head_instance_id=all_ids[0],
    )


def wait_instances(cluster_name_on_cloud: str, region: str,
                   zone: Optional[str], state: Optional[str]) -> None:
    del zone
    ec2 = client_factory(region)
    want = {'running': ('running',),
            'stopped': ('stopped',)}.get(state or 'running',
                                         ('running',))
    deadline = time.time() + _WAIT_TIMEOUT
    while time.time() < deadline:
        instances = _list_instances(ec2, cluster_name_on_cloud)
        if instances and all(
                i['State']['Name'] in want for i in instances):
            return
        time.sleep(_POLL_INTERVAL)
    raise exceptions.ProvisionError(
        f'Timed out waiting for {cluster_name_on_cloud} to reach '
        f'{state!r}.')


def query_instances(
        cluster_name_on_cloud: str, region: str, zone: Optional[str],
        non_terminated_only: bool = True) -> Dict[str, Optional[str]]:
    del zone
    ec2 = client_factory(region)
    resp = ec2.describe_instances(Filters=[
        {'Name': f'tag:{_CLUSTER_TAG}',
         'Values': [cluster_name_on_cloud]},
    ])
    out: Dict[str, Optional[str]] = {}
    for reservation in resp.get('Reservations', []):
        for inst in reservation.get('Instances', []):
            aws_state = inst['State']['Name']
            status = {
                'pending': 'pending',
                'running': 'running',
                'stopping': 'stopped',
                'stopped': 'stopped',
                'shutting-down': 'terminated',
                'terminated': 'terminated',
            }.get(aws_state, 'pending')
            if non_terminated_only and status == 'terminated':
                continue
            out[inst['InstanceId']] = status
    return out


def get_cluster_info(cluster_name_on_cloud: str, region: str,
                     zone: Optional[str]) -> common.ClusterInfo:
    ec2 = client_factory(region)
    instances = _list_instances(ec2, cluster_name_on_cloud)
    infos: Dict[str, List[common.InstanceInfo]] = {}
    for inst in sorted(instances, key=lambda i: i['InstanceId']):
        infos[inst['InstanceId']] = [
            common.InstanceInfo(
                instance_id=inst['InstanceId'],
                internal_ip=inst.get('PrivateIpAddress', ''),
                external_ip=inst.get('PublicIpAddress'),
                host_index=0,
                tags={t['Key']: t['Value']
                      for t in inst.get('Tags', [])},
            )
        ]
    head = min(infos) if infos else None
    return common.ClusterInfo(
        provider_name='aws',
        cluster_name_on_cloud=cluster_name_on_cloud,
        region=region,
        zone=zone,
        instances=infos,
        head_instance_id=head,
        ssh_user='ubuntu',
    )


def stop_instances(cluster_name_on_cloud: str, region: str,
                   zone: Optional[str]) -> None:
    del zone
    ec2 = client_factory(region)
    ids = [i['InstanceId']
           for i in _list_instances(ec2, cluster_name_on_cloud)
           if i['State']['Name'] in ('pending', 'running')]
    if ids:
        ec2.stop_instances(InstanceIds=ids)


def terminate_instances(cluster_name_on_cloud: str, region: str,
                        zone: Optional[str]) -> None:
    del zone
    ec2 = client_factory(region)
    ids = [i['InstanceId']
           for i in _list_instances(ec2, cluster_name_on_cloud)]
    if ids:
        ec2.terminate_instances(InstanceIds=ids)


def _cluster_sg_ids(ec2, cluster_name_on_cloud: str) -> List[str]:
    """Security-group ids attached to the cluster's instances."""
    sgs: List[str] = []
    for inst in _list_instances(ec2, cluster_name_on_cloud):
        for sg in inst.get('SecurityGroups', []):
            if sg['GroupId'] not in sgs:
                sgs.append(sg['GroupId'])
    return sgs


def _rule_marker(cluster_name_on_cloud: str) -> str:
    return f'skytpu:{cluster_name_on_cloud}'


def _owns_rule(ec2, sg_id: str, permission: Dict[str, Any],
               marker: str) -> bool:
    """Whether the existing rule matching ``permission`` carries this
    cluster's marker (duplicate-on-relaunch is benign).

    The match is the FULL rule identity — protocol, port range, and
    CIDR — and every matching permission is inspected: an SG can hold
    a UDP rule or a different-CIDR TCP rule on the same port range,
    and keying on ports alone could mis-attribute the probed rule to
    (or away from) this cluster."""
    want_cidrs = {r['CidrIp'] for r in permission['IpRanges']}
    try:
        resp = ec2.describe_security_groups(GroupIds=[sg_id])
    except Exception:  # pylint: disable=broad-except
        return False
    for sg in resp.get('SecurityGroups', []):
        for perm in sg.get('IpPermissions', []):
            if (perm.get('IpProtocol') != permission['IpProtocol'] or
                    perm.get('FromPort') != permission['FromPort'] or
                    perm.get('ToPort') != permission['ToPort']):
                continue
            if any(r.get('CidrIp') in want_cidrs and
                   r.get('Description') == marker
                   for r in perm.get('IpRanges', [])):
                return True
    return False


def open_ports(cluster_name_on_cloud: str, ports: List[str],
               region: str, zone: Optional[str]) -> None:
    """Authorize TCP ingress on the instances' security groups
    (reference sky/provision/aws open_ports).

    One authorize call PER rule: AWS rejects a batch atomically on
    any duplicate, which would silently skip genuinely-new ports.
    Each rule's description carries a cluster marker so
    cleanup_ports can revoke exactly what this cluster added (the
    default SG is shared VPC infrastructure that outlives the
    instances)."""
    del zone
    ec2 = client_factory(region)
    marker = _rule_marker(cluster_name_on_cloud)
    for sg_id in _cluster_sg_ids(ec2, cluster_name_on_cloud):
        for p in ports:
            permission = {
                'IpProtocol': 'tcp',
                'FromPort': int(str(p).split('-')[0]),
                'ToPort': int(str(p).split('-')[-1]),
                'IpRanges': [{'CidrIp': '0.0.0.0/0',
                              'Description': marker}],
            }
            try:
                ec2.authorize_security_group_ingress(
                    GroupId=sg_id, IpPermissions=[permission])
            except Exception as e:  # pylint: disable=broad-except
                resp = getattr(e, 'response', None)
                code = ''
                if isinstance(resp, dict):
                    code = str(resp.get('Error', {}).get('Code', ''))
                if code == 'InvalidPermission.Duplicate':
                    # AWS rule identity ignores descriptions: the
                    # existing rule may be OURS (benign relaunch) or
                    # another cluster's on a shared default SG, whose
                    # teardown will revoke it out from under us. Only
                    # the foreign case deserves a warning.
                    if not _owns_rule(ec2, sg_id, permission, marker):
                        logger.warning(
                            'aws: port %s on %s is already open by '
                            'another rule (possibly another cluster '
                            'on this shared security group); it may '
                            'close when that owner tears down. Use a '
                            'dedicated SG/VPC for isolation.', p,
                            sg_id)
                    continue
                raise translate_error(e, 'open_ports') from e


def cleanup_ports(cluster_name_on_cloud: str, region: str,
                  zone: Optional[str]) -> None:
    """Revoke the marker-tagged ingress rules open_ports added.

    Runs BEFORE terminate (provisioner.teardown_cluster) so the
    instances still resolve their security groups; without this, the
    0.0.0.0/0 rules would persist on the VPC's shared default SG
    forever."""
    del zone
    ec2 = client_factory(region)
    marker = _rule_marker(cluster_name_on_cloud)
    for sg_id in _cluster_sg_ids(ec2, cluster_name_on_cloud):
        try:
            resp = ec2.describe_security_groups(GroupIds=[sg_id])
        except Exception as e:  # pylint: disable=broad-except
            raise translate_error(e, 'cleanup_ports') from e
        for sg in resp.get('SecurityGroups', []):
            to_revoke = []
            for perm in sg.get('IpPermissions', []):
                ranges = [r for r in perm.get('IpRanges', [])
                          if r.get('Description') == marker]
                if ranges:
                    to_revoke.append({**perm, 'IpRanges': ranges})
            if to_revoke:
                try:
                    ec2.revoke_security_group_ingress(
                        GroupId=sg_id, IpPermissions=to_revoke)
                except Exception as e:  # pylint: disable=broad-except
                    raise translate_error(e, 'cleanup_ports') from e
