"""Regenerate data/lambda_catalog.csv.

Counterpart of reference ``sky/clouds/service_catalog/data_fetchers/
fetch_lambda_cloud.py`` (which queries /instance-types with an API
key). With a key in the env this could query the live endpoint; the
hermetic default regenerates from an embedded snapshot of Lambda's
public on-demand prices (lambdalabs.com/service/gpu-cloud, 2025).
Lambda has no spot market, so SpotPrice mirrors Price (use_spot is
never feasible on this cloud anyway) and no zones.

Run: ``python -m skypilot_tpu.catalog.data_fetchers.fetch_lambda``
"""
from __future__ import annotations

import csv
import os

# (type, vcpu, mem GiB, $/hr)
_TYPES = [
    ('cpu_4x_general', 4, 16, 0.08),
    ('gpu_1x_a10', 30, 200, 0.75),
    ('gpu_1x_a100_sxm4', 30, 200, 1.29),
    ('gpu_1x_h100_pcie', 26, 200, 2.49),
    ('gpu_8x_a100_80gb_sxm4', 240, 1800, 14.32),
    ('gpu_8x_h100_sxm5', 208, 1800, 23.92),
]

_REGIONS = ['us-east-1', 'us-west-1', 'us-south-1',
            'europe-central-1', 'asia-northeast-1']


def fetch(out_path: str = None) -> str:
    out_path = out_path or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        'data', 'lambda_catalog.csv')
    with open(out_path, 'w', newline='', encoding='utf-8') as f:
        w = csv.writer(f)
        w.writerow(['InstanceType', 'vCPUs', 'MemoryGiB', 'Region',
                    'AvailabilityZone', 'Price', 'SpotPrice'])
        for name, vcpu, mem, price in _TYPES:
            for region in _REGIONS:
                w.writerow([name, vcpu, mem, region, '', price, price])
    return out_path


if __name__ == '__main__':
    print(fetch())
