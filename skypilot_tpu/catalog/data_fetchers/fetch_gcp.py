"""Generate the offline GCP catalog CSV snapshot.

Re-design of reference ``sky/clouds/service_catalog/data_fetchers/
fetch_gcp.py`` (which scrapes GCP SKU APIs and hand-codes v5p/v6e TPU
prices at :34-79). With zero egress in the build image we hand-code the
whole snapshot: per-chip-hour TPU prices and per-hour GCE host prices,
by region. Run::

    python -m skypilot_tpu.catalog.data_fetchers.fetch_gcp

to regenerate ``skypilot_tpu/catalog/data/{tpu,gce}_catalog.csv``.
Prices are an approximation of public list prices (2025 snapshot);
they only need to be *relatively* correct for the optimizer's ranking.
"""
from __future__ import annotations

import csv
import os

from skypilot_tpu.utils import tpu_utils

_DATA_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), 'data')

# generation -> on-demand USD per chip-hour (base region us-central1)
_TPU_CHIP_HOUR = {
    'v2': 1.125,
    'v3': 2.00,
    'v4': 3.22,
    'v5e': 1.20,
    'v5p': 4.20,
    'v6e': 2.70,
}
# spot multiplier per generation
_SPOT_FACTOR = {
    'v2': 0.30, 'v3': 0.30, 'v4': 0.35,
    'v5e': 0.40, 'v5p': 0.40, 'v6e': 0.40,
}
# generation -> zones offering it (approximate public availability)
_TPU_ZONES = {
    'v2': ['us-central1-b', 'us-central1-c', 'us-central1-f',
           'europe-west4-a', 'asia-east1-c'],
    'v3': ['us-central1-a', 'us-central1-b', 'europe-west4-a'],
    'v4': ['us-central2-b'],
    'v5e': ['us-central1-a', 'us-west4-a', 'us-west4-b', 'us-east1-c',
            'us-east5-b', 'europe-west4-b', 'asia-southeast1-b'],
    'v5p': ['us-east5-a', 'us-central1-a', 'europe-west4-b'],
    'v6e': ['us-east5-b', 'us-east1-d', 'us-central2-b', 'europe-west4-a',
            'asia-northeast1-b', 'us-south1-a'],
}
# region -> price multiplier vs us-central1
_REGION_FACTOR = {
    'us-central1': 1.00,
    'us-central2': 1.00,
    'us-east1': 1.00,
    'us-east5': 1.00,
    'us-west4': 1.05,
    'us-south1': 1.00,
    'europe-west4': 1.10,
    'asia-east1': 1.15,
    'asia-southeast1': 1.17,
    'asia-northeast1': 1.20,
}

# GCE instance families: name pattern, per-vCPU $/hr, per-GiB-mem $/hr,
# memory GiB per vCPU.
_GCE_FAMILIES = {
    'n2-standard': (0.0315, 0.0042, 4),
    'n2-highmem': (0.0315, 0.0042, 8),
    'e2-standard': (0.0218, 0.0029, 4),
    'c3-standard': (0.0335, 0.0045, 4),
}
_GCE_SIZES = [2, 4, 8, 16, 32, 48, 64, 96]
_GCE_REGIONS = sorted(_REGION_FACTOR)
_GCE_SPOT_FACTOR = 0.30

# GPU shapes (type, vcpu, mem, $/hr, spot $/hr, accelerator, count):
# a2 (A100), a3 (H100), g2 (L4), n1+attached T4/V100 — public list
# 2025 snapshot, offered in three GPU zones.
_GPU_TYPES = [
    ('g2-standard-4', 4, 16, 0.71, 0.213, 'L4', 1),
    ('g2-standard-48', 48, 192, 3.997, 1.199, 'L4', 4),
    ('g2-standard-96', 96, 384, 7.994, 2.398, 'L4', 8),
    ('n1-standard-8-t4', 8, 30, 0.73, 0.219, 'T4', 1),
    ('n1-standard-8-v100', 8, 30, 2.86, 0.858, 'V100', 1),
    ('a2-highgpu-1g', 12, 85, 3.673, 1.102, 'A100', 1),
    ('a2-highgpu-4g', 48, 340, 14.694, 4.408, 'A100', 4),
    ('a2-highgpu-8g', 96, 680, 29.387, 8.816, 'A100', 8),
    ('a2-ultragpu-1g', 12, 170, 5.069, 1.521, 'A100-80GB', 1),
    ('a2-ultragpu-8g', 96, 1360, 40.55, 12.165, 'A100-80GB', 8),
    ('a3-highgpu-8g', 208, 1872, 88.25, 26.475, 'H100', 8),
]
_GPU_ZONES = [('us-central1', 'us-central1-a'),
              ('us-east1', 'us-east1-b'),
              ('europe-west4', 'europe-west4-a')]


def _region_of(zone: str) -> str:
    return zone.rsplit('-', 1)[0]


def write_tpu_catalog(path: str) -> int:
    rows = []
    for gen, zones in _TPU_ZONES.items():
        for acc_name in tpu_utils.list_sizes(gen):
            s = tpu_utils.parse(acc_name)
            for zone in zones:
                region = _region_of(zone)
                factor = _REGION_FACTOR[region]
                price = _TPU_CHIP_HOUR[gen] * factor
                # Spot varies per zone (+6% per zone letter): the
                # optimizer's cheapest-spot-zone ranking and the
                # failover provisioner's per-zone candidates depend
                # on this variation existing.
                zi = ord(zone[-1]) - ord('a')
                spot = price * _SPOT_FACTOR[gen] * (1 + 0.06 * zi)
                rows.append({
                    'AcceleratorName': s.name,
                    'AcceleratorCount': 1,
                    'NumChips': s.num_chips,
                    'NumHosts': s.num_hosts,
                    'Topology': s.topology,
                    'Region': region,
                    'AvailabilityZone': zone,
                    'PricePerChipHour': round(price, 4),
                    'SpotPricePerChipHour': round(spot, 4),
                })
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, 'w', newline='', encoding='utf-8') as f:
        writer = csv.DictWriter(f, fieldnames=list(rows[0].keys()))
        writer.writeheader()
        writer.writerows(rows)
    return len(rows)


def write_gce_catalog(path: str) -> int:
    rows = []
    for family, (vcpu_price, mem_price, mem_ratio) in _GCE_FAMILIES.items():
        for size in _GCE_SIZES:
            if family.startswith('e2') and size > 32:
                continue
            mem = size * mem_ratio
            base = size * vcpu_price + mem * mem_price
            for region in _GCE_REGIONS:
                factor = _REGION_FACTOR[region]
                for zi, zone_suffix in enumerate(('a', 'b', 'c')):
                    zone = f'{region}-{zone_suffix}'
                    rows.append({
                        'InstanceType': f'{family}-{size}',
                        'vCPUs': size,
                        'MemoryGiB': mem,
                        'Region': region,
                        'AvailabilityZone': zone,
                        'Price': round(base * factor, 4),
                        # Per-zone spot variation (see TPU rows).
                        'SpotPrice': round(
                            base * factor * _GCE_SPOT_FACTOR *
                            (1 + 0.06 * zi), 4),
                        'AcceleratorName': '',
                        'AcceleratorCount': '',
                    })
    for (name, vcpu, mem, price, spot, acc, n) in _GPU_TYPES:
        for region, zone in _GPU_ZONES:
            rows.append({
                'InstanceType': name,
                'vCPUs': vcpu,
                'MemoryGiB': mem,
                'Region': region,
                'AvailabilityZone': zone,
                'Price': price,
                'SpotPrice': spot,
                'AcceleratorName': acc,
                'AcceleratorCount': n,
            })
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, 'w', newline='', encoding='utf-8') as f:
        writer = csv.DictWriter(f, fieldnames=list(rows[0].keys()))
        writer.writeheader()
        writer.writerows(rows)
    return len(rows)


def main() -> None:
    n_tpu = write_tpu_catalog(os.path.join(_DATA_DIR, 'tpu_catalog.csv'))
    n_gce = write_gce_catalog(os.path.join(_DATA_DIR, 'gce_catalog.csv'))
    print(f'Wrote {n_tpu} TPU rows, {n_gce} GCE rows to {_DATA_DIR}')


if __name__ == '__main__':
    main()
