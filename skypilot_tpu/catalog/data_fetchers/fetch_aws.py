"""Regenerate data/aws_catalog.csv.

Counterpart of ``fetch_gcp.py`` (reference
``sky/clouds/service_catalog/data_fetchers/fetch_aws.py`` walks the
AWS pricing API). With credentials + boto3 available this queries the
live Pricing API; without (the common case for this repo's hermetic
environment) it regenerates the CSV from the embedded snapshot of
public on-demand prices (aws.amazon.com/ec2/pricing, 2025) — the same
hand-maintained-fallback pattern the reference uses for v5p/v6e TPU
prices (its fetch_gcp.py:34-79).

Run: ``python -m skypilot_tpu.catalog.data_fetchers.fetch_aws``
"""
from __future__ import annotations

import csv
import os

# (type, vcpu, mem GiB, $/hr us-east-1); spot fractions per-AZ below.
_TYPES = [
    ('t3.medium', 2, 4, 0.0416), ('t3.xlarge', 4, 16, 0.1664),
    ('m6i.large', 2, 8, 0.096), ('m6i.xlarge', 4, 16, 0.192),
    ('m6i.2xlarge', 8, 32, 0.384), ('m6i.4xlarge', 16, 64, 0.768),
    ('m6i.8xlarge', 32, 128, 1.536), ('m6i.16xlarge', 64, 256, 3.072),
    ('c6i.xlarge', 4, 8, 0.17), ('c6i.2xlarge', 8, 16, 0.34),
    ('c6i.4xlarge', 16, 32, 0.68), ('c6i.8xlarge', 32, 64, 1.36),
    ('r6i.xlarge', 4, 32, 0.252), ('r6i.2xlarge', 8, 64, 0.504),
    ('r6i.4xlarge', 16, 128, 1.008), ('m5.8xlarge', 32, 128, 1.536),
]

# region -> (price multiplier vs us-east-1, zone letters)
_REGIONS = {
    'us-east-1': (1.00, 'abc'),
    'us-east-2': (1.00, 'abc'),
    'us-west-2': (1.00, 'abc'),
    'eu-west-1': (1.11, 'abc'),
    'eu-central-1': (1.15, 'abc'),
    'ap-northeast-1': (1.22, 'abc'),
}

# GPU SKUs (type, vcpu, mem, $/hr, spot $/hr, accelerator, count) —
# p3/p4/p5 + g4dn/g5/g6 families (public on-demand list, 2025
# snapshot), offered in the three largest GPU regions.
_GPU_TYPES = [
    ('g4dn.xlarge', 4, 16, 0.526, 0.158, 'T4', 1),
    ('g4dn.12xlarge', 48, 192, 3.912, 1.174, 'T4', 4),
    ('g5.xlarge', 4, 16, 1.006, 0.302, 'A10G', 1),
    ('g5.12xlarge', 48, 192, 5.672, 1.702, 'A10G', 4),
    ('g5.48xlarge', 192, 768, 16.288, 4.886, 'A10G', 8),
    ('g6.xlarge', 4, 16, 0.805, 0.242, 'L4', 1),
    ('g6.12xlarge', 48, 192, 4.602, 1.381, 'L4', 4),
    ('p3.2xlarge', 8, 61, 3.06, 0.918, 'V100', 1),
    ('p3.8xlarge', 32, 244, 12.24, 3.672, 'V100', 4),
    ('p3.16xlarge', 64, 488, 24.48, 7.344, 'V100', 8),
    ('p4d.24xlarge', 96, 1152, 32.773, 9.832, 'A100', 8),
    ('p4de.24xlarge', 96, 1152, 40.966, 12.29, 'A100-80GB', 8),
    ('p5.48xlarge', 192, 2048, 98.32, 29.5, 'H100', 8),
]
_GPU_REGIONS = ['us-east-1', 'us-west-2', 'eu-west-1']


def fetch(out_path: str = None) -> str:
    out_path = out_path or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        'data', 'aws_catalog.csv')
    with open(out_path, 'w', newline='', encoding='utf-8') as f:
        w = csv.writer(f)
        w.writerow(['InstanceType', 'vCPUs', 'MemoryGiB', 'Region',
                    'AvailabilityZone', 'Price', 'SpotPrice',
                    'AcceleratorName', 'AcceleratorCount'])
        for name, vcpu, mem, base in _TYPES:
            for region, (mult, letters) in _REGIONS.items():
                price = round(base * mult, 4)
                for i, letter in enumerate(letters):
                    # Spot varies per AZ (the failover provisioner's
                    # per-zone candidates depend on that).
                    spot = round(price * (0.30 + 0.02 * i), 4)
                    w.writerow([name, vcpu, mem, region,
                                f'{region}{letter}', price, spot,
                                '', ''])
        for name, vcpu, mem, price, spot, acc, n in _GPU_TYPES:
            for region in _GPU_REGIONS:
                w.writerow([name, vcpu, mem, region, f'{region}a',
                            price, spot, acc, n])
    return out_path


if __name__ == '__main__':
    print(fetch())
