"""Regenerate data/azure_catalog.csv.

Counterpart of reference ``sky/clouds/service_catalog/data_fetchers/
fetch_azure.py`` (which walks the Azure Retail Prices API). With zero
egress in this build image the CSV regenerates from an embedded
snapshot of public pay-as-you-go prices (azure.com pricing, 2025);
spot ≈ 13% of on-demand (Azure's typical eviction-priced discount).
Azure exposes no user-facing zones in this catalog — placement inside
a region is the allocator's job — so AvailabilityZone stays empty.

Run: ``python -m skypilot_tpu.catalog.data_fetchers.fetch_azure``
"""
from __future__ import annotations

import csv
import os

# (size, vcpu, mem GiB, $/hr eastus)
_TYPES = [
    ('Standard_B2s', 2, 4, 0.0416),
    ('Standard_D2s_v5', 2, 8, 0.096),
    ('Standard_D4s_v5', 4, 16, 0.192),
    ('Standard_D8s_v5', 8, 32, 0.384),
    ('Standard_D16s_v5', 16, 64, 0.768),
    ('Standard_D32s_v5', 32, 128, 1.536),
    ('Standard_D64s_v5', 64, 256, 3.072),
    ('Standard_E4s_v5', 4, 32, 0.252),
    ('Standard_E8s_v5', 8, 64, 0.504),
    ('Standard_E16s_v5', 16, 128, 1.008),
    ('Standard_E32s_v5', 32, 256, 2.016),
    ('Standard_F4s_v2', 4, 8, 0.169),
    ('Standard_F8s_v2', 8, 16, 0.338),
    ('Standard_F16s_v2', 16, 32, 0.676),
    ('Standard_F32s_v2', 32, 64, 1.353),
]

# region -> price multiplier vs eastus.
_REGIONS = {
    'eastus': 1.0,
    'westus2': 1.0,
    'westeurope': 1.115,
    'southcentralus': 1.042,
    'southeastasia': 1.125,
}

_SPOT_FRACTION = 0.13

# GPU SKUs (size, vcpu, mem, $/hr, spot $/hr, accelerator, count) —
# NC (T4/V100/A100) + ND (A100/H100) series, public list 2025
# snapshot, offered in the three largest GPU regions.
_GPU_TYPES = [
    ('Standard_NC4as_T4_v3', 4, 28, 0.526, 0.158, 'T4', 1),
    ('Standard_NC64as_T4_v3', 64, 440, 4.352, 1.306, 'T4', 4),
    ('Standard_NC6s_v3', 6, 112, 3.06, 0.918, 'V100', 1),
    ('Standard_NC24s_v3', 24, 448, 12.24, 3.672, 'V100', 4),
    ('Standard_NC24ads_A100_v4', 24, 220, 3.673, 1.102,
     'A100-80GB', 1),
    ('Standard_NC96ads_A100_v4', 96, 880, 14.692, 4.408,
     'A100-80GB', 4),
    ('Standard_ND96asr_v4', 96, 900, 27.197, 8.159, 'A100', 8),
    ('Standard_ND96amsr_A100_v4', 96, 1900, 32.77, 9.831,
     'A100-80GB', 8),
    ('Standard_ND96isr_H100_v5', 96, 1900, 98.32, 29.496, 'H100', 8),
]
_GPU_REGIONS = ['eastus', 'westus2', 'westeurope']


def fetch(out_path: str = None) -> str:
    out_path = out_path or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        'data', 'azure_catalog.csv')
    with open(out_path, 'w', newline='', encoding='utf-8') as f:
        w = csv.writer(f)
        w.writerow(['InstanceType', 'vCPUs', 'MemoryGiB', 'Region',
                    'AvailabilityZone', 'Price', 'SpotPrice',
                    'AcceleratorName', 'AcceleratorCount'])
        for name, vcpu, mem, base in _TYPES:
            for region, mult in _REGIONS.items():
                price = round(base * mult, 4)
                w.writerow([name, vcpu, mem, region, '', price,
                            round(price * _SPOT_FRACTION, 4), '', ''])
        for name, vcpu, mem, price, spot, acc, n in _GPU_TYPES:
            for region in _GPU_REGIONS:
                w.writerow([name, vcpu, mem, region, '', price, spot,
                            acc, n])
    return out_path


if __name__ == '__main__':
    print(fetch())
