"""Flash attention for TPU: Pallas forward + backward kernels.

Forward: blockwise online-softmax tiled for the MXU — 128-lane blocks,
f32 accumulation in VMEM scratch, the K dimension as the innermost
'arbitrary' grid axis so the running (m, l, acc) state persists in
scratch across K blocks. The log-sum-exp is saved (broadcast across a
128-lane trailing dim, the standard TPU layout) for the backward.

Backward: two kernels recomputing P from the saved lse — a dQ kernel
(grid over Q blocks, accumulating over K blocks) and a dK/dV kernel
(grid over K blocks, accumulating over Q blocks). Nothing of size
S x S ever touches HBM, so memory stays O(S) and long-context training
(seq 8k+) fits on one chip.

Layout convention at the public API: [batch, seq, heads, head_dim]
(model layout); kernels run in [batch, heads, seq, head_dim].
"""
from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30
_LANES = 128


def reference_attention(q, k, v, *, causal=True, scale=None):
    """O(S^2)-memory einsum attention; ground truth for tests.

    q: [B, Sq, H, D]; k, v: [B, Sk, H, D]. Supports GQA (H_kv divides H).
    """
    q, k, v = _repeat_kv(q, k, v)
    if scale is None:
        scale = q.shape[-1]**-0.5
    s = jnp.einsum('bqhd,bkhd->bhqk', q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        q_pos = lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        k_pos = lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(q_pos + (sk - sq) >= k_pos, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum('bhqk,bkhd->bqhd', p, v.astype(p.dtype),
                   preferred_element_type=jnp.float32)
    return o.astype(q.dtype)


def _repeat_kv(q, k, v):
    h_q, h_kv = q.shape[2], k.shape[2]
    if h_q != h_kv:
        assert h_q % h_kv == 0, (h_q, h_kv)
        rep = h_q // h_kv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return q, k, v


from skypilot_tpu.ops._pallas_compat import (HAS_PALLAS as _HAS_PALLAS,
                                             CompilerParams as
                                             _CompilerParams, pl, pltpu)


def _use_pallas():
    return _HAS_PALLAS and jax.default_backend() == 'tpu'


def _causal_mask(s, q_start, k_start, bq, bk, offset):
    """Bottom-right-aligned causal mask: q_pos + offset >= k_pos,
    offset = Sk - Sq (matches reference_attention / _xla_fwd so TPU and
    fallback agree when Sq != Sk, e.g. decode against a KV cache)."""
    q_pos = q_start + offset + lax.broadcasted_iota(
        jnp.int32, (bq, bk), 0)
    k_pos = k_start + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return jnp.where(q_pos >= k_pos, s, _NEG_INF)


# --------------------------------------------------------- forward kernel


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr,
                acc_scr, *, scale, causal, block_q, block_k,
                num_k_blocks, mask_offset):
    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # Causal: K blocks strictly above the diagonal contribute nothing.
    run = (((iq + 1) * block_q - 1 + mask_offset >= ik * block_k)
           if causal else True)

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)          # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)          # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bq, bk]
        if causal:
            s = _causal_mask(s, iq * block_q, ik * block_k, block_q,
                             block_k, mask_offset)
        m_prev = m_scr[:, :1]                         # [bq, 1]
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                        # [bq, bk]
        corr = jnp.exp(m_prev - m_new)                # [bq, 1]
        l_new = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0, 0].astype(jnp.float32)           # [bk, d]
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_scr[...] = acc_scr[...] * corr + pv
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ik == num_k_blocks - 1)
    def _finalize():
        l_safe = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0, 0] = (acc_scr[...] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = (m_scr[...] + jnp.log(l_safe))


def _flash_fwd_pallas(q, k, v, *, causal, scale, block_q, block_k,
                      interpret):
    """q,k,v: [B,H,S,D] -> (o [B,H,S,D], lse [B,H,S,128] f32)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    assert sq % block_q == 0 and sk % block_k == 0, (sq, sk)
    nq, nk = sq // block_q, sk // block_k

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k,
                               num_k_blocks=nk, mask_offset=sk - sq)
    o, lse = pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b, h, iq, ik: (b, h, iq, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b, h, iq, ik: (b, h, ik, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b, h, iq, ik: (b, h, ik, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b, h, iq, ik: (b, h, iq, 0)),
            pl.BlockSpec((1, 1, block_q, _LANES),
                         lambda b, h, iq, ik: (b, h, iq, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, sq, _LANES), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=('parallel', 'parallel', 'parallel',
                                 'arbitrary')),
        interpret=interpret,
    )(q, k, v)
    return o, lse


# -------------------------------------------------------- backward kernels


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, dq_ref,
               dq_scr, *, scale, causal, block_q, block_k,
               num_k_blocks, mask_offset):
    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    run = (((iq + 1) * block_q - 1 + mask_offset >= ik * block_k)
           if causal else True)

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)           # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)           # [bk, d]
        v = v_ref[0, 0].astype(jnp.float32)           # [bk, d]
        do = do_ref[0, 0].astype(jnp.float32)         # [bq, d]
        o = o_ref[0, 0].astype(jnp.float32)           # [bq, d]
        lse = lse_ref[0, 0][:, :1]                    # [bq, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, iq * block_q, ik * block_k, block_q,
                             block_k, mask_offset)
        p = jnp.exp(s - lse)                          # [bq, bk]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)       # [bq, bk]
        delta = jnp.sum(do * o, axis=1, keepdims=True)  # [bq, 1]
        ds = p * (dp - delta) * scale                 # [bq, bk]
        dq_scr[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ik == num_k_blocks - 1)
    def _finalize():
        dq_ref[0, 0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, dk_ref,
                dv_ref, dk_scr, dv_scr, *, scale, causal, block_q,
                block_k, num_q_blocks, mask_offset):
    ik = pl.program_id(2)
    iq = pl.program_id(3)

    @pl.when(iq == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    run = (((iq + 1) * block_q - 1 + mask_offset >= ik * block_k)
           if causal else True)

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)           # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)           # [bk, d]
        v = v_ref[0, 0].astype(jnp.float32)           # [bk, d]
        do = do_ref[0, 0].astype(jnp.float32)         # [bq, d]
        o = o_ref[0, 0].astype(jnp.float32)           # [bq, d]
        lse = lse_ref[0, 0][:, :1]                    # [bq, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, iq * block_q, ik * block_k, block_q,
                             block_k, mask_offset)
        p = jnp.exp(s - lse)                          # [bq, bk]
        dv_scr[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # [bk, d]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)       # [bq, bk]
        delta = jnp.sum(do * o, axis=1, keepdims=True)
        ds = p * (dp - delta) * scale                 # [bq, bk]
        dk_scr[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # [bk, d]

    @pl.when(iq == num_q_blocks - 1)
    def _finalize():
        dk_ref[0, 0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_bwd_pallas(q, k, v, o, lse, do, *, causal, scale, block_q,
                      block_k, interpret):
    """All [B,H,S,D] (lse [B,H,S,128]); returns (dq, dk, dv)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    # Same divisibility contract as the forward: a silent floor-div
    # here would skip the tail blocks and return wrong gradients.
    assert sq % block_q == 0, (sq, block_q)
    assert sk % block_k == 0, (sk, block_k)
    nq, nk = sq // block_q, sk // block_k

    q_spec = pl.BlockSpec((1, 1, block_q, d),
                          lambda b, h, i, j: (b, h, i, 0))
    lse_spec = pl.BlockSpec((1, 1, block_q, _LANES),
                            lambda b, h, i, j: (b, h, i, 0))
    k_inner = pl.BlockSpec((1, 1, block_k, d),
                           lambda b, h, i, j: (b, h, j, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          num_k_blocks=nk, mask_offset=sk - sq),
        grid=(b, h, nq, nk),
        in_specs=[q_spec, k_inner, k_inner, q_spec, q_spec, lse_spec],
        out_specs=q_spec,
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=('parallel', 'parallel', 'parallel',
                                 'arbitrary')),
        interpret=interpret,
    )(q, k, v, do, o, lse)

    # dK/dV: grid over K blocks; Q is the inner accumulation axis.
    k_outer = pl.BlockSpec((1, 1, block_k, d),
                           lambda b, h, i, j: (b, h, i, 0))
    q_inner = pl.BlockSpec((1, 1, block_q, d),
                           lambda b, h, i, j: (b, h, j, 0))
    lse_inner = pl.BlockSpec((1, 1, block_q, _LANES),
                             lambda b, h, i, j: (b, h, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          num_q_blocks=nq, mask_offset=sk - sq),
        grid=(b, h, nk, nq),
        in_specs=[q_inner, k_outer, k_outer, q_inner, q_inner,
                  lse_inner],
        out_specs=[k_outer, k_outer],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        out_shape=[jax.ShapeDtypeStruct((b, h, sk, d), k.dtype),
                   jax.ShapeDtypeStruct((b, h, sk, d), v.dtype)],
        compiler_params=_CompilerParams(
            dimension_semantics=('parallel', 'parallel', 'parallel',
                                 'arbitrary')),
        interpret=interpret,
    )(q, k, v, do, o, lse)
    return dq, dk, dv


# ----------------------------------------------------------- XLA fallback


def _xla_fwd(qt, kt, vt, *, causal, scale):
    """[B,H,S,D] reference forward returning (o, lse [B,H,S,128])."""
    s = jnp.einsum('bhqd,bhkd->bhqk', qt.astype(jnp.float32),
                   kt.astype(jnp.float32)) * scale
    if causal:
        sq, sk = qt.shape[2], kt.shape[2]
        q_pos = lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        k_pos = lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(q_pos + (sk - sq) >= k_pos, s, _NEG_INF)
    lse = jax.nn.logsumexp(s, axis=-1)                # [B,H,Sq]
    p = jnp.exp(s - lse[..., None])
    o = jnp.einsum('bhqk,bhkd->bhqd', p, vt.astype(jnp.float32))
    lse128 = jnp.broadcast_to(lse[..., None],
                              lse.shape + (_LANES,))
    return o.astype(qt.dtype), lse128


def _xla_bwd(qt, kt, vt, ot, lse, dot_, *, causal, scale):
    qf, kf, vf = (x.astype(jnp.float32) for x in (qt, kt, vt))
    of, dof = ot.astype(jnp.float32), dot_.astype(jnp.float32)
    s = jnp.einsum('bhqd,bhkd->bhqk', qf, kf) * scale
    if causal:
        sq, sk = qf.shape[2], kf.shape[2]
        q_pos = lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        k_pos = lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(q_pos + (sk - sq) >= k_pos, s, _NEG_INF)
    p = jnp.exp(s - lse[..., :1])
    dv = jnp.einsum('bhqk,bhqd->bhkd', p, dof)
    dp = jnp.einsum('bhqd,bhkd->bhqk', dof, vf)
    delta = jnp.sum(dof * of, axis=-1)                # [B,H,Sq]
    ds = p * (dp - delta[..., None]) * scale
    dq = jnp.einsum('bhqk,bhkd->bhqd', ds, kf)
    dk = jnp.einsum('bhqk,bhqd->bhkd', ds, qf)
    return (dq.astype(qt.dtype), dk.astype(kt.dtype),
            dv.astype(vt.dtype))


# ------------------------------------------------------------ custom vjp


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, scale, block_q, block_k):
    return _flash_fwd(q, k, v, causal, scale, block_q, block_k)[0]


def _flash_fwd(q, k, v, causal, scale, block_q, block_k):
    """[B,S,H,D] in/out; residuals for backward."""
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    if _use_pallas():
        ot, lse = _flash_fwd_pallas(qt, kt, vt, causal=causal,
                                    scale=scale, block_q=block_q,
                                    block_k=block_k, interpret=False)
    else:
        ot, lse = _xla_fwd(qt, kt, vt, causal=causal, scale=scale)
    return ot.transpose(0, 2, 1, 3), (q, k, v, ot, lse)


def _flash_bwd(causal, scale, block_q, block_k, res, do):
    q, k, v, ot, lse = res
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    dot_ = do.transpose(0, 2, 1, 3)
    if _use_pallas():
        dq, dk, dv = _flash_bwd_pallas(qt, kt, vt, ot, lse, dot_,
                                       causal=causal, scale=scale,
                                       block_q=block_q,
                                       block_k=block_k,
                                       interpret=False)
    else:
        dq, dk, dv = _xla_bwd(qt, kt, vt, ot, lse, dot_,
                              causal=causal, scale=scale)
    return (dq.transpose(0, 2, 1, 3), dk.transpose(0, 2, 1, 3),
            dv.transpose(0, 2, 1, 3))


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q: jax.Array,
                    k: jax.Array,
                    v: jax.Array,
                    *,
                    causal: bool = True,
                    scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None) -> jax.Array:
    """Flash attention, [batch, seq, heads, head_dim] layout, GQA-aware.

    Dispatches to the Pallas TPU kernels on TPU backends and to exact
    XLA implementations elsewhere; differentiable either way (the
    backward never materializes an S x S matrix on TPU).
    """
    q, k, v = _repeat_kv(q, k, v)
    if scale is None:
        scale = q.shape[-1]**-0.5
    if block_q is None:
        block_q = int(os.environ.get('SKYTPU_FLASH_BLOCK_Q', '1024'))
    if block_k is None:
        block_k = int(os.environ.get('SKYTPU_FLASH_BLOCK_K', '1024'))
    return _flash(q, k, v, causal, scale, block_q, block_k)


# ------------------------------------------- chunked-prefill attention
#
# The attention primitive behind Sarathi-style chunked prefill
# (models.inference.prefill_chunk): a C-token slice of a prompt at
# global positions [offset, offset + C) attends over the slot's
# prompt-region KV cache — into which the chunk's own K/V have
# already been written — under a *query-offset* causal rule
# ``kv_pos <= offset + i``. ``offset`` is per-row (each row of the
# chunk batch is a different serving slot at a different prefill
# cursor), so the mask cannot be a static flash ``mask_offset``: the
# Pallas variant scalar-prefetches the offsets, exactly as
# ``ops.decode_attention`` prefetches its row bounds, and uses them
# both to mask and to *early-exit* K blocks past a row's causal
# frontier (index maps clamp to the last live block, so dead prompt
# headroom is never fetched from HBM). Forward-only: prefill has no
# backward pass.


def _chunk_fwd_kernel(off_ref, q_ref, k_ref, v_ref, o_ref, m_scr,
                      l_scr, acc_scr, *, scale, chunk, block_k,
                      num_k_blocks):
    """Grid (G, H, k-block); online softmax across the K axis.

    off_ref: scalar-prefetched [G] int32 chunk start positions.
    Blocks: q/o (1, chunk, 1, hd); k/v (1, block_k, 1, hd); scratch
    m/l (chunk, LANES) and acc (chunk, hd) persist across K blocks
    (the 'arbitrary' innermost axis). Fully-masked rows accumulate
    exp(0)=1 garbage until their first live block, where the
    corr-factor exp(-inf) washes it to zero — the standard flash
    recurrence; every live row attends at least its own position.
    """
    g = pl.program_id(0)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # K blocks wholly past the row's causal frontier (offset + chunk)
    # contribute nothing — and were never fetched (the index maps
    # clamp to the last live block, eliding the copy).
    @pl.when(ik * block_k < off_ref[g] + chunk)
    def _compute():
        q = q_ref[0, :, 0].astype(jnp.float32)        # [chunk, hd]
        k = k_ref[0, :, 0].astype(jnp.float32)        # [block_k, hd]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        q_pos = off_ref[g] + lax.broadcasted_iota(
            jnp.int32, (chunk, block_k), 0)
        kv_pos = ik * block_k + lax.broadcasted_iota(
            jnp.int32, (chunk, block_k), 1)
        s = jnp.where(q_pos >= kv_pos, s, _NEG_INF)
        m_prev = m_scr[:, :1]
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # Explicitly zero masked probs (same hygiene as the paged
        # decode kernel): a fully-masked q row would otherwise
        # accumulate exp(0)=1 garbage, and NaN junk in masked K slots
        # must not reach the accumulator.
        p = jnp.where(q_pos >= kv_pos, jnp.exp(s - m_new), 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0, :, 0].astype(jnp.float32)        # [block_k, hd]
        acc_scr[...] = acc_scr[...] * corr + lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ik == num_k_blocks - 1)
    def _finalize():
        l_safe = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0, :, 0] = (acc_scr[...] / l_safe).astype(o_ref.dtype)


def _chunk_fwd_pallas(q, k, v, q_offset, *, block_k, interpret):
    """q: [G, C, H, D]; k/v: [G, S, H_kv, D]; q_offset: [G] int32."""
    g, c, h, d = q.shape
    s = k.shape[1]
    n_kv = k.shape[2]
    rep = h // n_kv
    block_k = min(block_k, s)
    assert s % block_k == 0, (s, block_k)
    nk = s // block_k

    def _last_block(off_ref, gi):
        # Last K block any query of row gi can see (>= 0).
        return jnp.maximum(off_ref[gi] + c - 1, 0) // block_k

    def q_map(gi, hi, ik, off_ref):
        del ik, off_ref
        return gi, 0, hi, 0

    def kv_map(gi, hi, ik, off_ref):
        # GQA: query head hi reads shared KV head hi // rep; clamp to
        # the row's last live block so skipped blocks repeat an index
        # and the pipeline elides the fetch.
        return gi, jnp.minimum(ik, _last_block(off_ref, gi)), \
            hi // rep, 0

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(g, h, nk),
        in_specs=[
            pl.BlockSpec((1, c, 1, d), q_map),
            pl.BlockSpec((1, block_k, 1, d), kv_map),
            pl.BlockSpec((1, block_k, 1, d), kv_map),
        ],
        out_specs=pl.BlockSpec((1, c, 1, d), q_map),
        scratch_shapes=[
            pltpu.VMEM((c, _LANES), jnp.float32),
            pltpu.VMEM((c, _LANES), jnp.float32),
            pltpu.VMEM((c, d), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _chunk_fwd_kernel, scale=d**-0.5, chunk=c, block_k=block_k,
        num_k_blocks=nk)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((g, c, h, d), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=('parallel', 'parallel', 'arbitrary')),
        interpret=interpret,
    )(q_offset.astype(jnp.int32), q, k, v)


def _masked_attention_reference(q, k, v, allow, k_scale=None,
                                v_scale=None):
    """Shared masked-einsum attention for the chunk-shaped reference
    paths (chunk prefill + spec-decode verify): GQA-native (K/V stay
    at n_kv heads), int8 per-vector scales applied on scores for K
    and folded into probs for V — same discipline as the decode
    paths. ``allow``: [B, C, S] bool — which cache columns each query
    may attend; the callers own the mask semantics."""
    b, c, h, d = q.shape
    n_kv = k.shape[2]
    rep = h // n_kv
    qf = q.reshape(b, c, n_kv, rep, d)
    scores = jnp.einsum(
        'gcnrd,gsnd->gcnrs', qf, k.astype(qf.dtype),
        preferred_element_type=jnp.float32) * d**-0.5
    if k_scale is not None:
        # [B, S, n_kv] -> [B, 1, n_kv, 1, S]
        scores = scores * jnp.transpose(
            k_scale, (0, 2, 1))[:, None, :, None, :].astype(jnp.float32)
    scores = jnp.where(allow[:, :, None, None, :], scores, _NEG_INF)
    m = jnp.max(scores, axis=-1, keepdims=True)
    e = jnp.exp(scores - m)
    probs = e / jnp.sum(e, axis=-1, keepdims=True)
    if v_scale is not None:
        probs = probs * jnp.transpose(
            v_scale, (0, 2, 1))[:, None, :, None, :].astype(probs.dtype)
    out = jnp.einsum('gcnrs,gsnd->gcnrd', probs.astype(q.dtype),
                     v.astype(q.dtype),
                     preferred_element_type=jnp.float32)
    return out.reshape(b, c, h, d).astype(q.dtype)


def chunk_attention_reference(q, k, v, q_offset, k_scale=None,
                              v_scale=None):
    """Masked-einsum reference for the chunk kernel — and the real
    path for int8 caches and off-TPU backends. Purely positional
    causal mask: query i attends columns <= q_offset + i.
    """
    c = q.shape[1]
    s = k.shape[1]
    q_pos = q_offset[:, None] + jnp.arange(c, dtype=jnp.int32)[None, :]
    allow = (jnp.arange(s, dtype=jnp.int32)[None, None, :] <=
             q_pos[:, :, None])                       # [G, C, S]
    return _masked_attention_reference(q, k, v, allow, k_scale,
                                       v_scale)


def _sharded_chunk_call(inner, mesh, q_specs, args):
    """shard_map one of the chunk-shaped Pallas kernels over a mesh.

    ``q_specs``: per-arg PartitionSpecs (kv-heads on 'tp'; scalars
    replicated). Attention is embarrassingly parallel per kv head, so
    each shard runs the unchanged single-device kernel on its local
    head slice; the kv-group-major query fold (hi // rep) keeps the
    concatenated local outputs identical to the unsharded layout.
    """
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map
    import jax as _jax
    # Honor an ambient partial-manual mesh (see
    # parallel.ring_attention.ring_attention_sharded).
    ambient = getattr(_jax.sharding, 'get_abstract_mesh',
                      lambda: None)()
    if ambient is not None and len(ambient.shape) > 0:
        mesh = ambient
    in_specs, out_spec = q_specs
    # check_rep=False: pallas_call has no replication rule.
    fn = shard_map(inner, mesh=mesh, in_specs=in_specs,
                   out_specs=out_spec, check_rep=False)
    return fn(*args)


def _chunk_impl(impl, s, block_k, k_scale):
    """Shared impl resolution for the chunk-shaped kernels (chunk
    prefill + spec-decode verify): Pallas on TPU for non-quantized
    caches when the K axis tiles, the exact einsum elsewhere."""
    if impl is None:
        impl = ('pallas' if (_use_pallas() and k_scale is None and
                             s % block_k == 0) else 'xla')
    if impl not in ('pallas', 'xla'):
        raise ValueError(f'chunk attention impl {impl!r} not in '
                         "('pallas', 'xla')")
    if impl == 'pallas':
        if k_scale is not None:
            raise ValueError('the Pallas chunk kernel reads bf16/f32 '
                             'caches; int8 goes through the xla path')
        if s % block_k != 0:
            raise ValueError(f'cache region {s} is not a multiple of '
                             f'block_k {block_k}')
    return impl


def chunk_prefill_attention(q: jax.Array,
                            k: jax.Array,
                            v: jax.Array,
                            q_offset: jax.Array,
                            k_scale: Optional[jax.Array] = None,
                            v_scale: Optional[jax.Array] = None,
                            *,
                            impl: Optional[str] = None,
                            block_k: Optional[int] = None,
                            interpret: Optional[bool] = None,
                            mesh=None) -> jax.Array:
    """Query-offset causal attention for one prefill chunk.

    q: [G, C, H, D] — C-token prompt slices, row g's queries sit at
    global positions ``q_offset[g] + i``; k/v: [G, S, H_kv, D] — each
    row's prompt-region KV with the chunk already written at
    [offset, offset + C) (bf16/f32, or int8 with per-vector
    k_scale/v_scale [G, S, H_kv]). Every position <= its query's is
    attended (earlier chunks + causal-within-chunk); later positions
    — including padding garbage past a partial chunk — are masked.
    Returns [G, C, H, D].

    ``impl``: 'pallas' | 'xla' | None (auto: Pallas on TPU for
    non-quantized caches when S divides by block_k, the exact einsum
    elsewhere — interpret-mode Pallas is orders slower on CPU, so
    tests opt in explicitly). ``mesh``: with a mesh, the Pallas path
    runs under shard_map with kv heads sharded over 'tp' (rows stay
    replicated across the data axes — the engine's chunk rows are
    gathered across batch slots, so they carry no stable batch
    sharding); the xla path needs nothing, GSPMD partitions the
    einsums.
    """
    s = k.shape[1]
    if block_k is None:
        block_k = min(_LANES, s)
    if interpret is None:
        interpret = jax.default_backend() != 'tpu'
    impl = _chunk_impl(impl, s, block_k, k_scale)
    if impl == 'pallas':
        if mesh is not None:
            from jax.sharding import PartitionSpec as P
            h_spec = P(None, None, 'tp', None)
            return _sharded_chunk_call(
                functools.partial(_chunk_fwd_pallas, block_k=block_k,
                                  interpret=interpret),
                mesh,
                ((h_spec, h_spec, h_spec, P(None)), h_spec),
                (q, k, v, q_offset))
        return _chunk_fwd_pallas(q, k, v, q_offset, block_k=block_k,
                                 interpret=interpret)
    return chunk_attention_reference(q, k, v, q_offset, k_scale,
                                     v_scale)


# --------------------------------------------- spec-decode verify
#
# The attention primitive behind draft-and-verify speculative decoding
# (models.inference.verify_step): a V-token verify segment per decode
# slot — the current token plus up to V-1 drafted candidates — has
# already been written into the slot's cache row at columns
# [seg_start, seg_start + V), and every candidate position must attend
# causally into the paged KV cache. Unlike the prefill chunk, the
# decode-region cache is POSITION != COLUMN: continuous batching
# leaves dmask holes inside the live region (recycled slots, rejected
# candidates from earlier verify ticks), so the mask cannot be the
# chunk kernel's purely positional ``kv_pos <= offset + i`` rule. The
# verify rule is the union of the two authorities:
#
#     attend(col, i) = dmask[b, col]                 (the live cache)
#                    | seg_start <= col <= seg_start + i   (the
#                      segment, causal within itself — query i sees
#                      f_0..f_i, self-inclusive like decode's self
#                      term)
#
# dmask is False at and beyond ``seg_start`` (the shared write
# frontier is monotone and recycled rows are cleared), so the two
# terms never overlap. The Pallas variant reuses the chunk kernel's
# scalar-prefetched query-offset masking for the segment term — the
# prefetched scalar here is ``seg_start`` — plus the paged decode
# kernel's int8-mask input for the dmask term, and clamps its K-block
# index maps to the last block any query can see (blocks past the
# frontier are never fetched). Forward-only, like the chunk kernel.


def _verify_fwd_kernel(seg_ref, q_ref, k_ref, v_ref, mask_ref, o_ref,
                       m_scr, l_scr, acc_scr, *, scale, v_len,
                       block_k, num_k_blocks):
    """Grid (B, H, k-block); online softmax across the K axis.

    seg_ref: scalar-prefetched [1] int32 segment start column (the
    shared write frontier — one scalar, every row writes the same
    columns). mask_ref: (1, block_k) int8 dmask block. Same flash
    recurrence and masked-prob hygiene as the chunk kernel."""
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # Blocks wholly past the segment's end contribute nothing — and
    # were never fetched (index maps clamp to the last live block).
    @pl.when(ik * block_k < seg_ref[0] + v_len)
    def _compute():
        q = q_ref[0, :, 0].astype(jnp.float32)        # [v_len, hd]
        k = k_ref[0, :, 0].astype(jnp.float32)        # [block_k, hd]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        col = ik * block_k + lax.broadcasted_iota(
            jnp.int32, (v_len, block_k), 1)
        qi = lax.broadcasted_iota(jnp.int32, (v_len, block_k), 0)
        seg = (col >= seg_ref[0]) & (col <= seg_ref[0] + qi)
        allow = (mask_ref[0, :] != 0)[None, :] | seg
        s = jnp.where(allow, s, _NEG_INF)
        m_prev = m_scr[:, :1]
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.where(allow, jnp.exp(s - m_new), 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0, :, 0].astype(jnp.float32)        # [block_k, hd]
        acc_scr[...] = acc_scr[...] * corr + lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ik == num_k_blocks - 1)
    def _finalize():
        l_safe = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0, :, 0] = (acc_scr[...] / l_safe).astype(o_ref.dtype)


def _verify_fwd_pallas(q, k, v, valid, seg_start, *, block_k,
                       interpret):
    """q: [B, V, H, D]; k/v: [B, S, H_kv, D]; valid: [B, S] bool;
    seg_start: scalar int32."""
    b, v_len, h, d = q.shape
    s = k.shape[1]
    n_kv = k.shape[2]
    rep = h // n_kv
    block_k = min(block_k, s)
    assert s % block_k == 0, (s, block_k)
    nk = s // block_k
    seg = jnp.asarray(seg_start, jnp.int32).reshape(1)

    def _last_block(seg_ref):
        # Last K block any verify query can see (>= 0): the segment's
        # final column seg_start + v_len - 1.
        return jnp.maximum(seg_ref[0] + v_len - 1, 0) // block_k

    def q_map(bi, hi, ik, seg_ref):
        del ik, seg_ref
        return bi, 0, hi, 0

    def kv_map(bi, hi, ik, seg_ref):
        return bi, jnp.minimum(ik, _last_block(seg_ref)), \
            hi // rep, 0

    def mask_map(bi, hi, ik, seg_ref):
        del hi
        return bi, jnp.minimum(ik, _last_block(seg_ref))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, h, nk),
        in_specs=[
            pl.BlockSpec((1, v_len, 1, d), q_map),
            pl.BlockSpec((1, block_k, 1, d), kv_map),
            pl.BlockSpec((1, block_k, 1, d), kv_map),
            pl.BlockSpec((1, block_k), mask_map),
        ],
        out_specs=pl.BlockSpec((1, v_len, 1, d), q_map),
        scratch_shapes=[
            pltpu.VMEM((v_len, _LANES), jnp.float32),
            pltpu.VMEM((v_len, _LANES), jnp.float32),
            pltpu.VMEM((v_len, d), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _verify_fwd_kernel, scale=d**-0.5, v_len=v_len,
        block_k=block_k, num_k_blocks=nk)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, v_len, h, d), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=('parallel', 'parallel', 'arbitrary')),
        interpret=interpret,
    )(seg, q, k, v, valid.astype(jnp.int8))


def verify_attention_reference(q, k, v, valid, seg_start,
                               k_scale=None, v_scale=None):
    """Masked-einsum reference for the verify kernel — and the real
    path for int8 caches and off-TPU backends. Mask is the union of
    the live-cache dmask and the segment-causal term (query i sees
    segment columns seg_start..seg_start + i, self-inclusive)."""
    vq = q.shape[1]
    s = k.shape[1]
    seg_start = jnp.asarray(seg_start, jnp.int32)
    col = jnp.arange(s, dtype=jnp.int32)[None, None, :]
    qi = jnp.arange(vq, dtype=jnp.int32)[None, :, None]
    seg = (col >= seg_start) & (col <= seg_start + qi)
    allow = valid[:, None, :] | seg                    # [B, V, S]
    return _masked_attention_reference(q, k, v, allow, k_scale,
                                       v_scale)


def verify_attention(q: jax.Array,
                     k: jax.Array,
                     v: jax.Array,
                     valid: jax.Array,
                     seg_start: jax.Array,
                     k_scale: Optional[jax.Array] = None,
                     v_scale: Optional[jax.Array] = None,
                     *,
                     impl: Optional[str] = None,
                     block_k: Optional[int] = None,
                     interpret: Optional[bool] = None,
                     mesh=None) -> jax.Array:
    """dmask-valid + segment-causal attention for one verify pass.

    q: [B, V, H, D] — the V-token verify segment's queries (current
    token + drafted candidates); k/v: [B, S, H_kv, D] — each row's
    cache region with the segment K/V already written at columns
    [seg_start, seg_start + V) (bf16/f32, or int8 with per-vector
    k_scale/v_scale [B, S, H_kv]); valid: [B, S] bool — the cache
    dmask (False at and beyond ``seg_start``); seg_start: traced
    scalar column of the shared write frontier. Query i attends every
    dmask-true column plus segment columns seg_start..seg_start + i
    (self-inclusive). Returns [B, V, H, D].

    ``impl``: 'pallas' | 'xla' | None — same auto rule as
    ``chunk_prefill_attention``. ``mesh``: with a mesh, the Pallas
    path runs under shard_map — kv heads on 'tp', batch on the data
    axes (mirroring the cache's CACHE_SPEC), the seg_start scalar
    replicated.
    """
    s = k.shape[1]
    if block_k is None:
        block_k = min(_LANES, s)
    if interpret is None:
        interpret = jax.default_backend() != 'tpu'
    impl = _chunk_impl(impl, s, block_k, k_scale)
    if impl == 'pallas':
        if mesh is not None:
            from jax.sharding import PartitionSpec as P
            data = ('dp', 'fsdp')
            return _sharded_chunk_call(
                functools.partial(_verify_fwd_pallas, block_k=block_k,
                                  interpret=interpret),
                mesh,
                ((P(data, None, 'tp', None), P(data, None, 'tp', None),
                  P(data, None, 'tp', None), P(data, None), P()),
                 P(data, None, 'tp', None)),
                (q, k, v, valid, jnp.asarray(seg_start, jnp.int32)))
        return _verify_fwd_pallas(q, k, v, valid, seg_start,
                                  block_k=block_k, interpret=interpret)
    return verify_attention_reference(q, k, v, valid, seg_start,
                                      k_scale, v_scale)
