"""TPU compute kernels (Pallas) + XLA reference implementations.

The reference framework ships no kernels (it is pure-Python
orchestration; SURVEY.md §2 native-code note) — its GPU recipes lean on
torch/NCCL. Our TPU-first equivalent keeps the hot ops here: flash
attention on the MXU via Pallas, with an XLA einsum reference used for
CPU tests and as the autodiff fallback.
"""
from skypilot_tpu.ops.flash_attention import (flash_attention,
                                              reference_attention)
from skypilot_tpu.ops.decode_attention import (num_pages_for,
                                               paged_gqa_decode_attention)

__all__ = ['flash_attention', 'reference_attention',
           'paged_gqa_decode_attention', 'num_pages_for']
