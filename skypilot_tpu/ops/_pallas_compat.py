"""Shared Pallas import guard + jax version compat for ops/ kernels.

Kept in one place so the next jax API rename is fixed once: the
TPUCompilerParams -> CompilerParams rename is handled here, and the
import stays optional so control-plane code paths never pay for
Pallas (or fail where it is absent).
"""
from __future__ import annotations

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    HAS_PALLAS = True
    CompilerParams = getattr(pltpu, 'CompilerParams', None) or getattr(
        pltpu, 'TPUCompilerParams')
except ImportError:  # pragma: no cover
    pl = pltpu = CompilerParams = None
    HAS_PALLAS = False
