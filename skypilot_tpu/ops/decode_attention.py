"""Paged ragged decode attention: the Pallas kernel behind decode.

Decode is HBM-bandwidth-bound: every generated token re-reads the KV
cache, so bytes-read-per-step IS the step time. The lax einsum path
(`models.inference._gqa_decode_attention`, kept as the parity
reference) contracts over the entire preallocated ``[B, max_seq]``
cache and masks dead positions afterwards — a batch of short
sequences pays full-``max_seq`` traffic per token. This module reads
only live cache *pages* instead (the PagedAttention / JetStream
ragged-attention observation):

- **Paging.** The cache's ``max_seq`` axis is tiled into fixed
  ``page``-sized blocks. The kernel grid is ``(B, n_kv_heads,
  num_pages)`` with an online softmax accumulated across the page
  axis in VMEM scratch (same running (m, l, acc) recurrence as
  ``ops.flash_attention``).
- **Per-row early exit.** Each row's live upper bound (``row_bound``,
  scalar-prefetched so it is available to the *index maps*, not just
  the kernel body) gates both compute (`pl.when(i * page < bound)`)
  and DMA: the K/V/mask index maps clamp the page index to the row's
  last live page, and Pallas elides a block copy whose index did not
  change — dead pages are never fetched from HBM. A poison test
  (NaNs planted beyond the bound) asserts this.
- **Dispatch-level page count.** Callers pass ``num_pages`` (static)
  so the grid itself — and therefore worst-case traffic — scales with
  occupancy, not ``max_seq``. ``num_pages_for`` is the shared
  occupancy -> page-count policy (page-granular, with a power-of-two
  headroom round-up so the number of compiled programs stays
  logarithmic, matching the serving engine's chunk discipline).
- **Fused int8 KV dequant.** With a quantized cache the kernel reads
  int8 pages (half the bytes) and applies the per-vector scales
  in-register: on the score matrix for K, folded into the probs for V
  — the dequantized page never exists anywhere.
- **Ragged validity stays exact.** ``dmask`` remains the authority on
  which slots are readable (continuous batching leaves masked holes
  *inside* the live region: a recycled slot's stale tail, the gap
  between a short prompt and the decode base). ``row_bound`` is only
  a conservative upper bound used to skip whole pages.

The incoming token's own K/V (the "self" term) is merged *outside*
the kernel by one more online-softmax step in plain lax — it is a
single position and keeping it out of the kernel keeps the page loop
uniform.

CPU tier-1 tests exercise the real kernel through ``interpret=True``
(auto-selected off-TPU), so the grid logic, index-map clamping, and
fused dequant are covered without hardware.
"""
from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30
_LANES = 128
DEFAULT_PAGE = 128

from skypilot_tpu.ops._pallas_compat import (HAS_PALLAS as _HAS_PALLAS,
                                             CompilerParams as
                                             _CompilerParams, pl, pltpu)


def default_page() -> int:
    """Page size (cache slots per block). 128 matches the TPU lane
    width and the bf16/int8 tile constraints; override with
    SKYTPU_DECODE_PAGE for experiments."""
    return int(os.environ.get('SKYTPU_DECODE_PAGE', str(DEFAULT_PAGE)))


def resolve_impl(impl: Optional[str] = None) -> str:
    """'paged' | 'lax' from an explicit choice, SKYTPU_DECODE_ATTN,
    or 'auto' (paged on TPU, lax elsewhere — interpret-mode Pallas is
    orders slower than the einsum on CPU, so auto never picks it;
    tests force 'paged' explicitly)."""
    impl = impl or os.environ.get('SKYTPU_DECODE_ATTN', 'auto')
    if impl not in ('auto', 'paged', 'lax'):
        raise ValueError(
            f"decode attention impl {impl!r} not in "
            "('auto', 'paged', 'lax')")
    if not _HAS_PALLAS:
        return 'lax'
    if impl == 'auto':
        return 'paged' if jax.default_backend() == 'tpu' else 'lax'
    return impl


def num_pages_for(live: int, page: int, total_pages: int,
                  base_pages: int = 0) -> int:
    """Pages to dispatch for a live region of ``live`` slots.

    Page-granular (cost scales with occupancy), with the pages beyond
    ``base_pages`` (the always-live prompt region) rounded up to a
    power of two: as decode occupancy grows the page count takes at
    most log2(headroom/page) distinct values, so the number of
    compiled decode programs stays logarithmic — the same discipline
    the serving engine applies to its chunk sizes.
    """
    need = max(1, -(-live // page))
    if base_pages and need > base_pages:
        extra = need - base_pages
        p2 = 1
        while p2 < extra:
            p2 *= 2
        need = base_pages + p2
    return max(1, min(need, total_pages))


# ------------------------------------------------------------- kernel


def _paged_kernel(bound_ref, *refs, scale, page, num_pages, quant):
    """Grid (b, kv_head, page); online softmax over the page axis.

    bound_ref: scalar-prefetched [B] int32 — row's live slot count.
    Blocks: q (1,1,rep,hd); k/v (1,page,1,hd); mask (1,page) int8;
    [k_scale/v_scale (1,page,1)]; outs acc (1,1,rep,hd) f32 and
    m/l (1,1,rep,LANES) f32 — unnormalized, so the caller can merge
    the self term with one more online-softmax step.
    """
    if quant:
        (q_ref, k_ref, v_ref, mask_ref, ks_ref, vs_ref,
         acc_ref, m_ref, l_ref, m_scr, l_scr, acc_scr) = refs
    else:
        (q_ref, k_ref, v_ref, mask_ref,
         acc_ref, m_ref, l_ref, m_scr, l_scr, acc_scr) = refs
        ks_ref = vs_ref = None
    b = pl.program_id(0)
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # Per-row early exit: pages at/beyond the row's bound contribute
    # nothing — and were not even fetched (index maps clamp to the
    # row's last live page, so the block index repeats and the
    # pipeline elides the copy).
    @pl.when(i * page < bound_ref[b])
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)            # [rep, hd]
        k = k_ref[0, :, 0].astype(jnp.float32)         # [page, hd]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [rep, page]
        if ks_ref is not None:
            # int8 K: per-vector scale is constant over head_dim, so
            # it factors out of the contraction onto the scores.
            s = s * ks_ref[0, :, 0].astype(jnp.float32)[None, :]
        valid = (mask_ref[0, :] != 0)[None, :]         # [1, page]
        s = jnp.where(valid, s, _NEG_INF)
        m_prev = m_scr[:, :1]                          # [rep, 1]
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # Explicitly zero masked probs: on an all-masked page
        # exp(s - m_new) would be exp(0) = 1 (both at _NEG_INF), and
        # it kills any NaN garbage sitting in masked slots.
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0, :, 0].astype(jnp.float32)         # [page, hd]
        if vs_ref is not None:
            # int8 V: fold the per-vector scale into the probs (the
            # contraction is over the page axis, so a per-slot scale
            # factors through linearly).
            p = p * vs_ref[0, :, 0].astype(jnp.float32)[None, :]
        acc_scr[...] = acc_scr[...] * corr + lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(i == num_pages - 1)
    def _finalize():
        acc_ref[0, 0] = acc_scr[...]
        m_ref[0, 0] = m_scr[...]
        l_ref[0, 0] = l_scr[...]


def _paged_softmax_pages(q4, kc, vc, mask_i8, row_bound, k_scale,
                         v_scale, *, page, num_pages, interpret):
    """Run the page grid; returns unnormalized (acc, m, l) in f32."""
    b, s, n_kv, hd = kc.shape
    rep = q4.shape[2]
    quant = k_scale is not None

    def _last_page(bound_ref, bi):
        # Last live page for row bi (>= 0 so an empty row still maps
        # to a real block).
        return jnp.maximum(bound_ref[bi] - 1, 0) // page

    def q_map(bi, h, i, bound_ref):
        del i, bound_ref
        return bi, h, 0, 0

    def kv_map(bi, h, i, bound_ref):
        return bi, jnp.minimum(i, _last_page(bound_ref, bi)), h, 0

    def mask_map(bi, h, i, bound_ref):
        del h
        return bi, jnp.minimum(i, _last_page(bound_ref, bi))

    def scale_map(bi, h, i, bound_ref):
        return bi, jnp.minimum(i, _last_page(bound_ref, bi)), h

    in_specs = [
        pl.BlockSpec((1, 1, rep, hd), q_map),
        pl.BlockSpec((1, page, 1, hd), kv_map),
        pl.BlockSpec((1, page, 1, hd), kv_map),
        pl.BlockSpec((1, page), mask_map),
    ]
    args = [q4, kc, vc, mask_i8]
    if quant:
        in_specs += [pl.BlockSpec((1, page, 1), scale_map),
                     pl.BlockSpec((1, page, 1), scale_map)]
        args += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, n_kv, num_pages),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, rep, hd), q_map),
            pl.BlockSpec((1, 1, rep, _LANES), q_map),
            pl.BlockSpec((1, 1, rep, _LANES), q_map),
        ],
        scratch_shapes=[
            pltpu.VMEM((rep, _LANES), jnp.float32),
            pltpu.VMEM((rep, _LANES), jnp.float32),
            pltpu.VMEM((rep, hd), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _paged_kernel, scale=hd**-0.5, page=page, num_pages=num_pages,
        quant=quant)
    acc, m, l = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b, n_kv, rep, hd), jnp.float32),
            jax.ShapeDtypeStruct((b, n_kv, rep, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((b, n_kv, rep, _LANES), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=('parallel', 'parallel', 'arbitrary')),
        interpret=interpret,
    )(row_bound.astype(jnp.int32), *args)
    return acc, m, l


def paged_gqa_decode_attention(q, kc, vc, valid, row_bound,
                               k_self=None, v_self=None,
                               k_scale=None, v_scale=None, *,
                               page: Optional[int] = None,
                               num_pages: Optional[int] = None,
                               interpret: Optional[bool] = None
                               ) -> jax.Array:
    """One-position GQA attention against a paged cache (+ self).

    Drop-in signature match for the lax reference
    (``models.inference._gqa_decode_attention``) plus paging controls:
    q [B, n_heads, hd]; kc/vc [B, S, n_kv, hd] (bf16, or int8 with
    k_scale/v_scale [B, S, n_kv]); valid [B, S] bool; row_bound [B]
    int32 — per-row count of live slots (every valid slot of row b
    must lie below row_bound[b]; pages at/beyond it are skipped
    entirely). ``num_pages`` limits the grid (slots >= num_pages*page
    are never read — the caller guarantees they are dead);
    ``interpret`` defaults to True off-TPU so CPU tests run the real
    kernel. Returns [B, n_heads * hd].
    """
    b, s, n_kv, hd = kc.shape
    page = page or default_page()
    if s % page != 0:
        raise ValueError(f'cache length {s} is not a multiple of the '
                         f'page size {page}')
    total_pages = s // page
    num_pages = total_pages if num_pages is None else min(
        max(1, num_pages), total_pages)
    if interpret is None:
        interpret = jax.default_backend() != 'tpu'
    rep = q.shape[1] // n_kv
    q4 = q.reshape(b, n_kv, rep, hd)

    acc, m, l = _paged_softmax_pages(
        q4, kc, vc, valid.astype(jnp.int8), row_bound, k_scale,
        v_scale, page=page, num_pages=num_pages, interpret=interpret)
    m1 = m[..., 0]                                     # [B, n_kv, rep]
    l1 = l[..., 0]
    if k_self is None:
        out = acc / jnp.maximum(l1, 1e-30)[..., None]
    else:
        # Merge the incoming token's own K/V with one more
        # online-softmax step (mathematically identical to the
        # reference's concat-then-softmax).
        s_self = jnp.einsum(
            'bkrh,bkh->bkr', q4, k_self,
            preferred_element_type=jnp.float32) * hd**-0.5
        m2 = jnp.maximum(m1, s_self)
        c1 = jnp.exp(m1 - m2)
        c2 = jnp.exp(s_self - m2)
        l2 = jnp.maximum(l1 * c1 + c2, 1e-30)
        out = (acc * c1[..., None] +
               c2[..., None] * v_self[:, :, None].astype(jnp.float32)
               ) / l2[..., None]
    return out.reshape(b, n_kv * rep * hd).astype(q.dtype)


def sharded_paged_gqa_decode_attention(q, kc, vc, valid, row_bound,
                                       k_self=None, v_self=None,
                                       k_scale=None, v_scale=None, *,
                                       mesh,
                                       page: Optional[int] = None,
                                       num_pages: Optional[int] = None,
                                       interpret: Optional[bool] = None
                                       ) -> jax.Array:
    """Mesh-native paged decode: ``shard_map`` the single-device
    kernel over the mesh's data and tensor axes.

    Attention is embarrassingly parallel per KV head and the cache is
    already laid out kv-heads-on-'tp' / batch-on-('dp','fsdp')
    (``models.inference.CACHE_SPEC``), so each shard runs the
    unchanged kernel on its local head slice with the
    scalar-prefetched ``row_bound`` replicated across 'tp'. Query
    heads fold kv-group-major ([B, n_kv, rep, hd] — the same blocks a
    column-sharded wq produces), so concatenating the local
    [B, n_kv_local*rep*hd] outputs along the head axis IS the global
    unsharded result: no collective inside, the wo contraction's
    all-reduce stays where GSPMD already puts it. Requires
    ``n_kv_heads % tp == 0`` — the divisibility the sharded cache
    itself needs.
    """
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    import jax as _jax
    # Honor an ambient partial-manual mesh (see
    # parallel.ring_attention.ring_attention_sharded).
    ambient = getattr(_jax.sharding, 'get_abstract_mesh',
                      lambda: None)()
    if ambient is not None and len(ambient.shape) > 0:
        mesh = ambient
    n_kv = kc.shape[2]
    tp = dict(mesh.shape).get('tp', 1)
    if n_kv % tp:
        raise ValueError(f'n_kv_heads {n_kv} not divisible by '
                         f'tp {tp}')
    data = ('dp', 'fsdp')
    q_spec = P(data, 'tp', None)           # [B, heads, hd]
    kv_spec = P(data, None, 'tp', None)    # [B, S, n_kv, hd]
    in_specs = [q_spec, kv_spec, kv_spec, P(data, None), P(data)]
    args = [q, kc, vc, valid, row_bound]
    has_self = k_self is not None
    has_scale = k_scale is not None
    if has_self:
        in_specs += [q_spec, q_spec]       # [B, n_kv, hd]
        args += [k_self, v_self]
    if has_scale:
        in_specs += [P(data, None, 'tp')] * 2   # [B, S, n_kv]
        args += [k_scale, v_scale]

    def inner(q, kc, vc, valid, row_bound, *rest):
        rest = list(rest)
        ks = vs = ksc = vsc = None
        if has_self:
            ks, vs = rest[0], rest[1]
            rest = rest[2:]
        if has_scale:
            ksc, vsc = rest
        return paged_gqa_decode_attention(
            q, kc, vc, valid, row_bound, k_self=ks, v_self=vs,
            k_scale=ksc, v_scale=vsc, page=page, num_pages=num_pages,
            interpret=interpret)

    # check_rep=False: there is no replication rule for pallas_call,
    # and every output axis is genuinely sharded anyway.
    fn = shard_map(inner, mesh=mesh, in_specs=tuple(in_specs),
                   out_specs=P(data, 'tp'), check_rep=False)
    return fn(*args)
