"""LazyImport + per-cloud cached sessions."""
from __future__ import annotations

import importlib
import threading
from typing import Any, Callable, Optional


class LazyImport:
    """Defer a module import until first attribute access.

    ``boto3 = LazyImport('boto3')`` costs nothing unless AWS code
    actually runs; a missing SDK raises only when used, with an
    install hint (reference sky/adaptors/common.py:9).
    """

    def __init__(self, module_name: str,
                 import_error_message: Optional[str] = None) -> None:
        self._module_name = module_name
        self._module: Any = None
        self._error = import_error_message
        self._lock = threading.Lock()

    def _load(self) -> Any:
        if self._module is None:
            with self._lock:
                if self._module is None:
                    try:
                        self._module = importlib.import_module(
                            self._module_name)
                    except ImportError as e:
                        msg = self._error or (
                            f'Failed to import {self._module_name!r}; '
                            f'install it to use this cloud.')
                        raise ImportError(msg) from e
        return self._module

    def __getattr__(self, name: str) -> Any:
        return getattr(self._load(), name)


class CachedSession:
    """One authorized session per process (auth handshakes are
    hundreds of ms; status refresh loops would otherwise pay it per
    call — the reference caches via module globals in each adaptor)."""

    def __init__(self, factory: Callable[[], Any]) -> None:
        self._factory = factory
        self._session: Any = None
        self._lock = threading.Lock()

    def get(self) -> Any:
        if self._session is None:
            with self._lock:
                if self._session is None:
                    self._session = self._factory()
        return self._session

    def reset(self) -> None:
        with self._lock:
            self._session = None
