"""Cloud SDK adaptors: lazy imports + cached auth.

Re-design of reference ``sky/adaptors/`` (``common.py:9-45``
LazyImport): an unused cloud's SDK must cost nothing at import time —
``import skypilot_tpu`` pulls no boto3/google-auth — and repeated
credential loads within one process reuse one authorized session.
"""
from skypilot_tpu.adaptors.common import LazyImport

__all__ = ['LazyImport']
