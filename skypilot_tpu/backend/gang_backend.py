"""GangBackend — the cluster runtime driver (the framework's heart).

TPU-native re-design of reference ``CloudVmRayBackend``
(sky/backends/cloud_vm_ray_backend.py:2618) with Ray removed entirely
(SURVEY.md §7 design delta (a)): a TPU pod slice is gang-provisioned by
the cloud, so gang semantics come from a plain per-host fan-out driven
by the on-cluster agent (skypilot_tpu/agent/), not placement groups.

Responsibilities:
- RetryingProvisioner: zone→region failover with a blocked-resources
  set and typed error granularity (reference RetryingVmProvisioner
  :1125 + FailoverCloudErrorHandlerV2 :888), optional retry_until_up.
- Runtime setup via provisioner.post_provision_runtime_setup.
- Job submission through agent codegen (add-job/queue-job), with the
  rank/IP/topology env contract resolved from the slice topology.
- Log tailing, cancel, autostop, teardown.
"""
from __future__ import annotations

import json
import os
import shlex
import typing
from typing import Any, Dict, List, Optional

from skypilot_tpu import authentication
from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state
from skypilot_tpu import provision
from skypilot_tpu import trace as trace_lib
from skypilot_tpu.agent import cli as agent_cli
from skypilot_tpu.agent import constants as agent_constants
from skypilot_tpu.backend import backend as backend_lib
from skypilot_tpu.backend import backend_utils
from skypilot_tpu.provision import common as provision_common
from skypilot_tpu.provision import provisioner
from skypilot_tpu.utils import command_runner as runner_lib
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import docker_utils
from skypilot_tpu.utils import log as sky_logging
from skypilot_tpu.utils import registry
from skypilot_tpu.utils import retry as retry_lib
from skypilot_tpu.utils import status_lib
from skypilot_tpu.utils import subprocess_utils

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu import task as task_lib

logger = sky_logging.init_logger(__name__)

# retry_until_up rounds: unlimited attempts, capped exponential
# backoff (one shared RetryPolicy implementation; see utils/retry.py).
# Jitter-free: each round re-issues real provider API calls for every
# candidate zone, so the gap must be a guaranteed minimum, not
# uniform(0, base). The clock is swappable for wall-clock-free tests.
_PROVISION_BACKOFF_INITIAL = 5.0
_PROVISION_RETRY_POLICY = retry_lib.RetryPolicy(
    max_attempts=None,
    initial_backoff=_PROVISION_BACKOFF_INITIAL,
    max_backoff=300.0,
    multiplier=1.6,
    jitter='none',
    site='provision.retry_until_up')


def log_root() -> str:
    base = os.path.expanduser(
        os.environ.get('SKYTPU_DATA_DIR', '~/.skytpu'))
    return os.path.join(base, 'logs')


class GangResourceHandle(backend_lib.ResourceHandle):
    """Everything needed to reach and drive a provisioned cluster."""

    def __init__(self, *, cluster_name: str, cluster_name_on_cloud: str,
                 launched_resources: 'resources_lib.Resources',
                 launched_nodes: int,
                 cluster_info: provision_common.ClusterInfo,
                 state_dir: str,
                 ssh_private_key: Optional[str] = None) -> None:
        self.cluster_name = cluster_name
        self.cluster_name_on_cloud = cluster_name_on_cloud
        self.launched_resources = launched_resources
        self.launched_nodes = launched_nodes
        self.cluster_info = cluster_info
        self.state_dir = state_dir
        self.ssh_private_key = ssh_private_key

    # -- identity ------------------------------------------------------
    def get_cluster_name(self) -> str:
        return self.cluster_name

    @property
    def provider_name(self) -> str:
        return self.cluster_info.provider_name

    @property
    def region(self) -> str:
        return self.cluster_info.region

    @property
    def zone(self) -> Optional[str]:
        return self.cluster_info.zone

    # -- hosts ---------------------------------------------------------
    @property
    def num_hosts(self) -> int:
        """Gang width: total TPU hosts across all logical nodes
        (reference num_ips_per_node fan-out :2531,5052)."""
        return self.cluster_info.num_hosts()

    def ip_list(self) -> List[str]:
        return self.cluster_info.ip_list()

    def runners(self) -> List[runner_lib.CommandRunner]:
        return provisioner.make_runners(self.cluster_info,
                                        self.ssh_private_key)

    def head_runner(self) -> runner_lib.CommandRunner:
        return self.runners()[0]

    def __repr__(self) -> str:
        return (f'GangResourceHandle({self.cluster_name}, '
                f'{self.launched_resources!r}, hosts={self.num_hosts})')


# ----------------------------------------------------------------------
class RetryingProvisioner:
    """Candidate iteration with blocked-resource failover."""

    def __init__(self, cluster_name: str, cluster_name_on_cloud: str,
                 retry_until_up: bool,
                 blocked_regions=None) -> None:
        self._cluster_name = cluster_name
        self._cluster_name_on_cloud = cluster_name_on_cloud
        self._retry_until_up = retry_until_up
        # (region, zone) pairs proven unavailable this request. Callers
        # may seed whole regions (managed-jobs EAGER_NEXT_REGION blocks
        # the just-preempted region).
        self._blocked: set = {(r, None) for r in (blocked_regions or ())}
        self._seed_blocked = frozenset(self._blocked)

    def _candidates(self, to_provision: 'resources_lib.Resources'):
        cloud = to_provision.cloud
        for region, zone in cloud.zones_provision_loop(
                to_provision, region=to_provision.region):
            if (region, zone) in self._blocked:
                continue
            if (region, None) in self._blocked:
                continue
            yield region, zone

    @trace_lib.span('provision.attempt', slow_ok=True)
    def _one_attempt(
            self, to_provision: 'resources_lib.Resources',
            num_nodes: int, region: str, zone: Optional[str]
    ) -> provision_common.ClusterInfo:
        sp = trace_lib.current_span()
        if sp is not None:
            sp.set_attr(cluster=self._cluster_name, region=region,
                        zone=zone or '*')
        cloud = to_provision.cloud
        deploy_vars = cloud.make_deploy_resources_variables(
            to_provision, self._cluster_name_on_cloud, region, zone)
        # Every SSH-reachable cloud must install the FRAMEWORK keypair
        # (post-provision runtime setup / gang exec connect with
        # ~/.skytpu/keys): inject it once here so no per-cloud plugin
        # can forget it. Plugins with their own key channels (GCP
        # metadata) simply ignore the field.
        if 'ssh_public_key' not in deploy_vars:
            deploy_vars['ssh_public_key'] = (
                authentication.public_key_openssh())
        config = provision_common.ProvisionConfig(
            provider_name=cloud.provider_name(),
            cluster_name=self._cluster_name,
            cluster_name_on_cloud=self._cluster_name_on_cloud,
            region=region,
            zone=zone,
            node_config=deploy_vars,
            count=num_nodes,
            ports_to_open=to_provision.ports,
        )
        record = provisioner.bulk_provision(config)
        return provision.get_cluster_info(config.provider_name,
                                          record.cluster_name_on_cloud,
                                          record.region, record.zone)

    def provision_with_retries(
            self, to_provision: 'resources_lib.Resources',
            num_nodes: int) -> provision_common.ClusterInfo:
        """Iterate candidates; block failed ones at the right granularity
        (zone for stockouts, region for quota)."""
        retry_state = _PROVISION_RETRY_POLICY.new_state()
        failover_history: List[Exception] = []
        while True:
            for region, zone in self._candidates(to_provision):
                where = f'{region}/{zone or "*"}'
                logger.info('Provisioning %s (%r) in %s...',
                            self._cluster_name, to_provision, where)
                try:
                    return self._one_attempt(to_provision, num_nodes,
                                             region, zone)
                except exceptions.QuotaExceededError as e:
                    logger.warning('Quota exceeded in %s: %s', region, e)
                    failover_history.append(e)
                    self._blocked.add((region, None))
                except exceptions.ProvisionError as e:
                    # Stockout or generic capacity error: block the zone.
                    logger.warning('Provision failed in %s: %s', where, e)
                    failover_history.append(e)
                    self._blocked.add((region, zone))
                # Best-effort cleanup of partially-created resources.
                try:
                    provision.terminate_instances(
                        to_provision.cloud.provider_name(),
                        self._cluster_name_on_cloud, region, zone)
                except Exception as e:  # pylint: disable=broad-except
                    # Leaked partial resources cost money: make the
                    # failed cleanup visible even though failover
                    # continues regardless.
                    logger.warning(
                        'Cleanup of partially-provisioned resources '
                        'for %s in %s failed: %s',
                        self._cluster_name_on_cloud, where, e)
            if not self._retry_until_up:
                raise exceptions.ResourcesUnavailableError(
                    f'Failed to provision {to_provision!r} in all '
                    'candidate zones.',
                    failover_history=failover_history)
            # Keep caller-seeded blocks across rounds; clear only the
            # blocks learned from this request's failures.
            self._blocked = set(self._seed_blocked)
            backoff = retry_state.next_backoff()
            logger.info('retry_until_up: retrying in %.1fs.', backoff)
            _PROVISION_RETRY_POLICY.clock.sleep(backoff)


# ----------------------------------------------------------------------
@registry.BACKEND_REGISTRY.register(name='gang', default=True)
class GangBackend(backend_lib.Backend[GangResourceHandle]):
    """Provision clusters and gang-execute jobs on them."""

    NAME = 'gang'

    def __init__(self) -> None:
        self.run_timestamp = sky_logging.get_run_timestamp()
        self.log_dir = os.path.join(log_root(), self.run_timestamp)

    # ------------------------------------------------------------------
    def _provision(self, task: 'task_lib.Task',
                   to_provision: Optional['resources_lib.Resources'],
                   dryrun: bool, stream_logs: bool, cluster_name: str,
                   retry_until_up: bool = False,
                   blocked_regions=None
                   ) -> Optional[GangResourceHandle]:
        assert to_provision is not None
        to_provision.assert_launchable()
        if dryrun:
            logger.info('Dryrun: would provision %r as %s.', to_provision,
                        cluster_name)
            return None
        cloud = to_provision.cloud
        max_len = cloud.MAX_CLUSTER_NAME_LEN_LIMIT or 64
        cluster_name_on_cloud = common_utils.make_cluster_name_on_cloud(
            cluster_name, max_len)

        with backend_utils.cluster_file_lock(self._lock_name(cluster_name)):
            record = backend_utils.refresh_cluster_record(
                cluster_name, force_refresh=True, acquire_lock=False)
            is_restart = False
            if record is not None:
                handle = record['handle']
                if record['status'] == status_lib.ClusterStatus.UP:
                    self._check_resources_match(handle, task)
                    logger.info('Reusing existing cluster %s.',
                                cluster_name)
                    return handle
                # STOPPED / INIT: restart through the same provisioner
                # (run_instances resumes stopped instances).
                to_provision = handle.launched_resources
                cluster_name_on_cloud = handle.cluster_name_on_cloud
                is_restart = True

            # Cross-candidate failover (reference provision_with_retries
            # iterates clouds and regions): when the best candidate
            # exhausts its zones, move down the optimizer's
            # cheapest-first candidate list — next region, and
            # eventually the next cloud — before giving up.
            candidates = [to_provision]
            if not is_restart:
                # Restarts must stay on the recorded cloud/region:
                # failing over elsewhere would abandon the stopped
                # instances (still billed for disks) under a handle
                # that no longer points at them.
                for cand in (getattr(task, '_optimizer_candidates',
                                     None) or []):
                    if cand != to_provision:
                        candidates.append(cand)
            retry_state = _PROVISION_RETRY_POLICY.new_state()
            while True:
                last_error: Optional[Exception] = None
                cluster_info = None
                for cand in candidates:
                    if not is_restart:
                        # Name-length limits are per cloud: recompute
                        # for the candidate actually tried (a name
                        # legal on AWS (50) can violate GCP's 35-char
                        # cap). Restarts keep the RECORDED name — it
                        # must address the stopped instances even if
                        # name mangling changed since launch.
                        cand_max = (cand.cloud.MAX_CLUSTER_NAME_LEN_LIMIT
                                    or 64)
                        cluster_name_on_cloud = (
                            common_utils.make_cluster_name_on_cloud(
                                cluster_name, cand_max))
                    prov = RetryingProvisioner(
                        cluster_name, cluster_name_on_cloud,
                        retry_until_up=False,
                        blocked_regions=blocked_regions)
                    try:
                        cluster_info = prov.provision_with_retries(
                            cand, task.num_nodes)
                        to_provision = cand
                        break
                    except exceptions.ResourcesUnavailableError as e:
                        logger.warning(
                            'All candidates on %s failed; %s', cand.cloud,
                            'trying next cloud.'
                            if cand is not candidates[-1] else
                            'no more clouds.')
                        last_error = e
                if cluster_info is not None:
                    break
                if not retry_until_up:
                    assert last_error is not None
                    raise last_error
                backoff = retry_state.next_backoff()
                logger.info('retry_until_up: retrying all clouds in '
                            '%.1fs.', backoff)
                _PROVISION_RETRY_POLICY.clock.sleep(backoff)
            launched = to_provision.copy(
                region=cluster_info.region,
                zone=cluster_info.zone,
            )
            # Generate the framework keypair only when a real (SSH)
            # host is present; local simulated hosts need no key.
            needs_ssh = any(
                h.tags.get('host_dir') is None
                for h in cluster_info.all_hosts())
            if needs_ssh:
                from skypilot_tpu import authentication
                ssh_key, _ = authentication.get_or_generate_keys()
            else:
                ssh_key = None
            # Task container (image_id: docker:<img>): recorded on the
            # cluster info so hosts.json carries it to the gang driver.
            # Kubernetes is excluded — there image_id overrides the pod
            # image itself (provision/kubernetes/instance.py), no
            # nested container needed.
            docker_image = launched.extract_docker_image()
            if (docker_image is not None and
                    cluster_info.provider_name != 'kubernetes'):
                cluster_info.docker_config = (
                    docker_utils.make_docker_config(
                        docker_image, task.envs or {}, cluster_name))
            state_dir = provisioner.post_provision_runtime_setup(
                cluster_info,
                ssh_private_key=ssh_key,
                log_dir=self.log_dir)
            handle = GangResourceHandle(
                cluster_name=cluster_name,
                cluster_name_on_cloud=cluster_info.cluster_name_on_cloud,
                launched_resources=launched,
                launched_nodes=task.num_nodes,
                cluster_info=cluster_info,
                state_dir=state_dir,
                ssh_private_key=ssh_key,
            )
            global_user_state.add_or_update_cluster(
                cluster_name, handle, requested_resources=set(task.resources),
                ready=True)
            try:
                identities = to_provision.cloud.get_user_identities()
                if identities:
                    global_user_state.set_cluster_owner(
                        cluster_name,
                        ','.join(identities[0]))
            except Exception as e:  # pylint: disable=broad-except
                # Identity is best-effort safety metadata; the launch
                # succeeds without it, but say why it is missing.
                logger.warning(
                    'Could not record owner identity for cluster '
                    '%s: %s', cluster_name, e)
            return handle

    @staticmethod
    def _lock_name(cluster_name: str) -> str:
        return f'{cluster_name}.provision'

    def _check_resources_match(self, handle: GangResourceHandle,
                               task: 'task_lib.Task') -> None:
        launched = handle.launched_resources
        for want in task.resources:
            if want.less_demanding_than(launched):
                return
        raise exceptions.ResourcesMismatchError(
            f'Cluster {handle.cluster_name} was launched with {launched!r}, '
            f'which does not satisfy the requested {task.resources}. '
            'Use a new cluster name or tear this one down.')

    # ------------------------------------------------------------------
    def _sync_workdir(self, handle: GangResourceHandle,
                      workdir: str) -> None:
        workdir = os.path.abspath(os.path.expanduser(workdir))
        source = workdir.rstrip('/') + '/'

        def sync_one(runner: runner_lib.CommandRunner) -> None:
            runner.rsync(source, agent_constants.REMOTE_WORKDIR, up=True,
                         log_path=os.path.join(self.log_dir, 'workdir.log'))

        subprocess_utils.run_in_parallel(sync_one, handle.runners())
        logger.info('Synced workdir %s to %d host(s).', workdir,
                    handle.num_hosts)

    def _sync_file_mounts(self, handle: GangResourceHandle,
                          all_file_mounts: Optional[Dict[str, str]],
                          storage_mounts: Optional[Dict[str, Any]]) -> None:
        if all_file_mounts:
            from skypilot_tpu.data import cloud_stores
            runners = handle.runners()
            log_path = os.path.join(self.log_dir, 'file_mounts.log')

            def sync_mounts(runner: runner_lib.CommandRunner) -> None:
                for dst, src in all_file_mounts.items():
                    if cloud_stores.is_cloud_url(src):
                        # Bucket-URL source: the host fetches it
                        # itself (reference sky/cloud_stores.py).
                        runner.run(
                            cloud_stores.download_command(src, dst),
                            log_path=log_path, check=True)
                        continue
                    src = os.path.expanduser(src)
                    if os.path.isdir(src):
                        # file_mounts semantics: the source dir's
                        # contents appear AT dst (not nested under it).
                        src = src.rstrip('/') + '/'
                    runner.rsync(src, dst, up=True, log_path=log_path)

            subprocess_utils.run_in_parallel(sync_mounts, runners)
        if storage_mounts:
            from skypilot_tpu.data import storage_mounting
            storage_mounting.mount_storage_on_cluster(
                handle, storage_mounts, self.log_dir)

    # ------------------------------------------------------------------
    def _setup(self, handle: GangResourceHandle, task: 'task_lib.Task',
               detach_setup: bool) -> None:
        # Setup runs inside the job driver (per-host, before ranks), so
        # it shares the env contract and logging; mirroring the
        # reference's detached setup mode. Nothing to do eagerly.
        del handle, task, detach_setup

    # ------------------------------------------------------------------
    def _resolve_run_commands(self, task: 'task_lib.Task',
                              ips: List[str]) -> List[Optional[str]]:
        n = len(ips)
        if task.run is None:
            return [None] * n
        if isinstance(task.run, str):
            return [task.run] * n
        return [task.run(rank, ips) for rank in range(n)]

    def _job_spec(self, handle: GangResourceHandle,
                  task: 'task_lib.Task') -> Dict[str, Any]:
        ips = handle.ip_list()
        tpu = handle.launched_resources.tpu
        task_id = (f'{self.run_timestamp}-'
                   f'{common_utils.generate_run_id(4)}')
        return {
            'setup': task.setup,
            'run_commands': self._resolve_run_commands(task, ips),
            'env': task.envs,
            'ips': ips,
            'num_chips_per_host': tpu.chips_per_host if tpu else 0,
            'topology': tpu.topology if tpu else '',
            'accelerator_type': tpu.name if tpu else '',
            'task_id': task_id,
            'cluster_name': handle.cluster_name,
            'has_workdir': task.workdir is not None,
        }

    @staticmethod
    def _agent_cli_command(handle: GangResourceHandle,
                           args: List[str]) -> str:
        """The one place the on-host agent CLI invocation is built."""
        return ('export PYTHONPATH="$HOME/.skytpu_runtime:$PYTHONPATH"; '
                'python -u -m skypilot_tpu.agent.cli '
                f'--state-dir {runner_lib.shell_path(handle.state_dir)} ' +
                ' '.join(shlex.quote(a) for a in args))

    def run_on_head(self, handle: GangResourceHandle, args: List[str],
                    *, stream_logs: bool = False,
                    log_path: str = '/dev/null') -> Any:
        """Invoke the agent CLI on the head host; parse its JSON."""
        cmd = self._agent_cli_command(handle, args)
        runner = handle.head_runner()
        rc, stdout, stderr = runner.run(cmd, require_outputs=True,
                                        log_path=log_path)
        if rc != 0:
            raise exceptions.CommandError(rc, f'agent {args[0]}',
                                          stderr or stdout)
        return agent_cli.parse_output(stdout)

    def _execute(self, handle: GangResourceHandle, task: 'task_lib.Task',
                 detach_run: bool, dryrun: bool = False) -> Optional[int]:
        if dryrun:
            logger.info('Dryrun: would submit job to %s.',
                        handle.cluster_name)
            return None
        spec = self._job_spec(handle, task)
        out = self.run_on_head(handle, [
            'add-job',
            *(['--name', task.name] if task.name else []),
            '--username', common_utils.get_user_name(),
            '--run-timestamp', self.run_timestamp,
            '--resources', repr(handle.launched_resources),
            '--spec-json', json.dumps(spec),
        ])
        job_id = int(out['job_id'])
        self.run_on_head(handle, ['queue-job', '--job-id', str(job_id)])
        logger.info('Job %d submitted to cluster %s.', job_id,
                    handle.cluster_name)
        if not detach_run:
            self.tail_logs(handle, job_id)
        return job_id

    # ------------------------------------------------------------------
    def tail_logs(self, handle: GangResourceHandle,
                  job_id: Optional[int], follow: bool = True) -> int:
        args = ['tail-logs']
        if job_id is not None:
            args += ['--job-id', str(job_id)]
        if follow:
            args += ['--follow']
        cmd = self._agent_cli_command(handle, args)
        runner = handle.head_runner()
        return runner.run(cmd, stream_logs=True,
                          log_path=os.path.join(self.log_dir, 'tail.log'))

    def sync_down_logs(self, handle: GangResourceHandle,
                       job_id: Optional[int], local_dir: str) -> str:
        """Pull one job's log tree (driver + per-rank logs) off the
        head host (reference sync_down_logs,
        cloud_vm_ray_backend.py:3705)."""
        if job_id is None:
            jobs = self.get_job_queue(handle)
            if not jobs:
                raise exceptions.JobNotFoundError(
                    f'No jobs on {handle.cluster_name}.')
            job_id = max(j['job_id'] for j in jobs)
        src = agent_constants.job_dir(handle.state_dir, job_id)
        local_dir = os.path.expanduser(local_dir)
        dst = os.path.join(local_dir,
                           f'{handle.cluster_name}-job-{job_id}')
        os.makedirs(dst, exist_ok=True)
        head = handle.head_runner()
        if isinstance(head, runner_lib.LocalProcessRunner):
            # Local clusters share the filesystem, and the agent state
            # dir lives OUTSIDE the host sandbox the runner translates
            # paths into — copy straight from it.
            import shutil
            shutil.copytree(os.path.expanduser(src), dst,
                            dirs_exist_ok=True)
        else:
            head.rsync(
                src + '/', dst, up=False,
                log_path=os.path.join(self.log_dir,
                                      'sync_down_logs.log'))
        logger.info('Synced job %d logs to %s.', job_id, dst)
        return dst

    def cancel_jobs(self, handle: GangResourceHandle,
                    job_ids: Optional[List[int]]) -> List[int]:
        args = ['cancel']
        if job_ids:
            args += ['--job-ids'] + [str(j) for j in job_ids]
        out = self.run_on_head(handle, args)
        return out['cancelled']

    def get_job_status(
            self, handle: GangResourceHandle,
            job_ids: Optional[List[int]] = None
    ) -> Dict[int, Optional[status_lib.JobStatus]]:
        args = ['job-status']
        if job_ids:
            args += ['--job-ids'] + [str(j) for j in job_ids]
        out = self.run_on_head(handle, args)
        return {
            int(k): status_lib.JobStatus(v) if v else None
            for k, v in out.items()
        }

    def get_job_queue(self, handle: GangResourceHandle) -> List[Dict]:
        return self.run_on_head(handle, ['queue'])

    def set_autostop(self, handle: GangResourceHandle, idle_minutes: int,
                     down: bool = False) -> None:
        if idle_minutes >= 0 and not down:
            cloud = handle.launched_resources.cloud
            from skypilot_tpu.clouds import cloud as cloud_lib
            cloud.check_features_are_supported(
                handle.launched_resources,
                {cloud_lib.CloudImplementationFeatures.AUTOSTOP})
        args = [
            'set-autostop',
            '--idle-minutes', str(idle_minutes),
            '--provider-name', handle.provider_name,
            '--cluster-name-on-cloud', handle.cluster_name_on_cloud,
            '--region', handle.region,
        ]
        if handle.zone:
            args += ['--zone', handle.zone]
        if down:
            args += ['--down']
        self.run_on_head(handle, args)
        global_user_state.set_cluster_autostop_value(
            handle.cluster_name, idle_minutes, down)

    # ------------------------------------------------------------------
    def _teardown(self, handle: GangResourceHandle, terminate: bool,
                  purge: bool = False) -> None:
        cluster_name = handle.cluster_name
        with backend_utils.cluster_file_lock(self._lock_name(cluster_name)):
            try:
                provisioner.teardown_cluster(handle.provider_name,
                                             handle.cluster_name_on_cloud,
                                             handle.region, handle.zone,
                                             terminate=terminate)
            except Exception as e:  # pylint: disable=broad-except
                if not purge:
                    raise
                logger.warning('Purging %s despite teardown error: %r',
                               cluster_name, e)
            global_user_state.remove_cluster(cluster_name,
                                             terminate=terminate)
        logger.info('%s cluster %s.',
                    'Terminated' if terminate else 'Stopped', cluster_name)
