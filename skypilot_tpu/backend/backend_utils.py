"""Backend utilities: status reconciliation, cluster locks, handles.

Re-design of reference ``sky/backends/backend_utils.py``
(`_update_cluster_status` :1757, `refresh_cluster_record` :2072). The
local DB's view of a cluster is a cache; the cloud is the truth. Every
status read that matters (jobs recovery, serve probing, `status
--refresh`) reconciles the two here.
"""
from __future__ import annotations

import os
import typing
from typing import Any, Dict, Optional

import filelock

from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state
from skypilot_tpu import provision
from skypilot_tpu.utils import log as sky_logging
from skypilot_tpu.utils import status_lib

if typing.TYPE_CHECKING:
    from skypilot_tpu.backend import gang_backend

logger = sky_logging.init_logger(__name__)

CLUSTER_STATUS_LOCK_TIMEOUT_SECONDS = 20


def cluster_lock_path(cluster_name: str) -> str:
    base = os.path.expanduser(
        os.environ.get('SKYTPU_DATA_DIR', '~/.skytpu'))
    lock_dir = os.path.join(base, 'locks')
    os.makedirs(lock_dir, exist_ok=True)
    return os.path.join(lock_dir, f'{cluster_name}.lock')


def cluster_file_lock(cluster_name: str) -> filelock.FileLock:
    return filelock.FileLock(cluster_lock_path(cluster_name))


def _query_cloud_status(
        handle: 'gang_backend.GangResourceHandle'
) -> Optional[status_lib.ClusterStatus]:
    """Ask the provider; None means no instances exist (terminated)."""
    statuses = provision.query_instances(
        handle.provider_name,
        handle.cluster_name_on_cloud,
        handle.region,
        handle.zone,
        non_terminated_only=False,
    )
    if not statuses:
        return None
    values = set(statuses.values())
    if values == {'running'}:
        return status_lib.ClusterStatus.UP
    if 'terminated' in values or None in values:
        # Partial termination (e.g. one TPU host preempted) downs the
        # whole slice from the scheduler's perspective.
        return None
    if values == {'stopped'}:
        return status_lib.ClusterStatus.STOPPED
    return status_lib.ClusterStatus.INIT


def refresh_cluster_record(
        cluster_name: str,
        *,
        force_refresh: bool = False,
        acquire_lock: bool = True) -> Optional[Dict[str, Any]]:
    """Return the cluster record with status reconciled against the
    cloud. None if the cluster does not exist (and its record, if any,
    is removed)."""
    record = global_user_state.get_cluster_from_name(cluster_name)
    if record is None:
        return None
    if not force_refresh and record['status'] == (
            status_lib.ClusterStatus.STOPPED):
        return record

    def _refresh() -> Optional[Dict[str, Any]]:
        rec = global_user_state.get_cluster_from_name(cluster_name)
        if rec is None:
            return None
        handle = rec['handle']
        try:
            cloud_status = _query_cloud_status(handle)
        except Exception as e:  # pylint: disable=broad-except
            logger.warning('Failed to query cloud status for %s: %r',
                           cluster_name, e)
            return rec
        if cloud_status is None:
            logger.info('Cluster %s no longer exists on the cloud; '
                        'removing record.', cluster_name)
            global_user_state.remove_cluster(cluster_name, terminate=True)
            return None
        if cloud_status != rec['status']:
            global_user_state.update_cluster_status(cluster_name,
                                                    cloud_status)
            rec = global_user_state.get_cluster_from_name(cluster_name)
        return rec

    if not acquire_lock:
        return _refresh()
    lock = cluster_file_lock(cluster_name)
    try:
        with lock.acquire(timeout=CLUSTER_STATUS_LOCK_TIMEOUT_SECONDS):
            return _refresh()
    except filelock.Timeout:
        logger.debug('Lock timeout refreshing %s; returning cached.',
                     cluster_name)
        return record


def check_cluster_available(
        cluster_name: str) -> 'gang_backend.GangResourceHandle':
    """Cluster exists and is UP, else raise."""
    record = refresh_cluster_record(cluster_name, force_refresh=True)
    if record is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} does not exist.')
    if record['status'] != status_lib.ClusterStatus.UP:
        raise exceptions.ClusterNotUpError(
            f'Cluster {cluster_name!r} is {record["status"].value}, '
            'not UP.', cluster_status=record['status'],
            handle=record['handle'])
    return record['handle']
