"""Backend utilities: status reconciliation, cluster locks, handles.

Re-design of reference ``sky/backends/backend_utils.py``
(`_update_cluster_status` :1757, `refresh_cluster_record` :2072). The
local DB's view of a cluster is a cache; the cloud is the truth. Every
status read that matters (jobs recovery, serve probing, `status
--refresh`) reconciles the two here.
"""
from __future__ import annotations

import os
import typing
from typing import Any, Dict, Optional

import filelock

from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state
from skypilot_tpu import provision
from skypilot_tpu.utils import log as sky_logging
from skypilot_tpu.utils import status_lib

if typing.TYPE_CHECKING:
    from skypilot_tpu.backend import gang_backend

logger = sky_logging.init_logger(__name__)

CLUSTER_STATUS_LOCK_TIMEOUT_SECONDS = 20


def cluster_lock_path(cluster_name: str) -> str:
    base = os.path.expanduser(
        os.environ.get('SKYTPU_DATA_DIR', '~/.skytpu'))
    lock_dir = os.path.join(base, 'locks')
    os.makedirs(lock_dir, exist_ok=True)
    return os.path.join(lock_dir, f'{cluster_name}.lock')


class _TimelineFileLock(filelock.FileLock):
    """FileLock whose acquire wait is a timeline event (reference
    sky/utils/timeline.py FileLock events): contended cluster locks
    are exactly where a slow launch hides, and the B/E pair makes the
    wait visible in the Chrome trace. Zero overhead when tracing is
    off (timeline.Event no-ops)."""

    def acquire(self, *args, **kwargs):
        from skypilot_tpu.utils import timeline
        with timeline.Event(f'[lock.acquire] {self.lock_file}'):
            return super().acquire(*args, **kwargs)


def cluster_file_lock(cluster_name: str) -> filelock.FileLock:
    return _TimelineFileLock(cluster_lock_path(cluster_name))


def _query_cloud_status(
        handle: 'gang_backend.GangResourceHandle'
) -> Optional[status_lib.ClusterStatus]:
    """Ask the provider; None means no instances exist (terminated)."""
    statuses = provision.query_instances(
        handle.provider_name,
        handle.cluster_name_on_cloud,
        handle.region,
        handle.zone,
        non_terminated_only=False,
    )
    if not statuses:
        return None
    values = set(statuses.values())
    if values == {'running'}:
        return status_lib.ClusterStatus.UP
    if 'terminated' in values or None in values:
        if values <= {'terminated', None}:
            return None  # everything gone
        # Partial termination (e.g. one TPU host preempted): the job
        # is dead, but surviving instances still bill — DEGRADED, not
        # gone (removing the record here would orphan them; reference
        # _update_cluster_status keeps such clusters visible as INIT).
        return status_lib.ClusterStatus.DEGRADED
    if values == {'stopped'}:
        return status_lib.ClusterStatus.STOPPED
    return status_lib.ClusterStatus.INIT


def _agent_alive(handle: 'gang_backend.GangResourceHandle') -> bool:
    """Is agentd running on the head host? (the 'ray status' health
    probe of the reference, backend_utils.py:900)."""
    try:
        from skypilot_tpu.agent import constants as agent_constants
        from skypilot_tpu.utils import command_runner as runner_lib
        pid_file = runner_lib.shell_path(os.path.join(
            handle.state_dir, agent_constants.AGENT_PID_FILE))
        rc = handle.head_runner().run(
            f'kill -0 $(cat {pid_file}) 2>/dev/null')
        return rc == 0
    except Exception:  # pylint: disable=broad-except
        return False


def _check_owner_identity(
        rec: Dict[str, Any],
        handle: 'gang_backend.GangResourceHandle') -> None:
    """Refuse to reconcile a cluster launched under another cloud
    identity (reference _update_cluster_status's multi-identity
    safety, sky/backends/backend_utils.py:1757): operating on it with
    different credentials would tear down / bill someone else's
    resources."""
    owner = rec.get('owner')
    if not owner:
        return
    try:
        cloud = handle.launched_resources.cloud
        current = cloud.get_user_identities()
    except Exception:  # pylint: disable=broad-except
        return
    if not current:
        return
    flat_current = [i for ids in current for i in ids]
    flat_owner = owner.split(',')
    if not set(flat_owner) & set(flat_current):
        raise exceptions.ClusterOwnerIdentityMismatchError(
            f'Cluster {rec["name"]!r} was launched by identity '
            f'{owner!r}; current cloud identity is {flat_current!r}. '
            'Switch back to the owning account (or remove the record '
            'with `skytpu down --purge`).')


def refresh_cluster_record(
        cluster_name: str,
        *,
        force_refresh: bool = False,
        acquire_lock: bool = True) -> Optional[Dict[str, Any]]:
    """Return the cluster record with status reconciled against the
    cloud. None if the cluster does not exist (and its record, if any,
    is removed)."""
    record = global_user_state.get_cluster_from_name(cluster_name)
    if record is None:
        return None
    if not force_refresh and record['status'] == (
            status_lib.ClusterStatus.STOPPED):
        return record

    def _refresh() -> Optional[Dict[str, Any]]:
        rec = global_user_state.get_cluster_from_name(cluster_name)
        if rec is None:
            return None
        handle = rec['handle']
        if handle is None:
            # Corrupt/truncated handle blob (global_user_state degraded
            # the row rather than crashing the read): without a handle
            # there is no cloud to ask — report the record as-is.
            logger.warning(
                'Cluster %s has no usable handle (corrupt record); '
                'skipping cloud refresh.', cluster_name)
            return rec
        _check_owner_identity(rec, handle)
        try:
            cloud_status = _query_cloud_status(handle)
        except Exception as e:  # pylint: disable=broad-except
            logger.warning('Failed to query cloud status for %s: %r',
                           cluster_name, e)
            return rec
        if cloud_status is None:
            logger.info('Cluster %s no longer exists on the cloud; '
                        'removing record.', cluster_name)
            global_user_state.remove_cluster(cluster_name, terminate=True)
            return None
        if (cloud_status == status_lib.ClusterStatus.STOPPED and
                rec.get('to_down') and rec.get('autostop', -1) >= 0):
            # Autodown on refresh: the user asked for DOWN, but the
            # agent only got as far as stopping (or died after the
            # stop) — finish the teardown now (reference autodown
            # handling in _update_cluster_status).
            logger.info('Cluster %s is STOPPED with autodown set; '
                        'terminating it now.', cluster_name)
            try:
                provision.terminate_instances(
                    handle.provider_name, handle.cluster_name_on_cloud,
                    handle.region, handle.zone)
            except Exception as e:  # pylint: disable=broad-except
                logger.warning('Autodown-on-refresh failed for %s: %r',
                               cluster_name, e)
                return rec
            global_user_state.remove_cluster(cluster_name,
                                             terminate=True)
            return None
        if (cloud_status == status_lib.ClusterStatus.UP and
                rec['status'] == status_lib.ClusterStatus.INIT):
            # INIT-stuck handling: instances run but the record never
            # left INIT (the provisioning process died mid-flight, or
            # a crash raced the DB write). If no provisioning is in
            # flight (lock free) the truth is the agent: alive -> the
            # cluster is genuinely usable, promote to UP; dead -> stay
            # INIT so `start` re-runs runtime setup.
            from skypilot_tpu.backend import gang_backend as gb
            lock = cluster_file_lock(
                gb.GangBackend._lock_name(cluster_name))
            provisioning_in_flight = True
            try:
                with lock.acquire(timeout=0):
                    provisioning_in_flight = False
                    if _agent_alive(handle):
                        cloud_status = status_lib.ClusterStatus.UP
                    else:
                        cloud_status = status_lib.ClusterStatus.INIT
            except filelock.Timeout:
                pass
            if provisioning_in_flight:
                cloud_status = status_lib.ClusterStatus.INIT
        if cloud_status != rec['status']:
            global_user_state.update_cluster_status(cluster_name,
                                                    cloud_status)
            rec = global_user_state.get_cluster_from_name(cluster_name)
        return rec

    if not acquire_lock:
        return _refresh()
    lock = cluster_file_lock(cluster_name)
    try:
        with lock.acquire(timeout=CLUSTER_STATUS_LOCK_TIMEOUT_SECONDS):
            return _refresh()
    except filelock.Timeout:
        logger.debug('Lock timeout refreshing %s; returning cached.',
                     cluster_name)
        return record


def check_cluster_available(
        cluster_name: str) -> 'gang_backend.GangResourceHandle':
    """Cluster exists and is UP, else raise."""
    record = refresh_cluster_record(cluster_name, force_refresh=True)
    if record is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} does not exist.')
    if record['status'] != status_lib.ClusterStatus.UP:
        raise exceptions.ClusterNotUpError(
            f'Cluster {cluster_name!r} is {record["status"].value}, '
            'not UP.', cluster_status=record['status'],
            handle=record['handle'])
    return record['handle']
