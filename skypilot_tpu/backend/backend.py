"""Backend ABC + ResourceHandle.

Re-design of reference ``sky/backends/backend.py:24-151``: the
provision/sync/setup/execute/teardown contract every backend satisfies.
"""
from __future__ import annotations

import typing
from typing import Any, Dict, Generic, List, Optional, TypeVar

from skypilot_tpu.utils import timeline

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu import task as task_lib


class ResourceHandle:
    """Pickled per-cluster record stored in global user state."""

    def get_cluster_name(self) -> str:
        raise NotImplementedError


_HandleType = TypeVar('_HandleType', bound=ResourceHandle)


class Backend(Generic[_HandleType]):
    """Lifecycle driver for one kind of cluster runtime."""

    NAME = 'backend'

    # --- Lifecycle stages (wrapped with tracing; subclasses implement
    # the _underscore methods). -----------------------------------------
    @timeline.event
    def provision(self,
                  task: 'task_lib.Task',
                  to_provision: Optional['resources_lib.Resources'],
                  dryrun: bool,
                  stream_logs: bool,
                  cluster_name: str,
                  retry_until_up: bool = False,
                  blocked_regions=None) -> Optional[_HandleType]:
        return self._provision(task, to_provision, dryrun, stream_logs,
                               cluster_name, retry_until_up,
                               blocked_regions=blocked_regions)

    @timeline.event
    def sync_workdir(self, handle: _HandleType, workdir: str) -> None:
        return self._sync_workdir(handle, workdir)

    @timeline.event
    def sync_file_mounts(
        self,
        handle: _HandleType,
        all_file_mounts: Optional[Dict[str, str]],
        storage_mounts: Optional[Dict[str, Any]],
    ) -> None:
        return self._sync_file_mounts(handle, all_file_mounts,
                                      storage_mounts)

    @timeline.event
    def setup(self, handle: _HandleType, task: 'task_lib.Task',
              detach_setup: bool) -> None:
        return self._setup(handle, task, detach_setup)

    @timeline.event
    def execute(self,
                handle: _HandleType,
                task: 'task_lib.Task',
                detach_run: bool,
                dryrun: bool = False) -> Optional[int]:
        """Submit the task as a job; returns job_id (None for dryrun)."""
        return self._execute(handle, task, detach_run, dryrun)

    @timeline.event
    def teardown(self,
                 handle: _HandleType,
                 terminate: bool,
                 purge: bool = False) -> None:
        return self._teardown(handle, terminate, purge)

    # --- Subclass API ---------------------------------------------------
    def _provision(self, task, to_provision, dryrun, stream_logs,
                   cluster_name, retry_until_up, blocked_regions=None):
        raise NotImplementedError

    def _sync_workdir(self, handle, workdir):
        raise NotImplementedError

    def _sync_file_mounts(self, handle, all_file_mounts, storage_mounts):
        raise NotImplementedError

    def _setup(self, handle, task, detach_setup):
        raise NotImplementedError

    def _execute(self, handle, task, detach_run, dryrun):
        raise NotImplementedError

    def _teardown(self, handle, terminate, purge):
        raise NotImplementedError

    # Optional capabilities.
    def cancel_jobs(self, handle: _HandleType,
                    job_ids: Optional[List[int]]) -> List[int]:
        raise NotImplementedError

    def tail_logs(self, handle: _HandleType, job_id: Optional[int],
                  follow: bool = True) -> int:
        raise NotImplementedError
