"""Pluggable org-level request mutator/validator.

Re-design of reference ``sky/admin_policy.py:61-101``: a user-supplied
class (configured as ``admin_policy: my_module.MyPolicy`` in the config
file) sees every UserRequest (dag + config) before execution and may
mutate or reject it.
"""
from __future__ import annotations

import dataclasses
import importlib
import typing
from typing import Any, Dict, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import skypilot_config

if typing.TYPE_CHECKING:
    from skypilot_tpu import dag as dag_lib


@dataclasses.dataclass
class RequestOptions:
    cluster_name: Optional[str] = None
    idle_minutes_to_autostop: Optional[int] = None
    down: bool = False
    dryrun: bool = False


@dataclasses.dataclass
class UserRequest:
    dag: 'dag_lib.Dag'
    skypilot_config: Dict[str, Any]
    request_options: Optional[RequestOptions] = None


@dataclasses.dataclass
class MutatedUserRequest:
    dag: 'dag_lib.Dag'
    skypilot_config: Dict[str, Any]


class AdminPolicy:
    """Subclass and override validate_and_mutate."""

    @classmethod
    def validate_and_mutate(cls,
                            user_request: UserRequest) -> MutatedUserRequest:
        return MutatedUserRequest(dag=user_request.dag,
                                  skypilot_config=user_request.skypilot_config)


def apply(dag: 'dag_lib.Dag',
          request_options: Optional[RequestOptions] = None) -> 'dag_lib.Dag':
    """Apply the configured policy (if any) to the dag.

    Called from execution._execute on every request (reference
    sky/execution.py:180).
    """
    policy_path = skypilot_config.get_nested(('admin_policy',))
    if policy_path is None:
        return dag
    module_path, _, class_name = policy_path.rpartition('.')
    try:
        module = importlib.import_module(module_path)
        policy_cls = getattr(module, class_name)
    except (ImportError, AttributeError) as e:
        raise exceptions.SkyTpuError(
            f'Cannot load admin policy {policy_path!r}: {e}') from e
    if not issubclass(policy_cls, AdminPolicy):
        raise exceptions.SkyTpuError(
            f'{policy_path} must subclass skypilot_tpu.AdminPolicy')
    request = UserRequest(dag=dag,
                          skypilot_config=skypilot_config.to_dict(),
                          request_options=request_options)
    mutated = policy_cls.validate_and_mutate(request)
    if mutated.skypilot_config != request.skypilot_config:
        # Config mutations apply for the rest of this request.
        skypilot_config.override_config(mutated.skypilot_config).__enter__()
    return mutated.dag
