"""skytpu-lint: repo-native static analysis (docs/static_analysis.md).

A dependency-free AST lint pass encoding this repo's cross-cutting
invariants as rules STL001–STL012 (exception hygiene, RetryPolicy
discipline, daemon-thread explicitness, a heuristic race detector,
the SKYTPU_*/BENCH_* env registry, metric-registration hygiene,
fault-injection site names, JAX recompile/tracer hazards), with
per-line ``# skytpu-lint: disable=RULE`` suppressions and a
committed JSON baseline so only *new* violations fail.

CLI::

    python -m skypilot_tpu.analysis             # full run vs baseline
    python -m skypilot_tpu.analysis --changed   # git-diff-scoped
    python -m skypilot_tpu.analysis --update-baseline
    python -m skypilot_tpu.analysis --list-rules

Library entry points: :func:`analyze_source` (snippets, used by the
fixture tests) and :func:`analyze_files` (project runs).
"""
from skypilot_tpu.analysis.baseline import DEFAULT_BASELINE_PATH
from skypilot_tpu.analysis.core import Project
from skypilot_tpu.analysis.core import Rule
from skypilot_tpu.analysis.core import Violation
from skypilot_tpu.analysis.core import analyze_files
from skypilot_tpu.analysis.core import analyze_source
from skypilot_tpu.analysis.rules import RULE_IDS
from skypilot_tpu.analysis.rules import default_rules

__all__ = [
    'DEFAULT_BASELINE_PATH',
    'Project',
    'Rule',
    'RULE_IDS',
    'Violation',
    'analyze_files',
    'analyze_source',
    'default_rules',
]
