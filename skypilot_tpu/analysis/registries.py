"""Static extraction of the repo's declared-name registries.

The analyzer never imports production modules (importing
``skypilot_tpu.utils.fault_injection`` would drag in the metrics
subsystem; importing models would drag in jax). Instead the two
registries the rules cross-check against are read *statically*:

- **Env names** (STL005): every string literal matching
  ``(SKYTPU|BENCH)_[A-Z0-9_]+`` that appears in
  ``utils/env_contract.py`` or ``utils/env_registry.py`` — a name
  mentioned in a registry module IS a declaration (constants,
  ``register(...)`` calls and alias maps all count).
- **Fault sites** (STL007): the elements of the literal
  ``KNOWN_SITES = (...)`` tuple in ``utils/fault_injection.py``,
  order- and duplicate-preserving so the rule can flag double
  declarations.
"""
from __future__ import annotations

import ast
import os
from typing import List, Optional, Set

from skypilot_tpu.analysis import core

ENV_REGISTRY_FILES = ('utils/env_contract.py', 'utils/env_registry.py')
FAULT_SITE_FILE = 'utils/fault_injection.py'


def package_root() -> str:
    """Absolute path of the skypilot_tpu package directory."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _parse(path: str) -> Optional[ast.Module]:
    if not os.path.exists(path):
        return None
    with open(path, encoding='utf-8') as f:
        try:
            return ast.parse(f.read(), filename=path)
        except SyntaxError:
            return None


def declared_env_names(root: Optional[str] = None) -> Set[str]:
    root = root or package_root()
    names: Set[str] = set()
    pattern = core.env_name_re()
    for rel in ENV_REGISTRY_FILES:
        tree = _parse(os.path.join(root, *rel.split('/')))
        if tree is None:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and \
                    pattern.fullmatch(node.value):
                names.add(node.value)
    return names


def declared_fault_sites(root: Optional[str] = None) -> List[str]:
    root = root or package_root()
    tree = _parse(os.path.join(root, *FAULT_SITE_FILE.split('/')))
    if tree is None:
        return []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == 'KNOWN_SITES'
                   for t in node.targets):
            continue
        if isinstance(node.value, (ast.Tuple, ast.List)):
            return [elt.value for elt in node.value.elts
                    if isinstance(elt, ast.Constant) and
                    isinstance(elt.value, str)]
    return []
