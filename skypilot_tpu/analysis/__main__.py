"""``python -m skypilot_tpu.analysis`` entry point."""
import sys

from skypilot_tpu.analysis.cli import main

sys.exit(main())
