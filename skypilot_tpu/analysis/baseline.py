"""Baseline gating: only *new* violations fail.

The committed baseline (``skypilot_tpu/analysis/baseline.json``)
records accepted legacy findings by fingerprint (rule + path +
enclosing scope + source-line hash — see ``Violation.fingerprint``)
with an occurrence count, so:

- unrelated edits that shift line numbers don't churn the baseline;
- editing the flagged line itself *does* invalidate the entry (the
  finding must be re-fixed or re-accepted);
- the same fingerprint appearing more times than baselined is a new
  violation (a copy-pasted bad pattern doesn't hide behind its
  original).

``--update-baseline`` rewrites the file from the current run, which
also prunes entries whose findings were fixed.
"""
from __future__ import annotations

import collections
import json
import os
from typing import Dict, List, Sequence, Tuple

from skypilot_tpu.analysis.core import Violation

BASELINE_VERSION = 1

DEFAULT_BASELINE_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), 'baseline.json')


def load(path: str) -> Dict[str, dict]:
    """fingerprint -> entry dict; missing file = empty baseline."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding='utf-8') as f:
        data = json.load(f)
    if not isinstance(data, dict) or 'entries' not in data:
        raise ValueError(
            f'{path}: not a skytpu-lint baseline (no "entries" key)')
    return dict(data['entries'])


def save(path: str, violations: Sequence[Violation]) -> Dict[str, dict]:
    """Write a fresh baseline accepting every current violation."""
    entries: Dict[str, dict] = {}
    for v in violations:
        fp = v.fingerprint()
        entry = entries.get(fp)
        if entry is None:
            entries[fp] = {
                'count': 1,
                'rule': v.rule,
                'path': v.path,
                'context': v.context,
                'snippet': v.snippet,
            }
        else:
            entry['count'] += 1
    payload = {
        'version': BASELINE_VERSION,
        'generated_by': 'python -m skypilot_tpu.analysis '
                        '--update-baseline',
        'entries': {fp: entries[fp] for fp in sorted(entries)},
    }
    with open(path, 'w', encoding='utf-8') as f:
        json.dump(payload, f, indent=1, sort_keys=False)
        f.write('\n')
    return entries


def partition(
    violations: Sequence[Violation], baseline: Dict[str, dict]
) -> Tuple[List[Violation], List[Violation], List[str]]:
    """(new, baselined, stale-fingerprints).

    Occurrences of one fingerprint beyond its baselined count are new
    (stable order: the first N occurrences in file order are the
    baselined ones). Stale fingerprints — baseline entries with no
    matching finding — are surfaced so the baseline shrinks as debt
    is paid down.
    """
    budget = {fp: int(entry.get('count', 1))
              for fp, entry in baseline.items()}
    seen: collections.Counter = collections.Counter()
    new: List[Violation] = []
    old: List[Violation] = []
    for v in violations:
        fp = v.fingerprint()
        seen[fp] += 1
        if seen[fp] <= budget.get(fp, 0):
            old.append(v)
        else:
            new.append(v)
    stale = sorted(fp for fp, count in budget.items()
                   if seen[fp] < count)
    return new, old, stale
