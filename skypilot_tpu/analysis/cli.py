"""skytpu-lint CLI: ``python -m skypilot_tpu.analysis``.

Modes:

- default: lint the whole package (plus ``bench.py`` at the repo
  root) against the committed baseline; exit 1 on *new* violations.
- ``--changed``: lint only files changed vs git HEAD (staged,
  unstaged and untracked) — the fast pre-commit loop.
- ``--update-baseline``: rewrite the baseline to accept every
  current finding (also prunes fixed ones).
- ``--format json``: machine-readable report (CI annotation feeds).
- ``--list-rules``: the rule catalog with severities and rationale.

Exit codes: 0 clean, 1 new violations, 2 usage/environment error.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import Dict, List, Optional, Sequence, Tuple

from skypilot_tpu.analysis import baseline as baseline_mod
from skypilot_tpu.analysis import core
from skypilot_tpu.analysis import registries
from skypilot_tpu.analysis import rules as rules_mod


def repo_root() -> str:
    return os.path.dirname(registries.package_root())


def default_targets() -> List[str]:
    """Package dir + repo-root bench.py (the BENCH_* env surface)."""
    targets = [registries.package_root()]
    bench = os.path.join(repo_root(), 'bench.py')
    if os.path.exists(bench):
        targets.append(bench)
    return targets


def _iter_py_files(targets: Sequence[str]) -> List[Tuple[str, str]]:
    """[(repo-relative, absolute)] for every .py under the targets."""
    root = repo_root()
    out: List[Tuple[str, str]] = []
    seen = set()
    for target in targets:
        abspath = os.path.abspath(target)
        if os.path.isfile(abspath):
            files = [abspath]
        else:
            files = []
            for dirpath, dirnames, filenames in os.walk(abspath):
                dirnames[:] = [d for d in dirnames
                               if d not in ('__pycache__',)]
                files.extend(os.path.join(dirpath, f)
                             for f in filenames if f.endswith('.py'))
        for f in files:
            if f in seen or not f.endswith('.py'):
                continue
            seen.add(f)
            rel = os.path.relpath(f, root).replace(os.sep, '/')
            out.append((rel, f))
    out.sort()
    return out


def changed_files() -> List[str]:
    """Absolute paths of .py files changed vs HEAD (plus untracked),
    limited to the default lint targets — test fixtures deliberately
    contain rule-firing snippets and must not trip the pre-commit
    loop."""
    root = repo_root()
    targets = [os.path.abspath(t) for t in default_targets()]

    def in_scope(abspath: str) -> bool:
        return any(abspath == t or
                   abspath.startswith(t.rstrip(os.sep) + os.sep)
                   for t in targets)

    paths = set()
    for cmd in (['git', 'diff', '--name-only', 'HEAD'],
                ['git', 'ls-files', '--others', '--exclude-standard']):
        try:
            proc = subprocess.run(cmd, cwd=root, capture_output=True,
                                  text=True, check=True)
        except (subprocess.CalledProcessError, FileNotFoundError) as e:
            raise RuntimeError(
                f'--changed needs git ({" ".join(cmd)} failed: {e})'
            ) from e
        paths.update(line.strip() for line in proc.stdout.splitlines()
                     if line.strip().endswith('.py'))
    return [os.path.join(root, p) for p in sorted(paths)
            if os.path.exists(os.path.join(root, p)) and
            in_scope(os.path.abspath(os.path.join(root, p)))]


def run(paths: Sequence[str],
        baseline_path: Optional[str],
        update_baseline: bool = False) -> Tuple[List[core.Violation],
                                                List[core.Violation],
                                                List[str]]:
    """(new, baselined, stale) over the given targets."""
    project = core.Project(
        declared_env=registries.declared_env_names(),
        declared_sites=registries.declared_fault_sites())
    violations = core.analyze_files(_iter_py_files(paths),
                                    rules=rules_mod.default_rules(),
                                    project=project)
    if update_baseline:
        assert baseline_path is not None
        baseline_mod.save(baseline_path, violations)
        return [], violations, []
    baseline: Dict[str, dict] = {}
    if baseline_path is not None:
        baseline = baseline_mod.load(baseline_path)
    return baseline_mod.partition(violations, baseline)


def _print_text(new: List[core.Violation], old: List[core.Violation],
                stale: List[str], verbose: bool) -> None:
    for v in new:
        print(f'{v.path}:{v.line}:{v.col}: {v.rule} {v.severity}: '
              f'{v.message}')
        if v.snippet:
            print(f'    {v.snippet}')
    if verbose:
        for v in old:
            print(f'{v.path}:{v.line}: {v.rule} [baselined]')
    for fp in stale:
        print(f'stale baseline entry (finding fixed — run '
              f'--update-baseline to prune): {fp}')
    per_rule: Dict[str, int] = {}
    for v in new:
        per_rule[v.rule] = per_rule.get(v.rule, 0) + 1
    summary = ', '.join(f'{r}={n}' for r, n in sorted(per_rule.items()))
    print(f'skytpu-lint: {len(new)} new violation(s)'
          f'{" (" + summary + ")" if summary else ""}, '
          f'{len(old)} baselined, {len(stale)} stale baseline '
          f'entr{"y" if len(stale) == 1 else "ies"}.')


def _print_json(new: List[core.Violation], old: List[core.Violation],
                stale: List[str]) -> None:
    print(json.dumps({
        'new': [v.to_dict() for v in new],
        'baselined': [v.to_dict() for v in old],
        'stale_baseline_entries': stale,
    }, indent=1))


def _list_rules() -> None:
    for rule in rules_mod.default_rules():
        scope = (' [' + ', '.join(rule.path_filter) + '/]'
                 if rule.path_filter else '')
        print(f'{rule.id} {rule.name} ({rule.severity}){scope}')
        for line in rule.help.split('\n'):
            print(f'    {line}')


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog='python -m skypilot_tpu.analysis',
        description='skytpu-lint: repo-native AST analysis '
                    '(STL001-STL010), baseline-gated.')
    parser.add_argument('paths', nargs='*',
                        help='files/dirs to lint (default: the '
                             'skypilot_tpu package + bench.py)')
    parser.add_argument('--changed', action='store_true',
                        help='lint only files changed vs git HEAD')
    parser.add_argument('--update-baseline', action='store_true',
                        help='accept all current findings into the '
                             'baseline (prunes fixed ones)')
    parser.add_argument('--baseline',
                        default=baseline_mod.DEFAULT_BASELINE_PATH,
                        help='baseline JSON path (default: '
                             'skypilot_tpu/analysis/baseline.json)')
    parser.add_argument('--no-baseline', action='store_true',
                        help='report every finding (ignore baseline)')
    parser.add_argument('--format', choices=('text', 'json'),
                        default='text')
    parser.add_argument('--verbose', action='store_true',
                        help='also list baselined findings')
    parser.add_argument('--list-rules', action='store_true')
    args = parser.parse_args(argv)

    if args.list_rules:
        _list_rules()
        return 0
    if args.changed and args.paths:
        parser.error('--changed and explicit paths are exclusive')
    if args.update_baseline and (args.changed or args.paths):
        parser.error('--update-baseline needs a full run, not '
                     '--changed or explicit paths (a partial baseline '
                     'would drop every unvisited entry)')
    if args.update_baseline and args.no_baseline:
        parser.error('--update-baseline and --no-baseline are '
                     'contradictory')
    if args.changed:
        try:
            targets: List[str] = changed_files()
        except RuntimeError as e:
            print(f'skytpu-lint: {e}', file=sys.stderr)
            return 2
        if not targets:
            print('skytpu-lint: no changed .py files.')
            return 0
    else:
        targets = list(args.paths) or default_targets()

    baseline_path = None if args.no_baseline else args.baseline
    try:
        new, old, stale = run(targets, baseline_path,
                              update_baseline=args.update_baseline)
    except (OSError, ValueError) as e:
        print(f'skytpu-lint: {e}', file=sys.stderr)
        return 2
    if args.changed or args.paths:
        # Partial run: baseline entries for unvisited files are not
        # stale, they just weren't checked.
        stale = []
    if args.update_baseline:
        print(f'skytpu-lint: baseline rewritten with {len(old)} '
              f'finding(s) at {args.baseline}.')
        return 0
    if args.format == 'json':
        _print_json(new, old, stale)
    else:
        _print_text(new, old, stale, verbose=args.verbose)
    return 1 if new else 0


if __name__ == '__main__':  # pragma: no cover
    sys.exit(main())
