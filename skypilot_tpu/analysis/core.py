"""skytpu-lint core: one parse, one walk, rules as plugins.

The stack's cross-cutting invariants (the ``SKYTPU_*`` env contract,
metric-name hygiene, fault-injection site names, the
``utils/retry.RetryPolicy``-only rule, daemon-thread discipline) were
all enforced at runtime or by convention — drift surfaced only when
the bad path executed. This framework checks them *statically*:

- **Single parse + single walk.** Each file is ``ast.parse``-d once
  and visited once by :class:`LintVisitor`, which dispatches every
  node to every rule that registered interest in its type. Full-repo
  runtime stays well under the 10 s tier-1 budget.
- **Rules as plugins.** A rule subclasses :class:`Rule`, declares the
  node types it wants, and reports via ``ctx.report(...)``. Rules
  needing cross-file facts (declared env names, metric registrations)
  stash them on the shared :class:`Project` and emit from
  ``finalize()``.
- **Per-line suppressions.** ``# skytpu-lint: disable=STL001`` on any
  line of the flagged node's span (or the line directly above it)
  silences that rule there; ``disable`` with no ``=`` silences all.
- **Baseline gating.** Violations are fingerprinted by
  (rule, path, enclosing scope, source-line hash) — stable across
  line-number drift — and compared against a committed JSON baseline
  (:mod:`skypilot_tpu.analysis.baseline`): only *new* violations
  fail, so the gate can land before the last legacy finding is fixed.

No third-party dependencies; stdlib ``ast`` only.
"""
from __future__ import annotations

import ast
import dataclasses
import hashlib
import re
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

SEVERITIES = ('error', 'warning')

# ``# skytpu-lint: disable=STL001,STL004`` / ``# skytpu-lint: disable``.
_SUPPRESS_RE = re.compile(
    r'#\s*skytpu-lint:\s*disable(?:=(?P<rules>[A-Za-z0-9_,\s]+?))?'
    r'(?:\s*[—–-].*)?$')

_ENV_NAME_RE = re.compile(r'\A(?:SKYTPU|BENCH)_[A-Z0-9_]+\Z')
_METRIC_NAME_RE = re.compile(r'skytpu_[a-z0-9_]+\Z')
_LABEL_NAME_RE = re.compile(r'[a-z_][a-z0-9_]*\Z')


@dataclasses.dataclass
class Violation:
    """One finding: where, what rule, why."""
    rule: str
    severity: str
    path: str  # repo-relative, '/'-separated
    line: int
    col: int
    message: str
    context: str  # enclosing Class.method qualname ('' at module scope)
    snippet: str  # stripped source of the flagged line

    def fingerprint(self) -> str:
        """Line-number-free identity used by the baseline: a finding
        keeps its fingerprint when unrelated edits shift it up or
        down the file, and changes it when the flagged code itself
        (or its enclosing scope) changes."""
        digest = hashlib.sha1(self.snippet.encode()).hexdigest()[:12]
        return f'{self.rule}:{self.path}:{self.context}:{digest}'

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class Rule:
    """Base rule plugin.

    Subclasses set ``id`` (STLnnn), ``name`` (kebab slug), ``severity``
    and ``help`` (one-paragraph rationale shown by ``--list-rules``),
    declare ``node_types`` and implement ``check(ctx, node)``.
    Project-scoped rules may also implement ``finalize(project)``,
    which runs once after every file is walked.
    """

    id = ''
    name = ''
    severity = 'error'
    help = ''
    node_types: Tuple[type, ...] = ()
    # Only lint files whose repo-relative path contains one of these
    # directory names (empty = every file).
    path_filter: Tuple[str, ...] = ()

    def applies_to(self, path: str) -> bool:
        if not self.path_filter:
            return True
        parts = path.replace('\\', '/').split('/')
        return any(p in parts for p in self.path_filter)

    def check(self, ctx: 'FileContext', node: ast.AST) -> None:
        raise NotImplementedError

    def finalize(self, project: 'Project') -> None:
        pass


class Project:
    """Cross-file state shared by one analysis run.

    Rules append per-file facts here during the walk and cross-check
    them in ``finalize()``. The declared env-name and fault-site sets
    are injected by the driver (parsed statically from the registry
    modules) so the analyzer never imports production code.
    """

    def __init__(self,
                 declared_env: Optional[Set[str]] = None,
                 declared_sites: Optional[Sequence[str]] = None) -> None:
        self.declared_env: Set[str] = set(declared_env or ())
        self.declared_sites: List[str] = list(declared_sites or ())
        # STL006: metric name -> (kind, labels, path, line) first seen.
        self.metric_registrations: Dict[str, Tuple[str, Tuple[str, ...],
                                                   str, int]] = {}
        self.violations: List[Violation] = []
        # Deferred (finalize-time) reports still honor suppressions:
        # each file leaves its suppression map behind.
        self._suppressions: Dict[str, Dict[int, Optional[Set[str]]]] = {}
        self._sources: Dict[str, List[str]] = {}

    # ---------------------------------------------------- finalize API
    def report_at(self, rule: Rule, path: str, line: int, col: int,
                  message: str, context: str = '') -> None:
        """Report from ``finalize()`` against a previously-walked file
        (suppression comments there still apply)."""
        lines = self._sources.get(path, [])
        snippet = lines[line - 1].strip() if 0 < line <= len(lines) else ''
        if _is_suppressed(self._suppressions.get(path, {}), rule.id,
                          line, line):
            return
        self.violations.append(Violation(
            rule=rule.id, severity=rule.severity, path=path, line=line,
            col=col, message=message, context=context, snippet=snippet))


def _parse_suppressions(
        lines: Sequence[str]) -> Dict[int, Optional[Set[str]]]:
    """1-based line -> set of silenced rule ids (None = all rules).

    A suppression on a comment-only line also applies to the next
    code line (so a multi-line reason comment above the flagged
    statement works): the marker line starts the comment block, any
    further comment/blank lines are skipped.
    """
    out: Dict[int, Optional[Set[str]]] = {}

    def _merge(line_no: int, rules: Optional[Set[str]]) -> None:
        existing = out.get(line_no, 'absent')
        if existing == 'absent':
            out[line_no] = rules
        elif existing is None or rules is None:
            out[line_no] = None
        else:
            out[line_no] = existing | rules  # type: ignore[operator]

    for i, line in enumerate(lines, start=1):
        if 'skytpu-lint' not in line:
            continue
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        raw = m.group('rules')
        rules: Optional[Set[str]] = (
            None if raw is None else
            {r.strip().upper() for r in raw.split(',') if r.strip()})
        _merge(i, rules)
        if line.lstrip().startswith('#'):
            # Comment-only marker: attach to the next code line too.
            j = i + 1
            while j <= len(lines) and (
                    not lines[j - 1].strip() or
                    lines[j - 1].lstrip().startswith('#')):
                j += 1
            if j <= len(lines):
                _merge(j, rules)
    return out


def _is_suppressed(suppressions: Dict[int, Optional[Set[str]]],
                   rule_id: str, start: int, end: int) -> bool:
    """A suppression on any line of the node's span, or on the line
    directly above it (comment-above style), silences the finding."""
    for line in range(max(start - 1, 1), end + 1):
        rules = suppressions.get(line, 'absent')
        if rules == 'absent':
            continue
        if rules is None or rule_id in rules:
            return True
    return False


class FileContext:
    """Everything a rule may ask about the file being walked."""

    def __init__(self, path: str, source: str, tree: ast.Module,
                 project: Project) -> None:
        self.path = path
        self.lines = source.splitlines()
        self.tree = tree
        self.project = project
        self.suppressions = _parse_suppressions(self.lines)
        project._suppressions[path] = self.suppressions
        project._sources[path] = self.lines
        # Maintained by the visitor:
        self.scope_stack: List[ast.AST] = []  # ClassDef/FunctionDef
        self.loop_stack: List[ast.AST] = []  # For/While
        self.lock_depth = 0  # inside a `with <lock-like>` block
        self._parents_linked = False

    # -------------------------------------------------------- helpers
    def qualname(self) -> str:
        names = [getattr(n, 'name', '?') for n in self.scope_stack]
        return '.'.join(names)

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        if not self._parents_linked:
            for parent in ast.walk(self.tree):
                for child in ast.iter_child_nodes(parent):
                    child._skytpu_parent = parent  # type: ignore
            self._parents_linked = True
        return getattr(node, '_skytpu_parent', None)

    def enclosing_function(self) -> Optional[ast.AST]:
        for node in reversed(self.scope_stack):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return node
        return None

    def enclosing_class(self) -> Optional[ast.ClassDef]:
        for node in reversed(self.scope_stack):
            if isinstance(node, ast.ClassDef):
                return node
        return None

    # ------------------------------------------------------ reporting
    def report(self, rule: Rule, node: ast.AST, message: str,
               span: Optional[Tuple[int, int]] = None) -> None:
        start = node.lineno
        end = span[1] if span else getattr(node, 'end_lineno', start)
        if span:
            start = span[0]
        if _is_suppressed(self.suppressions, rule.id, start, end):
            return
        line = node.lineno
        snippet = (self.lines[line - 1].strip()
                   if 0 < line <= len(self.lines) else '')
        self.project.violations.append(Violation(
            rule=rule.id, severity=rule.severity, path=self.path,
            line=line, col=node.col_offset, message=message,
            context=self.qualname(), snippet=snippet))


class LintVisitor(ast.NodeVisitor):
    """One walk per file; dispatches each node to interested rules and
    maintains the scope/loop/lock context rules read."""

    def __init__(self, ctx: FileContext, rules: Sequence[Rule]) -> None:
        self.ctx = ctx
        self._dispatch: Dict[type, List[Rule]] = {}
        for rule in rules:
            if not rule.applies_to(ctx.path):
                continue
            for node_type in rule.node_types:
                self._dispatch.setdefault(node_type, []).append(rule)

    def visit(self, node: ast.AST) -> None:
        for rule in self._dispatch.get(type(node), ()):
            rule.check(self.ctx, node)
        ctx = self.ctx
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            ctx.scope_stack.append(node)
            self.generic_visit(node)
            ctx.scope_stack.pop()
        elif isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
            ctx.loop_stack.append(node)
            self.generic_visit(node)
            ctx.loop_stack.pop()
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            locked = any(_is_lock_like(item.context_expr)
                         for item in node.items)
            ctx.lock_depth += 1 if locked else 0
            self.generic_visit(node)
            ctx.lock_depth -= 1 if locked else 0
        else:
            self.generic_visit(node)


def _is_lock_like(expr: ast.AST) -> bool:
    """Heuristic: the with-context mentions an identifier containing
    'lock', 'mutex' or 'cond' (``self._lock``, ``engine.lock``,
    ``cv``-style condition variables spelled out)."""
    for node in ast.walk(expr):
        name = ''
        if isinstance(node, ast.Name):
            name = node.id
        elif isinstance(node, ast.Attribute):
            name = node.attr
        lowered = name.lower()
        if any(tok in lowered for tok in ('lock', 'mutex', 'cond')):
            return True
    return False


# ---------------------------------------------------------------- utils
# Small AST predicates shared by several rules.

def call_name(node: ast.Call) -> str:
    """Dotted name of the called expression ('' if not a plain path).

    ``threading.Thread(...)`` -> 'threading.Thread';
    ``fi.poll(...)`` -> 'fi.poll'; ``(f())(x)`` -> ''.
    """
    parts: List[str] = []
    cur: ast.AST = node.func
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return '.'.join(reversed(parts))
    return ''


def literal_str(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def arg_or_keyword(call: ast.Call, index: int,
                   keyword: str) -> Optional[ast.AST]:
    if len(call.args) > index:
        return call.args[index]
    for kw in call.keywords:
        if kw.arg == keyword:
            return kw.value
    return None


def env_name_re() -> 're.Pattern[str]':
    return _ENV_NAME_RE


def metric_name_re() -> 're.Pattern[str]':
    return _METRIC_NAME_RE


def label_name_re() -> 're.Pattern[str]':
    return _LABEL_NAME_RE


# ---------------------------------------------------------------- driver
def analyze_source(source: str,
                   path: str = '<memory>',
                   rules: Optional[Sequence[Rule]] = None,
                   project: Optional[Project] = None,
                   finalize: bool = True) -> List[Violation]:
    """Lint one source string (the unit-test entry point).

    ``project`` carries declared env names / fault sites for the
    registry-backed rules; a fresh empty one is used by default.
    """
    from skypilot_tpu.analysis import rules as rules_mod
    if rules is None:
        rules = rules_mod.default_rules()
    if project is None:
        project = Project()
    tree = ast.parse(source, filename=path)
    ctx = FileContext(path, source, tree, project)
    LintVisitor(ctx, rules).visit(tree)
    if finalize:
        for rule in rules:
            rule.finalize(project)
    return project.violations


def analyze_files(paths: Iterable[Tuple[str, str]],
                  rules: Optional[Sequence[Rule]] = None,
                  project: Optional[Project] = None) -> List[Violation]:
    """Lint many (repo-relative path, absolute path) files into one
    project; returns all violations (sorted by path/line)."""
    from skypilot_tpu.analysis import rules as rules_mod
    if rules is None:
        rules = rules_mod.default_rules()
    if project is None:
        project = Project()
    for rel, abspath in paths:
        with open(abspath, encoding='utf-8') as f:
            source = f.read()
        try:
            tree = ast.parse(source, filename=rel)
        except SyntaxError as e:
            # A file the interpreter can't parse is its own finding.
            project.violations.append(Violation(
                rule='STL000', severity='error', path=rel,
                line=e.lineno or 1, col=e.offset or 0,
                message=f'syntax error: {e.msg}', context='',
                snippet=(e.text or '').strip()))
            continue
        ctx = FileContext(rel, source, tree, project)
        LintVisitor(ctx, rules).visit(tree)
    for rule in rules:
        rule.finalize(project)
    project.violations.sort(key=lambda v: (v.path, v.line, v.rule))
    return project.violations
