"""skytpu-lint rule catalog (STL001–STL012).

Each rule encodes one repo invariant that used to be enforced only at
runtime or by convention; docs/static_analysis.md carries the full
rationale and fixture examples. Rules are deliberately heuristic
where a sound analysis is impossible (STL004's race detector,
STL008's tracer hazards): precision comes from the suppression +
baseline workflow, not from pretending the heuristic is exact.
"""
from __future__ import annotations

import ast
import fnmatch
from typing import Dict, List, Optional, Sequence, Set, Tuple

from skypilot_tpu.analysis import core
from skypilot_tpu.analysis.core import FileContext
from skypilot_tpu.analysis.core import Project
from skypilot_tpu.analysis.core import Rule


class SwallowedException(Rule):
    """STL001: a bare/broad except whose body is only ``pass``.

    ``except Exception: pass`` in serve/jobs control loops is how
    replica failures and controller errors vanish without a log line.
    Narrow typed excepts (``except OSError: pass``) are allowed —
    swallowing a *specific* expected error is a decision; swallowing
    everything is a bug magnet.
    """

    id = 'STL001'
    name = 'swallowed-exception'
    severity = 'error'
    help = ('Bare `except:` / `except Exception:` with a pass-only '
            'body silently swallows every error including bugs. Log '
            'at warning with context, narrow the exception type, or '
            'suppress with a reason comment.')
    node_types = (ast.ExceptHandler,)

    _BROAD = ('Exception', 'BaseException')

    def check(self, ctx: FileContext, node: ast.AST) -> None:
        assert isinstance(node, ast.ExceptHandler)
        if node.type is not None and not self._any_broad(node.type):
            return
        body = [stmt for stmt in node.body
                if not (isinstance(stmt, ast.Expr) and
                        core.literal_str(stmt.value) is not None)]
        if not all(isinstance(stmt, ast.Pass) or
                   (isinstance(stmt, ast.Expr) and
                    isinstance(stmt.value, ast.Constant) and
                    stmt.value.value is Ellipsis)
                   for stmt in body):
            return
        what = ('bare except' if node.type is None else
                'broad except')
        ctx.report(self, node,
                   f'{what} swallows all errors silently; log at '
                   'warning with context or narrow the type',
                   span=(node.lineno,
                         getattr(node, 'end_lineno', node.lineno)))

    @classmethod
    def _any_broad(cls, type_expr: ast.AST) -> bool:
        """Exception/BaseException, alone or anywhere in a tuple —
        `except (Exception, ValueError):` is just as broad."""
        exprs = (type_expr.elts if isinstance(type_expr, ast.Tuple)
                 else [type_expr])
        return any(isinstance(e, ast.Name) and e.id in cls._BROAD
                   for e in exprs)


class HandRolledRetry(Rule):
    """STL002: a try/except + ``time.sleep`` loop outside RetryPolicy.

    utils/retry.RetryPolicy is THE retry implementation (backoff cap,
    full jitter, deadline, typed retryable predicate, FakeClock for
    tests, per-site metrics). A hand-rolled sleep-in-a-loop retry
    bypasses all of that and is invisible to chaos tests.
    """

    id = 'STL002'
    name = 'hand-rolled-retry'
    severity = 'error'
    help = ('A loop containing both a try/except and time.sleep is a '
            'hand-rolled retry loop. Use utils/retry.RetryPolicy '
            '(seedable jitter, deadlines, retry metrics) instead.')
    node_types = (ast.Call,)

    def check(self, ctx: FileContext, node: ast.AST) -> None:
        assert isinstance(node, ast.Call)
        if core.call_name(node) != 'time.sleep':
            return
        if not ctx.loop_stack:
            return
        loop = ctx.loop_stack[-1]
        if not getattr(loop, '_skytpu_has_try', None):
            loop._skytpu_has_try = any(  # type: ignore[attr-defined]
                isinstance(n, ast.Try) for n in ast.walk(loop))
        if loop._skytpu_has_try:  # type: ignore[attr-defined]
            ctx.report(self, node,
                       'time.sleep retry loop outside RetryPolicy; '
                       'use utils/retry.RetryPolicy '
                       '(state.should_retry()/state.sleep())')


class ThreadWithoutDaemon(Rule):
    """STL003: ``threading.Thread(...)`` without an explicit daemon=.

    Python's default (inherit daemonness from the spawner) makes
    process shutdown depend on *which thread* created the worker. The
    reference orchestrator's hang-at-exit bugs all trace to this;
    every Thread here states its intent.
    """

    id = 'STL003'
    name = 'thread-daemon'
    severity = 'error'
    help = ('threading.Thread() without explicit daemon= inherits '
            'daemonness from the creating thread — shutdown behavior '
            'becomes spawn-site-dependent. Always pass daemon=True/'
            'False explicitly.')
    node_types = (ast.Call,)

    def check(self, ctx: FileContext, node: ast.AST) -> None:
        assert isinstance(node, ast.Call)
        if core.call_name(node) not in ('threading.Thread', 'Thread'):
            return
        for kw in node.keywords:
            if kw.arg == 'daemon' or kw.arg is None:  # None = **kwargs
                return
        ctx.report(self, node,
                   'threading.Thread without explicit daemon=; pass '
                   'daemon=True (helper) or daemon=False (must join)',
                   span=(node.lineno, node.lineno))


class UnlockedSharedMutation(Rule):
    """STL004: heuristic race detector for thread-spawning classes.

    In a class that constructs ``threading.Thread`` anywhere, an
    assignment to ``self.<attr>`` (or ``self.<attr>[...]``) outside a
    ``with <lock>`` block — and outside ``__init__``, which runs
    before the threads exist — is a candidate data race. Heuristic by
    design: single-word flag flips are atomic-enough in CPython, so
    intentional lock-free sites get a suppression with a reason.
    """

    id = 'STL004'
    name = 'unlocked-shared-mutation'
    severity = 'warning'
    help = ('Mutation of instance state in a thread-spawning class '
            'outside a `with <lock>` block. Take the lock, move the '
            'write to __init__, or suppress with a reason if the '
            'lock-free write is intentional (e.g. GIL-atomic flag).')
    node_types = (ast.Assign, ast.AugAssign)

    _SKIP_METHODS = ('__init__', '__new__', '__del__', '__enter__')

    def check(self, ctx: FileContext, node: ast.AST) -> None:
        cls = ctx.enclosing_class()
        if cls is None or ctx.lock_depth > 0:
            return
        fn = ctx.enclosing_function()
        if fn is None or fn.name in self._SKIP_METHODS:
            return
        if not self._spawns_threads(cls):
            return
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for target in targets:
            attr = self._self_attr(target)
            if attr is None:
                continue
            ctx.report(self, node,
                       f'write to self.{attr} outside a lock in '
                       f'thread-spawning class {cls.name}; guard with '
                       'the instance lock or suppress with a reason',
                       span=(node.lineno, node.lineno))
            return

    @staticmethod
    def _self_attr(target: ast.AST) -> Optional[str]:
        # self.x = ... / self.x[k] = ... / self.x += ...
        if isinstance(target, ast.Subscript):
            target = target.value
        if (isinstance(target, ast.Attribute) and
                isinstance(target.value, ast.Name) and
                target.value.id == 'self'):
            return target.attr
        return None

    @staticmethod
    def _spawns_threads(cls: ast.ClassDef) -> bool:
        cached = getattr(cls, '_skytpu_spawns_threads', None)
        if cached is None:
            cached = any(
                isinstance(n, ast.Call) and
                core.call_name(n) in ('threading.Thread', 'Thread')
                for n in ast.walk(cls))
            cls._skytpu_spawns_threads = cached  # type: ignore
        return cached


class UndeclaredEnvVar(Rule):
    """STL005: a ``SKYTPU_*``/``BENCH_*`` literal not in the registry.

    Every control-plane env knob must be declared exactly once, in
    ``utils/env_contract.py`` (the rank contract) or
    ``utils/env_registry.py`` (tunables) — that is what makes the env
    surface auditable and lets conftest/docs enumerate it. A literal
    anywhere else that the registry has never heard of is drift:
    either a typo'd name (reads get a silent default) or a brand-new
    knob smuggled in without declaration.
    """

    id = 'STL005'
    name = 'undeclared-env-var'
    severity = 'error'
    help = ('String literal names a SKYTPU_*/BENCH_* env var that is '
            'not declared in utils/env_contract.py or '
            'utils/env_registry.py. Declare it centrally (and '
            'preferably reference the registry constant).')
    node_types = (ast.Constant,)

    _ALLOWED_FILES = ('utils/env_contract.py', 'utils/env_registry.py')

    def applies_to(self, path: str) -> bool:
        norm = path.replace('\\', '/')
        return not any(norm.endswith(allowed)
                       for allowed in self._ALLOWED_FILES)

    def check(self, ctx: FileContext, node: ast.AST) -> None:
        assert isinstance(node, ast.Constant)
        value = node.value
        if not isinstance(value, str) or \
                not core.env_name_re().fullmatch(value):
            return
        if value in ctx.project.declared_env:
            return
        parent = ctx.parent(node)
        if isinstance(parent, ast.Expr):  # docstring / bare string
            return
        ctx.report(self, node,
                   f'env var {value!r} is not declared in the env '
                   'registry (utils/env_registry.py) or env contract',
                   span=(node.lineno, node.lineno))


class MetricRegistrationLint(Rule):
    """STL006: static mirror of the metrics registry's runtime lint.

    ``metrics/registry.py`` rejects bad names/missing help at
    registration — but only when the registering module is imported.
    This rule applies the same checks (name matches
    ``skytpu_[a-z0-9_]+``, non-empty help, sane label names) to every
    literal ``counter/gauge/histogram`` registration at parse time,
    and cross-checks that one metric name is never registered with
    two different kinds or label sets across the repo (the runtime
    conflict error, caught before both modules ever co-import).
    """

    id = 'STL006'
    name = 'metric-registration'
    severity = 'error'
    help = ('Literal metric registration violating the registry '
            'contract: name must match skytpu_[a-z0-9_]+, help must '
            'be a non-empty string, label names must be lowercase '
            'identifiers, and a name must keep one (kind, labels) '
            'across the whole repo.')
    node_types = (ast.Call,)

    _METHODS = ('counter', 'gauge', 'histogram')
    _RECEIVER_TOKENS = ('metric', 'registry')

    def check(self, ctx: FileContext, node: ast.AST) -> None:
        assert isinstance(node, ast.Call)
        func = node.func
        if not (isinstance(func, ast.Attribute) and
                func.attr in self._METHODS):
            return
        receiver = ''
        if isinstance(func.value, ast.Name):
            receiver = func.value.id
        elif isinstance(func.value, ast.Attribute):
            receiver = func.value.attr
        if not any(tok in receiver.lower()
                   for tok in self._RECEIVER_TOKENS):
            return
        kind = func.attr
        name_node = core.arg_or_keyword(node, 0, 'name')
        name = core.literal_str(name_node)
        if name is None:
            return  # dynamic name: runtime lint still covers it
        span = (node.lineno, node.lineno)
        if not core.metric_name_re().fullmatch(name):
            ctx.report(self, node,
                       f'metric name {name!r} must match '
                       'skytpu_[a-z0-9_]+', span=span)
        help_node = core.arg_or_keyword(node, 1, 'help')
        help_str = core.literal_str(help_node)
        if help_node is None or (help_str is not None and
                                 not help_str.strip()):
            ctx.report(self, node,
                       f'metric {name!r} needs a non-empty help string',
                       span=span)
        labels = self._literal_labels(node)
        if labels is not None:
            for label in labels:
                if not core.label_name_re().fullmatch(label):
                    ctx.report(self, node,
                               f'metric {name!r} label {label!r} must '
                               'be a lowercase identifier', span=span)
        seen = ctx.project.metric_registrations.get(name)
        signature = (kind, tuple(labels) if labels is not None else None)
        if seen is None:
            ctx.project.metric_registrations[name] = (
                signature[0], signature[1], ctx.path, node.lineno)
        else:
            # Dynamic labels (None) are unknowable statically: only a
            # kind mismatch is a definite conflict then; label sets
            # are compared when both sides are literal.
            kind_conflict = seen[0] != kind
            label_conflict = (labels is not None and
                              seen[1] is not None and
                              seen[1] != signature[1])
            if kind_conflict or label_conflict:
                ctx.report(self, node,
                           f'metric {name!r} re-registered as {kind}'
                           f'{signature[1] or ()} but '
                           f'{seen[2]}:{seen[3]} registered it as '
                           f'{seen[0]}{seen[1] or ()}',
                           span=span)

    @staticmethod
    def _literal_labels(node: ast.Call) -> Optional[Tuple[str, ...]]:
        # labels is the registry helpers' third positional parameter
        # (registry.py counter/gauge/histogram) or a keyword.
        labels_node = core.arg_or_keyword(node, 2, 'labels')
        if labels_node is None:
            return ()  # unlabeled registration
        if isinstance(labels_node, (ast.Tuple, ast.List)):
            out = []
            for elt in labels_node.elts:
                lit = core.literal_str(elt)
                if lit is None:
                    return None  # dynamic labels: skip
                out.append(lit)
            return tuple(out)
        return None


class UnknownFaultSite(Rule):
    """STL007: fault-injection site literals vs the site registry.

    Sites are just strings at ``fault_injection.poll/inject/pending``
    call sites; a typo there means the chaos plan never fires and the
    test silently stops testing anything. Every literal site must
    match ``fault_injection.KNOWN_SITES`` (exact or fnmatch pattern);
    the registry itself must not list a site twice.
    """

    id = 'STL007'
    name = 'unknown-fault-site'
    severity = 'error'
    help = ('Literal fault-injection site not declared in '
            'utils/fault_injection.KNOWN_SITES (or declared twice '
            'there). A typo\'d site makes chaos plans silently inert.')
    node_types = (ast.Call,)

    _METHODS = ('poll', 'inject', 'pending', 'crashpoint')

    def __init__(self) -> None:
        self._uses: List[Tuple[str, str, int, str]] = []

    def check(self, ctx: FileContext, node: ast.AST) -> None:
        assert isinstance(node, ast.Call)
        dotted = core.call_name(node)
        parts = dotted.split('.')
        if len(parts) < 2 or parts[-1] not in self._METHODS:
            return
        receiver = parts[-2]
        if receiver not in ('fault_injection', 'fi') and \
                'fault' not in receiver:
            return
        site = core.literal_str(core.arg_or_keyword(node, 0, 'site'))
        if site is None:
            return  # dynamic site (the provision router's f-string)
        self._uses.append((ctx.path, ctx.qualname(), node.lineno, site))

    def finalize(self, project: Project) -> None:
        declared = project.declared_sites
        dupes = {s for s in declared if declared.count(s) > 1}
        reported_dupes: Set[str] = set()
        for dupe in dupes:
            if dupe not in reported_dupes:
                reported_dupes.add(dupe)
                project.violations.append(core.Violation(
                    rule=self.id, severity=self.severity,
                    path='skypilot_tpu/utils/fault_injection.py',
                    line=1, col=0,
                    message=f'site {dupe!r} declared more than once '
                            'in KNOWN_SITES',
                    context='KNOWN_SITES', snippet=''))
        for path, context, line, site in self._uses:
            if any(site == pat or fnmatch.fnmatch(site, pat)
                   for pat in declared):
                continue
            project.report_at(
                self, path, line, 0,
                f'fault-injection site {site!r} is not declared in '
                'utils/fault_injection.KNOWN_SITES', context=context)
        self._uses = []


class JaxRecompileHazard(Rule):
    """STL008: tracer/recompile hazards inside ``jax.jit`` functions.

    Scoped to ``models/``, ``ops/``, ``parallel/``. Inside a function
    decorated ``@jax.jit`` / ``@functools.partial(jax.jit, ...)``:

    - ``np.*`` calls force a host sync / constant-fold per trace
      (use ``jnp`` or hoist out of the jit);
    - a Python ``if`` on a *traced* (non-static) argument raises
      ``TracerBoolConversionError`` at trace time or, worse, bakes
      one branch in silently when the arg is concrete during warmup;
    - ``int(arg)`` / ``range(arg)`` on a traced arg is the same
      hazard spelled differently.

    ``x is None`` checks, ``isinstance`` and ``.shape/.dtype/.ndim``
    accesses are static and allowed.
    """

    id = 'STL008'
    name = 'jax-recompile-hazard'
    severity = 'error'
    help = ('Inside a jax.jit-decorated function: np.* call, Python '
            '`if` on a traced argument, or int()/range() on a traced '
            'argument. Use jnp/lax.cond/static_argnames, or suppress '
            'with a reason if the value is genuinely static.')
    node_types = (ast.FunctionDef,)
    path_filter = ('models', 'ops', 'parallel')

    _NP_NAMES = ('np', 'numpy', '_np')
    _STATIC_ATTRS = ('shape', 'ndim', 'dtype', 'size', 'sharding')

    def check(self, ctx: FileContext, node: ast.AST) -> None:
        assert isinstance(node, ast.FunctionDef)
        static = self._jit_static_args(node)
        if static is None:
            return
        params = {a.arg for a in (node.args.posonlyargs + node.args.args +
                                  node.args.kwonlyargs)} - static
        params.discard('self')
        for sub in self._walk_own_body(node):
            if isinstance(sub, ast.Call):
                self._check_call(ctx, sub, params)
            elif isinstance(sub, ast.If):
                self._check_if(ctx, sub, params)

    @staticmethod
    def _walk_own_body(fn: ast.FunctionDef):
        """Walk fn's body without descending into nested defs (those
        get their own decorator treatment when the visitor reaches
        them)."""
        stack: List[ast.AST] = list(fn.body)
        while stack:
            sub = stack.pop()
            yield sub
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            stack.extend(ast.iter_child_nodes(sub))

    def _check_call(self, ctx: FileContext, node: ast.Call,
                    traced: Set[str]) -> None:
        dotted = core.call_name(node)
        root = dotted.split('.')[0] if dotted else ''
        if root in self._NP_NAMES and '.' in dotted:
            ctx.report(self, node,
                       f'{dotted}() inside jax.jit traces to a host '
                       'constant / sync; use jnp or hoist it out',
                       span=(node.lineno, node.lineno))
            return
        if dotted in ('int', 'range') and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Name) and arg.id in traced:
                ctx.report(self, node,
                           f'{dotted}({arg.id}) on a traced argument '
                           'inside jax.jit; mark it static_argnames '
                           'or keep it on-device',
                           span=(node.lineno, node.lineno))

    def _check_if(self, ctx: FileContext, node: ast.If,
                  traced: Set[str]) -> None:
        offender = self._traced_value_use(ctx, node.test, traced)
        if offender is not None:
            ctx.report(self, node,
                       f'Python `if` on traced argument {offender!r} '
                       'inside jax.jit (TracerBoolConversionError or '
                       'silently baked branch); use lax.cond/jnp.where '
                       'or static_argnames',
                       span=(node.lineno,
                             getattr(node.test, 'end_lineno',
                                     node.lineno)))

    def _traced_value_use(self, ctx: FileContext, test: ast.AST,
                          traced: Set[str]) -> Optional[str]:
        for sub in ast.walk(test):
            if not (isinstance(sub, ast.Name) and sub.id in traced):
                continue
            parent = ctx.parent(sub)
            if isinstance(parent, ast.Attribute) and \
                    parent.attr in self._STATIC_ATTRS:
                continue
            if isinstance(parent, ast.Compare) and \
                    all(isinstance(op, (ast.Is, ast.IsNot))
                        for op in parent.ops):
                continue
            if isinstance(parent, ast.Call):
                func = parent.func
                if isinstance(func, ast.Name) and \
                        func.id in ('isinstance', 'len', 'getattr',
                                    'hasattr'):
                    continue
            return sub.id
        return None

    @staticmethod
    def _jit_static_args(node: ast.FunctionDef) -> Optional[Set[str]]:
        """None if not jit-decorated; else the static arg-name set."""
        for dec in node.decorator_list:
            dotted = ''
            call = None
            if isinstance(dec, ast.Call):
                call = dec
                dotted = core.call_name(dec)
            elif isinstance(dec, (ast.Name, ast.Attribute)):
                dotted = core.call_name(
                    ast.Call(func=dec, args=[], keywords=[]))
            if dotted in ('jax.jit', 'jit'):
                static: Set[str] = set()
                if call is not None:
                    static = JaxRecompileHazard._static_from_call(
                        call, node)
                return static
            if dotted in ('functools.partial', 'partial') and \
                    call is not None and call.args:
                inner = call.args[0]
                inner_name = ''
                if isinstance(inner, (ast.Name, ast.Attribute)):
                    inner_name = core.call_name(
                        ast.Call(func=inner, args=[], keywords=[]))
                if inner_name in ('jax.jit', 'jit'):
                    return JaxRecompileHazard._static_from_call(
                        call, node)
        return None

    @staticmethod
    def _static_from_call(call: ast.Call,
                          fn: ast.FunctionDef) -> Set[str]:
        static: Set[str] = set()
        all_args = [a.arg for a in (fn.args.posonlyargs + fn.args.args)]
        for kw in call.keywords:
            if kw.arg == 'static_argnames':
                value = kw.value
                lit = core.literal_str(value)
                if lit is not None:
                    static.add(lit)
                elif isinstance(value, (ast.Tuple, ast.List)):
                    for elt in value.elts:
                        name = core.literal_str(elt)
                        if name is not None:
                            static.add(name)
            elif kw.arg in ('static_argnums', 'donate_argnums'):
                if kw.arg == 'donate_argnums':
                    continue
                nums: List[int] = []
                value = kw.value
                if isinstance(value, ast.Constant) and \
                        isinstance(value.value, int):
                    nums = [value.value]
                elif isinstance(value, (ast.Tuple, ast.List)):
                    nums = [elt.value for elt in value.elts
                            if isinstance(elt, ast.Constant) and
                            isinstance(elt.value, int)]
                for num in nums:
                    if 0 <= num < len(all_args):
                        static.add(all_args[num])
        return static


class BlockingSignalHandler(Rule):
    """STL009: a ``signal.signal`` handler doing more than flag-flips.

    A Python signal handler runs between bytecodes of whatever frame
    the signal interrupted — possibly while that frame holds the very
    lock the handler would need. Joins, sleeps, I/O, logging or any
    blocking call inside the handler can therefore deadlock or crash
    the process at the worst moment (the serving replica's graceful
    drain depends on SIGTERM being handled instantly). Handlers in
    package code may ONLY set flags/events (``event.set()``,
    ``self._flag = True``); the actual shutdown work belongs on a
    normal thread or task that watches the flag.
    """

    id = 'STL009'
    name = 'blocking-signal-handler'
    severity = 'error'
    help = ('signal.signal handler bodies may only set flags/events '
            '(event.set(), attribute assignment). Blocking calls, '
            'joins, sleeps, logging or I/O in the handler run inside '
            'an arbitrary interrupted frame and can deadlock; move '
            'the work to a thread/task that watches the flag.')
    node_types = (ast.Call,)

    # Call names (last dotted component) a handler may make: event /
    # flag setters and non-blocking flag reads (the second-signal
    # escalation pattern checks is_set() before raising).
    _ALLOWED_TAILS = ('set', 'is_set')

    def __init__(self) -> None:
        # One report per offending call even when the same handler is
        # registered for several signals.
        self._reported: Set[Tuple[str, int]] = set()

    def check(self, ctx: FileContext, node: ast.AST) -> None:
        assert isinstance(node, ast.Call)
        # Covers `signal.signal(...)` and the from-import alias
        # `signal(...)`; the handler may be the second positional arg
        # or the `handler=` keyword.
        if core.call_name(node) not in ('signal.signal', 'signal'):
            return
        handler = core.arg_or_keyword(node, 1, 'handler')
        if handler is None:
            return
        if isinstance(handler, ast.Lambda):
            self._check_calls(ctx, handler.body, 'lambda handler')
            return
        # Bare names AND bound methods / attributes (`self._on_term`)
        # resolve to a same-file FunctionDef by name; imported or
        # dynamic handlers (and signal.SIG_IGN-style constants, which
        # resolve to nothing) are not statically checkable.
        name = None
        if isinstance(handler, ast.Name):
            name = handler.id
        elif isinstance(handler, ast.Attribute):
            name = handler.attr
        if name is None:
            return
        fn = self._resolve(ctx, name)
        if fn is None:
            return
        for stmt in fn.body:
            self._check_calls(ctx, stmt, f'handler {fn.name!r}')

    def _check_calls(self, ctx: FileContext, root: ast.AST,
                     where: str) -> None:
        for sub in ast.walk(root):
            if not isinstance(sub, ast.Call):
                continue
            dotted = core.call_name(sub)
            tail = dotted.split('.')[-1] if dotted else ''
            if tail in self._ALLOWED_TAILS:
                continue
            key = (ctx.path, sub.lineno)
            if key in self._reported:
                continue
            self._reported.add(key)
            ctx.report(self, sub,
                       f'{dotted or "call"}() inside signal {where}: '
                       'signal handlers may only set flags/events '
                       '(.set() / assignment); do the work on a '
                       'thread or task that watches the flag',
                       span=(sub.lineno, sub.lineno))

    @staticmethod
    def _resolve(ctx: FileContext,
                 name: str) -> Optional[ast.FunctionDef]:
        for sub in ast.walk(ctx.tree):
            if isinstance(sub, ast.FunctionDef) and sub.name == name:
                return sub
        return None


class RawSqliteOutsideStateDB(Rule):
    """STL010: raw sqlite use outside ``utils/statedb``.

    ``utils/statedb.connect`` is the ONE way control-plane code opens
    sqlite (WAL journal mode, busy_timeout, synchronous=NORMAL,
    explicit-transaction autocommit — docs/crash_recovery.md); a bare
    ``sqlite3.connect`` silently loses all of that, and with it the
    crash-safety story. Likewise, a function issuing two or more
    write statements (INSERT/UPDATE/DELETE/REPLACE) outside a
    ``transaction()`` block is a torn-write hazard: a crash between
    the statements leaves the database half-mutated, which is exactly
    what the intent journal exists to make impossible.
    """

    id = 'STL010'
    name = 'raw-sqlite'
    severity = 'error'
    help = ('sqlite3.connect / executescript, or a function with 2+ '
            'write statements not under a statedb transaction() '
            'block, outside utils/statedb.py. Open connections with '
            'statedb.connect and wrap multi-statement writes in '
            'statedb.transaction() (or StateDB.transaction()) so '
            'they commit atomically.')
    node_types = (ast.Call, ast.FunctionDef)

    _ALLOWED_FILES = ('utils/statedb.py',)
    _WRITE_PREFIXES = ('insert', 'update', 'delete', 'replace')

    def applies_to(self, path: str) -> bool:
        norm = path.replace('\\', '/')
        return not any(norm.endswith(allowed)
                       for allowed in self._ALLOWED_FILES)

    def check(self, ctx: FileContext, node: ast.AST) -> None:
        if isinstance(node, ast.Call):
            self._check_call(ctx, node)
        else:
            assert isinstance(node, ast.FunctionDef)
            self._check_multi_write(ctx, node)

    def _check_call(self, ctx: FileContext, node: ast.Call) -> None:
        dotted = core.call_name(node)
        if dotted == 'sqlite3.connect':
            ctx.report(self, node,
                       'raw sqlite3.connect bypasses the statedb '
                       'recipe (WAL, busy_timeout, synchronous='
                       'NORMAL); use utils/statedb.connect',
                       span=(node.lineno, node.lineno))
        elif dotted.endswith('.executescript'):
            ctx.report(self, node,
                       'executescript runs multiple statements with '
                       'implicit commits; use explicit statements '
                       'under statedb.transaction()',
                       span=(node.lineno, node.lineno))

    def _check_multi_write(self, ctx: FileContext,
                           fn: ast.FunctionDef) -> None:
        writes: List[ast.Call] = []
        unguarded: List[ast.Call] = []
        for sub, guarded in self._walk_with_guard(fn):
            if not (isinstance(sub, ast.Call) and
                    isinstance(sub.func, ast.Attribute) and
                    sub.func.attr == 'execute' and sub.args):
                continue
            sql = self._sql_head(sub.args[0])
            if sql is None or not sql.lstrip().lower().startswith(
                    self._WRITE_PREFIXES):
                continue
            writes.append(sub)
            if not guarded:
                unguarded.append(sub)
        if len(writes) >= 2 and unguarded:
            first = unguarded[0]
            ctx.report(self, first,
                       f'{len(writes)} write statements in '
                       f'{fn.name}() with at least one outside a '
                       'statedb transaction() block; a crash between '
                       'them tears the state — wrap them in '
                       'statedb.transaction()',
                       span=(first.lineno, first.lineno))

    @classmethod
    def _walk_with_guard(cls, fn: ast.FunctionDef):
        """Yield (node, under_transaction_with) for fn's own body,
        without descending into nested defs."""
        stack = [(n, False) for n in fn.body]
        while stack:
            node, guarded = stack.pop()
            yield node, guarded
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            inner = guarded or (isinstance(node, (ast.With, ast.AsyncWith))
                                and cls._is_transaction_with(node))
            stack.extend((child, inner)
                         for child in ast.iter_child_nodes(node))

    @staticmethod
    def _is_transaction_with(node: ast.AST) -> bool:
        for item in getattr(node, 'items', ()):
            expr = item.context_expr
            if isinstance(expr, ast.Call):
                dotted = core.call_name(expr)
                if dotted and 'transaction' in dotted.split('.')[-1]:
                    return True
        return False

    @staticmethod
    def _sql_head(arg: ast.AST) -> Optional[str]:
        lit = core.literal_str(arg)
        if lit is not None:
            return lit
        if isinstance(arg, ast.JoinedStr) and arg.values:
            first = arg.values[0]
            if isinstance(first, ast.Constant) and \
                    isinstance(first.value, str):
                return first.value
        return None


class DirectClockInControlPlane(Rule):
    """STL011: direct wall-clock / raw-sqlite calls in the
    fleet-shared control plane (``jobs/``, ``serve/``, ``fleet/``).

    These layers are driven by the fleet scale harness and by tests
    under *injectable* time (``statedb.wall_now()`` behind the
    ``retry.Clock`` interface — a ``FakeClock`` deterministically
    drives lease expiry, restart budgets and probe deadlines) and by
    the ONE statedb connection recipe. A bare ``time.time()`` pins
    the code to the real clock (untestable expiry races); a bare
    ``sqlite3.connect`` bypasses the WAL/busy-timeout recipe (also
    STL010, flagged here too so the control-plane sweep is
    self-contained).
    """

    id = 'STL011'
    name = 'injectable-clock'
    severity = 'error'
    help = ('time.time() or sqlite3.connect() inside jobs/, serve/ '
            'or fleet/: use statedb.wall_now() (injectable clock) '
            'and statedb.connect so lease expiry, timestamps and '
            'durability stay testable under FakeClock and the WAL '
            'recipe.')
    node_types = (ast.Call,)
    path_filter = ('jobs', 'serve', 'fleet')

    def check(self, ctx: FileContext, node: ast.AST) -> None:
        assert isinstance(node, ast.Call)
        dotted = core.call_name(node)
        if dotted == 'time.time':
            ctx.report(self, node,
                       'direct time.time() in the control plane: '
                       'timestamps and expiries here must share the '
                       'injectable wall clock — call '
                       'statedb.wall_now() instead',
                       span=(node.lineno, node.lineno))
        elif dotted == 'sqlite3.connect':
            ctx.report(self, node,
                       'raw sqlite3.connect in the control plane '
                       'bypasses the statedb recipe; use '
                       'statedb.connect',
                       span=(node.lineno, node.lineno))


class HttpCallWithoutTimeout(Rule):
    """STL012: an outbound HTTP client call without ``timeout=``.

    Every intra-stack HTTP call — readiness probes, drain requests,
    cancel broadcasts, metrics scrapes, cloud REST calls — must carry
    an explicit bounded timeout: a peer that accepts the TCP connect
    and then goes silent would otherwise hang the calling thread (a
    probe loop, a teardown thread, the provisioner) indefinitely,
    which is exactly the failure mode the replica-survivability layer
    (docs/failover.md) exists to bound. Matched call shapes:
    ``requests.<verb>(...)``, ``<...>session.<verb>(...)`` /
    ``<...>_session.<verb>(...)`` (requests.Session and
    aiohttp.ClientSession alike), and ``urlopen(...)``. Calls that
    deliberately ride a session-level ``ClientTimeout`` (the serve
    LB's pooled streaming session) suppress with a reason.
    """

    id = 'STL012'
    name = 'http-timeout'
    severity = 'error'
    help = ('HTTP client call without an explicit timeout= argument: '
            'a silent peer hangs the calling thread forever. Pass a '
            'bounded (connect, read) tuple (requests) or '
            'aiohttp.ClientTimeout, or suppress with a reason when a '
            'session-level timeout is the deliberate bound.')
    node_types = (ast.Call,)

    _VERBS = ('get', 'post', 'put', 'delete', 'head', 'patch',
              'request')

    def check(self, ctx: FileContext, node: ast.AST) -> None:
        assert isinstance(node, ast.Call)
        dotted = core.call_name(node)
        if not dotted:
            return
        parts = dotted.split('.')
        verb = parts[-1]
        is_http = False
        if verb == 'urlopen':
            is_http = True
        elif verb in self._VERBS and len(parts) >= 2:
            base = parts[-2]
            is_http = (base == 'requests' or 'session' in base.lower())
        if not is_http:
            return
        if any(kw.arg == 'timeout' for kw in node.keywords):
            return
        ctx.report(self, node,
                   f'HTTP client call {dotted}() without timeout=: '
                   'a silent peer hangs this thread forever — pass '
                   'a bounded (connect, read) timeout',
                   span=(node.lineno, node.lineno))


def default_rules() -> List[Rule]:
    """Fresh rule instances (STL007/STL009 keep per-run state)."""
    return [
        SwallowedException(),
        HandRolledRetry(),
        ThreadWithoutDaemon(),
        UnlockedSharedMutation(),
        UndeclaredEnvVar(),
        MetricRegistrationLint(),
        UnknownFaultSite(),
        JaxRecompileHazard(),
        BlockingSignalHandler(),
        RawSqliteOutsideStateDB(),
        DirectClockInControlPlane(),
        HttpCallWithoutTimeout(),
    ]


RULE_IDS = tuple(r.id for r in default_rules())
