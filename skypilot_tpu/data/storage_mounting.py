"""Mount/download storage onto every cluster host.

Counterpart of reference ``sky/data/mounting_utils.py:293-365`` +
``cloud_vm_ray_backend._execute_storage_mounts`` (:4803): resolve each
``storage_mounts`` entry to a Storage, upload any local source, then run
the store's mount (MOUNT) or download (COPY) command on every host via
its command runner.
"""
from __future__ import annotations

import os
import typing
from typing import Any, Dict

from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state
from skypilot_tpu.data import storage as storage_lib
from skypilot_tpu.utils import command_runner as runner_lib
from skypilot_tpu.utils import log as sky_logging
from skypilot_tpu.utils import subprocess_utils

if typing.TYPE_CHECKING:
    from skypilot_tpu.backend import gang_backend

logger = sky_logging.init_logger(__name__)


def resolve_storage(spec: Any) -> storage_lib.Storage:
    if isinstance(spec, storage_lib.Storage):
        return spec
    if isinstance(spec, dict):
        return storage_lib.Storage.from_yaml_config(spec)
    raise exceptions.StorageSpecError(
        f'Invalid storage mount spec: {spec!r}')


def mount_storage_on_cluster(handle: 'gang_backend.GangResourceHandle',
                             storage_mounts: Dict[str, Any],
                             log_dir: str) -> None:
    resolved = {
        dst: resolve_storage(spec) for dst, spec in storage_mounts.items()
    }
    # Default hermetic clusters to the local store, real ones to GCS.
    is_local_cluster = handle.provider_name == 'local'
    for storage in resolved.values():
        if not storage.stores:
            storage.add_store(storage_lib.StoreType.LOCAL
                              if is_local_cluster
                              else storage_lib.StoreType.GCS)
        storage.sync()
        global_user_state.add_or_update_storage(storage.name, {
            'name': storage.name,
            'stores': [s.value for s in storage.stores],
        }, 'READY')

    runners = handle.runners()

    def mount_all(runner: runner_lib.CommandRunner) -> None:
        for dst, storage in resolved.items():
            store = storage.get_store()
            if storage.mode == storage_lib.StorageMode.MOUNT:
                cmd = store.mount_command(_host_path(runner, dst))
            else:
                cmd = store.download_command(_host_path(runner, dst))
            runner.run(cmd,
                       log_path=os.path.join(log_dir, 'storage_mounts.log'),
                       check=True)

    subprocess_utils.run_in_parallel(mount_all, runners)
    logger.info('Mounted %d storage(s) on %d host(s).', len(resolved),
                len(runners))


def _host_path(runner: runner_lib.CommandRunner, path: str) -> str:
    """Local simulated hosts sandbox absolute paths under the host dir;
    real hosts use the path as-is."""
    if isinstance(runner, runner_lib.LocalProcessRunner):
        return runner.translate(path)
    return path
