"""Bucket-URL file mounts: ``gs://`` / ``s3://`` / ``local://``
sources in ``file_mounts`` download on the cluster hosts.

Re-design of reference ``sky/cloud_stores.py:1-566`` (CloudStorage
classes generating fetch commands for file_mounts whose source is a
bucket URL): one dispatch point mapping a URL scheme onto a shell
command the host runs, reusing the Store classes' CLIs. ``local://``
resolves against the hermetic bucket root so recovery tests cover
this path with zero cloud deps.
"""
from __future__ import annotations

import posixpath
import shlex
from typing import Optional, Tuple

from skypilot_tpu import exceptions
from skypilot_tpu.data import storage as storage_lib

_SCHEMES = ('gs://', 's3://', 'r2://', 'az://', 'cos://', 'oci://',
            'local://')


def is_cloud_url(path: str) -> bool:
    return any(path.startswith(s) for s in _SCHEMES)


def _split(url: str) -> Tuple[str, str, str]:
    scheme, rest = url.split('://', 1)
    bucket, _, key = rest.partition('/')
    if not bucket:
        raise exceptions.StorageSpecError(f'Bad bucket URL: {url!r}')
    return scheme, bucket, key


def download_command(url: str, dst: str,
                     is_dir: Optional[bool] = None) -> str:
    """Shell command fetching ``url`` to ``dst`` on a cluster host.

    A trailing '/' (or an extensionless key, heuristically) is treated
    as a prefix/directory sync; otherwise a single-object copy.
    """
    scheme, bucket, key = _split(url)
    if is_dir is None:
        is_dir = url.endswith('/') or not posixpath.splitext(key)[1]
    src = url.rstrip('/')
    q_dst = shlex.quote(dst)
    if scheme in ('cos', 'oci'):
        # One S3-compat fetch shape for both; cos:// carries the
        # region as its first path segment
        # (cos://<region>/<bucket>/<key>, the reference's IBM URL
        # shape) and the region stays PER STORE — never process
        # state, or the first URL's region would leak into later
        # commands.
        store_kwargs = {}
        if scheme == 'cos':
            region, bucket, key = bucket, *key.partition('/')[::2]
            if not bucket:
                raise exceptions.StorageSpecError(
                    f'Bad COS URL {url!r}: want '
                    'cos://region/bucket/...')
            store_kwargs['region'] = region
        cls = (storage_lib.IbmCosStore if scheme == 'cos'
               else storage_lib.OciStore)
        store = cls(f'{bucket}/{key}'.rstrip('/') if key else bucket,
                    **store_kwargs)
        if is_dir:
            return store.download_command(dst)
        aws = cls(bucket, **store_kwargs)._aws()  # pylint: disable=protected-access
        obj = shlex.quote(f's3://{bucket}/{key}'.rstrip('/'))
        return (f'mkdir -p $(dirname {q_dst}) && '
                f'{aws} s3 cp {obj} {q_dst}')
    if scheme in ('gs', 's3', 'r2', 'az'):
        # Directory fetches reuse the Store classes' own download
        # commands (one place owns the gsutil/aws/az CLI invocations);
        # only the single-object copy is specific to this module.
        if scheme == 'az':
            # Azure container names cannot carry a '/': a key prefix
            # must go through --pattern, not into the -s container —
            # and download-batch recreates blob paths relative to the
            # container, so a prefix fetch stages through a temp dir
            # and moves the prefix's CONTENTS into dst (matching the
            # gs://'s rsync-of-prefix semantics).
            if is_dir:
                prefix = key.rstrip('/')
                if not prefix:
                    return (f'mkdir -p {q_dst} && '
                            f'az storage blob download-batch '
                            f'-d {q_dst} -s {bucket}')
                q_prefix = shlex.quote(prefix)
                return (
                    f'azdl=$(mktemp -d) && '
                    f'az storage blob download-batch -d "$azdl" '
                    f'-s {bucket} '
                    f'--pattern {shlex.quote(prefix + "/*")} && '
                    f'mkdir -p {q_dst} && '
                    f'cp -a "$azdl"/{q_prefix}/. {q_dst}/ && '
                    f'rm -rf "$azdl"')
            return (f'mkdir -p $(dirname {q_dst}) && '
                    f'az storage blob download -c {bucket} '
                    f'-n {shlex.quote(key)} -f {q_dst}')
        cls = {
            'gs': storage_lib.GcsStore,
            's3': storage_lib.S3Store,
            'r2': storage_lib.R2Store,
        }[scheme]
        store = cls(f'{bucket}/{key}'.rstrip('/') if key else bucket)
        if is_dir:
            return store.download_command(dst)
        if scheme == 'gs':
            tool, obj = 'gsutil cp', shlex.quote(src)
        else:
            # s3 and r2 share the aws CLI; R2 adds endpoint/creds.
            aws = (storage_lib.R2Store(bucket)._aws()  # pylint: disable=protected-access
                   if scheme == 'r2' else 'aws')
            tool = f'{aws} s3 cp'
            obj = shlex.quote(f's3://{bucket}/{key}'.rstrip('/'))
        return (f'mkdir -p $(dirname {q_dst}) && '
                f'{tool} {obj} {q_dst}')
    # local:// — hermetic bucket directory.
    root = storage_lib.LocalStore.bucket_root()
    path = shlex.quote(f'{root}/{bucket}/{key}'.rstrip('/'))
    if is_dir:
        return f'mkdir -p {q_dst} && cp -a {path}/. {q_dst}/'
    return (f'mkdir -p $(dirname {q_dst}) && cp -a {path} {q_dst}')
