"""Cross-store bucket transfer.

Re-design of reference ``sky/data/data_transfer.py`` (GCS Transfer
Service + rclone paths) on the CLI-not-SDK stance of this data layer:
``gsutil`` natively reads ``s3://`` (with AWS creds in ~/.boto or the
env), so S3→GCS is one rsync; GCS→S3 stages through a local temp dir
because the aws CLI cannot read ``gs://``. LOCAL buckets transfer by
plain copy, keeping the whole path hermetically testable.
"""
from __future__ import annotations

import os
import shutil
import tempfile

from skypilot_tpu import exceptions
from skypilot_tpu.data import storage as storage_lib
from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)


_run = storage_lib.run_storage_command


def transfer(src: storage_lib.AbstractStore,
             dst: storage_lib.AbstractStore) -> None:
    """Copy every object in ``src`` into ``dst``."""
    s_local = isinstance(src, storage_lib.LocalStore)
    d_local = isinstance(dst, storage_lib.LocalStore)
    if s_local and d_local:
        shutil.copytree(src.path(), dst.path(), dirs_exist_ok=True)
        return
    if s_local:
        # Reuse the store's own upload path with the bucket dir as
        # source.
        uploader = type(dst)(dst.name, source=src.path())
        uploader.upload()
        return
    if d_local:
        os.makedirs(dst.path(), exist_ok=True)
        _run(_fetch_command(src, dst.path()))
        return
    if isinstance(dst, storage_lib.GcsStore):
        # gsutil reads s3:// and gs:// alike — one server-side-ish
        # rsync (reference data_transfer.py s3_to_gcs).
        _run(f'gsutil -m rsync -r {src.url()} {dst.url()}')
        return
    if isinstance(dst, storage_lib.S3Store):
        # aws CLI can't read gs://; stage through a temp dir.
        with tempfile.TemporaryDirectory() as tmp:
            _run(_fetch_command(src, tmp))
            _run(f'aws s3 sync {tmp} {dst.url()}')
        return
    raise exceptions.StorageError(
        f'No transfer path {type(src).__name__} -> '
        f'{type(dst).__name__}.')


def _fetch_command(src: storage_lib.AbstractStore, dst_dir: str) -> str:
    return src.download_command(dst_dir)
