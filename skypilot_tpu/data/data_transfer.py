"""Cross-store bucket transfer with verification.

Re-design of reference ``sky/data/data_transfer.py:1-222`` (GCS
Transfer Service + rclone paths) on the CLI-not-SDK stance of this
data layer, with one property the reference's shell-outs lack: every
transfer is **verified** — after the copy, the (key, size) manifests
of source and destination are compared object-by-object and a
mismatch raises, so a silently-truncated multipart upload or a
partial sync can never masquerade as success.

Paths:
- ``gsutil`` natively reads ``s3://`` (with AWS creds in ~/.boto or
  the env), so S3→GCS is one server-side-ish rsync;
- everything else stages through a local temp dir using each store's
  own download/upload machinery (multipart handled by the CLIs);
- LOCAL buckets transfer by plain copy, keeping the whole path —
  including verification — hermetically testable.
"""
from __future__ import annotations

import os
import shutil
import tempfile
from typing import Dict

from skypilot_tpu import exceptions
from skypilot_tpu.data import storage as storage_lib
from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)


_run = storage_lib.run_storage_command


def transfer(src: storage_lib.AbstractStore,
             dst: storage_lib.AbstractStore,
             verify: bool = True) -> None:
    """Copy every object in ``src`` into ``dst`` (and verify)."""
    s_local = isinstance(src, storage_lib.LocalStore)
    d_local = isinstance(dst, storage_lib.LocalStore)
    if s_local and d_local:
        shutil.copytree(src.path(), dst.path(), dirs_exist_ok=True)
    elif s_local:
        # Reuse the store's own upload path with the bucket dir as
        # source (multipart thresholds handled by the store's CLI).
        # exclude_git=False: a bucket copy must move EVERY key, or
        # verification fails on '.git/'-prefixed objects.
        uploader = type(dst)(dst.name, source=src.path(),
                             exclude_git=False)
        uploader.upload()
    elif d_local:
        os.makedirs(dst.path(), exist_ok=True)
        _run(src.download_command(dst.path()))
    elif (isinstance(dst, storage_lib.GcsStore) and
          isinstance(src, (storage_lib.GcsStore, storage_lib.S3Store))
          and not isinstance(src, storage_lib.R2Store)):
        # gsutil reads s3:// and gs:// alike — one server-side-ish
        # rsync (reference data_transfer.py s3_to_gcs). R2 is excluded:
        # its endpoint is not AWS, gsutil can't reach it.
        _run(f'gsutil -m rsync -r {src.url()} {dst.url()}')
    elif (type(src) is type(dst) and
          isinstance(src, storage_lib.S3Store) and
          src._aws() == dst._aws()):  # pylint: disable=protected-access
        # Same-endpoint S3-family pair (S3->S3, R2->R2, same-region
        # COS->COS, OCI->OCI): bucket-to-bucket `s3 sync` issues
        # SERVER-SIDE CopyObject — no object bytes stage through this
        # host. This is the TB-scale path, the role the reference
        # delegates to cloud-side transfer services
        # (sky/data/data_transfer.py). The `_aws()` equality check is
        # the endpoint check: one CLI invocation addresses both
        # buckets, so same-type stores on DIFFERENT endpoints (e.g.
        # cross-region COS, whose bucket lives behind a per-region
        # endpoint) fall through to the staged generic path instead
        # of syncing the destination against the source's endpoint.
        _run(f'{src._aws()} s3 sync {src.url()} {dst.url()}')  # pylint: disable=protected-access
    elif (isinstance(src, storage_lib.AzureBlobStore) and
          isinstance(dst, storage_lib.AzureBlobStore)):
        # Azure-side async blob copy between containers (server-side).
        # start-batch only ENQUEUES copies, so poll until no blob in
        # the destination reports copy.status == pending — verifying
        # (or returning) against an in-flight copy would fail on (or
        # hand the caller) a partial bucket.
        _run(f'az storage blob copy start-batch '
             f'--destination-container {dst.name} '
             f'--source-container {src.name}')
        _run('for i in $(seq 180); do '
             f'pending=$(az storage blob list -c {dst.name} '
             '--query "length([?properties.copy.status==\'pending\'])" '
             '-o tsv); '
             '[ "${pending:-0}" = "0" ] && exit 0; sleep 5; done; '
             f'echo "azure copy into {dst.name} still pending" >&2; '
             'exit 1')
    else:
        # Generic path: stage through a temp dir with each store's own
        # CLI machinery (R2 endpoints, az batch uploads, ...).
        with tempfile.TemporaryDirectory() as tmp:
            _run(src.download_command(tmp))
            uploader = type(dst)(dst.name, source=tmp,
                                 exclude_git=False)
            uploader.upload()
    if verify:
        verify_transfer(src, dst)


def verify_transfer(src: storage_lib.AbstractStore,
                    dst: storage_lib.AbstractStore) -> None:
    """Assert dst holds every src object at the same size.

    Size+name manifests are the portable cross-store integrity check
    (etags/checksums are not comparable across stores or across
    multipart boundaries). dst may hold EXTRA objects (rsync into a
    non-empty bucket); missing or size-mismatched ones fail.
    """
    src_manifest: Dict[str, int] = dict(src.list_objects())
    dst_manifest: Dict[str, int] = dict(dst.list_objects())
    bad = {
        key: (size, dst_manifest.get(key))
        for key, size in src_manifest.items()
        if dst_manifest.get(key) != size
    }
    if bad:
        sample = dict(list(bad.items())[:5])
        raise exceptions.StorageError(
            f'Transfer verification failed {src.url()} -> '
            f'{dst.url()}: {len(bad)}/{len(src_manifest)} objects '
            f'missing or size-mismatched (key: (src, dst)): {sample}')
    logger.info('Verified transfer %s -> %s: %d objects, %d bytes.',
                src.url(), dst.url(), len(src_manifest),
                sum(src_manifest.values()))
