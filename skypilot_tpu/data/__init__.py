"""Data & storage layer."""
from skypilot_tpu.data.storage import Storage
from skypilot_tpu.data.storage import StorageMode
from skypilot_tpu.data.storage import StoreType

__all__ = ['Storage', 'StorageMode', 'StoreType']
