"""Storage — named buckets synced or FUSE-mounted onto clusters.

Re-design of reference ``sky/data/storage.py`` (Storage :484, StoreType
:114, GcsStore :1802) trimmed to the TPU-relevant stores:

- GCS (primary): data/checkpoint buckets for TPU jobs; COPY downloads
  to each host, MOUNT uses gcsfuse. The durable MOUNT bucket is the
  checkpoint/resume substrate for managed spot jobs (reference §5
  checkpoint discussion).
- LOCAL (hermetic): a directory under $SKYTPU_DATA_DIR/buckets acts as
  the bucket; MOUNT is a symlink. Lets recovery tests exercise the
  checkpoint-resume path with zero cloud deps.

All cloud interaction goes through the ``gsutil``/``gcloud storage``
CLI (like the reference's mounting shell, mounting_utils.py), so this
layer stays dependency-light.
"""
from __future__ import annotations

import enum
import os
import shutil
import subprocess
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)


class StoreType(enum.Enum):
    GCS = 'GCS'
    S3 = 'S3'
    LOCAL = 'LOCAL'


class StorageMode(enum.Enum):
    MOUNT = 'MOUNT'
    COPY = 'COPY'


def run_storage_command(cmd: str) -> None:
    """Run a storage CLI command; raise StorageError on failure (the
    one subprocess helper shared by all stores and data_transfer)."""
    proc = subprocess.run(cmd, shell=True, capture_output=True,
                          text=True, check=False)
    if proc.returncode != 0:
        raise exceptions.StorageError(
            f'Storage command failed ({cmd}): {proc.stderr}')


class AbstractStore:
    """One physical bucket in one store type."""

    _run = staticmethod(run_storage_command)

    def __init__(self, name: str, source: Optional[str] = None) -> None:
        self.name = name
        self.source = source

    def upload(self) -> None:
        """Sync self.source into the bucket (no-op if source is None)."""
        raise NotImplementedError

    def download_command(self, dst: str) -> str:
        """Shell command fetching bucket contents to dst (COPY mode)."""
        raise NotImplementedError

    def mount_command(self, mount_path: str) -> str:
        """Shell command mounting the bucket at mount_path (MOUNT mode)."""
        raise NotImplementedError

    def delete(self) -> None:
        raise NotImplementedError

    def url(self) -> str:
        raise NotImplementedError


class GcsStore(AbstractStore):
    """Google Cloud Storage bucket via gsutil/gcsfuse."""

    def url(self) -> str:
        return f'gs://{self.name}'

    def upload(self) -> None:
        if self.source is None:
            return
        src = os.path.abspath(os.path.expanduser(self.source))
        self._run(f'gsutil mb -c standard {self.url()} || true')
        if os.path.isdir(src):
            self._run(f'gsutil -m rsync -r -x ".git/*" {src} {self.url()}')
        else:
            self._run(f'gsutil cp {src} {self.url()}/')

    def download_command(self, dst: str) -> str:
        return (f'mkdir -p {dst} && '
                f'gsutil -m rsync -r {self.url()} {dst}')

    def mount_command(self, mount_path: str) -> str:
        # gcsfuse with implicit dirs; install if missing (reference
        # mounting_utils.py:25-268 installs FUSE adapters the same way).
        install = ('which gcsfuse >/dev/null 2>&1 || '
                   '(curl -sSL https://github.com/GoogleCloudPlatform/'
                   'gcsfuse/releases/download/v2.4.0/'
                   'gcsfuse_2.4.0_amd64.deb -o /tmp/gcsfuse.deb && '
                   'sudo dpkg -i /tmp/gcsfuse.deb)')
        return (f'{install}; mkdir -p {mount_path} && '
                f'(mountpoint -q {mount_path} || '
                f'gcsfuse --implicit-dirs {self.name} {mount_path})')

    def delete(self) -> None:
        self._run(f'gsutil -m rm -r {self.url()} || true')


class S3Store(AbstractStore):
    """Amazon S3 bucket via the aws CLI; MOUNT via goofys.

    Re-design of reference ``sky/data/storage.py:1300`` (S3Store) with
    the same CLI-not-SDK stance as GcsStore.
    """

    def url(self) -> str:
        return f's3://{self.name}'

    def upload(self) -> None:
        if self.source is None:
            return
        src = os.path.abspath(os.path.expanduser(self.source))
        self._run(f'aws s3 mb {self.url()} || true')
        if os.path.isdir(src):
            self._run(f'aws s3 sync --exclude ".git/*" {src} '
                      f'{self.url()}')
        else:
            self._run(f'aws s3 cp {src} {self.url()}/')

    def download_command(self, dst: str) -> str:
        return f'mkdir -p {dst} && aws s3 sync {self.url()} {dst}'

    def mount_command(self, mount_path: str) -> str:
        # goofys, as the reference's S3 MOUNT adapter
        # (sky/data/mounting_utils.py:25: goofys for S3).
        install = (
            'which goofys >/dev/null 2>&1 || '
            '(sudo curl -sSL https://github.com/kahing/goofys/releases/'
            'latest/download/goofys -o /usr/local/bin/goofys && '
            'sudo chmod +x /usr/local/bin/goofys)')
        return (f'{install}; mkdir -p {mount_path} && '
                f'(mountpoint -q {mount_path} || '
                f'goofys {self.name} {mount_path})')

    def delete(self) -> None:
        self._run(f'aws s3 rb --force {self.url()} || true')


class LocalStore(AbstractStore):
    """Directory-backed fake bucket for hermetic tests."""

    @staticmethod
    def bucket_root() -> str:
        base = os.path.expanduser(
            os.environ.get('SKYTPU_DATA_DIR', '~/.skytpu'))
        path = os.path.join(base, 'buckets')
        os.makedirs(path, exist_ok=True)
        return path

    def path(self) -> str:
        return os.path.join(self.bucket_root(), self.name)

    def url(self) -> str:
        return f'local://{self.name}'

    def upload(self) -> None:
        os.makedirs(self.path(), exist_ok=True)
        if self.source is None:
            return
        src = os.path.abspath(os.path.expanduser(self.source))
        if os.path.isdir(src):
            shutil.copytree(src, self.path(), dirs_exist_ok=True)
        else:
            shutil.copy2(src, self.path())

    def download_command(self, dst: str) -> str:
        return f'mkdir -p {dst} && cp -a {self.path()}/. {dst}/'

    def mount_command(self, mount_path: str) -> str:
        # Symlink stands in for a FUSE mount: writes are immediately
        # durable in the "bucket", which is exactly the property the
        # checkpoint-recovery path needs.
        return (f'mkdir -p {self.path()} && '
                f'mkdir -p $(dirname {mount_path}) && '
                f'rm -rf {mount_path} && '
                f'ln -sfn {self.path()} {mount_path}')

    def delete(self) -> None:
        shutil.rmtree(self.path(), ignore_errors=True)


_STORE_CLASSES = {
    StoreType.GCS: GcsStore,
    StoreType.S3: S3Store,
    StoreType.LOCAL: LocalStore,
}


class Storage:
    """User-facing named storage object.

    YAML form (under ``storage_mounts:``)::

        /checkpoints:
          name: my-ckpt-bucket
          store: gcs          # or local
          mode: MOUNT         # or COPY
          source: ./data      # optional: upload at launch
    """

    def __init__(self,
                 name: str,
                 source: Optional[str] = None,
                 mode: StorageMode = StorageMode.MOUNT,
                 store: Optional[StoreType] = None,
                 persistent: bool = True) -> None:
        if not name:
            raise exceptions.StorageSpecError('Storage needs a name.')
        self.name = name
        self.source = source
        self.mode = mode
        self.persistent = persistent
        self.stores: Dict[StoreType, AbstractStore] = {}
        if store is not None:
            self.add_store(store)
        if source is not None and not os.path.exists(
                os.path.expanduser(source)):
            raise exceptions.StorageSpecError(
                f'Storage source {source!r} does not exist.')

    def add_store(self, store_type: StoreType) -> AbstractStore:
        if store_type not in self.stores:
            cls = _STORE_CLASSES[store_type]
            self.stores[store_type] = cls(self.name, self.source)
        return self.stores[store_type]

    def get_store(self) -> AbstractStore:
        if not self.stores:
            self.add_store(StoreType.GCS)
        return next(iter(self.stores.values()))

    def sync(self) -> None:
        """Upload source to every store."""
        for store in self.stores.values():
            store.upload()

    def delete(self) -> None:
        for store in self.stores.values():
            store.delete()
        from skypilot_tpu import global_user_state
        global_user_state.remove_storage(self.name)

    # ------------------------------------------------------------------
    @classmethod
    def from_yaml_config(cls, config: Dict[str, Any]) -> 'Storage':
        if not isinstance(config, dict):
            raise exceptions.StorageSpecError(
                f'storage mount spec must be a mapping, got {config!r}')
        mode = StorageMode(config.get('mode', 'MOUNT').upper())
        store = config.get('store')
        store_type = StoreType(store.upper()) if store else None
        return cls(name=config.get('name'),
                   source=config.get('source'),
                   mode=mode,
                   store=store_type,
                   persistent=config.get('persistent', True))

    def to_yaml_config(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {'name': self.name, 'mode': self.mode.value}
        if self.source is not None:
            out['source'] = self.source
        if self.stores:
            out['store'] = next(iter(self.stores)).value.lower()
        if not self.persistent:
            out['persistent'] = False
        return out

    def __repr__(self) -> str:
        stores = ','.join(s.value for s in self.stores) or 'unbound'
        return f'Storage({self.name}, {self.mode.value}, {stores})'
