"""Storage — named buckets synced or FUSE-mounted onto clusters.

Re-design of reference ``sky/data/storage.py`` (Storage :484, StoreType
:114, GcsStore :1802, AzureBlobStore :2309, R2Store :3156) on the
TPU-relevant stores:

- GCS (primary): data/checkpoint buckets for TPU jobs; COPY downloads
  to each host, MOUNT uses gcsfuse. The durable MOUNT bucket is the
  checkpoint/resume substrate for managed spot jobs (reference §5
  checkpoint discussion).
- S3 / R2: aws CLI (R2 = S3 API against the Cloudflare account
  endpoint, credentials in ~/.cloudflare as the reference lays them
  out); MOUNT via goofys (R2: goofys --endpoint).
- AZURE: blob container via the az CLI; MOUNT via blobfuse2 — the
  reference's 4-tool FUSE matrix (mounting_utils.py:25-268:
  goofys/gcsfuse/blobfuse2/rclone) mapped onto this layer's
  CLI-not-SDK stance.
- LOCAL (hermetic): a directory under $SKYTPU_DATA_DIR/buckets acts as
  the bucket; MOUNT is a symlink. Lets recovery tests exercise the
  checkpoint-resume path with zero cloud deps.

Every store can ``list_objects()`` (name + size), which is what makes
cross-store transfer *verified* (data_transfer.verify_transfer).
"""
from __future__ import annotations

import enum
import os
import shutil
import subprocess
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import exceptions
from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)


class StoreType(enum.Enum):
    GCS = 'GCS'
    S3 = 'S3'
    R2 = 'R2'
    AZURE = 'AZURE'
    IBM = 'IBM'
    OCI = 'OCI'
    LOCAL = 'LOCAL'


class StorageMode(enum.Enum):
    MOUNT = 'MOUNT'
    COPY = 'COPY'


def run_storage_command(cmd: str) -> None:
    """Run a storage CLI command; raise StorageError on failure (the
    one subprocess helper shared by all stores and data_transfer)."""
    proc = subprocess.run(cmd, shell=True, capture_output=True,
                          text=True, check=False)
    if proc.returncode != 0:
        raise exceptions.StorageError(
            f'Storage command failed ({cmd}): {proc.stderr}')


def run_storage_command_output(cmd: str) -> str:
    """Like run_storage_command but returns stdout (listings)."""
    proc = subprocess.run(cmd, shell=True, capture_output=True,
                          text=True, check=False)
    if proc.returncode != 0:
        raise exceptions.StorageError(
            f'Storage command failed ({cmd}): {proc.stderr}')
    return proc.stdout


class AbstractStore:
    """One physical bucket in one store type."""

    _run = staticmethod(run_storage_command)

    def __init__(self, name: str, source: Optional[str] = None,
                 exclude_git: bool = True) -> None:
        self.name = name
        self.source = source
        # '.git/*' exclusion is a user-source-directory heuristic; a
        # bucket-to-bucket staged transfer must copy EVERY key or its
        # verification manifest fails (data_transfer sets False).
        self.exclude_git = exclude_git

    def upload(self) -> None:
        """Sync self.source into the bucket (no-op if source is None)."""
        raise NotImplementedError

    def download_command(self, dst: str) -> str:
        """Shell command fetching bucket contents to dst (COPY mode)."""
        raise NotImplementedError

    def mount_command(self, mount_path: str) -> str:
        """Shell command mounting the bucket at mount_path (MOUNT mode)."""
        raise NotImplementedError

    def delete(self) -> None:
        raise NotImplementedError

    def url(self) -> str:
        raise NotImplementedError

    def list_objects(self) -> List[Tuple[str, int]]:
        """(key, size) for every object — the transfer-verification
        manifest (data_transfer.verify_transfer compares src/dst)."""
        raise NotImplementedError

    _run_out = staticmethod(run_storage_command_output)


class GcsStore(AbstractStore):
    """Google Cloud Storage bucket via gsutil/gcsfuse."""

    def url(self) -> str:
        return f'gs://{self.name}'

    def upload(self) -> None:
        if self.source is None:
            return
        src = os.path.abspath(os.path.expanduser(self.source))
        self._run(f'gsutil mb -c standard {self.url()} || true')
        if os.path.isdir(src):
            exclude = ' -x ".git/*"' if self.exclude_git else ''
            self._run(f'gsutil -m rsync -r{exclude} {src} {self.url()}')
        else:
            self._run(f'gsutil cp {src} {self.url()}/')

    def download_command(self, dst: str) -> str:
        return (f'mkdir -p {dst} && '
                f'gsutil -m rsync -r {self.url()} {dst}')

    def mount_command(self, mount_path: str) -> str:
        # gcsfuse with implicit dirs; install if missing (reference
        # mounting_utils.py:25-268 installs FUSE adapters the same way).
        install = ('which gcsfuse >/dev/null 2>&1 || '
                   '(curl -sSL https://github.com/GoogleCloudPlatform/'
                   'gcsfuse/releases/download/v2.4.0/'
                   'gcsfuse_2.4.0_amd64.deb -o /tmp/gcsfuse.deb && '
                   'sudo dpkg -i /tmp/gcsfuse.deb)')
        return (f'{install}; mkdir -p {mount_path} && '
                f'(mountpoint -q {mount_path} || '
                f'gcsfuse --implicit-dirs {self.name} {mount_path})')

    def delete(self) -> None:
        self._run(f'gsutil -m rm -r {self.url()} || true')

    def list_objects(self) -> List[Tuple[str, int]]:
        # `gsutil ls -l -r`: "  <size>  <timestamp>  gs://bucket/key"
        # with a trailing "TOTAL:" line and "dir/:" section headers.
        # Listing failures must RAISE (a vacuously-empty manifest
        # would make transfer verification pass on a broken listing);
        # only the empty-bucket "matched no objects" case is benign.
        try:
            out = self._run_out(f'gsutil ls -l -r {self.url()}/**')
        except exceptions.StorageError as e:
            if 'matched no objects' in str(e):
                return []
            raise
        prefix = self.url() + '/'
        objs = []
        for line in out.splitlines():
            # maxsplit=2: keys may contain whitespace.
            parts = line.split(None, 2)
            if (len(parts) == 3 and parts[0].isdigit() and
                    parts[2].startswith(prefix)):
                objs.append((parts[2][len(prefix):], int(parts[0])))
        return objs


class S3Store(AbstractStore):
    """Amazon S3 bucket via the aws CLI; MOUNT via goofys.

    Re-design of reference ``sky/data/storage.py:1300`` (S3Store) with
    the same CLI-not-SDK stance as GcsStore.
    """

    def url(self) -> str:
        return f's3://{self.name}'

    def upload(self) -> None:
        if self.source is None:
            return
        src = os.path.abspath(os.path.expanduser(self.source))
        aws = self._aws()
        self._run(f'{aws} s3 mb {self.url()} || true')
        if os.path.isdir(src):
            exclude = (' --exclude ".git/*"' if self.exclude_git
                       else '')
            self._run(f'{aws} s3 sync{exclude} {src} {self.url()}')
        else:
            self._run(f'{aws} s3 cp {src} {self.url()}/')

    def download_command(self, dst: str) -> str:
        return (f'mkdir -p {dst} && '
                f'{self._aws()} s3 sync {self.url()} {dst}')

    def mount_command(self, mount_path: str) -> str:
        # goofys, as the reference's S3 MOUNT adapter
        # (sky/data/mounting_utils.py:25: goofys for S3).
        install = (
            'which goofys >/dev/null 2>&1 || '
            '(sudo curl -sSL https://github.com/kahing/goofys/releases/'
            'latest/download/goofys -o /usr/local/bin/goofys && '
            'sudo chmod +x /usr/local/bin/goofys)')
        return (f'{install}; mkdir -p {mount_path} && '
                f'(mountpoint -q {mount_path} || '
                f'goofys {self.name} {mount_path})')

    def delete(self) -> None:
        self._run(f'{self._aws()} s3 rb --force {self.url()} || true')

    def list_objects(self) -> List[Tuple[str, int]]:
        # `aws s3 ls --recursive`: "<date> <time> <size> <key>".
        out = self._run_out(
            f'{self._aws()} s3 ls --recursive {self.url()}')
        objs = []
        for line in out.splitlines():
            parts = line.split(None, 3)
            if len(parts) == 4 and parts[2].isdigit():
                objs.append((parts[3], int(parts[2])))
        return objs

    def _aws(self) -> str:
        """The aws CLI invocation (R2 overrides with endpoint/creds)."""
        return 'aws'


class R2Store(S3Store):
    """Cloudflare R2 bucket — the S3 API against the per-account R2
    endpoint (reference ``sky/data/storage.py:3156`` R2Store: aws CLI
    with ``AWS_SHARED_CREDENTIALS_FILE=~/.cloudflare/r2.credentials``,
    profile ``r2``; account id from ``~/.cloudflare/accountid``).
    MOUNT via goofys ``--endpoint`` (same adapter the reference's
    mounting matrix assigns to R2)."""

    CREDENTIALS_PATH = '~/.cloudflare/r2.credentials'
    ACCOUNT_ID_PATH = '~/.cloudflare/accountid'

    @classmethod
    def endpoint(cls) -> str:
        account_id = os.environ.get('R2_ACCOUNT_ID')
        if not account_id:
            try:
                with open(os.path.expanduser(cls.ACCOUNT_ID_PATH),
                          encoding='utf-8') as f:
                    account_id = f.read().strip()
            except OSError:
                raise exceptions.StorageError(
                    'R2 needs an account id: set R2_ACCOUNT_ID or '
                    f'write {cls.ACCOUNT_ID_PATH}.') from None
        return f'https://{account_id}.r2.cloudflarestorage.com'

    def _aws(self) -> str:
        creds = self.CREDENTIALS_PATH
        return (f'AWS_SHARED_CREDENTIALS_FILE={creds} aws '
                f'--endpoint-url {self.endpoint()} --profile r2')

    def url(self) -> str:
        # The aws CLI still addresses R2 buckets as s3://<name>; the
        # endpoint selects R2. r2:// is this layer's display scheme.
        # upload/download_command/delete are inherited from S3Store —
        # they differ only through the _aws() hook.
        return f's3://{self.name}'

    def display_url(self) -> str:
        return f'r2://{self.name}'

    def mount_command(self, mount_path: str) -> str:
        # Two FUSE adapters (completing the reference's 4-tool matrix
        # goofys/gcsfuse/blobfuse2/rclone,
        # sky/data/mounting_utils.py:25-268): goofys --endpoint by
        # default; SKYTPU_R2_MOUNT_TOOL=rclone switches to rclone
        # configured entirely via env vars (the reference's R2/IBM
        # adapter), which needs no config file on the host.
        if os.environ.get('SKYTPU_R2_MOUNT_TOOL') == 'rclone':
            install = ('which rclone >/dev/null 2>&1 || '
                       '(curl -sSL https://rclone.org/install.sh | '
                       'sudo bash)')
            env = (f'RCLONE_CONFIG_R2_TYPE=s3 '
                   f'RCLONE_CONFIG_R2_PROVIDER=Cloudflare '
                   f'RCLONE_CONFIG_R2_ENDPOINT={self.endpoint()} '
                   f'RCLONE_CONFIG_R2_ENV_AUTH=true '
                   f'AWS_SHARED_CREDENTIALS_FILE='
                   f'{self.CREDENTIALS_PATH} AWS_PROFILE=r2')
            return (f'{install}; mkdir -p {mount_path} && '
                    f'(mountpoint -q {mount_path} || '
                    f'{env} rclone mount r2:{self.name} {mount_path} '
                    f'--daemon --vfs-cache-mode writes)')
        install = (
            'which goofys >/dev/null 2>&1 || '
            '(sudo curl -sSL https://github.com/kahing/goofys/releases/'
            'latest/download/goofys -o /usr/local/bin/goofys && '
            'sudo chmod +x /usr/local/bin/goofys)')
        creds = self.CREDENTIALS_PATH
        return (f'{install}; mkdir -p {mount_path} && '
                f'(mountpoint -q {mount_path} || '
                f'AWS_SHARED_CREDENTIALS_FILE={creds} AWS_PROFILE=r2 '
                f'goofys --endpoint {self.endpoint()} '
                f'{self.name} {mount_path})')

    def delete(self) -> None:
        self._run(f'{self._aws()} s3 rb --force {self.url()} || true')


class IbmCosStore(S3Store):
    """IBM Cloud Object Storage bucket — COS's S3-compatible API
    against the regional endpoint (role of reference
    ``sky/data/storage.py:3600`` IBMCosStore, which drives ibm_boto3 +
    rclone; here the aws CLI with a dedicated profile does the same
    transfers, and MOUNT uses the reference's own IBM adapter:
    rclone). Region from ``IBM_COS_REGION`` or ``~/.ibm/cos_region``
    (default us-south); HMAC credentials in
    ``~/.ibm/cos.credentials`` profile ``ibm``."""

    CREDENTIALS_PATH = '~/.ibm/cos.credentials'
    REGION_PATH = '~/.ibm/cos_region'

    def __init__(self, name: str, source: Optional[str] = None,
                 exclude_git: bool = True,
                 region: Optional[str] = None) -> None:
        super().__init__(name, source, exclude_git)
        # Region is PER STORE (cos:// URLs carry it): two buckets in
        # different regions must not share process-global state.
        self._region = region

    def region(self) -> str:
        region = self._region or os.environ.get('IBM_COS_REGION')
        if not region:
            try:
                with open(os.path.expanduser(self.REGION_PATH),
                          encoding='utf-8') as f:
                    region = f.read().strip()
            except OSError:
                region = 'us-south'
        return region or 'us-south'

    def endpoint(self) -> str:
        return (f'https://s3.{self.region()}'
                '.cloud-object-storage.appdomain.cloud')

    def _aws(self) -> str:
        return (f'AWS_SHARED_CREDENTIALS_FILE={self.CREDENTIALS_PATH} '
                f'aws --endpoint-url {self.endpoint()} --profile ibm')

    def url(self) -> str:
        return f's3://{self.name}'

    def display_url(self) -> str:
        return f'cos://{self.region()}/{self.name}'

    def mount_command(self, mount_path: str) -> str:
        # rclone via env config (no host config file), the adapter the
        # reference's mounting matrix assigns to IBM COS.
        install = ('which rclone >/dev/null 2>&1 || '
                   '(curl -sSL https://rclone.org/install.sh | '
                   'sudo bash)')
        env = (f'RCLONE_CONFIG_IBM_TYPE=s3 '
               f'RCLONE_CONFIG_IBM_PROVIDER=IBMCOS '
               f'RCLONE_CONFIG_IBM_ENDPOINT={self.endpoint()} '
               f'RCLONE_CONFIG_IBM_ENV_AUTH=true '
               f'AWS_SHARED_CREDENTIALS_FILE={self.CREDENTIALS_PATH} '
               f'AWS_PROFILE=ibm')
        return (f'{install}; mkdir -p {mount_path} && '
                f'(mountpoint -q {mount_path} || '
                f'{env} rclone mount ibm:{self.name} {mount_path} '
                f'--daemon --vfs-cache-mode writes)')

    def delete(self) -> None:
        self._run(f'{self._aws()} s3 rb --force {self.url()} || true')


class OciStore(S3Store):
    """OCI Object Storage bucket — OCI's S3-compatible API against the
    namespace's compat endpoint (role of reference
    ``sky/data/storage.py:4053`` OciStore, which drives the oci SDK;
    the compat API lets one CLI family serve every S3-shaped store).
    Namespace from ``OCI_NAMESPACE`` or ``~/.oci/namespace``; region
    from ``OCI_REGION`` or ``~/.oci/region``; customer secret keys in
    ``~/.oci/s3.credentials`` profile ``oci``. MOUNT via goofys
    ``--endpoint`` (same adapter as R2)."""

    CREDENTIALS_PATH = '~/.oci/s3.credentials'
    NAMESPACE_PATH = '~/.oci/namespace'
    REGION_PATH = '~/.oci/region'

    @classmethod
    def _read(cls, env: str, path: str,
              what: str) -> str:
        value = os.environ.get(env)
        if not value:
            try:
                with open(os.path.expanduser(path),
                          encoding='utf-8') as f:
                    value = f.read().strip()
            except OSError:
                raise exceptions.StorageError(
                    f'OCI needs a {what}: set {env} or write '
                    f'{path}.') from None
        return value

    @classmethod
    def endpoint(cls) -> str:
        ns = cls._read('OCI_NAMESPACE', cls.NAMESPACE_PATH,
                       'namespace')
        region = cls._read('OCI_REGION', cls.REGION_PATH, 'region')
        return (f'https://{ns}.compat.objectstorage.{region}'
                '.oraclecloud.com')

    def _aws(self) -> str:
        return (f'AWS_SHARED_CREDENTIALS_FILE={self.CREDENTIALS_PATH} '
                f'aws --endpoint-url {self.endpoint()} --profile oci')

    def url(self) -> str:
        return f's3://{self.name}'

    def display_url(self) -> str:
        return f'oci://{self.name}'

    def mount_command(self, mount_path: str) -> str:
        install = (
            'which goofys >/dev/null 2>&1 || '
            '(sudo curl -sSL https://github.com/kahing/goofys/releases/'
            'latest/download/goofys -o /usr/local/bin/goofys && '
            'sudo chmod +x /usr/local/bin/goofys)')
        return (f'{install}; mkdir -p {mount_path} && '
                f'(mountpoint -q {mount_path} || '
                f'AWS_SHARED_CREDENTIALS_FILE={self.CREDENTIALS_PATH} '
                f'AWS_PROFILE=oci '
                f'goofys --endpoint {self.endpoint()} '
                f'{self.name} {mount_path})')

    def delete(self) -> None:
        self._run(f'{self._aws()} s3 rb --force {self.url()} || true')


class AzureBlobStore(AbstractStore):
    """Azure Blob container via the az CLI; MOUNT via blobfuse2.

    Re-design of reference ``sky/data/storage.py:2309``
    (AzureBlobStore) + ``mounting_utils.py`` blobfuse2 branch, on this
    layer's CLI stance: storage account from $AZURE_STORAGE_ACCOUNT
    (key/auth from the az CLI's own login or $AZURE_STORAGE_KEY).
    """

    @staticmethod
    def account() -> str:
        account = os.environ.get('AZURE_STORAGE_ACCOUNT')
        if not account:
            raise exceptions.StorageError(
                'Azure blob storage needs AZURE_STORAGE_ACCOUNT set '
                '(and az login / AZURE_STORAGE_KEY for auth).')
        return account

    def url(self) -> str:
        return f'az://{self.name}'

    def https_url(self) -> str:
        return (f'https://{self.account()}.blob.core.windows.net/'
                f'{self.name}')

    def upload(self) -> None:
        if self.source is None:
            return
        src = os.path.abspath(os.path.expanduser(self.source))
        self._run(f'az storage container create -n {self.name} || true')
        if os.path.isdir(src):
            if self.exclude_git and os.path.isdir(
                    os.path.join(src, '.git')):
                # upload-batch has include-patterns only; honoring the
                # '.git/*' exclusion (like GCS/S3/R2) means staging —
                # via tar --exclude, so only the bytes that will
                # upload are copied (cp-then-delete would stage the
                # whole .git object store too).
                self._run(
                    f'azup=$(mktemp -d) && '
                    f'tar -C {src} --exclude .git -cf - . | '
                    f'tar -xf - -C "$azup" && '
                    f'az storage blob upload-batch -d {self.name} '
                    f'-s "$azup" --overwrite && rm -rf "$azup"')
            else:
                self._run(
                    f'az storage blob upload-batch -d {self.name} '
                    f'-s {src} --overwrite')
        else:
            self._run(f'az storage blob upload -c {self.name} '
                      f'-f {src} -n {os.path.basename(src)} '
                      f'--overwrite')

    def download_command(self, dst: str) -> str:
        return (f'mkdir -p {dst} && az storage blob download-batch '
                f'-d {dst} -s {self.name}')

    def mount_command(self, mount_path: str) -> str:
        # blobfuse2 (reference mounting_utils.py blobfuse2 branch);
        # auth rides the env contract (AZURE_STORAGE_ACCOUNT/KEY).
        install = (
            'which blobfuse2 >/dev/null 2>&1 || '
            '(sudo apt-get update -qq && '
            'sudo apt-get install -y -qq blobfuse2)')
        return (f'{install}; mkdir -p {mount_path} && '
                f'(mountpoint -q {mount_path} || '
                f'blobfuse2 mount {mount_path} '
                f'--container-name={self.name} '
                f'--tmp-path=/tmp/blobfuse2-{self.name})')

    def delete(self) -> None:
        self._run(f'az storage container delete -n {self.name} || true')

    def list_objects(self) -> List[Tuple[str, int]]:
        out = self._run_out(
            f'az storage blob list -c {self.name} --query '
            f'"[].[name, properties.contentLength]" -o tsv')
        objs = []
        for line in out.splitlines():
            parts = line.rsplit('\t', 1)
            if len(parts) == 2 and parts[1].strip().isdigit():
                objs.append((parts[0], int(parts[1])))
        return objs


class LocalStore(AbstractStore):
    """Directory-backed fake bucket for hermetic tests."""

    @staticmethod
    def bucket_root() -> str:
        base = os.path.expanduser(
            os.environ.get('SKYTPU_DATA_DIR', '~/.skytpu'))
        path = os.path.join(base, 'buckets')
        os.makedirs(path, exist_ok=True)
        return path

    def path(self) -> str:
        return os.path.join(self.bucket_root(), self.name)

    def url(self) -> str:
        return f'local://{self.name}'

    def upload(self) -> None:
        os.makedirs(self.path(), exist_ok=True)
        if self.source is None:
            return
        src = os.path.abspath(os.path.expanduser(self.source))
        if os.path.isdir(src):
            shutil.copytree(src, self.path(), dirs_exist_ok=True)
        else:
            shutil.copy2(src, self.path())

    def download_command(self, dst: str) -> str:
        return f'mkdir -p {dst} && cp -a {self.path()}/. {dst}/'

    def mount_command(self, mount_path: str) -> str:
        # Symlink stands in for a FUSE mount: writes are immediately
        # durable in the "bucket", which is exactly the property the
        # checkpoint-recovery path needs.
        return (f'mkdir -p {self.path()} && '
                f'mkdir -p $(dirname {mount_path}) && '
                f'rm -rf {mount_path} && '
                f'ln -sfn {self.path()} {mount_path}')

    def delete(self) -> None:
        shutil.rmtree(self.path(), ignore_errors=True)

    def list_objects(self) -> List[Tuple[str, int]]:
        root = self.path()
        objs = []
        for dirpath, _, files in os.walk(root):
            for f in files:
                full = os.path.join(dirpath, f)
                objs.append((os.path.relpath(full, root),
                             os.path.getsize(full)))
        return sorted(objs)


_STORE_CLASSES = {
    StoreType.GCS: GcsStore,
    StoreType.S3: S3Store,
    StoreType.R2: R2Store,
    StoreType.AZURE: AzureBlobStore,
    StoreType.IBM: IbmCosStore,
    StoreType.OCI: OciStore,
    StoreType.LOCAL: LocalStore,
}


class Storage:
    """User-facing named storage object.

    YAML form (under ``storage_mounts:``)::

        /checkpoints:
          name: my-ckpt-bucket
          store: gcs          # or local
          mode: MOUNT         # or COPY
          source: ./data      # optional: upload at launch
    """

    def __init__(self,
                 name: str,
                 source: Optional[str] = None,
                 mode: StorageMode = StorageMode.MOUNT,
                 store: Optional[StoreType] = None,
                 persistent: bool = True) -> None:
        if not name:
            raise exceptions.StorageSpecError('Storage needs a name.')
        self.name = name
        self.source = source
        self.mode = mode
        self.persistent = persistent
        self.stores: Dict[StoreType, AbstractStore] = {}
        if store is not None:
            self.add_store(store)
        if source is not None and not os.path.exists(
                os.path.expanduser(source)):
            raise exceptions.StorageSpecError(
                f'Storage source {source!r} does not exist.')

    def add_store(self, store_type: StoreType) -> AbstractStore:
        if store_type not in self.stores:
            cls = _STORE_CLASSES[store_type]
            self.stores[store_type] = cls(self.name, self.source)
        return self.stores[store_type]

    def get_store(self) -> AbstractStore:
        if not self.stores:
            self.add_store(StoreType.GCS)
        return next(iter(self.stores.values()))

    def sync(self) -> None:
        """Upload source to every store."""
        for store in self.stores.values():
            store.upload()

    def delete(self) -> None:
        for store in self.stores.values():
            store.delete()
        from skypilot_tpu import global_user_state
        global_user_state.remove_storage(self.name)

    # ------------------------------------------------------------------
    @classmethod
    def from_yaml_config(cls, config: Dict[str, Any]) -> 'Storage':
        if not isinstance(config, dict):
            raise exceptions.StorageSpecError(
                f'storage mount spec must be a mapping, got {config!r}')
        mode = StorageMode(config.get('mode', 'MOUNT').upper())
        store = config.get('store')
        store_type = StoreType(store.upper()) if store else None
        return cls(name=config.get('name'),
                   source=config.get('source'),
                   mode=mode,
                   store=store_type,
                   persistent=config.get('persistent', True))

    def to_yaml_config(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {'name': self.name, 'mode': self.mode.value}
        if self.source is not None:
            out['source'] = self.source
        if self.stores:
            out['store'] = next(iter(self.stores)).value.lower()
        if not self.persistent:
            out['persistent'] = False
        return out

    def __repr__(self) -> str:
        stores = ','.join(s.value for s in self.stores) or 'unbound'
        return f'Storage({self.name}, {self.mode.value}, {stores})'
