"""Labeled metric primitives + registry (the in-process substrate).

Design constraints (docs/metrics.md):

- **No dependencies.** The instrumented code spans every layer from
  the serving engine's per-tick hot loop to provision retry sites —
  a prometheus_client dependency (or anything pip-installed) is off
  the table, and the primitives must be cheap enough that an
  uninstrumented-feeling `inc()` can sit inside `engine.step()`.
- **Thread-safe.** The engine driver thread, aiohttp event loops,
  replica-manager probe threads and retry sites all write
  concurrently; every mutation takes the metric's lock (one `dict`
  op under a `threading.Lock` — no atomics games).
- **Fixed-bucket histograms.** Latency histograms carry their bucket
  bounds at registration; `observe()` is a bisect + two adds. No
  quantile estimation, no decay — Prometheus-style cumulative
  buckets that merge exactly across processes (snapshot protocol).
- **Bounded cardinality.** A metric folds label sets beyond
  ``max_series`` into a reserved ``_other`` series instead of growing
  without bound (a load balancer fed hostile replica URLs must not
  OOM the controller).

Naming contract, enforced at registration: every metric name matches
``skytpu_[a-z0-9_]+`` and carries a non-empty help string (the lint
test in tests/unit_tests/test_metrics.py re-asserts this over every
metric the production modules register).
"""
from __future__ import annotations

import bisect
import re
import threading
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

_NAME_RE = re.compile(r'skytpu_[a-z0-9_]+\Z')

# Label sets beyond this fold into one '_other' series per metric.
DEFAULT_MAX_SERIES = 1000
OVERFLOW_LABEL = '_other'

# Default latency buckets (seconds): serving TTFT / request latency.
LATENCY_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                   2.5, 5.0, 10.0, 30.0, 60.0, 120.0)
# Finer buckets for per-token decode latency (ms-scale).
FAST_LATENCY_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                        0.05, 0.1, 0.25, 0.5, 1.0, 2.5)


class Metric:
    """Base: a named family of label-keyed series."""

    kind = ''

    def __init__(self, name: str, help: str,
                 label_names: Sequence[str] = (),
                 max_series: int = DEFAULT_MAX_SERIES) -> None:
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self.max_series = max_series
        self._series: Dict[Tuple[str, ...], Any] = {}
        self._lock = threading.Lock()

    # -------------------------------------------------------- internals
    def _key(self, labels: Dict[str, Any]) -> Tuple[str, ...]:
        if set(labels) != set(self.label_names):
            raise ValueError(
                f'{self.name}: got labels {sorted(labels)}, declared '
                f'{sorted(self.label_names)}')
        return tuple(str(labels[k]) for k in self.label_names)

    def _new_state(self) -> Any:
        raise NotImplementedError

    def _slot(self, key: Tuple[str, ...]) -> Any:
        """Get-or-create a series state. Caller holds the lock."""
        state = self._series.get(key)
        if state is None:
            if key and len(self._series) >= self.max_series:
                # Cardinality guard: fold into the reserved series.
                key = tuple(OVERFLOW_LABEL for _ in key)
                state = self._series.get(key)
            if state is None:
                state = self._new_state()
                self._series[key] = state
        return state

    def _read_slot(self, key: Tuple[str, ...]) -> Optional[Any]:
        """Series state for a read, applying the SAME overflow fold
        as writes: a label set folded into '_other' must read the
        shared series, not a phantom 0 (a least-load pick that read
        0 for every folded replica would route all traffic at them).
        Caller holds the lock; never creates."""
        state = self._series.get(key)
        if state is None and key and \
                len(self._series) >= self.max_series:
            state = self._series.get(
                tuple(OVERFLOW_LABEL for _ in key))
        return state

    # ---------------------------------------------------------- reading
    def series(self) -> List[Tuple[Dict[str, str], Any]]:
        """Consistent [(labels, state-copy)] snapshot of every series."""
        with self._lock:
            return [(dict(zip(self.label_names, key)),
                     self._copy_state(state))
                    for key, state in sorted(self._series.items())]

    @staticmethod
    def _copy_state(state: Any) -> Any:
        raise NotImplementedError

    def clear(self) -> None:
        """Drop every series (registration survives). Test hook."""
        with self._lock:
            self._series.clear()


class Counter(Metric):
    """Monotonic float counter. ``inc`` returns the new value so
    callers that derive rates (the autoscaler's QPS) read the same
    number operators scrape."""

    kind = 'counter'

    def _new_state(self) -> List[float]:
        return [0.0]

    @staticmethod
    def _copy_state(state: List[float]) -> float:
        return state[0]

    def inc(self, amount: float = 1.0, **labels: Any) -> float:
        if amount < 0:
            raise ValueError(
                f'{self.name}: counters only go up (amount={amount})')
        key = self._key(labels)
        with self._lock:
            state = self._slot(key)
            state[0] += amount
            return state[0]

    def value(self, **labels: Any) -> float:
        key = self._key(labels)
        with self._lock:
            state = self._read_slot(key)
            return state[0] if state is not None else 0.0


class Gauge(Metric):
    """Settable point value; supports inc/dec and series removal
    (replicas come and go)."""

    kind = 'gauge'

    def __init__(self, name: str, help: str,
                 label_names: Sequence[str] = (),
                 max_series: int = DEFAULT_MAX_SERIES) -> None:
        super().__init__(name, help, label_names, max_series)
        # Per-series exemplar ({'trace_id', 'value'}), carried through
        # families()/the snapshot spool exactly like histogram
        # exemplars. Written only by set(exemplar=...): derived
        # gauges (the p99 latency gauges) use it to pin the trace of
        # the observation that made the gauge interesting — an
        # SLO-violating request — so a dashboard alert resolves to a
        # concrete span tree (docs/tracing.md).
        self._exemplars: Dict[Tuple[str, ...], Dict[str, Any]] = {}

    def _new_state(self) -> List[float]:
        return [0.0]

    @staticmethod
    def _copy_state(state: List[float]) -> float:
        return state[0]

    def set(self, value: float, *, exemplar: Optional[str] = None,
            **labels: Any) -> None:
        """Set the series value. ``exemplar`` (a trace id) is STICKY:
        passing None keeps whatever exemplar a previous set pinned —
        so a violation's trace survives later unremarkable updates of
        the same gauge until the next violation replaces it."""
        key = self._key(labels)
        with self._lock:
            self._slot(key)[0] = float(value)
            if exemplar:
                self._exemplars[key] = {'trace_id': str(exemplar),
                                        'value': float(value)}

    def inc(self, amount: float = 1.0, **labels: Any) -> float:
        key = self._key(labels)
        with self._lock:
            state = self._slot(key)
            state[0] += amount
            return state[0]

    def dec(self, amount: float = 1.0, floor: Optional[float] = None,
            **labels: Any) -> float:
        """Decrement; ``floor`` clamps (an in-flight gauge must never
        go negative when a done() races a removal)."""
        key = self._key(labels)
        with self._lock:
            state = self._slot(key)
            state[0] -= amount
            if floor is not None and state[0] < floor:
                state[0] = floor
            return state[0]

    def value(self, **labels: Any) -> float:
        key = self._key(labels)
        with self._lock:
            state = self._read_slot(key)
            return state[0] if state is not None else 0.0

    def has_series(self, **labels: Any) -> bool:
        """Whether the EXACT label set has its own series (no
        overflow fold) — series-lifecycle decisions (retire a
        drained replica's gauge) must not act on the shared
        '_other' value."""
        key = self._key(labels)
        with self._lock:
            return key in self._series

    def touch(self, **labels: Any) -> None:
        """Ensure the series exists (exposed as 0 before first write)."""
        key = self._key(labels)
        with self._lock:
            self._slot(key)

    def remove(self, **labels: Any) -> None:
        key = self._key(labels)
        with self._lock:
            self._series.pop(key, None)
            self._exemplars.pop(key, None)

    def exemplar(self, **labels: Any) -> Optional[Dict[str, Any]]:
        """The series' pinned exemplar ({'trace_id', 'value'}) or
        None. Exact-key read: exemplars are point correlations, never
        folded into '_other'."""
        key = self._key(labels)
        with self._lock:
            e = self._exemplars.get(key)
            return dict(e) if e else None

    def clear(self) -> None:
        with self._lock:
            self._series.clear()
            self._exemplars.clear()


class Histogram(Metric):
    """Fixed-bucket histogram: per-bin counts + sum + count.

    Bounds are upper edges (no +Inf; the overflow bin is implicit as
    the last slot). Cumulative counts are materialized only at
    exposition, so ``observe`` is bisect + two adds.
    """

    kind = 'histogram'

    def __init__(self, name: str, help: str,
                 label_names: Sequence[str] = (),
                 buckets: Sequence[float] = LATENCY_BUCKETS,
                 max_series: int = DEFAULT_MAX_SERIES) -> None:
        super().__init__(name, help, label_names, max_series)
        if not buckets or list(buckets) != sorted(buckets):
            raise ValueError(
                f'{name}: buckets must be non-empty and sorted, got '
                f'{buckets!r}')
        self.buckets = tuple(float(b) for b in buckets)

    def _new_state(self) -> Dict[str, Any]:
        return {'counts': [0] * (len(self.buckets) + 1),
                'sum': 0.0, 'count': 0}

    @staticmethod
    def _copy_state(state: Dict[str, Any]) -> Dict[str, Any]:
        out = {'counts': list(state['counts']),
               'sum': state['sum'], 'count': state['count']}
        if 'exemplar' in state:
            out['exemplar'] = dict(state['exemplar'])
        return out

    def observe(self, value: float, *, exemplar: Optional[str] = None,
                **labels: Any) -> None:
        """Record one observation. ``exemplar`` (a trace id, see
        docs/tracing.md) links the series to a concrete trace:
        last-write-wins per series, carried through families()/the
        snapshot spool, and deliberately NOT rendered in the 0.0.4
        text exposition (the format predates exemplars)."""
        key = self._key(labels)
        idx = bisect.bisect_left(self.buckets, value)
        with self._lock:
            state = self._slot(key)
            state['counts'][idx] += 1
            state['sum'] += value
            state['count'] += 1
            if exemplar:
                state['exemplar'] = {'trace_id': str(exemplar),
                                     'value': float(value)}

    def quantile(self, q: float, **labels: Any) -> Optional[float]:
        """Quantile estimate from the series' cumulative buckets
        (PromQL ``histogram_quantile`` semantics — see
        :func:`bucket_quantile`). None when the series is empty or
        absent. The in-process counterpart of a dashboard's p99
        query: the SLO autoscaler and bench detail read exactly the
        number an operator's PromQL would produce."""
        key = self._key(labels)
        with self._lock:
            state = self._read_slot(key)
            counts = None if state is None else list(state['counts'])
        if counts is None:
            return None
        return bucket_quantile(self.buckets, counts, q)


def bucket_quantile(bounds: Sequence[float], counts: Sequence[int],
                    q: float) -> Optional[float]:
    """Quantile estimate from fixed-bucket counts — the ONE
    bucket-quantile implementation (``Histogram.quantile`` and the
    sliding-window estimator both call it).

    ``counts`` has ``len(bounds) + 1`` bins, the last being the
    implicit overflow bin. PromQL ``histogram_quantile`` semantics:
    rank = q * total, find the bin whose cumulative count crosses it,
    interpolate linearly between the bin's edges (the first bucket
    interpolates from 0). A rank landing in the overflow bin returns
    the highest finite bound — an estimate can never exceed what the
    buckets resolve. Returns None for an empty series or q outside
    [0, 1]."""
    total = sum(counts)
    if total <= 0 or not 0.0 <= q <= 1.0:
        return None
    rank = q * total
    acc = 0
    for i, c in enumerate(counts[:-1]):
        prev = acc
        acc += c
        if c and acc >= rank:
            lo = bounds[i - 1] if i else 0.0
            hi = bounds[i]
            return lo + (hi - lo) * (rank - prev) / c
    return float(bounds[-1])


class Registry:
    """Name -> metric map; registration is idempotent get-or-create
    (modules re-registering the same (name, kind, labels) share one
    metric; a conflicting re-registration raises)."""

    def __init__(self) -> None:
        self._metrics: Dict[str, Metric] = {}
        self._lock = threading.Lock()

    # ---------------------------------------------------- registration
    def _register(self, cls, name: str, help: str,
                  labels: Sequence[str], **kwargs: Any) -> Metric:
        if not _NAME_RE.fullmatch(name):
            raise ValueError(
                f'metric name {name!r} must match skytpu_[a-z0-9_]+')
        if not help or not help.strip():
            raise ValueError(f'metric {name!r} needs a help string')
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if (type(existing) is not cls or
                        existing.label_names != tuple(labels)):
                    raise ValueError(
                        f'metric {name!r} already registered as '
                        f'{type(existing).__name__}'
                        f'{existing.label_names}')
                want_buckets = kwargs.get('buckets')
                if (want_buckets is not None and
                        isinstance(existing, Histogram) and
                        existing.buckets != tuple(
                            float(b) for b in want_buckets)):
                    # Same name + different buckets would silently
                    # collapse one caller's observations into the
                    # other's bin edges.
                    raise ValueError(
                        f'metric {name!r} already registered with '
                        f'buckets {existing.buckets}')
                return existing
            metric = cls(name, help, labels, **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str,
                labels: Sequence[str] = (),
                max_series: int = DEFAULT_MAX_SERIES) -> Counter:
        return self._register(Counter, name, help, labels,
                              max_series=max_series)

    def gauge(self, name: str, help: str,
              labels: Sequence[str] = (),
              max_series: int = DEFAULT_MAX_SERIES) -> Gauge:
        return self._register(Gauge, name, help, labels,
                              max_series=max_series)

    def histogram(self, name: str, help: str,
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = LATENCY_BUCKETS,
                  max_series: int = DEFAULT_MAX_SERIES) -> Histogram:
        return self._register(Histogram, name, help, labels,
                              buckets=buckets, max_series=max_series)

    # --------------------------------------------------------- reading
    def collect(self) -> List[Metric]:
        with self._lock:
            return [self._metrics[n] for n in sorted(self._metrics)]

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def families(self) -> Dict[str, Dict[str, Any]]:
        """The interchange form (shared with the snapshot protocol):

            {name: {'kind', 'help', 'label_names', 'buckets'?,
                    'series': [{'labels': {...}, 'value': v} |
                               {'labels': {...}, 'counts': [...],
                                'sum': s, 'count': n}]}}
        """
        out: Dict[str, Dict[str, Any]] = {}
        for metric in self.collect():
            fam: Dict[str, Any] = {
                'kind': metric.kind,
                'help': metric.help,
                'label_names': list(metric.label_names),
                'series': [],
            }
            if isinstance(metric, Histogram):
                fam['buckets'] = list(metric.buckets)
            for labels, state in metric.series():
                if isinstance(metric, Histogram):
                    fam['series'].append({'labels': labels, **state})
                else:
                    entry: Dict[str, Any] = {'labels': labels,
                                             'value': state}
                    if isinstance(metric, Gauge):
                        ex = metric.exemplar(**labels)
                        if ex:
                            entry['exemplar'] = ex
                    fam['series'].append(entry)
            out[metric.name] = fam
        return out

    def reset(self) -> None:
        """Clear every metric's series (registrations survive) — the
        hermetic-test hook (tests/conftest.py wipes the default
        registry between tests so engines/LBs never see a previous
        test's numbers)."""
        for metric in self.collect():
            metric.clear()


def _series_ok(s: Any, kind: str) -> bool:
    """Shape-check one incoming snapshot series (spool files are
    outside-world input: a scrape must skip corruption, not crash on
    it or silently merge truncated bucket lists)."""
    if not isinstance(s, dict) or not isinstance(s.get('labels'), dict):
        return False
    if kind == 'histogram':
        return (isinstance(s.get('counts'), list) and
                isinstance(s.get('sum'), (int, float)) and
                isinstance(s.get('count'), int))
    return isinstance(s.get('value'), (int, float))


def merge_families(base: Dict[str, Dict[str, Any]],
                   other: Any) -> None:
    """Merge ``other`` into ``base`` in place (the scrape-side union
    of process snapshots): counters and gauges SUM per label set,
    histograms sum bucket-wise (bounds must match). Malformed or
    mismatched input — wrong kinds, different bucket bounds,
    truncated counts lists — is SKIPPED, never merged partially and
    never allowed to raise: one corrupt spool file must not take
    down (or corrupt) the fleet /metrics endpoint."""
    if not isinstance(other, dict):
        return
    for name, fam in other.items():
        if not isinstance(fam, dict):
            continue
        kind = fam.get('kind')
        series = [s for s in fam.get('series', ())
                  if _series_ok(s, kind)]
        if kind == 'histogram':
            n_bins = len(fam.get('buckets', ())) + 1
            series = [s for s in series if len(s['counts']) == n_bins]
        mine = base.get(name)
        if mine is None:
            base[name] = {
                **{k: v for k, v in fam.items() if k != 'series'},
                'series': [dict(s) for s in series],
            }
            continue
        if mine.get('kind') != kind:
            continue
        if (kind == 'histogram' and
                list(mine.get('buckets', ())) !=
                list(fam.get('buckets', ()))):
            continue
        index = {tuple(sorted(s['labels'].items())): s
                 for s in mine['series']}
        for s in series:
            key = tuple(sorted(s['labels'].items()))
            have = index.get(key)
            if have is None:
                new = dict(s)
                mine['series'].append(new)
                index[key] = new
            elif 'counts' in s:
                have['counts'] = [a + b for a, b in
                                  zip(have['counts'], s['counts'])]
                have['sum'] += s['sum']
                have['count'] += s['count']
                if isinstance(s.get('exemplar'), dict):
                    # Exemplars are point samples, not additive:
                    # latest merged snapshot wins.
                    have['exemplar'] = dict(s['exemplar'])
            else:
                have['value'] = have.get('value', 0.0) + s['value']
                if isinstance(s.get('exemplar'), dict):
                    # Same rule as histograms: exemplars are point
                    # samples, latest merged snapshot wins.
                    have['exemplar'] = dict(s['exemplar'])


# The process-wide default registry every production metric lives in.
REGISTRY = Registry()
