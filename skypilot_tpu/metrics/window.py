"""Sliding-window percentiles + the shared exact-percentile helper.

Two quantile surfaces feed the SLO machinery (docs/load_testing.md):

- :func:`percentile` — nearest-rank percentile over EXACT samples.
  The one sample-percentile implementation in the repo: bench.py's
  latency detail and loadgen's SLO scoring both call it (bench.py
  used to carry a private ``_pct`` copy).
- :class:`SlidingWindowPercentile` — a bucket-based estimator over a
  sliding time window, for signals that must FORGET: the cumulative
  ``skytpu_engine_ttft_seconds`` histogram remembers every request
  since process start, so its p99 cannot come back down after a
  transient regression — useless as an autoscaler input. The window
  splits into ``slices`` sub-windows of fixed-bucket counts
  (histogram-shaped, so the estimate is the same
  :func:`registry.bucket_quantile` math ``Histogram.quantile`` uses);
  ``observe`` is one bisect + add, stale slices age out as time
  advances, and ``to_state``/``restore`` round-trip across controller
  restarts like the autoscaler's QPS window does.

Thread-safe: the engine driver thread observes while HTTP scrape
threads read quantiles.
"""
from __future__ import annotations

import bisect
import math
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from skypilot_tpu.metrics.registry import LATENCY_BUCKETS
from skypilot_tpu.metrics.registry import bucket_quantile


def percentile(samples: Sequence[float], q: float) -> Optional[float]:
    """Nearest-rank percentile: ``sorted(s)[ceil(q * n) - 1]``
    (clamped to the sample range). None on no samples."""
    if not samples:
        return None
    s = sorted(samples)
    idx = min(len(s) - 1, max(1, math.ceil(len(s) * q)) - 1)
    return s[idx]


class SlidingWindowPercentile:
    """Quantile estimates over the last ``window_s`` seconds.

    Internally a ring of ``slices`` sub-windows, each a fixed-bucket
    count array; a sub-window older than the window is dropped on the
    next touch. Granularity: an observation lingers up to one
    sub-window length (window_s / slices) past the window edge —
    acceptable for a scaling signal, free of per-sample memory.
    """

    def __init__(self, window_s: float = 60.0, slices: int = 6,
                 buckets: Sequence[float] = LATENCY_BUCKETS) -> None:
        if window_s <= 0 or slices <= 0:
            raise ValueError(
                f'window_s ({window_s}) and slices ({slices}) must '
                'be positive')
        self.window_s = float(window_s)
        self.slices = int(slices)
        self.buckets = tuple(float(b) for b in buckets)
        self._slice_s = self.window_s / self.slices
        # slice epoch (int(now / slice_s)) -> per-bucket counts
        # (len(buckets) + 1, overflow last — the Histogram layout).
        self._bins: Dict[int, List[int]] = {}
        self._lock = threading.Lock()

    def _epoch(self, now: float) -> int:
        return int(now / self._slice_s)

    def _prune(self, epoch: int) -> None:
        """Drop slices outside the window. Caller holds the lock."""
        cutoff = epoch - self.slices
        for e in [e for e in self._bins if e <= cutoff]:
            del self._bins[e]

    def observe(self, value: float,
                now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        epoch = self._epoch(now)
        with self._lock:
            self._prune(epoch)
            bins = self._bins.get(epoch)
            if bins is None:
                bins = self._bins[epoch] = [0] * (len(self.buckets) + 1)
            bins[bisect.bisect_left(self.buckets, value)] += 1

    def _merged(self, now: float) -> List[int]:
        epoch = self._epoch(now)
        with self._lock:
            self._prune(epoch)
            merged = [0] * (len(self.buckets) + 1)
            for bins in self._bins.values():
                for i, c in enumerate(bins):
                    merged[i] += c
            return merged

    def count(self, now: Optional[float] = None) -> int:
        now = time.time() if now is None else now
        return sum(self._merged(now))

    def quantile(self, q: float,
                 now: Optional[float] = None) -> Optional[float]:
        """Bucket-quantile estimate over the live window; None while
        the window is empty (callers keep their last value — an empty
        window means no traffic, not zero latency)."""
        now = time.time() if now is None else now
        return bucket_quantile(self.buckets, self._merged(now), q)

    # -------------------------------------------------- durability
    def to_state(self) -> Dict[str, Any]:
        with self._lock:
            return {
                'window_s': self.window_s,
                'slices': self.slices,
                'buckets': list(self.buckets),
                'bins': {str(e): list(b)
                         for e, b in self._bins.items()},
            }

    def restore(self, state: Dict[str, Any]) -> None:
        """Rebuild the window from a snapshot. Mismatched bucket
        bounds or malformed state restore to EMPTY (never a partial
        merge of incompatible bins); slices outside the window age
        out at the next touch, so a long-dead snapshot contributes
        nothing."""
        if not isinstance(state, dict):
            return
        if list(state.get('buckets', ())) != list(self.buckets):
            return
        n_bins = len(self.buckets) + 1
        bins: Dict[int, List[int]] = {}
        for e, b in (state.get('bins') or {}).items():
            try:
                epoch = int(e)
            except (TypeError, ValueError):
                continue
            if isinstance(b, list) and len(b) == n_bins and \
                    all(isinstance(c, int) and c >= 0 for c in b):
                bins[epoch] = list(b)
        with self._lock:
            self._bins = bins
