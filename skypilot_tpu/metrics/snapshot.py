"""Cross-process snapshot spool (the fault-injection record-file
pattern, applied to metrics).

The stack's metric writers span processes a scraper can't reach: the
detached jobs controller (one process per managed job), the serve
controller, agents. Instead of running an HTTP server in every one,
each process periodically **dumps** its registry as one JSON file into
a spool directory (``SKYTPU_METRICS_DIR``), atomically
(write-tmp + rename — a scraper never reads a torn file). Any
``/metrics`` endpoint then **merges** the spool into its own live
registry at scrape time: counters and histograms sum exactly across
processes, gauges sum (per-process gauges should carry a
distinguishing label).

File naming: ``<component>.<pid>.json`` — one file per process,
overwritten in place, so the spool holds the LATEST snapshot of each
writer, not a growing log. The scraping process's own file is skipped
on load (its registry is already counted live). ``SKYTPU_METRICS_TTL``
(seconds, default 900) ages out snapshots of dead processes.
"""
from __future__ import annotations

import json
import os
import re
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu.metrics import registry as registry_lib
from skypilot_tpu.utils import env_registry

METRICS_DIR_ENV = env_registry.SKYTPU_METRICS_DIR
METRICS_TTL_ENV = env_registry.SKYTPU_METRICS_TTL
_DEFAULT_TTL_SECONDS = 900.0

_COMPONENT_RE = re.compile(r'[^A-Za-z0-9._-]+')


def spool_dir() -> Optional[str]:
    path = os.environ.get(METRICS_DIR_ENV)
    return os.path.expanduser(path) if path else None


def dump(component: str,
         registry: Optional[registry_lib.Registry] = None,
         dirpath: Optional[str] = None) -> Optional[str]:
    """Write this process's registry as ``<component>.<pid>.json``.

    No-op (returns None) when no spool dir is configured — production
    code calls this unconditionally from control loops, and the
    default must stay free. Never raises on I/O failure: losing one
    snapshot beats crashing a controller mid-recovery.
    """
    dirpath = dirpath or spool_dir()
    if not dirpath:
        return None
    registry = registry or registry_lib.REGISTRY
    component = _COMPONENT_RE.sub('_', component) or 'unnamed'
    path = os.path.join(dirpath, f'{component}.{os.getpid()}.json')
    payload = {
        'component': component,
        'pid': os.getpid(),
        'ts': time.time(),
        'metrics': registry.families(),
    }
    tmp = f'{path}.tmp.{os.getpid()}'
    try:
        os.makedirs(dirpath, exist_ok=True)
        with open(tmp, 'w', encoding='utf-8') as f:
            json.dump(payload, f)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None
    return path


def load(dirpath: Optional[str] = None,
         exclude_pid: Optional[int] = None,
         max_age: Optional[float] = None) -> List[Dict[str, Any]]:
    """Parse every snapshot in the spool (corrupt/stale files are
    skipped — a scrape must degrade, not fail)."""
    dirpath = dirpath or spool_dir()
    if not dirpath or not os.path.isdir(dirpath):
        return []
    if max_age is None:
        try:
            max_age = float(os.environ.get(METRICS_TTL_ENV,
                                           _DEFAULT_TTL_SECONDS))
        except ValueError:
            # 'a scrape must degrade, not fail': a typo'd TTL env
            # (e.g. '15m') falls back to the default, it does not
            # 500 every scrape until an operator fixes it.
            max_age = _DEFAULT_TTL_SECONDS
    now = time.time()
    out = []
    for name in sorted(os.listdir(dirpath)):
        if not name.endswith('.json'):
            continue
        try:
            with open(os.path.join(dirpath, name),
                      encoding='utf-8') as f:
                snap = json.load(f)
        except (OSError, ValueError):
            continue
        if (not isinstance(snap, dict) or
                not isinstance(snap.get('metrics'), dict)):
            continue
        if exclude_pid is not None and snap.get('pid') == exclude_pid:
            continue
        try:
            age = now - float(snap.get('ts', now))
        except (TypeError, ValueError):
            continue              # corrupt timestamp: skip the file
        if max_age and age > max_age:
            continue
        out.append(snap)
    return out


def merged_families(
        registry: Optional[registry_lib.Registry] = None,
        include_spool: bool = True,
        dirpath: Optional[str] = None) -> Dict[str, Dict[str, Any]]:
    """This process's live families, plus (optionally) every other
    process's spooled snapshot merged in — the scrape-time view."""
    registry = registry or registry_lib.REGISTRY
    families = registry.families()
    if include_spool:
        for snap in load(dirpath, exclude_pid=os.getpid()):
            registry_lib.merge_families(families, snap['metrics'])
    return families
