"""Prometheus text exposition (format version 0.0.4).

Renders a families dict (registry.families() — optionally merged with
cross-process snapshots, see snapshot.py) into the standard
``# HELP`` / ``# TYPE`` / sample-line text that any Prometheus scraper,
``curl | grep``, or dashboard agent reads. Histograms expose the
conventional cumulative ``_bucket{le=...}`` + ``_sum`` + ``_count``
triplet, so PromQL ``histogram_quantile`` works unmodified.
"""
from __future__ import annotations

from typing import Any, Dict

CONTENT_TYPE = 'text/plain; version=0.0.4; charset=utf-8'


def parse_values(text: str) -> Dict[str, float]:
    """Inverse of :func:`render` for SAMPLE lines:
    ``{'name{label="v"}': value}`` (comment/blank lines skipped).

    The scrape-side reader the SLO autoscaler uses on replica
    ``/metrics`` bodies. Scraped text is outside-world input, so a
    malformed line is skipped, never raised on — one mangled replica
    response must not kill a control loop."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith('#'):
            continue
        name, _, value = line.rpartition(' ')
        try:
            out[name] = float(value)
        except ValueError:
            continue
    return out


def _fmt(value: float) -> str:
    """Prometheus-friendly number: integral floats print as ints."""
    f = float(value)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_help(text: str) -> str:
    return text.replace('\\', r'\\').replace('\n', r'\n')


def _escape_label(text: str) -> str:
    return (text.replace('\\', r'\\').replace('"', r'\"')
            .replace('\n', r'\n'))


def _label_str(labels: Dict[str, str],
               extra: Dict[str, str] = None) -> str:
    items = list(labels.items()) + list((extra or {}).items())
    if not items:
        return ''
    body = ','.join(f'{k}="{_escape_label(str(v))}"'
                    for k, v in items)
    return '{' + body + '}'


def render(families: Dict[str, Dict[str, Any]]) -> str:
    """Families dict -> exposition text (trailing newline included)."""
    lines = []
    for name in sorted(families):
        fam = families[name]
        kind = fam.get('kind', 'untyped')
        lines.append(f'# HELP {name} {_escape_help(fam["help"])}')
        lines.append(f'# TYPE {name} {kind}')
        for s in fam.get('series', ()):
            labels = s.get('labels', {})
            if kind == 'histogram':
                acc = 0
                for bound, count in zip(fam.get('buckets', ()),
                                        s['counts']):
                    acc += count
                    lines.append(
                        f'{name}_bucket'
                        f'{_label_str(labels, {"le": _fmt(bound)})} '
                        f'{acc}')
                acc += s['counts'][-1]
                lines.append(
                    f'{name}_bucket{_label_str(labels, {"le": "+Inf"})}'
                    f' {acc}')
                lines.append(
                    f'{name}_sum{_label_str(labels)} {_fmt(s["sum"])}')
                lines.append(
                    f'{name}_count{_label_str(labels)} {s["count"]}')
            else:
                lines.append(
                    f'{name}{_label_str(labels)} {_fmt(s["value"])}')
    return '\n'.join(lines) + ('\n' if lines else '')
