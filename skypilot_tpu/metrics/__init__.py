"""Unified metrics subsystem: registry, Prometheus exposition,
cross-process snapshots.

The observability substrate the serving/jobs/provision stack writes
to and operators scrape from (docs/metrics.md):

- :mod:`registry` — thread-safe labeled ``Counter`` / ``Gauge`` /
  ``Histogram`` primitives and the process-wide default
  :data:`REGISTRY`. Zero dependencies, near-zero overhead: safe in
  the engine's per-tick loop.
- :mod:`exposition` — Prometheus text-format rendering
  (``render_exposition()`` backs the ``/metrics`` endpoints on
  ``serving_http.EngineServer``, ``server.server`` and the serve
  load balancer).
- :mod:`snapshot` — the spool-dir protocol (``SKYTPU_METRICS_DIR``)
  that lets detached controllers/agents export their counters as
  atomic JSON files, merged into any scrape.

Register metrics at module scope with the get-or-create helpers::

    from skypilot_tpu import metrics
    _FAULTS = metrics.counter(
        'skytpu_faults_injected_total',
        'Faults injected by the chaos harness.',
        labels=('site', 'kind'))
    _FAULTS.inc(1, site='provision.local.run_instances',
                kind='stockout')

Every name must match ``skytpu_[a-z0-9_]+`` and carry a help string
(enforced at registration and re-checked by the metrics lint test).
"""
from skypilot_tpu.metrics.exposition import CONTENT_TYPE
from skypilot_tpu.metrics.exposition import parse_values
from skypilot_tpu.metrics.exposition import render
from skypilot_tpu.metrics.registry import Counter
from skypilot_tpu.metrics.registry import DEFAULT_MAX_SERIES
from skypilot_tpu.metrics.registry import FAST_LATENCY_BUCKETS
from skypilot_tpu.metrics.registry import Gauge
from skypilot_tpu.metrics.registry import Histogram
from skypilot_tpu.metrics.registry import LATENCY_BUCKETS
from skypilot_tpu.metrics.registry import Metric
from skypilot_tpu.metrics.registry import OVERFLOW_LABEL
from skypilot_tpu.metrics.registry import REGISTRY
from skypilot_tpu.metrics.registry import Registry
from skypilot_tpu.metrics.registry import bucket_quantile
from skypilot_tpu.metrics.registry import merge_families
from skypilot_tpu.metrics.snapshot import METRICS_DIR_ENV
from skypilot_tpu.metrics.snapshot import dump as dump_snapshot
from skypilot_tpu.metrics.snapshot import load as load_snapshots
from skypilot_tpu.metrics.snapshot import merged_families
from skypilot_tpu.metrics.window import SlidingWindowPercentile
from skypilot_tpu.metrics.window import percentile

counter = REGISTRY.counter
gauge = REGISTRY.gauge
histogram = REGISTRY.histogram


def render_exposition(registry=None, include_spool: bool = False) -> str:
    """The default registry (or ``registry``) as Prometheus text;
    ``include_spool=True`` merges every other process's spooled
    snapshot (the aggregation-endpoint mode)."""
    return render(merged_families(registry, include_spool=include_spool))


def summary(registry=None) -> dict:
    """Flat ``{'name{label="v"}': value}`` dict of counters/gauges
    (histograms reduce to ``_count``/``_sum``) — the compact form
    bench.py embeds in each round's JSON detail.

    One derived line: when the serving engine's speculative-decoding
    counters have moved, ``skytpu_engine_spec_acceptance_rate`` =
    accepted/proposed is added (a ratio of counters is not a metric
    the registry stores, but it is THE number an operator reads the
    spec counters for — rendering it here keeps every bench detail
    and scrape summary self-interpreting)."""
    registry = registry or REGISTRY
    out = {}
    for name, fam in registry.families().items():
        for s in fam['series']:
            labels = ','.join(f'{k}="{v}"'
                              for k, v in sorted(s['labels'].items()))
            series_name = f'{name}{{{labels}}}' if labels else name
            if fam['kind'] == 'histogram':
                out[f'{series_name}_count'] = s['count']
                out[f'{series_name}_sum'] = round(s['sum'], 6)
            else:
                out[series_name] = s['value']
    proposed = out.get('skytpu_engine_spec_proposed_tokens_total', 0)
    if proposed:
        out['skytpu_engine_spec_acceptance_rate'] = round(
            out.get('skytpu_engine_spec_accepted_tokens_total', 0) /
            proposed, 4)
    return out


__all__ = [
    'CONTENT_TYPE', 'Counter', 'DEFAULT_MAX_SERIES',
    'FAST_LATENCY_BUCKETS', 'Gauge', 'Histogram', 'LATENCY_BUCKETS',
    'METRICS_DIR_ENV', 'Metric', 'OVERFLOW_LABEL', 'REGISTRY',
    'Registry', 'SlidingWindowPercentile', 'bucket_quantile',
    'counter', 'dump_snapshot', 'gauge', 'histogram',
    'load_snapshots', 'merge_families', 'merged_families',
    'parse_values', 'percentile', 'render', 'render_exposition',
    'summary',
]
