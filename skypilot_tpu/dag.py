"""Dag — a DAG of Tasks.

Re-design of reference ``sky/dag.py:11``. Like the reference, today's
executable shapes are a single task or a linear chain; general DAGs are
validated and stored (networkx) for the optimizer.
"""
from __future__ import annotations

import threading
from typing import List, Optional

import networkx as nx

from skypilot_tpu import task as task_lib


class Dag:
    """A directed acyclic graph of Tasks. Usable as a context manager."""

    def __init__(self, name: Optional[str] = None) -> None:
        self.name = name
        self.graph = nx.DiGraph()
        self.tasks: List[task_lib.Task] = []
        self.policy_applied = False

    def add(self, task: task_lib.Task) -> None:
        self.graph.add_node(task)
        self.tasks.append(task)
        task.dag = self

    def remove(self, task: task_lib.Task) -> None:
        self.graph.remove_node(task)
        self.tasks.remove(task)
        task.dag = None

    def add_edge(self, op1: task_lib.Task, op2: task_lib.Task) -> None:
        assert op1 in self.graph.nodes and op2 in self.graph.nodes
        self.graph.add_edge(op1, op2)
        if not nx.is_directed_acyclic_graph(self.graph):
            self.graph.remove_edge(op1, op2)
            raise ValueError('Adding this edge would create a cycle.')

    def __len__(self) -> int:
        return len(self.tasks)

    def __enter__(self) -> 'Dag':
        push_dag(self)
        return self

    def __exit__(self, *args) -> None:
        pop_dag()

    def __repr__(self) -> str:
        return f'Dag({self.name}, tasks={self.tasks})'

    def is_chain(self) -> bool:
        degrees = [self.graph.out_degree(t) for t in self.tasks]
        return all(d <= 1 for d in degrees) and sum(
            1 for d in degrees if d == 0) <= 1

    def get_sorted_tasks(self) -> List[task_lib.Task]:
        return list(nx.topological_sort(self.graph))


_thread_local = threading.local()


def _stack() -> List[Dag]:
    if not hasattr(_thread_local, 'stack'):
        _thread_local.stack = []
    return _thread_local.stack


def push_dag(dag: Dag) -> None:
    _stack().append(dag)


def pop_dag() -> Dag:
    return _stack().pop()


def get_current_dag() -> Optional[Dag]:
    stack = _stack()
    return stack[-1] if stack else None
