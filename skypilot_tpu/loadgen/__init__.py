"""Trace-driven load generation + SLO-goodput scoring.

The measurement backbone for "faster at scale" claims
(docs/load_testing.md, ROADMAP item 5):

- :mod:`workload` — seeded, deterministic production-shaped traces:
  Poisson / bursty (Markov-modulated) / uniform arrivals, Zipf-shared
  prefixes, log-normal mixed prompt/output lengths, per-request
  deadlines; replayable JSONL artifacts with a sha256 determinism
  digest.
- :mod:`replay` — open-loop replayers: in-process against a
  ``ServingEngine`` (hermetic tier-1 / ``bench.py serve_load``) or
  over HTTP/SSE against a replica or the serve LB.
- :mod:`score` — per-request SLO attainment (TTFT < a, per-request
  ITL p99 < b, deadline met) folded into a goodput report with
  attainment fractions, latency percentile tables and
  shed/expired/cancelled breakdowns.
"""
from skypilot_tpu.loadgen.replay import KillEvent
from skypilot_tpu.loadgen.replay import replay_engine
from skypilot_tpu.loadgen.replay import replay_http
from skypilot_tpu.loadgen.replay import replay_http_async
from skypilot_tpu.loadgen.replay import replay_http_chaos
from skypilot_tpu.loadgen.replay import replay_http_chaos_async
from skypilot_tpu.loadgen.replay import replay_http_preempt_async
from skypilot_tpu.loadgen.replay import run_kill_schedule
from skypilot_tpu.loadgen.replay import run_preempt_schedule
from skypilot_tpu.loadgen.replay import seeded_kill_schedule
from skypilot_tpu.loadgen.score import RequestRecord
from skypilot_tpu.loadgen.score import SLO
from skypilot_tpu.loadgen.score import score
from skypilot_tpu.loadgen.workload import TenantSpec
from skypilot_tpu.loadgen.workload import TraceRequest
from skypilot_tpu.loadgen.workload import WorkloadSpec
from skypilot_tpu.loadgen.workload import digest
from skypilot_tpu.loadgen.workload import dump_jsonl
from skypilot_tpu.loadgen.workload import generate
from skypilot_tpu.loadgen.workload import load_jsonl
from skypilot_tpu.loadgen.workload import load_jsonl_path
from skypilot_tpu.loadgen.workload import long_prompt
from skypilot_tpu.loadgen.workload import to_jsonl

__all__ = [
    'KillEvent', 'RequestRecord', 'SLO', 'TenantSpec', 'TraceRequest',
    'WorkloadSpec', 'digest', 'dump_jsonl', 'generate', 'load_jsonl',
    'load_jsonl_path', 'long_prompt', 'replay_engine', 'replay_http',
    'replay_http_async', 'replay_http_chaos',
    'replay_http_chaos_async', 'replay_http_preempt_async',
    'run_kill_schedule', 'run_preempt_schedule', 'score',
    'seeded_kill_schedule', 'to_jsonl',
]
