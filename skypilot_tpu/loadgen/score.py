"""SLO scoring: records -> goodput report (docs/load_testing.md).

Goodput is the AlpaServe-style metric the north star asks every
"faster at scale" claim to carry: not requests per second, but
requests per second that MET their service-level objectives —
TTFT under ``a``, per-request ITL p99 under ``b``, deadline met.
A replayer (loadgen.replay) produces one :class:`RequestRecord` per
trace request; :func:`score` folds them into the report ``bench.py
serve_load`` emits.

All percentile math is the shared :func:`skypilot_tpu.metrics.
percentile` helper — the same nearest-rank estimate bench detail
reports, so a goodput report and a bench line never disagree about
what "p99" means.
"""
from __future__ import annotations

import dataclasses
from collections import Counter
from typing import Any, Dict, List, Optional, Sequence

from skypilot_tpu.metrics import percentile

# Percentiles every latency table in the report carries.
REPORT_PERCENTILES = (0.50, 0.90, 0.95, 0.99)

# Terminal statuses a record may carry. 'finished' is the engine's
# natural completion; 'expired' its deadline expiry; 'cancelled' any
# mid-flight cancel; 'shed' an admission refusal (HTTP 429/503);
# 'deadline_rejected' an LB 504 for a request whose budget was gone
# before any replica saw it; 'error' transport/engine failure.
STATUSES = ('finished', 'expired', 'cancelled', 'shed',
            'deadline_rejected', 'error')


@dataclasses.dataclass
class SLO:
    """The objectives a request is scored against. None = that
    objective is not part of the contract (always attained).
    Deadlines are per-request (they ride on the trace), not here."""
    ttft_s: Optional[float] = None
    itl_p99_s: Optional[float] = None

    def to_json(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class RequestRecord:
    """What actually happened to one trace request. Times are offsets
    from replay start (the trace's own clock)."""
    request_id: int
    scheduled_s: float
    submitted_s: Optional[float] = None
    status: str = 'error'
    reason: Optional[str] = None
    ttft_s: Optional[float] = None
    itls: List[float] = dataclasses.field(default_factory=list)
    finished_s: Optional[float] = None
    n_tokens: int = 0
    deadline_s: Optional[float] = None
    # Recovery markers (docs/failover.md): how many mid-stream
    # replica deaths the LB resumed past for this request, and
    # whether a TTFT hedge was raced. A resumed/hedged 'finished' is
    # still SLO-scored like any other — the markers exist so chaos
    # reports can distinguish clean finishes from recovered ones.
    resumed: int = 0
    hedged: bool = False
    # Spot-native marker (docs/spot_serving.md): resumes triggered by
    # a preemption NOTICE — the LB proactively migrated this stream
    # off a doomed replica before the kill, rather than reacting to
    # a death. migrated <= resumed always.
    migrated: int = 0
    # Final token ids (populated by replay_http when requested):
    # the chaos bench's greedy-parity check re-runs resumed prompts
    # against a survivor and compares these bitwise.
    tokens: Optional[List[int]] = None
    # Multi-tenant QoS attribution (docs/qos.md): which tenant the
    # request belonged to and the priority class it ran under. None =
    # untagged (pre-QoS traces) — the report's per-tenant/per-class
    # sections only appear when at least one record carries a tag.
    tenant: Optional[str] = None
    priority_class: Optional[str] = None
    # Serving-arm attribution (docs/disaggregation.md): which A/B arm
    # served this request ('interleaved', 'disagg', ...). None =
    # untagged — the report's per-arm section only appears when at
    # least one record carries an arm, keeping pre-disagg report
    # bytes intact.
    arm: Optional[str] = None

    def itl_p99(self) -> Optional[float]:
        return percentile(self.itls, 0.99)


def _attained(rec: RequestRecord, slo: SLO) -> Dict[str, bool]:
    """Per-objective attainment for ONE request. A request that never
    finished attains nothing it was scored on: sheds and expiries are
    exactly the failures goodput exists to count."""
    finished = rec.status == 'finished'
    ttft_ok = finished and (slo.ttft_s is None or
                            (rec.ttft_s is not None and
                             rec.ttft_s <= slo.ttft_s))
    itl99 = rec.itl_p99()
    itl_ok = finished and (slo.itl_p99_s is None or itl99 is None or
                           itl99 <= slo.itl_p99_s)
    deadline_ok = finished and (
        rec.deadline_s is None or
        (rec.finished_s is not None and rec.submitted_s is not None
         and rec.finished_s - rec.submitted_s <= rec.deadline_s))
    return {'ttft': ttft_ok, 'itl': itl_ok, 'deadline': deadline_ok,
            'all': ttft_ok and itl_ok and deadline_ok}


def _pct_table(samples: Sequence[float]) -> Dict[str, Optional[float]]:
    s = sorted(samples)  # one O(n log n) sort; percentile's own re-sort
    out: Dict[str, Optional[float]] = {}  # is O(n) on sorted input
    for q in REPORT_PERCENTILES:
        p = percentile(s, q)
        out[f'p{int(q * 100)}'] = None if p is None else round(p, 4)
    return out


def _group_report(recs: Sequence[RequestRecord], slo: SLO,
                  wall_s: float) -> Dict[str, Any]:
    """The per-tenant / per-class slice of the goodput report: the
    same objectives and wall clock as the headline, folded over one
    group's records, so 'tenant A kept its goodput while tenant B
    burst' is a statement the report itself can make."""
    good = 0
    for r in recs:
        good += _attained(r, slo)['all']
    finished = [r for r in recs if r.status == 'finished']
    ttfts = [r.ttft_s for r in finished if r.ttft_s is not None]
    breakdown = Counter(r.status for r in recs)
    return {
        'n_requests': len(recs),
        'goodput_req_s': round(good / wall_s, 3),
        'attainment_all': (round(good / len(recs), 4)
                           if recs else None),
        'ttft': _pct_table(ttfts),
        'breakdown': {s: breakdown.get(s, 0) for s in STATUSES},
    }


def _arm_report(recs: Sequence[RequestRecord], slo: SLO,
                wall_s: float) -> Dict[str, Any]:
    """Per-serving-arm slice (docs/disaggregation.md): the disagg A/B
    story is a TTFT-vs-ITL trade, so unlike the tenant slice this one
    splits attainment BY OBJECTIVE and carries both latency tables —
    'disagg held ITL while interleaved missed it' must be readable
    straight off the report."""
    att = {k: 0 for k in ('ttft', 'itl', 'deadline', 'all')}
    for r in recs:
        a = _attained(r, slo)
        for k in att:
            att[k] += a[k]
    finished = [r for r in recs if r.status == 'finished']
    ttfts = [r.ttft_s for r in finished if r.ttft_s is not None]
    itls = [g for r in finished for g in r.itls]
    n = len(recs)
    breakdown = Counter(r.status for r in recs)
    return {
        'n_requests': n,
        'goodput_req_s': round(att['all'] / wall_s, 3),
        'attainment': {k: round(v / n, 4) if n else None
                       for k, v in att.items()},
        'ttft': _pct_table(ttfts),
        'itl': _pct_table(itls),
        'breakdown': {s: breakdown.get(s, 0) for s in STATUSES},
    }


def score(records: Sequence[RequestRecord], slo: SLO,
          wall_s: float) -> Dict[str, Any]:
    """Fold replay records into the goodput report:

    - ``goodput_req_s`` — SLO-attaining completions per wall second
      (the headline), next to ``offered_req_s`` and
      ``completed_req_s`` so degradation is attributable.
    - ``attainment`` — fraction of ALL requests meeting each
      objective (a shed request fails every objective: shedding is a
      capacity decision, not an excuse).
    - ``ttft`` / ``itl`` latency percentile tables over completed
      requests (ITL pooled across requests; per-request p99 is what
      the itl objective scores).
    - ``breakdown`` — terminal-status counts, sheds and expiries
      split out (the load-shedding story in one dict), plus
      ``resumed`` / ``hedged`` recovery counts (docs/failover.md):
      requests that finished only because the LB spliced a
      continuation past a dead replica, or raced a hedge — a chaos
      report must distinguish clean finishes from recovered ones.
    """
    n = len(records)
    breakdown = Counter(r.status for r in records)
    att = {k: 0 for k in ('ttft', 'itl', 'deadline', 'all')}
    good = 0
    for r in records:
        a = _attained(r, slo)
        for k in att:
            att[k] += a[k]
        good += a['all']
    finished = [r for r in records if r.status == 'finished']
    ttfts = [r.ttft_s for r in finished if r.ttft_s is not None]
    itls = [g for r in finished for g in r.itls]
    itl99s = [p for p in (r.itl_p99() for r in finished)
              if p is not None]
    wall_s = max(wall_s, 1e-9)
    # Offered load is a property of the TRACE, not the server: the
    # schedule span, never the wall clock — a slow server's drain
    # tail must not make the load it buckled under look lighter.
    span = (max(r.scheduled_s for r in records) -
            min(r.scheduled_s for r in records)) if records else 0.0
    offered = n / span if span > 0 else n / wall_s
    report: Dict[str, Any] = {
        'n_requests': n,
        'wall_s': round(wall_s, 3),
        'offered_req_s': round(offered, 3),
        'completed_req_s': round(len(finished) / wall_s, 3),
        'goodput_req_s': round(good / wall_s, 3),
        'slo': slo.to_json(),
        'attainment': {k: round(v / n, 4) if n else None
                       for k, v in att.items()},
        'ttft': _pct_table(ttfts),
        'itl': _pct_table(itls),
        'itl_p99_per_request': _pct_table(itl99s),
        'output_tokens': sum(r.n_tokens for r in records),
        'breakdown': {
            **{s: breakdown.get(s, 0) for s in STATUSES},
            # Recovery markers are orthogonal to terminal status
            # (a resumed request still counts under 'finished'):
            # sub-breakdowns, not new statuses.
            'resumed': sum(1 for r in records if r.resumed),
            'migrated': sum(1 for r in records if r.migrated),
            'hedged': sum(1 for r in records if r.hedged),
            **{f'_{s}': c for s, c in breakdown.items()
               if s not in STATUSES},
        },
    }
    # Per-tenant / per-class slices (docs/qos.md) only when some
    # record is tagged: untagged replays keep the pre-QoS report
    # shape byte-for-byte (golden tests depend on it).
    if any(r.tenant is not None or r.priority_class is not None
           for r in records):
        by_tenant: Dict[str, List[RequestRecord]] = {}
        by_class: Dict[str, List[RequestRecord]] = {}
        for r in records:
            by_tenant.setdefault(r.tenant or '_untagged',
                                 []).append(r)
            by_class.setdefault(r.priority_class or '_untagged',
                                []).append(r)
        report['tenants'] = {
            t: _group_report(recs, slo, wall_s)
            for t, recs in sorted(by_tenant.items())}
        report['classes'] = {
            c: _group_report(recs, slo, wall_s)
            for c, recs in sorted(by_class.items())}
    # Per-serving-arm slice (docs/disaggregation.md), same
    # only-when-tagged rule: untagged replays keep their bytes.
    if any(r.arm is not None for r in records):
        by_arm: Dict[str, List[RequestRecord]] = {}
        for r in records:
            by_arm.setdefault(r.arm or '_untagged', []).append(r)
        report['arms'] = {
            a: _arm_report(recs, slo, wall_s)
            for a, recs in sorted(by_arm.items())}
    return report
