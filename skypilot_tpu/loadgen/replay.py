"""Open-loop trace replay (docs/load_testing.md).

Open-loop means arrivals follow the TRACE's clock, never the
server's: a slow server does not slow the offered load down, it
builds queue — which is exactly how overload happens in production,
and exactly what closed-loop benchmarks (submit-next-on-completion)
can never show. Two drivers share one record shape:

- :func:`replay_engine` — straight into a ``ServingEngine`` on this
  host (no HTTP): the hermetic tier-1 / ``bench.py serve_load`` path.
  The engine's ``on_token`` hook times TTFT and inter-token gaps; the
  driver thread steps the engine between admissions.
- :func:`replay_http` — an aiohttp client fleet against a replica's
  (or the LB's) ``/generate``, streaming SSE so TTFT is the first
  token event, not the response tail. 429/503 sheds and 504
  deadline rejects become scored statuses, not errors.

Both return ``(records, wall_s)`` ready for :func:`loadgen.score.
score`.

Chaos replay (docs/failover.md): :func:`seeded_kill_schedule` turns a
seed into trace-relative replica SIGKILL times, and
:func:`replay_http_chaos` runs the open-loop HTTP replay with that
schedule executing concurrently — each kill flows through the
``serve.replica.kill`` fault site, so an armed fault plan can record
(or veto) individual kills with the usual cross-process receipts.
``bench.py serve_chaos`` scores the run against a same-seed no-chaos
baseline.
"""
from __future__ import annotations

import asyncio
import dataclasses
import json
import random
import time
from typing import (Any, Callable, Dict, List, Optional, Sequence,
                    Tuple)

from skypilot_tpu import metrics as metrics_lib
from skypilot_tpu.loadgen.score import RequestRecord
from skypilot_tpu.loadgen.workload import TraceRequest
from skypilot_tpu.utils import fault_injection
from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)

# Shared with serve/replica_managers.py via the registry's
# get-or-create semantics: the bench's preempt-schedule runner has no
# probe loop, so it accounts notice/kill phases itself
# (docs/spot_serving.md).
_M_PREEMPTIONS = metrics_lib.counter(
    'skytpu_serve_preemptions_total',
    'Spot replica preemptions, by phase: notice (advance warning '
    'observed) and kill (the replica actually went away).',
    labels=('phase',))


@dataclasses.dataclass(frozen=True)
class KillEvent:
    """One scheduled replica kill: WHEN (offset seconds from replay
    start, the trace's own clock) and WHICH replica (index into the
    harness's replica list)."""
    at_s: float
    replica: int


def seeded_kill_schedule(seed: int, n_kills: int, n_replicas: int,
                         t_min: float, t_max: float
                         ) -> List[KillEvent]:
    """Deterministic kill schedule: ``n_kills`` distinct replicas
    (clamped so at least one survivor remains) at seeded times inside
    ``[t_min, t_max]`` — mid-run, where streams are in flight. Same
    seed => same times and same targets, the chaos bench's
    determinism receipt."""
    n_kills = max(0, min(n_kills, n_replicas - 1))
    rng = random.Random(seed)
    targets = rng.sample(range(n_replicas), n_kills)
    span = max(0.0, t_max - t_min)
    events = [KillEvent(at_s=t_min + rng.random() * span, replica=t)
              for t in targets]
    return sorted(events, key=lambda e: (e.at_s, e.replica))


async def run_kill_schedule(schedule: Sequence[KillEvent],
                            kill_fn: Callable[[int], None],
                            executed: Optional[List[KillEvent]] = None
                            ) -> int:
    """Execute a kill schedule on the running event loop's clock.
    Each kill polls the ``serve.replica.kill`` fault site first: with
    an armed plan, only a fired CRASH spec kills (so a plan can veto
    or count kills, and the record file proves what was killed
    where); with no plan the schedule is authoritative. Returns the
    number of kills executed; ``executed`` (if given) accumulates
    them AS they happen, so a caller that cancels this coroutine
    mid-schedule still sees the kills that already ran."""
    loop = asyncio.get_event_loop()
    start = loop.time()
    count = 0
    for ev in sorted(schedule, key=lambda e: (e.at_s, e.replica)):
        await asyncio.sleep(max(0.0, ev.at_s - (loop.time() - start)))
        spec = fault_injection.poll(
            'serve.replica.kill',
            kinds=(fault_injection.FaultKind.CRASH,),
            replica=ev.replica)
        if spec is None and fault_injection.active_plan() is not None:
            logger.info('Kill of replica %d at t=%.2fs vetoed by the '
                        'active fault plan.', ev.replica, ev.at_s)
            continue
        logger.warning('CHAOS: killing replica %d at t=%.2fs.',
                       ev.replica, ev.at_s)
        kill_fn(ev.replica)
        count += 1
        if executed is not None:
            executed.append(ev)
    return count


async def run_preempt_schedule(
        schedule: Sequence[KillEvent],
        notice_fn: Callable[[int], None],
        kill_fn: Callable[[int], None],
        notice_s: float,
        executed_notices: Optional[List[KillEvent]] = None,
        executed_kills: Optional[List[KillEvent]] = None
) -> Tuple[int, int]:
    """Execute a notice→kill preemption schedule on the loop clock
    (docs/spot_serving.md): each :class:`KillEvent`'s replica gets a
    cloud-style preemption notice ``notice_s`` seconds before its
    kill (clamped at t=0), then the SIGKILL at the scheduled time.
    The notice flows through the ``serve.replica.preempt_notice``
    fault site (kind ``preempt_notice``) and the kill through
    ``serve.replica.kill`` (kind ``crash``), each with the usual
    armed-plan veto/record semantics — a vetoed notice still lets
    its kill fire, which IS an unnoticed preemption (the reactive
    path). Each executed phase bumps
    ``skytpu_serve_preemptions_total{phase}``; the bench harness has
    no probe loop to account them. Returns ``(notices, kills)``
    executed; the optional lists accumulate events AS they run, so a
    caller cancelling mid-schedule still sees what happened."""
    timeline = []
    for ev in schedule:
        timeline.append((max(0.0, ev.at_s - max(0.0, notice_s)),
                         'notice', ev))
        timeline.append((ev.at_s, 'kill', ev))
    # Kills sort after notices at equal instants (notice_s=0 still
    # delivers the warning first).
    timeline.sort(key=lambda t: (t[0], t[1] == 'kill', t[2].replica))
    loop = asyncio.get_event_loop()
    start = loop.time()
    notices = kills = 0
    for at_s, phase, ev in timeline:
        await asyncio.sleep(max(0.0, at_s - (loop.time() - start)))
        if phase == 'notice':
            spec = fault_injection.poll(
                'serve.replica.preempt_notice',
                kinds=(fault_injection.FaultKind.PREEMPT_NOTICE,),
                replica=ev.replica)
            if (spec is None and
                    fault_injection.active_plan() is not None):
                logger.info(
                    'Preemption notice for replica %d at t=%.2fs '
                    'vetoed by the active fault plan (its kill '
                    'becomes unnoticed).', ev.replica, at_s)
                continue
            logger.warning(
                'CHAOS: preemption notice for replica %d at t=%.2fs '
                '(kill at t=%.2fs).', ev.replica, at_s, ev.at_s)
            notice_fn(ev.replica)
            _M_PREEMPTIONS.inc(1, phase='notice')
            notices += 1
            if executed_notices is not None:
                executed_notices.append(ev)
        else:
            spec = fault_injection.poll(
                'serve.replica.kill',
                kinds=(fault_injection.FaultKind.CRASH,),
                replica=ev.replica)
            if (spec is None and
                    fault_injection.active_plan() is not None):
                logger.info(
                    'Kill of replica %d at t=%.2fs vetoed by the '
                    'active fault plan.', ev.replica, at_s)
                continue
            logger.warning('CHAOS: killing replica %d at t=%.2fs.',
                           ev.replica, at_s)
            kill_fn(ev.replica)
            _M_PREEMPTIONS.inc(1, phase='kill')
            kills += 1
            if executed_kills is not None:
                executed_kills.append(ev)
    return notices, kills


def replay_engine(engine: Any, trace: Sequence[TraceRequest]
                  ) -> Tuple[List[RequestRecord], float]:
    """Replay ``trace`` open-loop into a (warmed) ``ServingEngine``.

    The loop interleaves trace-clock admissions with engine ticks:
    every iteration submits whatever the schedule says has arrived,
    then runs one tick if any work is live, else sleeps toward the
    next arrival. Per-request deadlines become absolute engine
    deadlines at submit — the engine's own expiry/shed machinery is
    what gets measured, not a replayer re-implementation.
    """
    from skypilot_tpu.models.serving_engine import Request

    ordered = sorted(trace, key=lambda r: (r.arrival_s, r.request_id))
    records: Dict[int, RequestRecord] = {
        r.request_id: RequestRecord(request_id=r.request_id,
                                    scheduled_s=r.arrival_s,
                                    deadline_s=r.deadline_s,
                                    tenant=r.tenant,
                                    priority_class=r.priority_class)
        for r in ordered}
    last_emit: Dict[Any, float] = {}

    prev_hook = engine.on_token

    def on_token(rid: Any, toks: List[int]) -> None:
        now = time.perf_counter() - start
        rec = records.get(rid)
        if rec is not None:
            prev = last_emit.get(rid)
            if prev is None:
                rec.ttft_s = (now - rec.submitted_s
                              if rec.submitted_s is not None else None)
            else:
                rec.itls.append(now - prev)
            last_emit[rid] = now
        if prev_hook is not None:
            prev_hook(rid, toks)

    engine.on_token = on_token
    start = time.perf_counter()
    i = 0
    try:
        while (i < len(ordered) or engine.queue or
               engine.num_active() or engine.has_pending):
            now = time.perf_counter() - start
            while i < len(ordered) and ordered[i].arrival_s <= now:
                r = ordered[i]
                i += 1
                rec = records[r.request_id]
                rec.submitted_s = time.perf_counter() - start
                try:
                    engine.submit(Request(
                        r.request_id, list(r.tokens), r.max_new,
                        deadline=(time.time() + r.deadline_s
                                  if r.deadline_s is not None
                                  else None),
                        tenant=r.tenant,
                        priority_class=r.priority_class))
                except ValueError as e:
                    rec.status = 'error'
                    rec.reason = str(e)
            if engine.queue or engine.num_active() or \
                    engine.has_pending:
                engine.step()
            elif i < len(ordered):
                # Idle gap: sleep toward the next arrival (bounded,
                # so a long lull still polls the trace clock).
                now = time.perf_counter() - start
                # skytpu-lint: disable=STL002 — schedule pacing, not
                # a retry loop: the sleep tracks the trace's arrival
                # clock, there is nothing to back off from.
                time.sleep(min(0.05,
                               max(0.0, ordered[i].arrival_s - now)))
            for rid, res in engine.drain_results().items():
                rec = records.get(rid)
                if rec is None:
                    continue
                rec.status = res.status
                rec.reason = res.reason
                rec.finished_s = time.perf_counter() - start
                rec.n_tokens = len(res.tokens)
        wall = time.perf_counter() - start
    finally:
        engine.on_token = prev_hook
    # Flush the throttled SLO gauges so a scrape right after a short
    # run reflects THIS run's window (steady-state gauge updates ride
    # the 4 Hz refresher, not the per-token path).
    engine.refresh_slo_gauges(force=True)
    return [records[r.request_id] for r in ordered], wall


# ----------------------------------------------------------- HTTP
async def _replay_one(session: Any, url: str, r: TraceRequest,
                      rec: RequestRecord, start: float,
                      timeout_s: float,
                      keep_tokens: bool = False) -> None:
    import aiohttp

    loop = asyncio.get_event_loop()
    await asyncio.sleep(max(0.0, r.arrival_s - (loop.time() - start)))
    rec.submitted_s = loop.time() - start
    body = {'tokens': list(r.tokens), 'max_new': r.max_new,
            'stream': True}
    if r.deadline_s is not None:
        body['timeout_s'] = r.deadline_s
    # QoS tags ride the body (docs/qos.md): replicas accept them as
    # header OR body keys, and body keys survive every LB hop (the
    # SSE driver re-sends the parsed payload on hedge/resume).
    if r.tenant is not None:
        body['tenant'] = r.tenant
    if r.priority_class is not None:
        body['priority_class'] = r.priority_class
    try:
        async with session.post(
                url.rstrip('/') + '/generate', json=body,
                timeout=aiohttp.ClientTimeout(total=timeout_s)) as resp:
            if resp.status in (429, 503):
                rec.status = 'shed'
                try:
                    rec.reason = (await resp.json()).get('reason')
                except (ValueError, aiohttp.ClientError):
                    pass
                return
            if resp.status == 504:
                rec.status = 'deadline_rejected'
                rec.reason = 'deadline_exceeded'
                return
            if resp.status != 200:
                rec.status = 'error'
                rec.reason = f'http {resp.status}'
                return
            last: Optional[float] = None
            async for raw in resp.content:
                line = raw.decode('utf-8', 'replace').strip()
                if not line.startswith('data:'):
                    continue
                try:
                    event = json.loads(line[len('data:'):])
                except ValueError:
                    # Streamed bytes are outside-world input: a
                    # truncated data: line (replica died mid-write)
                    # fails THIS record, never the whole replay.
                    rec.status = 'error'
                    rec.reason = 'malformed SSE event'
                    return
                now = loop.time() - start
                if event.get('done'):
                    rec.status = event.get('status', 'finished')
                    rec.reason = event.get('reason')
                    rec.finished_s = now
                    rec.n_tokens = len(event.get('tokens') or ())
                    # Recovery markers the LB stamps on spliced /
                    # hedged streams (docs/failover.md) flow into the
                    # scored breakdown.
                    rec.resumed = int(event.get('resumed') or 0)
                    rec.migrated = int(event.get('migrated') or 0)
                    rec.hedged = bool(event.get('hedged'))
                    if keep_tokens:
                        rec.tokens = list(event.get('tokens') or ())
                    return
                if 'error' in event:
                    rec.status = 'error'
                    rec.reason = str(event['error'])
                    return
                if last is None:
                    rec.ttft_s = now - rec.submitted_s
                else:
                    rec.itls.append(now - last)
                last = now
            rec.status = 'error'
            rec.reason = 'stream ended without a done event'
    except (aiohttp.ClientError, asyncio.TimeoutError) as e:
        rec.status = 'error'
        rec.reason = type(e).__name__


async def replay_http_async(url: str, trace: Sequence[TraceRequest],
                            timeout_s: float = 600.0,
                            keep_tokens: bool = False
                            ) -> Tuple[List[RequestRecord], float]:
    """Open-loop SSE replay against ``url`` (an EngineServer replica
    or the serve LB — both speak the same /generate). One task per
    request sleeps to its arrival offset, so concurrency is whatever
    the schedule demands — never capped by a semaphore that would
    quietly turn the benchmark closed-loop. ``keep_tokens`` records
    each finished request's final token ids (the chaos bench's
    greedy-parity material)."""
    import aiohttp

    ordered = sorted(trace, key=lambda r: (r.arrival_s, r.request_id))
    records = [RequestRecord(request_id=r.request_id,
                             scheduled_s=r.arrival_s,
                             deadline_s=r.deadline_s,
                             tenant=r.tenant,
                             priority_class=r.priority_class)
               for r in ordered]
    loop = asyncio.get_event_loop()
    start = loop.time()
    async with aiohttp.ClientSession() as session:
        # return_exceptions: one request's unexpected failure becomes
        # that record's 'error' status — never the loss of every
        # other record in the run.
        outcomes = await asyncio.gather(
            *(_replay_one(session, url, r, rec, start, timeout_s,
                          keep_tokens=keep_tokens)
              for r, rec in zip(ordered, records)),
            return_exceptions=True)
    for rec, outcome in zip(records, outcomes):
        if isinstance(outcome, BaseException):
            rec.status = 'error'
            rec.reason = rec.reason or type(outcome).__name__
            logger.warning('replay_http request %s failed: %r',
                           rec.request_id, outcome)
    return records, loop.time() - start


def replay_http(url: str, trace: Sequence[TraceRequest],
                timeout_s: float = 600.0,
                keep_tokens: bool = False
                ) -> Tuple[List[RequestRecord], float]:
    return asyncio.run(replay_http_async(url, trace,
                                         timeout_s=timeout_s,
                                         keep_tokens=keep_tokens))


async def replay_http_chaos_async(
        url: str, trace: Sequence[TraceRequest],
        schedule: Sequence[KillEvent],
        kill_fn: Callable[[int], None],
        timeout_s: float = 600.0, keep_tokens: bool = True
) -> Tuple[List[RequestRecord], float, int]:
    """Open-loop HTTP replay with a concurrent seeded kill schedule:
    the chaos run of ``bench.py serve_chaos``. ``kill_fn(replica)``
    performs the real SIGKILL (the harness owns the subprocesses).
    Returns ``(records, wall_s, kills_executed)``."""
    executed: List[KillEvent] = []
    killer = asyncio.ensure_future(
        run_kill_schedule(schedule, kill_fn, executed=executed))
    try:
        records, wall = await replay_http_async(
            url, trace, timeout_s=timeout_s, keep_tokens=keep_tokens)
    finally:
        if not killer.done():
            killer.cancel()
    try:
        kills = await killer
    except asyncio.CancelledError:
        # The replay outlived the schedule window: the kills that
        # already ran still count.
        kills = len(executed)
    return records, wall, kills


async def replay_http_preempt_async(
        url: str, trace: Sequence[TraceRequest],
        schedule: Sequence[KillEvent],
        notice_fn: Callable[[int], None],
        kill_fn: Callable[[int], None],
        notice_s: float,
        timeout_s: float = 600.0, keep_tokens: bool = True
) -> Tuple[List[RequestRecord], float, int, int]:
    """Open-loop HTTP replay under a concurrent notice→kill
    preemption schedule: the mixed-pool run of ``bench.py
    serve_spot`` (docs/spot_serving.md). ``notice_fn(replica)``
    delivers the advance warning (POST /preempt_notice + LB
    mark_preempting); ``kill_fn(replica)`` performs the real SIGKILL.
    Returns ``(records, wall_s, notices, kills)``."""
    executed_n: List[KillEvent] = []
    executed_k: List[KillEvent] = []
    runner = asyncio.ensure_future(run_preempt_schedule(
        schedule, notice_fn, kill_fn, notice_s,
        executed_notices=executed_n, executed_kills=executed_k))
    try:
        records, wall = await replay_http_async(
            url, trace, timeout_s=timeout_s, keep_tokens=keep_tokens)
    finally:
        if not runner.done():
            runner.cancel()
    try:
        notices, kills = await runner
    except asyncio.CancelledError:
        # The replay outlived the schedule window: the events that
        # already ran still count.
        notices, kills = len(executed_n), len(executed_k)
    return records, wall, notices, kills


def replay_http_chaos(url: str, trace: Sequence[TraceRequest],
                      schedule: Sequence[KillEvent],
                      kill_fn: Callable[[int], None],
                      timeout_s: float = 600.0,
                      keep_tokens: bool = True
                      ) -> Tuple[List[RequestRecord], float, int]:
    return asyncio.run(replay_http_chaos_async(
        url, trace, schedule, kill_fn, timeout_s=timeout_s,
        keep_tokens=keep_tokens))
