"""Open-loop trace replay (docs/load_testing.md).

Open-loop means arrivals follow the TRACE's clock, never the
server's: a slow server does not slow the offered load down, it
builds queue — which is exactly how overload happens in production,
and exactly what closed-loop benchmarks (submit-next-on-completion)
can never show. Two drivers share one record shape:

- :func:`replay_engine` — straight into a ``ServingEngine`` on this
  host (no HTTP): the hermetic tier-1 / ``bench.py serve_load`` path.
  The engine's ``on_token`` hook times TTFT and inter-token gaps; the
  driver thread steps the engine between admissions.
- :func:`replay_http` — an aiohttp client fleet against a replica's
  (or the LB's) ``/generate``, streaming SSE so TTFT is the first
  token event, not the response tail. 429/503 sheds and 504
  deadline rejects become scored statuses, not errors.

Both return ``(records, wall_s)`` ready for :func:`loadgen.score.
score`.
"""
from __future__ import annotations

import asyncio
import json
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from skypilot_tpu.loadgen.score import RequestRecord
from skypilot_tpu.loadgen.workload import TraceRequest
from skypilot_tpu.utils import log as sky_logging

logger = sky_logging.init_logger(__name__)


def replay_engine(engine: Any, trace: Sequence[TraceRequest]
                  ) -> Tuple[List[RequestRecord], float]:
    """Replay ``trace`` open-loop into a (warmed) ``ServingEngine``.

    The loop interleaves trace-clock admissions with engine ticks:
    every iteration submits whatever the schedule says has arrived,
    then runs one tick if any work is live, else sleeps toward the
    next arrival. Per-request deadlines become absolute engine
    deadlines at submit — the engine's own expiry/shed machinery is
    what gets measured, not a replayer re-implementation.
    """
    from skypilot_tpu.models.serving_engine import Request

    ordered = sorted(trace, key=lambda r: (r.arrival_s, r.request_id))
    records: Dict[int, RequestRecord] = {
        r.request_id: RequestRecord(request_id=r.request_id,
                                    scheduled_s=r.arrival_s,
                                    deadline_s=r.deadline_s)
        for r in ordered}
    last_emit: Dict[Any, float] = {}

    prev_hook = engine.on_token

    def on_token(rid: Any, toks: List[int]) -> None:
        now = time.perf_counter() - start
        rec = records.get(rid)
        if rec is not None:
            prev = last_emit.get(rid)
            if prev is None:
                rec.ttft_s = (now - rec.submitted_s
                              if rec.submitted_s is not None else None)
            else:
                rec.itls.append(now - prev)
            last_emit[rid] = now
        if prev_hook is not None:
            prev_hook(rid, toks)

    engine.on_token = on_token
    start = time.perf_counter()
    i = 0
    try:
        while (i < len(ordered) or engine.queue or
               engine.num_active() or engine.has_pending):
            now = time.perf_counter() - start
            while i < len(ordered) and ordered[i].arrival_s <= now:
                r = ordered[i]
                i += 1
                rec = records[r.request_id]
                rec.submitted_s = time.perf_counter() - start
                try:
                    engine.submit(Request(
                        r.request_id, list(r.tokens), r.max_new,
                        deadline=(time.time() + r.deadline_s
                                  if r.deadline_s is not None
                                  else None)))
                except ValueError as e:
                    rec.status = 'error'
                    rec.reason = str(e)
            if engine.queue or engine.num_active() or \
                    engine.has_pending:
                engine.step()
            elif i < len(ordered):
                # Idle gap: sleep toward the next arrival (bounded,
                # so a long lull still polls the trace clock).
                now = time.perf_counter() - start
                # skytpu-lint: disable=STL002 — schedule pacing, not
                # a retry loop: the sleep tracks the trace's arrival
                # clock, there is nothing to back off from.
                time.sleep(min(0.05,
                               max(0.0, ordered[i].arrival_s - now)))
            for rid, res in engine.drain_results().items():
                rec = records.get(rid)
                if rec is None:
                    continue
                rec.status = res.status
                rec.reason = res.reason
                rec.finished_s = time.perf_counter() - start
                rec.n_tokens = len(res.tokens)
        wall = time.perf_counter() - start
    finally:
        engine.on_token = prev_hook
    # Flush the throttled SLO gauges so a scrape right after a short
    # run reflects THIS run's window (steady-state gauge updates ride
    # the 4 Hz refresher, not the per-token path).
    engine.refresh_slo_gauges(force=True)
    return [records[r.request_id] for r in ordered], wall


# ----------------------------------------------------------- HTTP
async def _replay_one(session: Any, url: str, r: TraceRequest,
                      rec: RequestRecord, start: float,
                      timeout_s: float) -> None:
    import aiohttp

    loop = asyncio.get_event_loop()
    await asyncio.sleep(max(0.0, r.arrival_s - (loop.time() - start)))
    rec.submitted_s = loop.time() - start
    body = {'tokens': list(r.tokens), 'max_new': r.max_new,
            'stream': True}
    if r.deadline_s is not None:
        body['timeout_s'] = r.deadline_s
    try:
        async with session.post(
                url.rstrip('/') + '/generate', json=body,
                timeout=aiohttp.ClientTimeout(total=timeout_s)) as resp:
            if resp.status in (429, 503):
                rec.status = 'shed'
                try:
                    rec.reason = (await resp.json()).get('reason')
                except (ValueError, aiohttp.ClientError):
                    pass
                return
            if resp.status == 504:
                rec.status = 'deadline_rejected'
                rec.reason = 'deadline_exceeded'
                return
            if resp.status != 200:
                rec.status = 'error'
                rec.reason = f'http {resp.status}'
                return
            last: Optional[float] = None
            async for raw in resp.content:
                line = raw.decode('utf-8', 'replace').strip()
                if not line.startswith('data:'):
                    continue
                try:
                    event = json.loads(line[len('data:'):])
                except ValueError:
                    # Streamed bytes are outside-world input: a
                    # truncated data: line (replica died mid-write)
                    # fails THIS record, never the whole replay.
                    rec.status = 'error'
                    rec.reason = 'malformed SSE event'
                    return
                now = loop.time() - start
                if event.get('done'):
                    rec.status = event.get('status', 'finished')
                    rec.reason = event.get('reason')
                    rec.finished_s = now
                    rec.n_tokens = len(event.get('tokens') or ())
                    return
                if 'error' in event:
                    rec.status = 'error'
                    rec.reason = str(event['error'])
                    return
                if last is None:
                    rec.ttft_s = now - rec.submitted_s
                else:
                    rec.itls.append(now - last)
                last = now
            rec.status = 'error'
            rec.reason = 'stream ended without a done event'
    except (aiohttp.ClientError, asyncio.TimeoutError) as e:
        rec.status = 'error'
        rec.reason = type(e).__name__


async def replay_http_async(url: str, trace: Sequence[TraceRequest],
                            timeout_s: float = 600.0
                            ) -> Tuple[List[RequestRecord], float]:
    """Open-loop SSE replay against ``url`` (an EngineServer replica
    or the serve LB — both speak the same /generate). One task per
    request sleeps to its arrival offset, so concurrency is whatever
    the schedule demands — never capped by a semaphore that would
    quietly turn the benchmark closed-loop."""
    import aiohttp

    ordered = sorted(trace, key=lambda r: (r.arrival_s, r.request_id))
    records = [RequestRecord(request_id=r.request_id,
                             scheduled_s=r.arrival_s,
                             deadline_s=r.deadline_s)
               for r in ordered]
    loop = asyncio.get_event_loop()
    start = loop.time()
    async with aiohttp.ClientSession() as session:
        # return_exceptions: one request's unexpected failure becomes
        # that record's 'error' status — never the loss of every
        # other record in the run.
        outcomes = await asyncio.gather(
            *(_replay_one(session, url, r, rec, start, timeout_s)
              for r, rec in zip(ordered, records)),
            return_exceptions=True)
    for rec, outcome in zip(records, outcomes):
        if isinstance(outcome, BaseException):
            rec.status = 'error'
            rec.reason = rec.reason or type(outcome).__name__
            logger.warning('replay_http request %s failed: %r',
                           rec.request_id, outcome)
    return records, loop.time() - start


def replay_http(url: str, trace: Sequence[TraceRequest],
                timeout_s: float = 600.0
                ) -> Tuple[List[RequestRecord], float]:
    return asyncio.run(replay_http_async(url, trace,
                                         timeout_s=timeout_s))
