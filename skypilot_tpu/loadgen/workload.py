"""Seeded, production-shaped workload traces (docs/load_testing.md).

Every serve number before PR 13 came from 192 uniform back-to-back
requests. Real chat/agent traffic is nothing like that: arrivals are
Poisson at best and bursty in practice, prompts share Zipf-popular
prefixes (system prompts, multi-turn history), lengths are heavy-
tailed, and requests carry deadlines. This module turns a
:class:`WorkloadSpec` into a deterministic list of
:class:`TraceRequest` — same seed, same trace, byte-for-byte (the
``digest`` of the canonical JSONL is the determinism receipt
``bench.py serve_load`` records) — plus JSONL round-tripping so a
trace is a replayable artifact, not a transient.

Arrival models:

- ``uniform`` — fixed ``1/qps`` gaps (the legacy bench shape, kept as
  the control arm).
- ``poisson`` — i.i.d. exponential inter-arrivals at ``qps``.
- ``bursty`` — a 2-state Markov-modulated Poisson process: a HI
  state at ``qps * burst_factor`` and a LO state at
  ``qps / burst_factor``, drawing exponential gaps at the current
  state's rate, with asymmetric exponential dwell (mean
  ``burst_dwell_s / burst_factor`` in HI vs ``burst_dwell_s`` in LO)
  chosen so the time-weighted mean rate is exactly ``qps``. Same
  long-run offered load as ``poisson``, far spikier short-run — the
  traffic shape that makes p99-driven autoscaling earn its keep.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import math
from typing import Any, Dict, Iterable, List, Optional, Sequence

import numpy as np

from skypilot_tpu.utils import qos as qos_lib

TRACE_FORMAT_VERSION = 1

ARRIVAL_MODELS = ('uniform', 'poisson', 'bursty')


@dataclasses.dataclass
class TraceRequest:
    """One scheduled request: WHEN it arrives (offset seconds from
    trace start — open-loop, independent of completions), WHAT it
    asks (prompt token ids, output budget) and HOW LONG it may take
    (relative deadline budget; None = immortal)."""
    request_id: int
    arrival_s: float
    tokens: List[int]
    max_new: int
    deadline_s: Optional[float] = None
    # Which shared prefix (Zipf rank, 0 = most popular) the prompt
    # starts with; None = a unique prompt. Carried so replay reports
    # can split hit/miss traffic without re-deriving prefixes.
    prefix_rank: Optional[int] = None
    # Multi-tenant QoS attribution (docs/qos.md); None = untagged.
    # Serialized only when set, so single-tenant traces keep their
    # pre-QoS canonical bytes (and digests).
    tenant: Optional[str] = None
    priority_class: Optional[str] = None

    def to_json(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            'id': self.request_id,
            'arrival_s': round(self.arrival_s, 6),
            'tokens': list(self.tokens),
            'max_new': self.max_new,
            'deadline_s': self.deadline_s,
            'prefix_rank': self.prefix_rank,
        }
        if self.tenant is not None:
            d['tenant'] = self.tenant
        if self.priority_class is not None:
            d['priority_class'] = self.priority_class
        return d

    @classmethod
    def from_json(cls, d: Dict[str, Any]) -> 'TraceRequest':
        return cls(request_id=int(d['id']),
                   arrival_s=float(d['arrival_s']),
                   tokens=[int(t) for t in d['tokens']],
                   max_new=int(d['max_new']),
                   deadline_s=(None if d.get('deadline_s') is None
                               else float(d['deadline_s'])),
                   prefix_rank=(None if d.get('prefix_rank') is None
                                else int(d['prefix_rank'])),
                   tenant=(None if d.get('tenant') is None
                           else str(d['tenant'])),
                   priority_class=(
                       None if d.get('priority_class') is None
                       else str(d['priority_class'])))


@dataclasses.dataclass
class TenantSpec:
    """One tenant's sub-stream in a multi-tenant mix (docs/qos.md).

    Each tenant draws from its OWN rng, seeded by (workload seed,
    tenant index), so tenant i's requests — arrivals, lengths,
    tokens — are a pure function of (seed, i, this TenantSpec).
    Cranking one tenant's rate or count leaves every other tenant's
    sub-stream byte-identical, which is exactly the property the
    burst-isolation A/B bench leans on: the victim traffic in the
    control and treatment arms is the same trace.

    Fields left at ``None`` inherit the base :class:`WorkloadSpec`
    value; ``n_requests``/``qps`` are always per-tenant.
    """
    name: str
    priority_class: str = 'standard'
    n_requests: int = 32
    qps: float = 4.0
    arrival: Optional[str] = None
    prompt_median: Optional[int] = None
    output_median: Optional[int] = None
    # Tenant deadline budget; None inherits the base spec's.
    deadline_s: Optional[float] = None


@dataclasses.dataclass
class WorkloadSpec:
    """Everything the generator needs — and nothing ambient: two
    specs that compare equal generate identical traces."""
    seed: int = 0
    n_requests: int = 64
    qps: float = 8.0
    arrival: str = 'poisson'
    # bursty (MMPP-2) knobs: HI rate = qps * burst_factor, LO rate =
    # qps / burst_factor, exponential dwell with mean burst_dwell_s.
    burst_factor: float = 4.0
    burst_dwell_s: float = 2.0
    vocab_size: int = 1000
    # Log-normal prompt/output lengths (median ~ *_median, clipped):
    # the mixed heavy-tailed shape of real traffic.
    prompt_median: int = 64
    prompt_sigma: float = 0.6
    prompt_min: int = 4
    prompt_max: int = 256
    output_median: int = 16
    output_sigma: float = 0.5
    output_min: int = 1
    output_max: int = 64
    # Zipf-shared prefixes (composes with the engine prefix cache /
    # BENCH_SERVE_PREFIX_* workloads): 0 prefixes = unique prompts.
    n_prefixes: int = 0
    prefix_len: int = 0
    zipf_s: float = 1.1
    # Relative per-request deadline budget; None = no deadlines.
    deadline_s: Optional[float] = None
    # Multi-tenant mix (docs/qos.md): when non-empty, the trace is
    # the arrival-ordered merge of one independently seeded
    # sub-stream per tenant (spec.n_requests/qps/arrival become the
    # per-tenant defaults; each TenantSpec overrides its own).
    tenants: List[TenantSpec] = dataclasses.field(default_factory=list)

    def validate(self) -> None:
        if self.arrival not in ARRIVAL_MODELS:
            raise ValueError(
                f'arrival must be one of {ARRIVAL_MODELS}, got '
                f'{self.arrival!r}')
        if self.qps <= 0 or self.n_requests <= 0:
            raise ValueError('qps and n_requests must be positive')
        if self.n_prefixes and self.prefix_len <= 0:
            raise ValueError(
                'n_prefixes > 0 needs a positive prefix_len')
        if self.n_prefixes and self.prefix_len >= self.prompt_max:
            raise ValueError(
                f'prefix_len ({self.prefix_len}) must leave room for '
                f'a suffix under prompt_max ({self.prompt_max})')
        if self.burst_factor < 1.0:
            raise ValueError('burst_factor must be >= 1')
        seen = set()
        for t in self.tenants:
            if qos_lib.validate_tenant(t.name) is None:
                raise ValueError(
                    f'tenant name must be non-empty, got {t.name!r}')
            if t.name in seen:
                raise ValueError(f'duplicate tenant {t.name!r}')
            seen.add(t.name)
            qos_lib.validate_class(t.priority_class)
            if t.qps <= 0 or t.n_requests <= 0:
                raise ValueError(
                    f'tenant {t.name!r}: qps and n_requests must be '
                    f'positive')
            if (t.arrival is not None and
                    t.arrival not in ARRIVAL_MODELS):
                raise ValueError(
                    f'tenant {t.name!r}: arrival must be one of '
                    f'{ARRIVAL_MODELS}, got {t.arrival!r}')
            if t.n_requests >= _TENANT_ID_STRIDE:
                raise ValueError(
                    f'tenant {t.name!r}: n_requests must stay under '
                    f'{_TENANT_ID_STRIDE} (request-id namespacing)')

    def to_json(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def _arrivals(spec: WorkloadSpec,
              rng: np.random.Generator) -> List[float]:
    n = spec.n_requests
    if spec.arrival == 'uniform':
        return [i / spec.qps for i in range(n)]
    if spec.arrival == 'poisson':
        gaps = rng.exponential(1.0 / spec.qps, n)
        return list(np.cumsum(gaps) - gaps[0])
    # bursty: 2-state MMPP with rates qps*f (HI) and qps/f (LO).
    # Dwell means are ASYMMETRIC so the time-weighted mean rate is
    # exactly qps: with mean dwells d_hi, d_lo the long-run rate is
    # (d_hi*qps*f + d_lo*qps/f) / (d_hi + d_lo), which equals qps
    # iff d_hi = d_lo / f — HI bursts are short and hot, LO valleys
    # long and quiet, same offered load as the poisson arm (the
    # comparison the p99 story rests on).
    hi = spec.qps * spec.burst_factor
    lo = spec.qps / spec.burst_factor
    dwell = {True: spec.burst_dwell_s / spec.burst_factor,
             False: spec.burst_dwell_s}
    out: List[float] = []
    t = 0.0
    in_hi = bool(rng.integers(0, 2))
    dwell_left = float(rng.exponential(dwell[in_hi]))
    while len(out) < n:
        rate = hi if in_hi else lo
        gap = float(rng.exponential(1.0 / rate))
        if gap >= dwell_left:
            # State flips before the next arrival: burn the dwell and
            # redraw in the new state (memorylessness makes the
            # discard exact).
            t += dwell_left
            in_hi = not in_hi
            dwell_left = float(rng.exponential(dwell[in_hi]))
            continue
        t += gap
        dwell_left -= gap
        out.append(t)
    return [a - out[0] for a in out]


def _lengths(rng: np.random.Generator, n: int, median: int,
             sigma: float, lo: int, hi: int) -> np.ndarray:
    raw = rng.lognormal(math.log(max(1, median)), sigma, n)
    return np.clip(raw.astype(np.int64), lo, hi)


# Request-id namespace per tenant sub-stream: tenant i's requests
# are numbered i*stride, i*stride+1, ... — stable across mix changes
# so A/B runs can join per-request records by id.
_TENANT_ID_STRIDE = 1_000_000


def generate(spec: WorkloadSpec) -> List[TraceRequest]:
    """Spec -> deterministic trace. One seeded RNG drives arrivals,
    lengths, prefix picks and token draws in a fixed order, so the
    whole trace — schedule included — is a pure function of the
    spec. With ``spec.tenants`` set, each tenant gets its own
    ``default_rng((seed, tenant_index))`` sub-stream and the trace is
    the arrival-ordered merge — perturbing one tenant's knobs leaves
    every other sub-stream byte-identical."""
    spec.validate()
    if spec.tenants:
        merged: List[TraceRequest] = []
        for idx, tenant in enumerate(spec.tenants):
            sub = dataclasses.replace(
                spec,
                tenants=[],
                n_requests=tenant.n_requests,
                qps=tenant.qps,
                arrival=(tenant.arrival if tenant.arrival is not None
                         else spec.arrival),
                prompt_median=(tenant.prompt_median
                               if tenant.prompt_median is not None
                               else spec.prompt_median),
                output_median=(tenant.output_median
                               if tenant.output_median is not None
                               else spec.output_median),
                deadline_s=(tenant.deadline_s
                            if tenant.deadline_s is not None
                            else spec.deadline_s),
            )
            rng = np.random.default_rng((spec.seed, idx))
            for r in _generate_stream(sub, rng):
                r.request_id += idx * _TENANT_ID_STRIDE
                r.tenant = tenant.name
                r.priority_class = tenant.priority_class
                merged.append(r)
        merged.sort(key=lambda r: (r.arrival_s, r.request_id))
        return merged
    return _generate_stream(spec, np.random.default_rng(spec.seed))


def _generate_stream(spec: WorkloadSpec,
                     rng: np.random.Generator) -> List[TraceRequest]:
    arrivals = _arrivals(spec, rng)
    n = spec.n_requests
    plens = _lengths(rng, n, spec.prompt_median, spec.prompt_sigma,
                     spec.prompt_min, spec.prompt_max)
    outs = _lengths(rng, n, spec.output_median, spec.output_sigma,
                    spec.output_min, spec.output_max)
    prefixes: List[List[int]] = []
    weights: Optional[np.ndarray] = None
    if spec.n_prefixes:
        prefixes = [
            [int(t) for t in rng.integers(0, spec.vocab_size,
                                          spec.prefix_len)]
            for _ in range(spec.n_prefixes)]
        weights = np.arange(1, spec.n_prefixes + 1,
                            dtype=np.float64) ** -spec.zipf_s
        weights /= weights.sum()
    trace: List[TraceRequest] = []
    for i in range(n):
        rank: Optional[int] = None
        if prefixes:
            rank = int(rng.choice(spec.n_prefixes, p=weights))
            suffix_len = max(1, int(plens[i]) - spec.prefix_len)
            tokens = prefixes[rank] + [
                int(t) for t in rng.integers(0, spec.vocab_size,
                                             suffix_len)]
        else:
            tokens = [int(t) for t in rng.integers(
                0, spec.vocab_size, int(plens[i]))]
        trace.append(TraceRequest(
            request_id=i,
            arrival_s=float(arrivals[i]),
            tokens=tokens,
            max_new=int(outs[i]),
            deadline_s=spec.deadline_s,
            prefix_rank=rank))
    return trace


# ---------------------------------------------------------- presets
def long_prompt(seed: int = 0, n_requests: int = 64,
                qps: float = 8.0, **overrides: Any) -> WorkloadSpec:
    """Heavy-prefill mix (docs/disaggregation.md): the workload shape
    disaggregated prefill/decode exists for. Long log-normal prompts
    (median 192, tail to 512) over Zipf-shared 64-token prefixes,
    SHORT outputs (median 8) — per-request compute is dominated by
    prefill, so interleaved serving stalls decode streams behind
    prefill chunks while a split pool keeps ITL flat. Keyword
    overrides replace any field after the preset shape is applied."""
    spec = WorkloadSpec(
        seed=seed,
        n_requests=n_requests,
        qps=qps,
        arrival='poisson',
        prompt_median=192,
        prompt_sigma=0.5,
        prompt_min=64,
        prompt_max=512,
        output_median=8,
        output_sigma=0.4,
        output_min=1,
        output_max=24,
        n_prefixes=8,
        prefix_len=64,
        zipf_s=1.1,
    )
    return dataclasses.replace(spec, **overrides) if overrides else spec


# ------------------------------------------------------------ JSONL
def to_jsonl(trace: Sequence[TraceRequest],
             spec: Optional[WorkloadSpec] = None) -> str:
    """Canonical JSONL text: an optional header line naming the
    format version and generating spec, then one line per request.
    Canonical (sorted keys, fixed rounding) so equal traces are equal
    BYTES — the property :func:`digest` certifies."""
    lines = []
    if spec is not None:
        lines.append(json.dumps(
            {'loadgen_trace': TRACE_FORMAT_VERSION,
             'spec': spec.to_json()}, sort_keys=True))
    for r in trace:
        lines.append(json.dumps(r.to_json(), sort_keys=True))
    return '\n'.join(lines) + '\n'


def dump_jsonl(trace: Sequence[TraceRequest], path: str,
               spec: Optional[WorkloadSpec] = None) -> None:
    with open(path, 'w', encoding='utf-8') as f:
        f.write(to_jsonl(trace, spec))


def load_jsonl(source: Iterable[str]) -> List[TraceRequest]:
    """Parse a trace from JSONL lines (a file object works); header
    lines are recognized and skipped."""
    out: List[TraceRequest] = []
    for line in source:
        line = line.strip()
        if not line:
            continue
        d = json.loads(line)
        if 'loadgen_trace' in d:
            continue
        out.append(TraceRequest.from_json(d))
    return out


def load_jsonl_path(path: str) -> List[TraceRequest]:
    with open(path, encoding='utf-8') as f:
        return load_jsonl(f)


def digest(trace: Sequence[TraceRequest]) -> str:
    """sha256 of the canonical JSONL (header excluded): the
    determinism receipt — same seed, same digest, across processes
    and platforms."""
    return hashlib.sha256(to_jsonl(trace).encode()).hexdigest()
