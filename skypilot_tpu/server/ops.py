"""Executable request bodies, shared by server routes and workers.

Each op takes the JSON body and returns a JSON-safe result. LONG ops
run in a worker process (skypilot_tpu.server.worker); SHORT ops run on
the server's thread pool.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

from skypilot_tpu.server.requests import ScheduleType


def _task_from_body(body: Dict[str, Any]):
    from skypilot_tpu import task as task_lib
    return task_lib.Task.from_yaml_config(body['task'])


def _launch(body: Dict[str, Any]) -> Dict[str, Any]:
    from skypilot_tpu import execution
    task = _task_from_body(body)
    job_id, handle = execution.launch(
        task,
        cluster_name=body.get('cluster_name'),
        dryrun=body.get('dryrun', False),
        stream_logs=False,
        detach_run=True,
        idle_minutes_to_autostop=body.get('idle_minutes_to_autostop'),
        down=body.get('down', False),
        retry_until_up=body.get('retry_until_up', False),
    )
    return {
        'job_id': job_id,
        'cluster_name': handle.cluster_name if handle else None,
    }


def _exec(body: Dict[str, Any]) -> Dict[str, Any]:
    from skypilot_tpu import execution
    task = _task_from_body(body)
    job_id, handle = execution.exec_(task,
                                     cluster_name=body['cluster_name'],
                                     stream_logs=False,
                                     detach_run=True)
    return {
        'job_id': job_id,
        'cluster_name': handle.cluster_name if handle else None,
    }


def _status(body: Dict[str, Any]) -> Any:
    from skypilot_tpu import core
    records = core.status(body.get('cluster_names'),
                          refresh=body.get('refresh', False))
    out = []
    for r in records:
        handle = r.get('handle')
        out.append({
            'name': r['name'],
            'status': r['status'].value,
            'resources': str(handle.launched_resources) if handle else '',
            'launched_at': r.get('launched_at'),
            'autostop': r.get('autostop'),
        })
    return out


def _core_op(method: str) -> Callable[[Dict[str, Any]], Any]:

    def run(body: Dict[str, Any]) -> Any:
        from skypilot_tpu import core
        return getattr(core, method)(**body)

    return run


def _queue(body: Dict[str, Any]) -> Any:
    from skypilot_tpu import core
    jobs = core.queue(body['cluster_name'])
    for j in jobs:
        if hasattr(j.get('status'), 'value'):
            j['status'] = j['status'].value
    return jobs


def _job_status(body: Dict[str, Any]) -> Any:
    from skypilot_tpu import core
    statuses = core.job_status(body['cluster_name'],
                               body.get('job_ids'))
    return {
        str(k): (v.value if v is not None else None)
        for k, v in statuses.items()
    }


def _jobs_launch(body: Dict[str, Any]) -> Any:
    from skypilot_tpu.jobs import core as jobs_core
    job_id = jobs_core.launch(_task_from_body(body),
                              name=body.get('name'),
                              on_controller=body.get('on_controller'))
    return {'managed_job_id': job_id}


def _jobs_queue(body: Dict[str, Any]) -> Any:
    from skypilot_tpu.jobs import core as jobs_core
    out = []
    for j in jobs_core.queue():
        out.append({
            'job_id': j['job_id'],
            'name': j['name'],
            'status': j['status'].value,
            'cluster_name': j['cluster_name'],
            'recovery_count': j['recovery_count'],
            'submitted_at': j['submitted_at'],
        })
    return out


def _jobs_cancel(body: Dict[str, Any]) -> Any:
    from skypilot_tpu.jobs import core as jobs_core
    return {
        'cancelled': jobs_core.cancel(body.get('job_ids'),
                                      all_jobs=body.get('all', False))
    }


def _serve_up(body: Dict[str, Any]) -> Any:
    from skypilot_tpu.serve import core as serve_core
    return serve_core.up(_task_from_body(body),
                         body.get('service_name'))


def _serve_update(body: Dict[str, Any]) -> Any:
    from skypilot_tpu.serve import core as serve_core
    return serve_core.update(_task_from_body(body),
                             body['service_name'])


def _serve_down(body: Dict[str, Any]) -> Any:
    from skypilot_tpu.serve import core as serve_core
    serve_core.down(body['service_name'], purge=body.get('purge', False))
    return {'ok': True}


def _serve_status(body: Dict[str, Any]) -> Any:
    from skypilot_tpu.serve import core as serve_core
    out = []
    for s in serve_core.status(body.get('service_name')):
        out.append({
            'name': s['name'],
            'status': s['status'].value,
            'endpoint': s['endpoint'],
            'version': s['version'],
            'replicas': [{
                'replica_id': r['replica_id'],
                'status': r['status'].value,
                'url': r['url'],
                'version': r['version'],
                'is_spot': r['is_spot'],
            } for r in s['replicas']],
        })
    return out


def _check(body: Dict[str, Any]) -> Any:
    from skypilot_tpu import check as check_lib
    enabled = check_lib.check(quiet=True)
    return [str(c) for c in enabled]


# op name -> (callable, schedule type)
OPS: Dict[str, Tuple[Callable[[Dict[str, Any]], Any], ScheduleType]] = {
    'launch': (_launch, ScheduleType.LONG),
    'exec': (_exec, ScheduleType.LONG),
    'stop': (_core_op('stop'), ScheduleType.LONG),
    'start': (_core_op('start'), ScheduleType.LONG),
    'down': (_core_op('down'), ScheduleType.LONG),
    'autostop': (_core_op('autostop'), ScheduleType.SHORT),
    'cancel': (_core_op('cancel'), ScheduleType.SHORT),
    'status': (_status, ScheduleType.SHORT),
    'queue': (_queue, ScheduleType.SHORT),
    'job_status': (_job_status, ScheduleType.SHORT),
    'cost_report': (_core_op('cost_report'), ScheduleType.SHORT),
    'check': (_check, ScheduleType.SHORT),
    'jobs.launch': (_jobs_launch, ScheduleType.LONG),
    'jobs.queue': (_jobs_queue, ScheduleType.SHORT),
    'jobs.cancel': (_jobs_cancel, ScheduleType.SHORT),
    'serve.up': (_serve_up, ScheduleType.LONG),
    'serve.update': (_serve_update, ScheduleType.SHORT),
    'serve.down': (_serve_down, ScheduleType.LONG),
    'serve.status': (_serve_status, ScheduleType.SHORT),
}
