"""Long-request worker process: execute one request row and record it.

Re-design of reference ``sky/server/requests/executor.py:171-224``
(`_request_execution_wrapper`): stdout/stderr are already redirected to
the per-request log by the spawner; this module loads the body, runs
the op, and writes the result/error back to the request DB.
"""
from __future__ import annotations

import json
import sys
import traceback

from skypilot_tpu.server import ops
from skypilot_tpu.server import requests as requests_db


def main() -> None:
    request_id = sys.argv[1]
    record = requests_db.get(request_id)
    if record is None:
        print(f'request {request_id} not found', file=sys.stderr)
        sys.exit(2)
    body = json.loads(record['body_json'])
    fn, _ = ops.OPS[record['name']]
    try:
        result = fn(body)
    except Exception as e:  # pylint: disable=broad-except
        traceback.print_exc()
        requests_db.finish(request_id,
                           error=f'{type(e).__name__}: {e}')
        sys.exit(1)
    requests_db.finish(request_id, result=result)


if __name__ == '__main__':
    main()
