"""Request DB + executor.

Re-design of reference ``sky/server/requests/requests.py:398`` +
``executor.py:282``: requests persist to SQLite; LONG requests
(launch/exec/down/...) run in detached worker processes with output
redirected to a per-request log file; SHORT requests (status/queue)
run on a thread pool in the server process. Results are JSON.
"""
from __future__ import annotations

import enum
import json
import os
import sqlite3
import subprocess
import sys
import threading
import time
import traceback
import uuid
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional

from skypilot_tpu.utils import log as sky_logging
from skypilot_tpu.utils import statedb

logger = sky_logging.init_logger(__name__)

_DB_PATH_ENV = 'SKYTPU_REQUESTS_DB'
_DEFAULT_DB = '~/.skytpu/api_requests.db'
_LOG_DIR_ENV = 'SKYTPU_REQUESTS_LOG_DIR'
_DEFAULT_LOG_DIR = '~/.skytpu/api_requests'

_MAX_LONG_WORKERS = max(2, (os.cpu_count() or 4) // 2)


class RequestStatus(enum.Enum):
    PENDING = 'PENDING'
    RUNNING = 'RUNNING'
    SUCCEEDED = 'SUCCEEDED'
    FAILED = 'FAILED'
    CANCELLED = 'CANCELLED'

    def is_terminal(self) -> bool:
        return self in (RequestStatus.SUCCEEDED, RequestStatus.FAILED,
                        RequestStatus.CANCELLED)


class ScheduleType(enum.Enum):
    LONG = 'long'     # launch/exec/down/start/stop — own process
    SHORT = 'short'   # status/queue/... — server thread pool


def _db_path() -> str:
    return os.path.expanduser(os.environ.get(_DB_PATH_ENV, _DEFAULT_DB))


def log_dir() -> str:
    return os.path.expanduser(
        os.environ.get(_LOG_DIR_ENV, _DEFAULT_LOG_DIR))


def _conn() -> sqlite3.Connection:
    # statedb.connect: the one connection recipe (WAL + busy_timeout +
    # synchronous=NORMAL + autocommit; docs/crash_recovery.md). All
    # writes here are single statements, so no explicit transactions.
    conn = statedb.connect(_db_path())
    conn.execute("""
        CREATE TABLE IF NOT EXISTS requests (
            request_id TEXT PRIMARY KEY,
            name TEXT,
            status TEXT,
            schedule_type TEXT,
            body_json TEXT,
            result_json TEXT,
            error TEXT,
            pid INTEGER,
            created_at REAL,
            finished_at REAL
        )""")
    return conn


def create(name: str, body: Dict[str, Any],
           schedule_type: ScheduleType) -> str:
    request_id = uuid.uuid4().hex[:16]
    with _conn() as conn:
        conn.execute(
            'INSERT INTO requests (request_id, name, status, '
            'schedule_type, body_json, created_at) VALUES (?,?,?,?,?,?)',
            (request_id, name, RequestStatus.PENDING.value,
             schedule_type.value, json.dumps(body), time.time()))
    return request_id


def set_running(request_id: str, pid: Optional[int] = None) -> None:
    with _conn() as conn:
        conn.execute(
            'UPDATE requests SET status = ?, pid = ? WHERE request_id = ?',
            (RequestStatus.RUNNING.value, pid, request_id))


def finish(request_id: str, *, result: Any = None,
           error: Optional[str] = None,
           cancelled: bool = False) -> None:
    status = (RequestStatus.CANCELLED if cancelled else
              RequestStatus.FAILED if error is not None else
              RequestStatus.SUCCEEDED)
    with _conn() as conn:
        conn.execute(
            'UPDATE requests SET status = ?, result_json = ?, error = ?, '
            'finished_at = ? WHERE request_id = ?',
            (status.value, json.dumps(result), error, time.time(),
             request_id))


def get(request_id: str) -> Optional[Dict[str, Any]]:
    with _conn() as conn:
        row = conn.execute(
            'SELECT * FROM requests WHERE request_id = ?',
            (request_id,)).fetchone()
    if row is None:
        return None
    d = dict(row)
    d['status'] = RequestStatus(d['status'])
    if d.get('result_json'):
        d['result'] = json.loads(d['result_json'])
    return d


def list_requests(limit: int = 100) -> List[Dict[str, Any]]:
    with _conn() as conn:
        rows = conn.execute(
            'SELECT request_id, name, status, created_at, finished_at '
            'FROM requests ORDER BY created_at DESC LIMIT ?',
            (limit,)).fetchall()
    return [dict(r) for r in rows]


def request_log_path(request_id: str) -> str:
    return os.path.join(log_dir(), f'{request_id}.log')


def cancel(request_id: str) -> bool:
    record = get(request_id)
    if record is None or record['status'].is_terminal():
        return False
    pid = record.get('pid')
    if pid:
        import signal
        try:
            os.killpg(os.getpgid(pid), signal.SIGTERM)
        except (OSError, ProcessLookupError):
            try:
                os.kill(pid, signal.SIGTERM)
            except (OSError, ProcessLookupError):
                pass
    finish(request_id, cancelled=True)
    return True


# ----------------------------------------------------------- executor


_short_pool = ThreadPoolExecutor(max_workers=8,
                                 thread_name_prefix='short-req')
_long_slots = threading.Semaphore(_MAX_LONG_WORKERS)


def run_short(request_id: str, fn: Callable[[], Any]) -> None:
    """Execute in the server process (fast, non-blocking ops)."""

    def work():
        set_running(request_id)
        try:
            result = fn()
            finish(request_id, result=result)
        except Exception as e:  # pylint: disable=broad-except
            logger.warning('Short request %s failed:\n%s', request_id,
                           traceback.format_exc())
            finish(request_id, error=f'{type(e).__name__}: {e}')

    _short_pool.submit(work)


def spawn_long(request_id: str) -> None:
    """Execute in a detached worker process; output → request log."""

    def work():
        with _long_slots:
            os.makedirs(log_dir(), exist_ok=True)
            log_path = request_log_path(request_id)
            env = dict(os.environ)
            repo_root = os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))))
            existing = env.get('PYTHONPATH', '')
            if repo_root not in existing.split(os.pathsep):
                env['PYTHONPATH'] = repo_root + (
                    os.pathsep + existing if existing else '')
            with open(log_path, 'ab') as log_f:
                proc = subprocess.Popen(
                    [sys.executable, '-u', '-m',
                     'skypilot_tpu.server.worker', request_id],
                    stdout=log_f, stderr=subprocess.STDOUT,
                    start_new_session=True, env=env)
            set_running(request_id, pid=proc.pid)
            proc.wait()
            # The worker writes the result row itself; if it died
            # without doing so, record the crash.
            record = get(request_id)
            if record is not None and not record['status'].is_terminal():
                finish(request_id,
                       error=f'worker exited with {proc.returncode} '
                       'before recording a result')

    threading.Thread(target=work, daemon=True).start()
