"""API server: HTTP front-end over the core API.

Re-design of reference ``sky/server/`` (SURVEY.md §2.8): every SDK
call becomes a POST that persists a request row, gets executed by a
worker (detached process for long operations, thread for short ones),
and is polled/streamed back by the client. aiohttp replaces FastAPI.
"""
